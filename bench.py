"""Headline benchmark: linearizability ops verified per second per chip.

Workload (BASELINE.md config 4 shape — the reference's own scaling
strategy): a batch of independent per-key CAS-register histories, as
produced by ``independent/concurrent-generator`` keyspace sharding
(reference: jepsen/src/jepsen/independent.clj:103-238).  The TPU path
packs all histories to common shapes and sweeps them in one vmapped
kernel; the baseline is the single-host knossos-equivalent DFS
(jepsen_tpu.checker.wgl_cpu.dfs_analysis) over the same histories.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent / "tools"))

from genhist import corrupt, valid_register_history  # noqa: E402

from jepsen_tpu import models as m  # noqa: E402
from jepsen_tpu.checker import wgl_cpu  # noqa: E402
from jepsen_tpu.parallel import batch_analysis  # noqa: E402

N_HISTORIES = 256
OPS_PER_HISTORY = 40
PROCS = 4
INFO_RATE = 0.1


def main() -> None:
    model = m.CASRegister(None)
    hists = []
    for i in range(N_HISTORIES):
        hist = valid_register_history(OPS_PER_HISTORY, PROCS, seed=i, info_rate=INFO_RATE)
        if i % 5 == 4:
            hist = corrupt(hist, seed=i)
        hists.append(hist)
    total_ops = sum(len(hh) for hh in hists) // 2  # invoke+completion pairs

    # Warm-up at the MEASURED shapes (full batch, both capacity stages) so
    # the measurement excludes compilation, then measure a steady-state run.
    batch_analysis(model, hists, capacity=(64, 512, 4096), cpu_fallback=False)
    t0 = time.perf_counter()
    tpu_results = batch_analysis(model, hists, capacity=(64, 512, 4096), cpu_fallback=False)
    tpu_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    cpu_results = [wgl_cpu.dfs_analysis(model, hh) for hh in hists]
    cpu_s = time.perf_counter() - t0

    # Verdict agreement sanity (unknowns excluded — capacity-bounded).
    for tr, cr in zip(tpu_results, cpu_results):
        if tr["valid?"] != "unknown" and cr["valid?"] != "unknown":
            assert tr["valid?"] == cr["valid?"], (tr, cr)

    value = total_ops / tpu_s
    baseline = total_ops / cpu_s
    print(
        json.dumps(
            {
                "metric": "linearizability ops verified/sec/chip (256-key CAS batch)",
                "value": round(value, 1),
                "unit": "ops/s",
                "vs_baseline": round(value / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
