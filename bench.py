"""Headline benchmark: linearizability ops verified per second per chip.

Workload (shape constants below; the metric string is derived from them):
a batch of N_HISTORIES independent register histories in the
worst-case-branching regime the north star targets (BASELINE config 4's
batch shape at config 5's difficulty): OPS_PER_HISTORY ops x PROCS
processes per history, INFO_RATE indeterminate (:info) completions —
crashed ops stay concurrent forever, multiplying the configuration
frontier — and 1/CORRUPT_EVERY of the histories corrupted, because
refuting an invalid history is the expensive case that matters (jepsen
runs checkers to FIND violations).

TPU path: the batched fast-frontier kernel with a per-stage capacity
ladder; every stage's verdicts are exact (content-confirmed kills), so
escalation is purely capacity (jepsen_tpu.parallel.batch_analysis).
Baseline: the single-host config-set sweep
(jepsen_tpu.checker.wgl_cpu.sweep_analysis — the same frontier
algorithm, i.e. the knossos-linear-equivalent and the strongest CPU
oracle here; the DFS oracle goes exponential and never finishes this
workload), capped at CPU_MAX_CONFIGS explored configurations per history
(a deterministic work budget; BUDGET_S is only a wall-clock backstop).
Cap hits make the reported vs_baseline an UNDERestimate.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent / "tools"))

# ---------------------------------------------------------------------------
# Outage guard.  The TPU tunnel can be down (round 4 lost its perf evidence
# to exactly this: backend init raised deep inside the first device call and
# the bench died rc=1 with a raw traceback).  A perf harness must degrade to
# a STRUCTURED failure line the driver can record, so before importing
# anything that initializes the backend we probe it in a subprocess with a
# timeout (backend-init hangs are C-level and not reliably interruptible
# in-process).  JEPSEN_TPU_BENCH_PROBE overrides the probe command (tests
# simulate outages with it); JEPSEN_TPU_BENCH_PROBE_TIMEOUT the timeout.
# ---------------------------------------------------------------------------
_PROBE_SRC = (
    # honor the same platform override the real bench applies, so a user
    # forcing JEPSEN_TPU_PLATFORM=cpu probes (and then runs) on CPU
    # instead of hanging on a dead tunnel
    "from jepsen_tpu._platform import honor_env_platform; "
    "honor_env_platform(); import jax; jax.devices()"
)
try:
    _PROBE_TIMEOUT = float(
        os.environ.get("JEPSEN_TPU_BENCH_PROBE_TIMEOUT", "300")
    )
except ValueError:
    _PROBE_TIMEOUT = 300.0  # malformed override must not crash the bench


def _fingerprint(probe_devices: bool) -> dict:
    """The machine-identity block every bench line carries (the ledger
    groups noise baselines on it): git sha + jax/jaxlib/backend/device/
    CPU/host via obs.regress.  ``probe_devices=False`` on the outage
    path — the probe just established the backend is down, and an
    in-process jax.devices() could hang."""
    from jepsen_tpu.obs import regress

    fp = regress.fingerprint(probe_devices=probe_devices)
    return {**fp, "git": regress.git_info().get("sha", "unknown")}


def _unavailable_line(reason: str) -> str:
    return json.dumps(
        {
            "metric": "linearizability ops verified/sec/chip",
            "value": 0,
            "unit": "ops/s",
            "vs_baseline": 0,
            "tpu_unavailable": True,
            "reason": reason[-2000:],
            "fingerprint": _fingerprint(probe_devices=False),
        }
    )


def _probe_backend() -> str | None:
    """Returns None if the accelerator backend comes up, else the reason."""
    cmd = os.environ.get("JEPSEN_TPU_BENCH_PROBE")
    argv = (
        ["/bin/sh", "-c", cmd] if cmd else [sys.executable, "-c", _PROBE_SRC]
    )
    try:
        r = subprocess.run(
            argv, capture_output=True, text=True, timeout=_PROBE_TIMEOUT,
            cwd=str(Path(__file__).resolve().parent),
        )
    except subprocess.TimeoutExpired:
        return f"backend probe hung > {_PROBE_TIMEOUT:.0f}s (tunnel down?)"
    if r.returncode != 0:
        tail = (r.stderr or r.stdout or "").strip().splitlines()
        return "backend probe failed: " + (tail[-1] if tail else f"rc={r.returncode}")
    return None


_reason = _probe_backend()
if _reason is not None:
    print(_unavailable_line(_reason))
    sys.exit(0)

from genhist import corrupt, valid_register_history  # noqa: E402

from jepsen_tpu import models as m  # noqa: E402
from jepsen_tpu import obs  # noqa: E402
from jepsen_tpu.checker import wgl_cpu  # noqa: E402
from jepsen_tpu.ops.hashing import dedup_round_probe  # noqa: E402
from jepsen_tpu.parallel import batch_analysis  # noqa: E402
from jepsen_tpu.parallel.batch import warm_confirm_pool  # noqa: E402

N_HISTORIES = 128
OPS_PER_HISTORY = 100
PROCS = 8
INFO_RATE = 0.3
N_VALUES = 8
CORRUPT_EVERY = 4
CAPS = (128, 512, 2048)
EXACT = ()
BUDGET_S = 10.0  # wall-clock backstop only; the real cap is work-based
CPU_MAX_CONFIGS = 100_000  # deterministic sweep budget (low run variance)
CPU_SAMPLE = 48  # CPU baseline measured on this many histories, extrapolated

# Fixed-work secondary metric: the exact sweep over a PINNED history
# subset with a PINNED explored-configuration budget and no wall-clock
# alarm.  The work (configs explored) is a deterministic function of the
# histories + budget — bit-identical every run — so configs/sec carries
# only timer noise (±a few %), where vs_baseline's wall-clock ratio
# swings ±20% with host load.  Kernel wins move `value`; this metric
# pins the denominator side so they resolve above the noise.
FIXED_WORK_HISTS = 12       # deterministic subset (same seeds every round)
FIXED_WORK_CONFIGS = 25_000  # pinned per-history budget


def fixed_work_metric(model, hists, repeats: int = 2) -> dict:
    """configs explored/sec on the exact CPU sweep at a pinned work
    budget (see the FIXED_WORK_* constants).  Returns the JSON fragment
    for the bench line: {"metric", "configs", "seconds", "value"} —
    ``configs`` is deterministic across runs (asserted), ``value`` =
    configs/sec of the BEST of ``repeats`` passes: the work is fixed, so
    the fastest pass is the least-interfered one and max-throughput is
    the reproducible statistic (mean would re-import the host-load noise
    this metric exists to shed)."""
    sample = hists[:FIXED_WORK_HISTS]
    best_dt = None
    total = 0
    for _ in range(max(1, repeats)):
        run_total = 0
        t0 = time.perf_counter()
        for hh in sample:
            st: dict = {}
            wgl_cpu.sweep_analysis(
                model, hh, max_configs=FIXED_WORK_CONFIGS, stats=st
            )
            run_total += int(st.get("configs_explored", 0))
        dt = time.perf_counter() - t0
        assert total in (0, run_total), "fixed work was not deterministic"
        total = run_total
        best_dt = dt if best_dt is None else min(best_dt, dt)
    return {
        "metric": (
            f"cpu sweep configs explored/sec ({len(sample)} pinned "
            f"histories, {FIXED_WORK_CONFIGS}-config budget, "
            f"best of {max(1, repeats)})"
        ),
        "configs": total,
        "seconds": round(best_dt, 4),
        "value": round(total / best_dt, 1) if best_dt else 0,
    }


def cpu_check(model, hist):
    """sweep_analysis with a wall-clock budget."""

    def bail(*_):
        raise TimeoutError

    old = signal.signal(signal.SIGALRM, bail)
    signal.setitimer(signal.ITIMER_REAL, BUDGET_S)
    try:
        r = wgl_cpu.sweep_analysis(model, hist, max_configs=CPU_MAX_CONFIGS)
        return r, r.get("cause") is not None
    except TimeoutError:
        return {"valid?": "unknown", "cause": "budget"}, True
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, old)


def main() -> None:
    model = m.CASRegister(None)
    hists = []
    for i in range(N_HISTORIES):
        hist = valid_register_history(
            OPS_PER_HISTORY, PROCS, seed=i, info_rate=INFO_RATE, n_values=N_VALUES
        )
        if i % CORRUPT_EVERY == CORRUPT_EVERY - 1:
            hist = corrupt(hist, seed=i)
        hists.append(hist)
    total_ops = sum(len(hh) for hh in hists) // 2  # invoke+completion pairs

    kw = dict(capacity=CAPS, exact_escalation=EXACT, cpu_fallback=False)
    # Warm-up at the MEASURED shapes (full batch, every ladder stage) so
    # the measurement excludes compilation, and spawn the confirmation
    # workers so pool startup stays outside the timed window.  The
    # warm-up runs inside a THROWAWAY recording when telemetry is on:
    # batch_analysis's telemetry-gated dedup probe (and its first-time
    # jit compiles) fires only when a recorder is active, so without
    # this it would fire for the first time INSIDE the measured window
    # and deflate the headline (review catch, round 6).  The probe is
    # once-per-shape-per-process, so the measured run pays nothing.
    warm_confirm_pool()
    warm_dir = (
        Path(tempfile.mkdtemp(prefix="jepsen-tpu-bench-warm-"))
        if obs.env_enabled(True) else None
    )
    with obs.recording(warm_dir, enabled=warm_dir is not None):
        batch_analysis(model, hists, **kw)
    if warm_dir is not None:
        shutil.rmtree(warm_dir, ignore_errors=True)
    # Telemetry rides the measured run (per-stage spans only — a dozen
    # events, noise relative to the kernel launches): the ladder-stage
    # table lands in the JSON line so every perf PR reports through it.
    # JEPSEN_TPU_TELEMETRY=0 turns it off.
    tele_dir = (
        Path(tempfile.mkdtemp(prefix="jepsen-tpu-bench-telemetry-"))
        if obs.env_enabled(True) else None
    )
    with obs.recording(tele_dir, enabled=tele_dir is not None) as rec:
        t0 = time.perf_counter()
        tpu_results = batch_analysis(model, hists, **kw)
        tpu_s = time.perf_counter() - t0
        if tele_dir is not None:
            # The warm-up recording consumed the once-per-shape auto
            # probe, so emit this run's dedup.round spans explicitly —
            # AFTER the timed window (jits are warm; a few ms).
            dedup_round_probe(CAPS[0], PROCS, 8)
    telemetry = None
    if rec is not None and rec.summary is not None:
        telemetry = {
            "ladder": rec.summary["ladder"],
            "counters": rec.summary["counters"],
            "file": str(tele_dir / "telemetry.json"),
        }
        if rec.summary.get("dedup"):
            # per-round dedup probe, every resolvable backend (sort /
            # bucket / pallas-where-feasible), at this run's first-rung
            # candidate shape (ops.hashing.dedup_round_probe); pallas
            # rows carry an honest `interpret` flag off-chip
            telemetry["dedup"] = rec.summary["dedup"]

    # Fixed-work secondary metric (deterministic work, pinned histories):
    # resolvable above the wall-clock baseline's ±20% denominator noise.
    fixed_work = fixed_work_metric(model, hists)

    # CPU baseline on a deterministic sample, extrapolated (the full set
    # at the budget cap alone would take >20 min).
    sample = hists[:CPU_SAMPLE]
    t0 = time.perf_counter()
    cpu_results = []
    cap_hits = 0
    for hh in sample:
        r, hit = cpu_check(model, hh)
        cpu_results.append(r)
        cap_hits += hit
    cpu_s = (time.perf_counter() - t0) * (len(hists) / len(sample))

    # Verdict agreement sanity (unknowns excluded — capacity/budget-bounded).
    disagree = sum(
        1
        for tr, cr in zip(tpu_results[: len(cpu_results)], cpu_results)
        if "unknown" not in (tr["valid?"], cr["valid?"]) and tr["valid?"] != cr["valid?"]
    )
    assert disagree == 0, f"{disagree} verdict disagreements"
    unknowns = sum(1 for r in tpu_results if r["valid?"] == "unknown")

    value = total_ops / tpu_s
    baseline = total_ops / cpu_s
    line = {
        "metric": (
            "linearizability ops verified/sec/chip "
            f"({N_HISTORIES}x{OPS_PER_HISTORY}-op batch, {PROCS} procs, "
            f"{int(INFO_RATE*100)}% info, 1/{CORRUPT_EVERY} corrupted; "
            f"tpu unknowns {unknowns}, cpu {CPU_SAMPLE}-sample budget-capped {cap_hits})"
        ),
        "value": round(value, 1),
        "unit": "ops/s",
        "vs_baseline": round(value / baseline, 2),
        "fixed_work": fixed_work,
    }
    if telemetry is not None:
        line["telemetry"] = telemetry
    # Machine fingerprint: chip rounds and CPU-fallback rounds used to be
    # distinguishable only by parsing warning text in the driver's
    # "tail" — now the line says what produced the number, and the perf
    # ledger groups noise baselines on it.
    line["fingerprint"] = _fingerprint(probe_devices=True)
    print(json.dumps(line))
    _append_ledger(line, rec.summary if rec is not None else None)


def _append_ledger(line: dict, summary: dict | None) -> None:
    """Append this run to the perf-regression ledger (obs.regress) —
    headline + fixed_work metrics and the per-stage telemetry rollup.
    Best-effort: a full disk or read-only checkout must not turn a
    successful bench into a failure."""
    try:
        from jepsen_tpu.obs import regress

        fw = line.get("fixed_work") or {}
        metrics = {
            "ops_per_s": line.get("value"),
            "vs_baseline": line.get("vs_baseline"),
            "fixed_work_configs_per_s": fw.get("value"),
            "fixed_work_s": fw.get("seconds"),
        }
        stages, extra_metrics = regress.stage_rollup(summary)
        metrics.update(extra_metrics)
        fp = dict(line.get("fingerprint") or {})
        fp.pop("git", None)  # the record envelope carries git separately
        record = regress.make_record("bench", metrics, stages=stages, fp=fp)
        regress.append_record(record)
    except Exception as e:  # noqa: BLE001 — never fail the bench on this
        print(f"warning: perf-ledger append failed: {e}", file=sys.stderr)


def _is_backend_outage(e: BaseException) -> bool:
    s = f"{type(e).__name__}: {e}"
    return any(
        k in s
        for k in (
            "Unable to initialize backend",
            "UNAVAILABLE",
            "DEADLINE_EXCEEDED",
            "Socket closed",
            "failed to connect",
        )
    )


if __name__ == "__main__":
    try:
        main()
    except Exception as e:  # noqa: BLE001 — mid-run tunnel drops must
        # still produce a structured line; real bugs still fail loudly.
        if _is_backend_outage(e):
            print(_unavailable_line(f"mid-run backend failure: {e!r}"))
            sys.exit(0)
        raise
