"""libfaketime wrappers: run DB binaries on lying clocks.

Mirrors ``jepsen.faketime`` (reference: jepsen/src/jepsen/faketime.clj:
8-47): wrap a database binary in a shell script that LD_PRELOADs
libfaketime with a per-process rate/offset, so different nodes' *daemons*
experience different clock speeds — a softer, always-on cousin of the
bump/strobe nemesis (jepsen_tpu.nemesis.time).

The reference fetches its own libfaketime fork and builds it on the node;
here the library path is configurable (distro packages ship
``libfaketime.so.1``) and ``install`` builds from a source tree when one
is provided via the fs cache.
"""

from __future__ import annotations

import random
from typing import Mapping

from jepsen_tpu import control

#: common distro install locations, probed in order
LIB_CANDIDATES = (
    "/usr/lib/x86_64-linux-gnu/faketime/libfaketime.so.1",
    "/usr/lib/faketime/libfaketime.so.1",
    "/usr/local/lib/faketime/libfaketime.so.1",
    "/opt/jepsen/libfaketime.so.1",
)


def find_lib(session: control.Session) -> str | None:
    for p in LIB_CANDIDATES:
        if session.exec_result("test", "-e", p).get("exit") == 0:
            return p
    return None


def script(binary: str, lib: str, rate: float = 1.0, offset_s: float = 0.0) -> str:
    """The wrapper script body (faketime.clj:8-30): exec the real binary
    under libfaketime at ``rate`` × real speed, offset by ``offset_s``."""
    spec = f"{'+' if offset_s >= 0 else ''}{offset_s:.3f}s x{rate:.6f}"
    return (
        "#!/bin/bash\n"
        f"# jepsen faketime wrapper for {binary}\n"
        f"export LD_PRELOAD={lib}\n"
        f'export FAKETIME="{spec}"\n'
        "export FAKETIME_DONT_FAKE_MONOTONIC=1\n"
        f'exec {binary}.real "$@"\n'
    )


def wrap_binary(
    session: control.Session,
    binary: str,
    rate: float = 1.0,
    offset_s: float = 0.0,
    lib: str | None = None,
):
    """Replace ``binary`` with a faketime wrapper (the original moves to
    ``<binary>.real``), idempotently (faketime.clj:32-47)."""
    lib = lib or find_lib(session)
    if lib is None:
        raise RuntimeError("libfaketime not found on node; install it or pass lib=")
    with session.su():
        moved = session.exec_result("test", "-e", f"{binary}.real").get("exit") == 0
        if not moved:
            session.exec("mv", binary, f"{binary}.real")
        session.write_file(script(binary, lib, rate, offset_s), binary)
        session.exec("chmod", "+x", binary)


def unwrap_binary(session: control.Session, binary: str):
    """Restore the real binary."""
    with session.su():
        if session.exec_result("test", "-e", f"{binary}.real").get("exit") == 0:
            session.exec("mv", f"{binary}.real", binary)


def rand_factor(max_skew: float = 5.0) -> float:
    """A random clock rate in [1/max_skew, max_skew], log-uniform
    (faketime.clj:57-65)."""
    import math

    return math.exp(random.uniform(-math.log(max_skew), math.log(max_skew)))
