"""CLI: turn a test constructor into a command-line program.

Mirrors ``jepsen.cli`` (reference: jepsen/src/jepsen/cli.clj): subcommand
dispatch with the exit-code contract (cli.clj:127-139):

  0    test passed (valid? true)
  1    test failed (valid? false)
  2    analysis inconclusive (valid? unknown)
  254  usage error
  255  crash

Subcommands (cli.clj:355-431, 336-353, 491-519):

  test      run a test_fn-constructed test `--test-count` times
  analyze   re-run checkers on a stored history, no cluster needed
  serve     browse the store directory over HTTP

Harness authors call ``run_cli(test_fn)`` from their ``__main__``, like the
reference's ``(cli/run! (merge (cli/single-test-cmd ...) (cli/serve-cmd)))``
(zookeeper/src/jepsen/zookeeper.clj:131-137).
"""

from __future__ import annotations

import argparse
import logging
import os
import sys
from typing import Callable, Mapping

from jepsen_tpu import core, store

logger = logging.getLogger(__name__)

EXIT_VALID = 0
EXIT_INVALID = 1
EXIT_UNKNOWN = 2
EXIT_USAGE = 254
EXIT_CRASH = 255

DEFAULT_NODES = ["n1", "n2", "n3", "n4", "n5"]


def add_test_opts(p: argparse.ArgumentParser):
    """The shared option vocabulary (cli.clj:64-111)."""
    p.add_argument("--nodes", default=",".join(DEFAULT_NODES),
                   help="comma-separated node hostnames")
    p.add_argument("--node", action="append", default=None,
                   help="a node to test (repeatable; overrides --nodes)")
    p.add_argument("--nodes-file", default=None,
                   help="file with one node hostname per line")
    p.add_argument("--concurrency", default="1n",
                   help="number of workers; '3n' means 3× node count")
    p.add_argument("--time-limit", type=float, default=60.0,
                   help="how long to run the workload, in seconds")
    p.add_argument("--test-count", type=int, default=1,
                   help="how many times to run the test")
    p.add_argument("--username", default="root", help="ssh user")
    p.add_argument("--password", default=None, help="ssh password (unused; use keys)")
    p.add_argument("--private-key-path", default=None, help="ssh identity file")
    p.add_argument("--ssh-port", type=int, default=None, help="ssh port")
    p.add_argument("--no-ssh", action="store_true",
                   help="use the dummy remote: run no remote commands")
    p.add_argument("--local", action="store_true",
                   help="use the local-subprocess remote (single-machine tests)")
    p.add_argument("--docker", action="store_true",
                   help="use the docker-exec remote (node names = container names)")
    p.add_argument("--leave-db-running", action="store_true",
                   help="skip DB teardown at the end")
    p.add_argument("--store-dir", default=None, help="where test runs are stored")
    tele = p.add_mutually_exclusive_group()
    tele.add_argument("--telemetry", dest="telemetry", action="store_true",
                      default=None,
                      help="record telemetry.jsonl/.json into the store dir "
                           "(default: on; env JEPSEN_TPU_TELEMETRY)")
    tele.add_argument("--no-telemetry", dest="telemetry", action="store_false",
                      help="disable telemetry recording for this run")
    p.add_argument("--dedup-backend", choices=("sort", "bucket", "pallas"),
                   default=None,
                   help="frontier dedup backend for the TPU checker's "
                        "ladder rungs: 'sort' (multi-key hash sort), "
                        "'bucket' (packed radix buckets), or 'pallas' "
                        "(fused wide-stage Pallas kernel — wide rungs "
                        "only, interpret mode on CPU; infeasible "
                        "geometry falls back to bucket/sort); default: "
                        "env JEPSEN_TPU_DEDUP_BACKEND, else 'sort'")
    p.add_argument("--frontier-budget-mb", type=float, default=None,
                   metavar="MB",
                   help="device-memory budget for the exact checker's "
                        "frontier working set: ladder rungs that don't "
                        "fit host-spill overflow rows instead of going "
                        "lossy, and a history fixed memory can't decide "
                        "returns an unknown carrying a machine-readable "
                        "undecidability report (default: env "
                        "JEPSEN_TPU_FRONTIER_BUDGET_MB, else unbounded)")
    p.add_argument("--perf-ledger", default=None, metavar="PATH",
                   help="perf-regression ledger (obs.regress) every "
                        "bench/loadgen/budget tool in this process tree "
                        "appends to (sets JEPSEN_TPU_PERF_LEDGER; "
                        "default store/perf-ledger.jsonl; 'off' "
                        "disables)")
    p.add_argument("--stream", action="store_true",
                   help="live streaming check: tee the interpreter's op "
                        "log into an incremental checker "
                        "(checker.streaming) and report a "
                        "linearizability violation WHILE the test runs; "
                        "the post-hoc analysis stays authoritative")
    p.add_argument("--stream-every", type=int, default=None, metavar="N",
                   help="ops per live-streaming epoch (default 32; each "
                        "epoch re-packs the current prefix, so smaller "
                        "epochs detect sooner but cost more host work)")
    p.add_argument("--check-deadline", type=float, default=None,
                   metavar="SECONDS",
                   help="wall-clock budget for the checker phase: on "
                        "expiry the TPU ladder checkpoints, marks the "
                        "remaining histories 'unknown' (cause "
                        "deadline-exceeded + a checkpoint pointer), and "
                        "results.json is still written complete")


def options_to_test_opts(opts: argparse.Namespace) -> dict:
    """argparse → the test-map option fragment (cli.clj:150-233)."""
    if opts.node:
        nodes = list(opts.node)
    elif opts.nodes_file:
        nodes = [l.strip() for l in open(opts.nodes_file) if l.strip()]
    else:
        nodes = [n for n in opts.nodes.split(",") if n]
    ssh: dict = {"user": opts.username}
    if opts.no_ssh:
        ssh["dummy?"] = True
    if getattr(opts, "local", False):
        ssh["local?"] = True
    if getattr(opts, "docker", False):
        ssh["docker?"] = True
    if opts.private_key_path:
        ssh["private-key-path"] = opts.private_key_path
    if opts.ssh_port:
        ssh["port"] = opts.ssh_port
    out = {
        "nodes": nodes,
        "concurrency": opts.concurrency,
        "time-limit": opts.time_limit,
        "ssh": ssh,
        "leave-db-running?": opts.leave_db_running,
    }
    if opts.store_dir:
        out["store-dir"] = opts.store_dir
    if getattr(opts, "check_deadline", None) is not None:
        out["check-deadline"] = opts.check_deadline
    if getattr(opts, "stream", False):
        out["stream?"] = True
    if getattr(opts, "stream_every", None) is not None:
        out["stream-every"] = opts.stream_every
    return out


def _exit_code(result: Mapping) -> int:
    v = (result or {}).get("valid?")
    if v is True:
        return EXIT_VALID
    if v == "unknown":
        return EXIT_UNKNOWN
    return EXIT_INVALID


def _apply_telemetry_opt(test: Mapping, opts) -> dict:
    """Pin the CLI's run-mode choices onto the built test map — harness
    test_fns copy options selectively, so these flags are applied after
    the map is built, on every command path.  Telemetry is tri-state: an
    unset flag leaves the map alone so obs.enabled_for falls through to
    the JEPSEN_TPU_TELEMETRY env var (default on for run/analyze)."""
    t = dict(test)
    if getattr(opts, "telemetry", None) is not None:
        t["telemetry?"] = opts.telemetry
    if getattr(opts, "stream", False):
        t["stream?"] = True
    if getattr(opts, "stream_every", None) is not None:
        t["stream-every"] = opts.stream_every
    return t


def _cmd_test(test_fn: Callable, opts) -> int:
    code = EXIT_VALID
    for i in range(opts.test_count):
        test = _apply_telemetry_opt(test_fn(options_to_test_opts(opts)), opts)
        completed = core.run_test(test)
        c = _exit_code(completed.get("results"))
        code = max(code, c)
        if c != EXIT_VALID and opts.test_count > 1:
            logger.warning("run %d/%d not valid (exit %d)", i + 1, opts.test_count, c)
    return code


def _cmd_analyze(test_fn: Callable, opts) -> int:
    """Re-check a stored history without touching a cluster
    (cli.clj:402-431).  ``--resume <run-dir>`` re-enters an interrupted
    checker run from that dir's checker-checkpoint.json (written per
    ladder stage; see jepsen_tpu.store.checkpoint)."""
    resume_dir = getattr(opts, "resume", None)
    if resume_dir:
        stored = store.load_dir(resume_dir)
    elif opts.test_dir:
        stored = store.load_dir(opts.test_dir)
    else:
        stored = store.latest(store_dir=opts.store_dir)
    if stored is None:
        print("no stored test found", file=sys.stderr)
        return EXIT_USAGE
    cli_test = test_fn(options_to_test_opts(opts))
    if cli_test.get("name") and stored.get("name") and cli_test["name"] != stored["name"]:
        print(
            f"stored test {stored['name']!r} doesn't match this CLI's test "
            f"{cli_test['name']!r}",
            file=sys.stderr,
        )
        return EXIT_USAGE
    merged = {**cli_test, **{k: v for k, v in stored.items() if k in
                             ("name", "start-time-str", "history")}}
    merged.setdefault("start-time-str", store.time_str())
    if resume_dir:
        merged["resume?"] = True
        merged["checkpoint-dir"] = resume_dir
    merged = _apply_telemetry_opt(merged, opts)
    completed = core.analyze(merged)
    core.log_results(completed)
    print(completed["results"].get("valid?"))
    return _exit_code(completed.get("results"))


def _cmd_test_all(suite_fn: Callable, opts) -> int:
    """Run a whole suite of tests back to back (cli.clj:491-519): every
    test map the suite yields runs through core.run_test; the exit code is
    the worst individual verdict and a summary table prints at the end."""
    rows = []
    code = EXIT_VALID
    for test in suite_fn(options_to_test_opts(opts)):
        test = _apply_telemetry_opt(test, opts)
        try:
            completed = core.run_test(test)
            c = _exit_code(completed.get("results"))
            valid = (completed.get("results") or {}).get("valid?")
        except Exception:  # noqa: BLE001 — one crash shouldn't end the suite
            logger.exception("test %s crashed", test.get("name"))
            c, valid = EXIT_UNKNOWN, "crashed"
        code = max(code, c)
        rows.append((test.get("name"), valid))
    width = max((len(str(n)) for n, _ in rows), default=4)
    print(f"\n{'test':<{width}}  valid?")
    for name, valid in rows:
        print(f"{str(name):<{width}}  {valid}")
    return code


def _cmd_serve(opts) -> int:
    """``serve``: the store browser, plus — with ``--check`` — the
    persistent check service (jepsen_tpu.serve): POST /check admits
    histories into the shared batching queue, bounded at --max-queue
    (beyond it: 429 + Retry-After), and Ctrl-C drains gracefully,
    checkpointing still-queued work into --drain-dir.  With
    ``--replicas N`` the check API instead fronts a fleet of N replica
    services behind a geometry-affinity router (jepsen_tpu.serve.fleet)
    sharing one idempotency map and quarantine registry under
    --fleet-dir."""
    from jepsen_tpu import web

    svc = None
    router = None
    if getattr(opts, "check", False):
        from jepsen_tpu.serve import CheckService

        capacity = tuple(
            int(c) for c in str(opts.check_capacity).split(",") if c
        )
        probe_s = opts.health_probe_s
        if probe_s == 0:
            # default: probe only when a mesh exists to probe
            probe_s = 10.0 if opts.check_devices else None
        elif probe_s is not None and probe_s < 0:
            probe_s = None
        replicas = max(1, int(getattr(opts, "replicas", 1) or 1))

        def _mk_service(*, journal_dir, journal_shared, idempotency_dir,
                        idempotency_shared, quarantine_dir, evidence_dir,
                        drain_dir, stream_dir=None):
            return CheckService(
                capacity=capacity,
                slo_specs=opts.slo_file,
                max_streams=opts.max_streams,
                stream_dir=stream_dir,
                max_queue=opts.max_queue,
                max_interactive_queue=opts.max_interactive_queue,
                max_batch=opts.max_batch,
                batch_window_s=opts.batch_window_ms / 1000.0,
                interactive_max_b=opts.interactive_max_b,
                continuous=not opts.no_continuous,
                devices=opts.check_devices,
                verify_placement=opts.verify_placement,
                evidence_dir=evidence_dir,
                drain_dir=drain_dir,
                journal_dir=journal_dir,
                journal_shared=journal_shared,
                idempotency_dir=idempotency_dir,
                idempotency_shared=idempotency_shared,
                quarantine_dir=quarantine_dir,
                idempotency_ttl_s=opts.idempotency_ttl,
                quarantine_ttl_s=opts.quarantine_ttl,
                breaker_threshold=opts.breaker_threshold,
                breaker_cooldown_s=opts.breaker_cooldown,
                watchdog_factor=opts.launch_watchdog or None,
                health_probe_every_s=probe_s,
            ).start()

        if replicas > 1:
            from pathlib import Path

            from jepsen_tpu.serve import fleet as _fleet

            # Per-replica private dirs + fleet-shared durable state
            # (idempotency map, quarantine registry) under one root:
            # the shared pieces are what make fencing exactly-once and
            # quarantine fleet-wide.
            base = Path(opts.fleet_dir or
                        (Path(opts.store_dir or "store") / "fleet"))

            def _replica_dirs(name):
                return dict(
                    journal_dir=(Path(opts.journal_dir) / name
                                 if opts.journal_dir
                                 else base / "journal" / name),
                    journal_shared=True,
                    idempotency_dir=(opts.idempotency_dir
                                     or base / "idempotency"),
                    idempotency_shared=True,
                    quarantine_dir=(opts.quarantine_dir
                                    or base / "quarantine"),
                    evidence_dir=(Path(opts.evidence_dir) / name
                                  if opts.evidence_dir else None),
                    drain_dir=(Path(opts.drain_dir) / name
                               if opts.drain_dir
                               else base / "drain" / name),
                    # streams are replica-sticky; their checkpoints are
                    # per-replica private state, never fleet-shared
                    stream_dir=(Path(opts.stream_dir) / name
                                if opts.stream_dir else None),
                )

            def _successor(name, old_svc):
                return _mk_service(**_replica_dirs(name))

            router = _fleet.FleetRouter(
                probe_every_s=opts.fleet_probe_s or None,
                successor_factory=_successor,
            )
            for i in range(replicas):
                name = f"r{i}"
                router.add_local(name, _mk_service(**_replica_dirs(name)))
            router.start()
            logger.info(
                "fleet up: %d replicas, shared state under %s "
                "(affinity routing + power-of-two spill; "
                "POST /fleet/rollout cycles replicas)", replicas, base,
            )
        else:
            svc = _mk_service(
                journal_dir=opts.journal_dir, journal_shared=False,
                idempotency_dir=opts.idempotency_dir,
                idempotency_shared=False,
                quarantine_dir=getattr(opts, "quarantine_dir", None),
                evidence_dir=opts.evidence_dir, drain_dir=opts.drain_dir,
                stream_dir=opts.stream_dir,
            )
            logger.info(
                "check service up: max_queue=%d max_batch=%d capacity=%s "
                "continuous=%s devices=%s interactive_max_b=%d journal=%s "
                "breaker=%d watchdog=%s",
                opts.max_queue, opts.max_batch, capacity,
                not opts.no_continuous, opts.check_devices or 1,
                opts.interactive_max_b, opts.journal_dir or "off",
                opts.breaker_threshold,
                f"{opts.launch_watchdog}x" if opts.launch_watchdog
                else "off",
            )
    profiler = None
    if getattr(opts, "profile_dir", None):
        from jepsen_tpu.obs.profiler import ProfilerHook

        profiler = ProfilerHook(
            opts.profile_dir, max_seconds=opts.profile_max_seconds
        )
        logger.info(
            "profiler hook armed: POST /profile/start (captures land in "
            "%s, bounded at %.0fs)", opts.profile_dir,
            opts.profile_max_seconds,
        )
    web.serve(host=opts.host, port=opts.port, store_dir=opts.store_dir,
              check_service=svc, profiler=profiler,
              max_request_mb=opts.max_request_mb, fleet=router)
    return EXIT_VALID


def _fleet_status_lines(doc) -> str:
    """Compact per-replica observability summary under the GET /fleet
    JSON: where each replica's metrics endpoint and recorder stream
    live, plus its recorder t0 epoch — the inputs an operator feeds
    ``tools/trace_export.py`` to merge the fleet timeline."""
    lines = ["", "replicas:"]
    for name, row in sorted((doc.get("replicas") or {}).items()):
        parts = [f"  {name}: {row.get('kind', '?')}/{row.get('state', '?')}"]
        if row.get("metrics_url"):
            parts.append(f"metrics={row['metrics_url']}")
        tele = row.get("telemetry") or {}
        if tele.get("jsonl"):
            shared = " (shared with router)" if tele.get("shared") else ""
            parts.append(f"telemetry={tele['jsonl']}{shared}")
        if tele.get("t0") is not None:
            parts.append(f"t0={tele['t0']}")
        lines.append("  ".join(parts))
    rt = doc.get("router_telemetry")
    if rt:
        lines.append(
            f"  router: telemetry={rt.get('jsonl')}  t0={rt.get('t0')}")
    return "\n".join(lines)


def _cmd_fleet(opts) -> int:
    """``fleet``: operate a running fleet over its HTTP admin surface
    — ``fleet status --url`` prints GET /fleet (plus a compact
    per-replica endpoint/recorder summary), ``fleet rollout --url``
    drives the zero-downtime replica cycle (POST /fleet/rollout)."""
    import json as _json
    import urllib.error
    import urllib.request

    url = opts.url.rstrip("/")
    try:
        if opts.fleet_command == "rollout":
            body = {}
            if opts.names:
                body["names"] = [n for n in opts.names.split(",") if n]
            req = urllib.request.Request(
                url + "/fleet/rollout",
                data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
        else:
            req = urllib.request.Request(url + "/fleet")
        with urllib.request.urlopen(req, timeout=opts.timeout) as resp:
            doc = _json.loads(resp.read() or b"{}")
    except urllib.error.HTTPError as e:
        try:
            doc = _json.loads(e.read() or b"{}")
        except ValueError:
            doc = {"error": str(e)}
        print(_json.dumps(doc, indent=2, default=str))
        return EXIT_CRASH
    except (urllib.error.URLError, OSError) as e:
        print(_json.dumps({"error": str(e)}, indent=2))
        return EXIT_CRASH
    print(_json.dumps(doc, indent=2, default=str))
    if opts.fleet_command != "rollout" and doc.get("replicas"):
        print(_fleet_status_lines(doc))
    return EXIT_VALID


def run_cli(
    test_fn: Callable | None = None,
    argv=None,
    extra_opts: Callable | None = None,
    suite_fn: Callable | None = None,
) -> int:
    """Dispatch subcommands; returns the exit code (call sys.exit on it).

    ``test_fn(opts_dict) -> test-map`` builds the test from CLI options.
    ``extra_opts(parser)`` may add harness-specific flags.
    ``suite_fn(opts_dict) -> iterable[test-map]`` enables the ``test-all``
    subcommand (cli.clj:491-519).
    """
    parser = argparse.ArgumentParser(prog="jepsen-tpu")
    sub = parser.add_subparsers(dest="command")

    if test_fn is not None:
        p_test = sub.add_parser("test", help="run the test")
        add_test_opts(p_test)
        if extra_opts:
            extra_opts(p_test)

        if suite_fn is not None:
            p_all = sub.add_parser("test-all", help="run the whole test suite")
            add_test_opts(p_all)
            if extra_opts:
                extra_opts(p_all)

        p_an = sub.add_parser("analyze", help="re-check a stored history")
        add_test_opts(p_an)
        p_an.add_argument("--test-dir", default=None,
                          help="stored test directory (default: latest)")
        p_an.add_argument("--resume", default=None, metavar="RUN_DIR",
                          help="resume an interrupted checker run from this "
                               "stored run dir's checker checkpoint "
                               "(implies --test-dir RUN_DIR)")
        if extra_opts:
            extra_opts(p_an)

    p_serve = sub.add_parser(
        "serve", help="browse results over HTTP (+ check service)")
    p_serve.add_argument("--host", default="0.0.0.0")
    p_serve.add_argument("--port", type=int, default=8080)
    p_serve.add_argument("--store-dir", default=None)
    p_serve.add_argument("--check", action="store_true",
                         help="mount the check service (POST /check, "
                              "GET /check/<id>, GET /queue): a persistent "
                              "queue batching concurrent callers' histories "
                              "into shared kernel launches")
    p_serve.add_argument("--max-queue", type=int, default=256,
                         help="admission bound; a full queue rejects with "
                              "429 + Retry-After (default 256)")
    p_serve.add_argument("--max-batch", type=int, default=64,
                         help="max requests packed per shared launch "
                              "(default 64)")
    p_serve.add_argument("--batch-window-ms", type=float, default=2.0,
                         help="pile-in pause before each batch so "
                              "concurrent submitters coalesce (default 2)")
    p_serve.add_argument("--check-capacity", default="64,512,4096",
                         help="the service ladder's capacity stages "
                              "(comma-separated; default 64,512,4096)")
    p_serve.add_argument("--check-devices", type=int, default=None,
                         help="lane-shard every launch across the first "
                              "N jax devices (mesh placement; default: "
                              "single device)")
    p_serve.add_argument("--verify-placement", action="store_true",
                         help="re-run the first mesh-sharded batch on a "
                              "single device and report any verdict "
                              "disagreement (placement parity probe)")
    p_serve.add_argument("--interactive-max-b", type=int, default=12,
                         help="histories with at most this many barriers "
                              "auto-route to the interactive tier (the "
                              "speculative greedy fast path; 0 disables "
                              "auto-routing — requests still opt in via "
                              "the POST /check \"class\" key; default 12)")
    p_serve.add_argument("--max-interactive-queue", type=int, default=None,
                         help="dedicated interactive-tier admission "
                              "allowance on top of --max-queue, so batch "
                              "backlog can't starve the fast lane")
    p_serve.add_argument("--no-continuous", action="store_true",
                         help="disable rung-boundary admission into "
                              "running ladders (restores window-then-"
                              "launch batching, for A/B comparison)")
    p_serve.add_argument("--max-streams", type=int, default=8,
                         help="bound on concurrently OPEN op-streams "
                              "(POST /stream; beyond it: 429 + a "
                              "Retry-After quoted from the stream "
                              "lane's own session-duration EWMA)")
    p_serve.add_argument("--stream-dir", default=None,
                         help="per-stream durable checkpoint root: a "
                              "SIGKILL'd stream re-opened with "
                              "resume=true continues mid-history with "
                              "identical verdicts (default: streams "
                              "are memory-only)")
    p_serve.add_argument("--evidence-dir", default=None,
                         help="durably persist every served verdict's "
                              "evidence bundle here (GET /evidence/<id> "
                              "then survives restart; audit offline with "
                              "tools/evidence.py verify|replay)")
    p_serve.add_argument("--drain-dir", default=None,
                         help="where shutdown checkpoints still-queued "
                              "requests (resume with "
                              "jepsen_tpu.serve.resume_drained)")
    p_serve.add_argument("--journal-dir", default=None,
                         help="fsync'd admission journal: every admitted "
                              "request lands here until it settles, and a "
                              "restarted service replays the survivors "
                              "(crash-safe restart; request ids are kept "
                              "so GET /check/<id> works across the crash)")
    p_serve.add_argument("--idempotency-dir", default=None,
                         help="journaled idempotency-key map: duplicate "
                              "POST /check submits carrying the same "
                              "idempotency_key attach to the original "
                              "request (or its settled result) instead of "
                              "re-running the check — across a SIGKILL "
                              "restart when set (default: in-memory only)")
    p_serve.add_argument("--idempotency-ttl", type=float, default=3600.0,
                         help="seconds an idempotency key answers "
                              "duplicates after its last write "
                              "(default 3600)")
    p_serve.add_argument("--max-request-mb", type=float, default=32.0,
                         help="POST /check body bound; larger payloads "
                              "are rejected 413 before the JSON parse "
                              "(default 32)")
    p_serve.add_argument("--quarantine-ttl", type=float, default=900.0,
                         help="seconds a poison history's fingerprint "
                              "stays quarantined after bisection "
                              "isolates it (default 900)")
    p_serve.add_argument("--breaker-threshold", type=int, default=5,
                         help="consecutive batch failures that open the "
                              "circuit breaker (503 + Retry-After until "
                              "the cooldown's half-open probe; default 5)")
    p_serve.add_argument("--breaker-cooldown", type=float, default=30.0,
                         help="seconds an open breaker waits before "
                              "half-opening for a probe batch "
                              "(default 30)")
    p_serve.add_argument("--launch-watchdog", type=float, default=16.0,
                         metavar="FACTOR",
                         help="hung-launch watchdog: cap each batch's "
                              "wall clock at FACTOR x the launch-time "
                              "EWMA and retry a hung launch once on "
                              "reduced placement (0 disables; default 16)")
    p_serve.add_argument("--slo-file", default=None, metavar="JSON",
                         help="SLO spec file for the live burn-rate "
                              "engine (a JSON list merged over the "
                              "built-in defaults by name; see "
                              "jepsen_tpu/serve/slo.py).  GET /alerts "
                              "and the home-page panel surface the "
                              "burn rates either way")
    p_serve.add_argument("--health-probe-s", type=float, default=0,
                         metavar="SECONDS",
                         help="mesh device-health probe interval: a "
                              "failed device shrinks placement to the "
                              "survivors and re-runs the parity probe "
                              "(default: 10 when --check-devices is set, "
                              "else off; negative disables)")
    p_serve.add_argument("--perf-ledger", default=None, metavar="PATH",
                         help="perf-regression ledger the /perf "
                              "trajectory page and the /metrics headline "
                              "gauges read (sets JEPSEN_TPU_PERF_LEDGER; "
                              "default <store-dir>/perf-ledger.jsonl)")
    p_serve.add_argument("--profile-dir", default=None,
                         help="arm the bounded jax.profiler capture hook: "
                              "POST /profile/start (optional {\"seconds\": "
                              "n} body) / POST /profile/stop drive device "
                              "captures into this directory")
    p_serve.add_argument("--profile-max-seconds", type=float, default=120.0,
                         help="hard bound per profiler capture; every "
                              "start auto-stops after at most this long "
                              "(default 120)")
    p_serve.add_argument("--replicas", type=int, default=1, metavar="N",
                         help="front the check API with a fleet of N "
                              "replica services behind the geometry-"
                              "affinity router (jepsen_tpu.serve.fleet): "
                              "replica death degrades capacity instead "
                              "of taking the front door down, and POST "
                              "/fleet/rollout cycles replicas with zero "
                              "downtime (default 1: single service)")
    p_serve.add_argument("--fleet-dir", default=None, metavar="PATH",
                         help="root for fleet state: per-replica "
                              "journal/drain dirs plus the FLEET-SHARED "
                              "idempotency map and quarantine registry "
                              "(advisory-file-locked; what makes "
                              "failover exactly-once and quarantine "
                              "fleet-wide).  Default <store-dir>/fleet")
    p_serve.add_argument("--fleet-probe-s", type=float, default=2.0,
                         metavar="SECONDS",
                         help="fleet health-probe interval: readiness + "
                              "forward-progress staleness per replica; "
                              "repeated fatal failures fence the replica "
                              "and resubmit its in-flight work "
                              "(0 disables; default 2)")
    p_serve.add_argument("--quarantine-dir", default=None, metavar="PATH",
                         help="durable (and shareable) quarantine "
                              "registry dir: poison fingerprints persist "
                              "across restart and are refused by every "
                              "process pointed at the same dir "
                              "(default: in-memory only)")

    p_fleet = sub.add_parser(
        "fleet", help="operate a running fleet (status / rollout)")
    fleet_sub = p_fleet.add_subparsers(dest="fleet_command")
    p_fstat = fleet_sub.add_parser(
        "status", help="print GET /fleet: per-replica state + router "
                       "totals")
    p_froll = fleet_sub.add_parser(
        "rollout", help="cycle replicas with zero downtime (drain -> "
                        "successor with journal replay + resume_drained "
                        "-> swap; no 5xx, no verdict loss)")
    for p in (p_fstat, p_froll):
        p.add_argument("--url", default="http://127.0.0.1:8080",
                       help="base URL of the serving process "
                            "(default http://127.0.0.1:8080)")
        p.add_argument("--timeout", type=float, default=600.0,
                       help="HTTP timeout seconds (default 600)")
    p_froll.add_argument("--names", default=None,
                         help="comma-separated replica names to roll "
                              "(default: every local replica)")

    try:
        opts = parser.parse_args(argv)
    except SystemExit as e:
        return EXIT_USAGE if e.code not in (0, None) else 0

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)-5s %(name)s: %(message)s",
    )
    if getattr(opts, "dedup_backend", None):
        # The checkers resolve the backend from this env var at call
        # time (ops.hashing.resolve_dedup_backend), so the flag reaches
        # every engine — batched ladder, chunked escalations, confirm
        # launches — without threading through each test map.
        os.environ["JEPSEN_TPU_DEDUP_BACKEND"] = opts.dedup_backend
    if getattr(opts, "perf_ledger", None):
        # Same env-threading as the dedup backend: obs.regress resolves
        # the ledger path at append/read time, so one flag routes every
        # producer (bench, loadgen, budget gate) and the web /perf page.
        os.environ["JEPSEN_TPU_PERF_LEDGER"] = opts.perf_ledger
    if getattr(opts, "frontier_budget_mb", None) is not None:
        # Same env-threading as the dedup backend: ops.spill resolves
        # the budget at call time, so the flag reaches the chunked
        # exact paths inside every engine without new plumbing.
        os.environ["JEPSEN_TPU_FRONTIER_BUDGET_MB"] = str(
            opts.frontier_budget_mb)
    try:
        if opts.command == "test":
            return _cmd_test(test_fn, opts)
        if opts.command == "test-all":
            return _cmd_test_all(suite_fn, opts)
        if opts.command == "analyze":
            return _cmd_analyze(test_fn, opts)
        if opts.command == "serve":
            return _cmd_serve(opts)
        if opts.command == "fleet":
            if not getattr(opts, "fleet_command", None):
                parser.parse_args(["fleet", "--help"])
                return EXIT_USAGE
            return _cmd_fleet(opts)
        parser.print_help()
        return EXIT_USAGE
    except KeyboardInterrupt:
        return EXIT_CRASH
    except Exception:  # noqa: BLE001
        logger.exception("test crashed")
        return EXIT_CRASH


def main(test_fn=None, argv=None, **kw):
    sys.exit(run_cli(test_fn, argv, **kw))


if __name__ == "__main__":  # pragma: no cover - exercised via subprocess
    main()
