"""OS automation: preparing nodes to host a database.

Mirrors ``jepsen.os`` (reference: jepsen/src/jepsen/os.clj:4-8 — a
two-method protocol) and the Debian implementation
(jepsen/src/jepsen/os/debian.clj: package install, hostfile setup).
Named ``os_support`` to avoid shadowing the stdlib ``os``.
"""

from __future__ import annotations

from typing import Mapping, Sequence


class OS:
    """(os.clj:4-8)."""

    def setup(self, test, node, session) -> None:
        pass

    def teardown(self, test, node, session) -> None:
        pass


class NoopOS(OS):
    pass


def noop() -> OS:
    return NoopOS()


class DebianOS(OS):
    """apt-based setup (os/debian.clj): install base packages, populate
    /etc/hosts so nodes see each other by name."""

    def __init__(self, extra_packages: Sequence[str] = ()):
        self.packages = ["curl", "wget", "unzip", "iptables", "psmisc", "tar",
                        "iputils-ping", "logrotate", *extra_packages]

    def setup(self, test, node, session):
        with session.su():
            self.setup_hostfile(test, node, session)
            if not self._installed(session, self.packages):
                session.exec(
                    "env", "DEBIAN_FRONTEND=noninteractive",
                    "apt-get", "install", "-y", "--no-install-recommends",
                    *self.packages,
                )

    def _installed(self, session, packages) -> bool:
        r = session.exec_result("dpkg-query", "-W", *packages)
        return r.get("exit") == 0

    def setup_hostfile(self, test, node, session):
        """Map every node name to its IP in /etc/hosts
        (os/debian.clj hostfile setup)."""
        lines = ["127.0.0.1 localhost"]
        for n in test.get("nodes") or []:
            if n == node:
                lines.append(f"127.0.1.1 {n}")
            else:
                out = session.exec_result("getent", "ahosts", n)
                ip = (out.get("out") or "").split()
                if ip:
                    lines.append(f"{ip[0]} {n}")
        session.write_file("\n".join(lines) + "\n", "/etc/hosts")


class CentosOS(OS):
    """yum-based setup (os/centos.clj): EPEL-capable package install,
    hostfile, ntp stop so the clock nemesis owns the clock."""

    def __init__(self, extra_packages: Sequence[str] = ()):
        self.packages = ["curl", "wget", "unzip", "iptables", "psmisc", "tar",
                        "iputils", "logrotate", "gcc", *extra_packages]

    def setup(self, test, node, session):
        with session.su():
            DebianOS.setup_hostfile(self, test, node, session)
            if not self._installed(session, self.packages):
                session.exec("yum", "install", "-y", *self.packages)

    def _installed(self, session, packages) -> bool:
        r = session.exec_result("rpm", "-q", *packages)
        return r.get("exit") == 0


class UbuntuOS(DebianOS):
    """Ubuntu rides the Debian implementation (os/ubuntu.clj is a 46-line
    specialization); the only practical difference is sudo-by-default
    images and the universe repo already being enabled."""


def debian() -> OS:
    return DebianOS()


def centos() -> OS:
    return CentosOS()


def ubuntu() -> OS:
    return UbuntuOS()


class SmartOS(OS):
    """pkgin-based setup (os/smartos.clj, 132 LoC in the reference —
    shipped for the mongodb-smartos harness): package install + hostfile."""

    def __init__(self, extra_packages: Sequence[str] = ()):
        self.packages = ["curl", "wget", "gtar", *extra_packages]

    def setup(self, test, node, session):
        with session.su():
            DebianOS.setup_hostfile(self, test, node, session)
            session.exec_result("pkgin", "-y", "install", *self.packages)


def smartos() -> OS:
    return SmartOS()
