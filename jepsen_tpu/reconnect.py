"""Auto-reconnecting connection wrapper.

Mirrors ``jepsen.reconnect`` (reference: jepsen/src/jepsen/reconnect.clj):
a wrapper owning one connection, an RW lock around its use, and
close-then-reopen semantics when an operation throws — so flaky network
links degrade to retried opens instead of poisoned clients.  The
interpreter's ClientWorker covers clients; this generic wrapper serves
everything else (db consoles, admin channels, control-plane helpers).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Callable

logger = logging.getLogger(__name__)


class Wrapper:
    """(reconnect.clj:16-91).

    ``open_fn()`` → a connection; ``close_fn(conn)`` tears one down;
    ``log_name`` labels log lines.
    """

    def __init__(
        self,
        open_fn: Callable[[], Any],
        close_fn: Callable[[Any], None] = lambda c: None,
        log_name: str = "conn",
    ):
        self.open_fn = open_fn
        self.close_fn = close_fn
        self.log_name = log_name
        self._conn: Any = None
        self._open = False
        #: bumps on every reopen so concurrent failures reopen once
        self._generation = 0
        self._lock = threading.RLock()

    def open(self) -> "Wrapper":
        with self._lock:
            if not self._open:
                self._conn = self.open_fn()
                self._open = True
        return self

    def close(self):
        with self._lock:
            if self._open:
                try:
                    self.close_fn(self._conn)
                finally:
                    self._conn = None
                    self._open = False

    def reopen(self):
        """Close (best-effort) and open a fresh connection
        (reconnect.clj:76-91)."""
        with self._lock:
            try:
                self.close()
            except Exception:  # noqa: BLE001
                logger.warning("[%s] close during reopen failed", self.log_name, exc_info=True)
            self._generation += 1
            return self.open()

    def _reopen_if_current(self, generation: int):
        """Reopen only if nobody else already did (so a burst of failures
        across threads reopens once, not once per thread)."""
        with self._lock:
            if self._generation == generation:
                self.reopen()

    def with_conn(self, f: Callable[[Any], Any], retries: int = 1, backoff: float = 0.1):
        """Run ``f(conn)``; on exception, close + reopen and (optionally)
        retry (reconnect.clj:93-146).  The final failure propagates.

        The lock guards only connection state — ``f(conn)`` and the retry
        backoff run outside it, so a shared wrapper doesn't serialize its
        users (the reference holds a READ lock during ops and the write
        lock only across reopen)."""
        attempt = 0
        while True:
            with self._lock:
                self.open()
                conn, generation = self._conn, self._generation
            try:
                return f(conn)
            except Exception:
                logger.info("[%s] op failed; reopening", self.log_name, exc_info=True)
                try:
                    self._reopen_if_current(generation)
                except Exception:  # noqa: BLE001
                    logger.warning("[%s] reopen failed", self.log_name, exc_info=True)
                if attempt >= retries:
                    raise
                attempt += 1
                time.sleep(backoff * (2 ** (attempt - 1)))


def wrapper(open_fn, close_fn=lambda c: None, log_name="conn") -> Wrapper:
    return Wrapper(open_fn, close_fn, log_name)
