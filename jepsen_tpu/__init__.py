"""jepsen_tpu — a TPU-native distributed-systems testing framework.

A ground-up rebuild of Jepsen (reference: /root/reference, Clojure) with the
checker phase designed TPU-first: histories are packed into dense SoA tensors,
linearizability search (Wing–Gong–Lowe) runs as a jit-compiled beam over
linearization prefixes, and transactional-anomaly detection (Elle-style) runs
as batched dense-reachability kernels on the MXU.  The run-time harness
(generators, interpreter, control layer, nemeses, storage, CLI, web) is
host-side Python, mirroring the reference's semantics
(jepsen/src/jepsen/core.clj:2-14) without porting its JVM architecture.

Layer map (cf. SURVEY.md §1):

  L0 control/    remote execution (ssh subprocess / docker / dummy)
  L1 os/, db     environment automation
  L2 nemesis/    fault injection
  L3 client      client protocol + reconnect wrapper
  L4 generator/  pure scheduling DSL
  L5 generator.interpreter  concurrency runtime
  L6 core        orchestration (run / analyze)
  L7 checker/    analysis — the TPU-accelerated layer (ops/ holds kernels)
  L8 store/      persistence
  L9 cli, web    presentation
"""

__version__ = "0.1.0"
