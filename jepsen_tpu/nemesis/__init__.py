"""Nemesis: fault injection into the system under test.

Mirrors ``jepsen.nemesis`` (reference: jepsen/src/jepsen/nemesis.clj).  A
nemesis is a special client bound to the whole cluster rather than one node
(nemesis.clj:11-16):

  setup(test)       -> prepared nemesis
  invoke(test, op)  -> perform a fault op, return its completion
  teardown(test)

``fs()`` (the Reflection protocol, nemesis.clj:18-21) reports which :f
values this nemesis handles, enabling ``compose`` to route ops by :f
(nemesis.clj:334-428).

The partition *grudge* math (who refuses traffic from whom) is pure and
lives here: bisect, split_one, complete_grudge, bridge, majorities_ring
(nemesis.clj:108-281).  Network manipulation itself goes through the test's
``net`` (jepsen_tpu.net) over the control layer.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Callable, Iterable, Mapping, Sequence

from jepsen_tpu.utils import majority, real_pmap


class Nemesis:
    """Base nemesis; the default does nothing (nemesis.clj:28-47)."""

    def setup(self, test: Mapping) -> "Nemesis":
        return self

    def invoke(self, test: Mapping, op: Mapping) -> Mapping:
        return {**op, "type": "info"}

    def teardown(self, test: Mapping) -> None:
        pass

    def fs(self) -> set:
        """Which :f values this nemesis handles (nemesis.clj:18-21)."""
        return set()


class NoopNemesis(Nemesis):
    pass


def noop() -> Nemesis:
    return NoopNemesis()


class ValidatingNemesis(Nemesis):
    """Completion must match the invocation's :f and :process
    (nemesis.clj:49-84)."""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        return ValidatingNemesis(self.nemesis.setup(test))

    def invoke(self, test, op):
        comp = self.nemesis.invoke(test, op)
        if not isinstance(comp, Mapping) or comp.get("f") != op.get("f") or comp.get(
            "process"
        ) != op.get("process"):
            raise ValueError(f"invalid nemesis completion {comp!r} for {op!r}")
        return comp

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()


def validate(nemesis: Nemesis) -> Nemesis:
    return ValidatingNemesis(nemesis)


class TimeoutNemesis(Nemesis):
    """Cap invoke at dt seconds; on timeout return an :info completion noting
    the timeout rather than blocking the nemesis thread forever
    (nemesis.clj:92-106)."""

    def __init__(self, dt: float, nemesis: Nemesis):
        self.dt = dt
        self.nemesis = nemesis

    def setup(self, test):
        return TimeoutNemesis(self.dt, self.nemesis.setup(test))

    def invoke(self, test, op):
        result: list = []

        def run():
            try:
                result.append(self.nemesis.invoke(test, op))
            except Exception as e:  # noqa: BLE001 - reported via completion
                result.append(e)

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(self.dt)
        if not result:
            return {**op, "type": "info", "value": f"timed out after {self.dt} s"}
        if isinstance(result[0], Exception):
            raise result[0]
        return result[0]

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()


def timeout(dt: float, nemesis: Nemesis) -> Nemesis:
    return TimeoutNemesis(dt, nemesis)


# ---------------------------------------------------------------------------
# Partition grudge math (pure; nemesis.clj:108-281)
# ---------------------------------------------------------------------------


def bisect(coll: Sequence) -> tuple[list, list]:
    """Split a collection into two halves, first smaller on odd sizes
    (nemesis.clj:108-113)."""
    xs = list(coll)
    mid = len(xs) // 2
    return xs[:mid], xs[mid:]


def split_one(coll: Sequence, node=None) -> tuple[list, list]:
    """Isolate one node (random unless given) from the rest
    (nemesis.clj:115-123)."""
    xs = list(coll)
    if node is None:
        node = random.choice(xs)
    return [node], [x for x in xs if x != node]


def complete_grudge(components: Sequence[Sequence]) -> dict:
    """Given components, a map node -> set of nodes it should refuse traffic
    from: everyone outside its own component (nemesis.clj:125-135)."""
    comps = [list(c) for c in components]
    all_nodes = [n for c in comps for n in c]
    grudge = {}
    for c in comps:
        others = {n for n in all_nodes if n not in c}
        for n in c:
            grudge[n] = set(others)
    return grudge


def invert_grudge(grudge: Mapping) -> dict:
    """Flip a grudge: nodes cut from each other stay connected and vice
    versa (nemesis.clj:137-144)."""
    nodes = sorted(grudge)
    out: dict = {n: set() for n in nodes}
    for a in nodes:
        for b in nodes:
            if a != b and b not in grudge.get(a, set()):
                out[a].add(b)
    return out


def bridge(nodes: Sequence) -> dict:
    """Two components joined by a single bridge node that can see both
    (nemesis.clj:146-155)."""
    xs = list(nodes)
    n = len(xs) // 2
    bridge_node = xs[n]
    a, b = xs[:n], xs[n + 1 :]
    grudge = {}
    for x in a:
        grudge[x] = set(b)
    for x in b:
        grudge[x] = set(a)
    grudge[bridge_node] = set()
    return grudge


def majorities_ring(nodes: Sequence) -> dict:
    """Every node sees a majority, but no two majorities agree: each node
    grudges the (n - majority) nodes 'opposite' it on a ring.  Exact for
    ≤ 5 nodes, stochastic beyond (nemesis.clj:202-275)."""
    xs = list(nodes)
    n = len(xs)
    if n <= 5:
        m = majority(n)
        shuffled = list(xs)
        random.shuffle(shuffled)
        grudge = {}
        for i, node in enumerate(shuffled):
            # Node i keeps itself + the next m-1 clockwise; grudges the rest.
            keep = {shuffled[(i + d) % n] for d in range(m)}
            grudge[node] = {x for x in shuffled if x not in keep}
        return grudge
    # Stochastic variant: random ring, each node keeps a majority window.
    shuffled = list(xs)
    random.shuffle(shuffled)
    m = majority(n)
    grudge = {}
    for i, node in enumerate(shuffled):
        half = (m - 1) // 2
        keep = {shuffled[(i + d) % n] for d in range(-half, m - half)}
        grudge[node] = {x for x in shuffled if x not in keep}
    return grudge


# ---------------------------------------------------------------------------
# Partitioner nemeses (nemesis.clj:157-281)
# ---------------------------------------------------------------------------


class Partitioner(Nemesis):
    """Respond to ``{:f :start}`` by partitioning the network per
    grudge(nodes) and ``{:f :stop}`` by healing (nemesis.clj:157-183).

    ``grudge_fn(nodes) -> grudge dict`` chooses the partition shape; the
    start op may carry an explicit grudge in :value.
    """

    def __init__(self, grudge_fn: Callable | None = None, start_f="start", stop_f="stop"):
        self.grudge_fn = grudge_fn
        self.start_f = start_f
        self.stop_f = stop_f

    def setup(self, test):
        test["net"].heal(test)
        return self

    def invoke(self, test, op):
        f = op.get("f")
        if f == self.start_f:
            grudge = op.get("value") or (
                self.grudge_fn(list(test["nodes"])) if self.grudge_fn else None
            )
            if grudge is None:
                raise ValueError("partition start op needs a grudge")
            test["net"].drop_all(test, grudge)
            desc = {n: sorted(g) for n, g in grudge.items()}
            return {**op, "type": "info", "value": f"Cut off {desc}"}
        if f == self.stop_f:
            test["net"].heal(test)
            return {**op, "type": "info", "value": "fully connected"}
        raise ValueError(f"partitioner doesn't understand :f {f!r}")

    def teardown(self, test):
        test["net"].heal(test)

    def fs(self):
        return {self.start_f, self.stop_f}


def partitioner(grudge_fn=None) -> Nemesis:
    return Partitioner(grudge_fn)


def partition_halves() -> Nemesis:
    """Cut the network in half (nemesis.clj:185-192)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves() -> Nemesis:
    """Random halves each time (nemesis.clj:194-200)."""

    def g(nodes):
        xs = list(nodes)
        random.shuffle(xs)
        return complete_grudge(bisect(xs))

    return Partitioner(g)


def partition_random_node() -> Nemesis:
    """Isolate a single random node (nemesis.clj:185-190)."""
    return Partitioner(lambda nodes: complete_grudge(split_one(nodes)))


def partition_majorities_ring() -> Nemesis:
    """Intersecting-majorities ring partition (nemesis.clj:202-275)."""
    return Partitioner(majorities_ring)


# ---------------------------------------------------------------------------
# Composition (nemesis.clj:285-428)
# ---------------------------------------------------------------------------


class FMapNemesis(Nemesis):
    """Rename the :f vocabulary of a nemesis via bijection m
    (nemesis.clj:285-327)."""

    def __init__(self, m: Mapping, nemesis: Nemesis):
        self.m = dict(m)
        self.inv = {v: k for k, v in self.m.items()}
        self.nemesis = nemesis

    def setup(self, test):
        return FMapNemesis(self.m, self.nemesis.setup(test))

    def invoke(self, test, op):
        inner_op = {**op, "f": self.inv.get(op.get("f"), op.get("f"))}
        comp = self.nemesis.invoke(test, inner_op)
        return {**comp, "f": self.m.get(comp.get("f"), comp.get("f"))}

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        return {self.m.get(f, f) for f in self.nemesis.fs()}


def f_map(m: Mapping, nemesis: Nemesis) -> Nemesis:
    return FMapNemesis(m, nemesis)


class ComposedNemesis(Nemesis):
    """Route each op to the nemesis whose fs() contains its :f
    (nemesis.clj:334-428).  Also accepts explicit {fset: nemesis} maps."""

    def __init__(self, nemeses: Sequence[Nemesis] | Mapping):
        if isinstance(nemeses, Mapping):
            self.routes = [(frozenset(fs_), n) for fs_, n in nemeses.items()]
        else:
            self.routes = [(frozenset(n.fs()), n) for n in nemeses]

    def _route(self, f):
        for fs_, n in self.routes:
            if f in fs_:
                return n
        raise ValueError(
            f"no nemesis handles :f {f!r} (routes: {[sorted(fs_) for fs_, _ in self.routes]})"
        )

    def setup(self, test):
        routes = [(fs_, n.setup(test)) for fs_, n in self.routes]
        out = ComposedNemesis([])
        out.routes = routes
        return out

    def invoke(self, test, op):
        return self._route(op.get("f")).invoke(test, op)

    def teardown(self, test):
        for _, n in self.routes:
            n.teardown(test)

    def fs(self):
        out: set = set()
        for fs_, _ in self.routes:
            out |= fs_
        return out


def compose(nemeses) -> Nemesis:
    return ComposedNemesis(nemeses)


# ---------------------------------------------------------------------------
# Process-wrangling nemeses (nemesis.clj:435-539) — need the control layer;
# they accept the test map's db/control handles at invoke time.
# ---------------------------------------------------------------------------


class NodeStartStopper(Nemesis):
    """:start → run start_fn on targeted nodes (degrade, e.g. kill the db);
    :stop → run stop_fn (restore, e.g. restart it) (nemesis.clj:452-495).

    ``targeter(test, nodes) -> nodes`` picks victims each :start.
    """

    def __init__(self, targeter, start_fn, stop_fn, start_f="start", stop_f="stop"):
        self.targeter = targeter
        self.start_fn = start_fn  # invoked on :start ops (degrade)
        self.stop_fn = stop_fn  # invoked on :stop ops (restore)
        self.start_f = start_f
        self.stop_f = stop_f
        self.affected: list = []

    def invoke(self, test, op):
        f = op.get("f")
        if f == self.start_f:
            nodes = list(self.targeter(test, list(test["nodes"])))
            res = dict(
                real_pmap(lambda n: (n, self.start_fn(test, n)), nodes)
            )
            self.affected = nodes
            return {**op, "type": "info", "value": res}
        if f == self.stop_f:
            nodes = self.affected or list(test["nodes"])
            res = dict(real_pmap(lambda n: (n, self.stop_fn(test, n)), nodes))
            self.affected = []
            return {**op, "type": "info", "value": res}
        raise ValueError(f"node-start-stopper doesn't understand :f {f!r}")

    def fs(self):
        return {self.start_f, self.stop_f}


def node_start_stopper(targeter, start_fn, stop_fn) -> Nemesis:
    return NodeStartStopper(targeter, start_fn, stop_fn)


# ---------------------------------------------------------------------------
# Clock scrambler, hammer-time, truncate-file (nemesis.clj:435-539)
# ---------------------------------------------------------------------------


class ClockScrambler(Nemesis):
    """:start → jump each node's clock by a uniform random offset within
    ±dt seconds; :stop → set clocks back to control time
    (nemesis.clj:435-450).  Uses the on-node C tools from
    jepsen_tpu.nemesis.time."""

    def __init__(self, dt: float):
        self.dt = dt

    def setup(self, test):
        from jepsen_tpu.nemesis import time as nt

        real_pmap(
            lambda n: (nt.install_tools(test["sessions"][n]), nt.stop_ntp(test["sessions"][n])),
            list(test["nodes"]),
        )
        return self

    def invoke(self, test, op):
        from jepsen_tpu.nemesis import time as nt

        f = op.get("f")
        if f == "start":
            deltas = {
                n: int(random.uniform(-self.dt, self.dt) * 1000)
                for n in test["nodes"]
            }
            real_pmap(
                lambda kv: nt.bump_time(test["sessions"][kv[0]], kv[1]),
                list(deltas.items()),
            )
            return {**op, "type": "info", "value": deltas}
        if f == "stop":
            real_pmap(lambda n: nt.reset_time(test["sessions"][n]), list(test["nodes"]))
            return {**op, "type": "info", "value": "clocks reset"}
        raise ValueError(f"clock scrambler doesn't understand :f {f!r}")

    def teardown(self, test):
        from jepsen_tpu.nemesis import time as nt

        real_pmap(lambda n: nt.reset_time(test["sessions"][n]), list(test["nodes"]))

    def fs(self):
        return {"start", "stop"}


def clock_scrambler(dt: float) -> Nemesis:
    return ClockScrambler(dt)


def hammer_time(process_pattern: str, targeter=None) -> Nemesis:
    """SIGSTOP the matching processes on targeted nodes on :start, SIGCONT
    on :stop (nemesis.clj:497-511) — the process is frozen, not killed, so
    its sockets stay open while it stops responding."""
    from jepsen_tpu.control import util as cu

    targeter = targeter or (lambda test, nodes: [random.choice(nodes)])

    def stop_procs(test, node):
        s = test["sessions"][node]
        with s.su():
            cu.signal(s, process_pattern, "STOP")
        return "paused"

    def cont_procs(test, node):
        s = test["sessions"][node]
        with s.su():
            cu.signal(s, process_pattern, "CONT")
        return "resumed"

    return NodeStartStopper(targeter, stop_procs, cont_procs)


class TruncateFile(Nemesis):
    """:truncate → chop the tail off a file on the targeted nodes, modeling
    torn writes / lost suffixes after crashes (nemesis.clj:513-539).

    The op's :value may override {node: {path, drop}} per node; otherwise
    every node's default path loses ``drop`` bytes."""

    def __init__(self, path: str, drop: int = 64):
        self.path = path
        self.drop = drop

    def invoke(self, test, op):
        if op.get("f") != "truncate":
            raise ValueError(f"truncate-file doesn't understand :f {op.get('f')!r}")
        value = op.get("value") or {n: {"path": self.path, "drop": self.drop} for n in test["nodes"]}

        def go(kv):
            node, spec = kv
            s = test["sessions"][node]
            path = spec.get("path", self.path)
            drop = int(spec.get("drop", self.drop))
            with s.su():
                size = int(s.exec("stat", "-c", "%s", path))
                s.exec("truncate", "-s", str(max(0, size - drop)), path)
            return {"path": path, "from": size, "to": max(0, size - drop)}

        res = dict(real_pmap(lambda kv: (kv[0], go(kv)), list(value.items())))
        return {**op, "type": "info", "value": res}

    def fs(self):
        return {"truncate"}


def truncate_file(path: str, drop: int = 64) -> Nemesis:
    return TruncateFile(path, drop)
