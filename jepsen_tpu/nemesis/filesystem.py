"""Filesystem fault injection: unreliable disks under the database.

The reference integrates CharybdeFS — an external C++/FUSE/Thrift
filesystem built from source on each node (charybdefs/src/jepsen/
charybdefs.clj:7-67) — to serve a ``/faulty`` directory that can return
EIO or drop writes.  This rebuild reaches the same capability with stock
Linux device-mapper targets instead of an external FUSE stack: the DB's
data directory is backed by a loopback ext4 image whose dm table can be
live-swapped between ``linear`` (healthy) and ``flakey``/``error``
(faulty) — no daemons, no Thrift, kill-safe.

  FaultyDirDB   db wrapper: create image → losetup → dm linear → mkfs →
                mount at ``mount_point`` (setup); unmount + detach
                (teardown)
  FlakeyFS      nemesis: {:f :start-flakey} swaps the table to flakey
                (drops all IO for up/down intervals), {:f :fail-fs} to
                error (every IO fails), {:f :heal-fs} back to linear

Requires root on the node (as CharybdeFS did).  Self-tests drive it
against the dummy remote and assert the dmsetup commands.
"""

from __future__ import annotations

from typing import Mapping

from jepsen_tpu import db as jdb
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.utils import real_pmap


class FaultyDirDB(jdb.DB):
    """Back ``mount_point`` with a dm device that nemeses can degrade
    (the /faulty role, charybdefs.clj:40-67)."""

    def __init__(self, mount_point: str = "/faulty", size_mb: int = 256,
                 image: str = "/var/lib/jepsen-faulty.img", name: str = "jepsen-faulty"):
        self.mount_point = mount_point
        self.size_mb = size_mb
        self.image = image
        self.name = name

    def _sectors(self) -> int:
        return self.size_mb * 2048  # 512-byte sectors

    def setup(self, test, node, session):
        with session.su():
            session.exec("mkdir", "-p", self.mount_point)
            session.exec("truncate", "-s", f"{self.size_mb}M", self.image)
            loop = session.exec("losetup", "--find", "--show", self.image).strip()
            session.exec(
                "dmsetup", "create", self.name, "--table",
                f"0 {self._sectors()} linear {loop} 0",
            )
            dev = f"/dev/mapper/{self.name}"
            session.exec("mkfs.ext4", "-q", dev)
            session.exec("mount", dev, self.mount_point)

    def teardown(self, test, node, session):
        with session.su():
            session.exec_result("umount", "-f", self.mount_point)
            session.exec_result("dmsetup", "remove", "-f", self.name)
            loop = session.exec_result("losetup", "-j", self.image).get("out", "")
            if loop:
                session.exec_result("losetup", "-d", loop.split(":")[0])
            session.exec_result("rm", "-f", self.image)

    def log_files(self, test, node):
        return []


class FlakeyFS(Nemesis):
    """Swap the dm table live: flakey / error / linear
    (CharybdeFS's set_fault / clear_faults RPCs, without the RPCs)."""

    def __init__(self, db: FaultyDirDB, up_s: int = 1, down_s: int = 3):
        self.db = db
        self.up_s = up_s
        self.down_s = down_s

    def _loop_of(self, session) -> str:
        out = session.exec("losetup", "-j", self.db.image)
        return out.split(":")[0].strip()

    def _swap_table(self, session, table_type: str):
        loop = self._loop_of(session)
        sectors = self.db._sectors()
        if table_type == "flakey":
            table = f"0 {sectors} flakey {loop} 0 {self.up_s} {self.down_s}"
        elif table_type == "error":
            table = f"0 {sectors} error"
        else:
            table = f"0 {sectors} linear {loop} 0"
        with session.su():
            session.exec("dmsetup", "suspend", self.db.name)
            session.exec("dmsetup", "load", self.db.name, "--table", table)
            session.exec("dmsetup", "resume", self.db.name)

    def invoke(self, test, op):
        f = op.get("f")
        table = {"start-flakey": "flakey", "fail-fs": "error", "heal-fs": "linear"}.get(f)
        if table is None:
            raise ValueError(f"filesystem nemesis doesn't understand :f {f!r}")
        nodes = list(op.get("value") or test["nodes"])
        real_pmap(lambda n: self._swap_table(test["sessions"][n], table), nodes)
        return {**op, "type": "info", "value": {n: table for n in nodes}}

    def teardown(self, test):
        try:
            real_pmap(
                lambda n: self._swap_table(test["sessions"][n], "linear"),
                list(test["nodes"]),
            )
        except Exception:  # noqa: BLE001 - teardown is best-effort
            pass

    def fs(self):
        return {"start-flakey", "fail-fs", "heal-fs"}


def faulty_dir(mount_point: str = "/faulty", **kw) -> FaultyDirDB:
    return FaultyDirDB(mount_point, **kw)


def flakey_fs(db: FaultyDirDB, **kw) -> Nemesis:
    return FlakeyFS(db, **kw)
