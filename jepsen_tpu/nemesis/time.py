"""Clock nemesis: skew, jump, and strobe node wall clocks.

Mirrors ``jepsen.nemesis.time`` (reference: jepsen/src/jepsen/nemesis/
time.clj).  The C tools are shipped in ``jepsen_tpu/resources`` and
compiled *on the db node* with gcc at setup time, exactly as the
reference does (time.clj:20-50); the nemesis then execs the binaries
remotely:

  bump-time DELTA_MS                      — one-shot clock jump
  strobe-time DELTA_MS PERIOD_MS DUR_S    — oscillate for a duration

Ops (time.clj:98-146):
  {:f :reset,         :value [nodes]}         → set clocks to control time
  {:f :bump,          :value {node: delta_ms}} → jump each node's clock
  {:f :strobe,        :value {node: {...}}}    → strobe each node's clock
  {:f :check-offsets}                          → measure offsets, no change

Every completion carries ``:clock-offsets`` — a {node: seconds} map the
clock checker plots (checker/clock.clj:13-34).
"""

from __future__ import annotations

import logging
import random
import time as _time
from pathlib import Path
from typing import Mapping

from jepsen_tpu import control
from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.utils import real_pmap

logger = logging.getLogger(__name__)

RESOURCES = Path(__file__).resolve().parent.parent / "resources"
TOOL_DIR = "/opt/jepsen"


def install_tools(session: control.Session, tool_dir: str = TOOL_DIR):
    """Upload the C sources and build them on the node (time.clj:20-39).

    Requires gcc on the node (the reference installs build-essential via
    the OS layer; jepsen_tpu.os_support does the same)."""
    with session.su():
        session.exec("mkdir", "-p", tool_dir)
        for src, bin_name in (("bump_time.c", "bump-time"), ("strobe_time.c", "strobe-time")):
            source = (RESOURCES / src).read_text()
            remote_src = f"{tool_dir}/{src}"
            session.write_file(source, remote_src)
            session.exec("gcc", "-O2", "-o", f"{tool_dir}/{bin_name}", remote_src)


def bump_time(session: control.Session, delta_ms: int, tool_dir: str = TOOL_DIR):
    """Jump this node's wall clock by delta_ms (time.clj:86-90)."""
    with session.su():
        session.exec(f"{tool_dir}/bump-time", str(int(delta_ms)))


def strobe_time(
    session: control.Session,
    delta_ms: int,
    period_ms: int,
    duration_s: float,
    tool_dir: str = TOOL_DIR,
):
    """Oscillate this node's clock by ±delta_ms every period_ms for
    duration_s (time.clj:92-96)."""
    with session.su():
        session.exec(
            f"{tool_dir}/strobe-time", str(int(delta_ms)), str(int(period_ms)), str(int(duration_s))
        )


def reset_time(session: control.Session):
    """Set the node's clock to the control node's current time
    (time.clj:81-84)."""
    with session.su():
        session.exec("date", "-s", f"@{int(_time.time())}")


def current_offset(session: control.Session) -> float:
    """Node wall-clock minus control wall-clock, seconds (time.clj:53-60)."""
    remote = float(session.exec("date", "+%s.%N"))
    return remote - _time.time()


def clock_offsets(test: Mapping, nodes=None) -> dict:
    """Measure every node's clock offset in parallel (time.clj:62-70)."""
    sessions = test["sessions"]
    nodes = list(nodes if nodes is not None else test["nodes"])
    return dict(real_pmap(lambda n: (n, current_offset(sessions[n])), nodes))


def stop_ntp(session: control.Session):
    """Best-effort: keep ntp daemons from snapping the clock back
    (time.clj:72-79)."""
    with session.su():
        for svc in ("ntp", "ntpd", "systemd-timesyncd", "chronyd"):
            session.exec_result("service", svc, "stop")
        session.exec_result("timedatectl", "set-ntp", "false")


class ClockNemesis(Nemesis):
    """Drive the on-node clock tools (time.clj:98-146)."""

    def __init__(self, tool_dir: str = TOOL_DIR):
        self.tool_dir = tool_dir

    def setup(self, test):
        def prep(node):
            s = test["sessions"][node]
            install_tools(s, self.tool_dir)
            stop_ntp(s)
            return node

        real_pmap(prep, list(test["nodes"]))
        return self

    def invoke(self, test, op):
        f = op.get("f")
        value = op.get("value")
        sessions = test["sessions"]
        if f == "reset":
            nodes = list(value if value is not None else test["nodes"])
            real_pmap(lambda n: reset_time(sessions[n]), nodes)
        elif f == "bump":
            if not isinstance(value, Mapping):
                raise ValueError(f"bump op value must be {{node: delta_ms}}, got {value!r}")
            real_pmap(
                lambda kv: bump_time(sessions[kv[0]], kv[1], self.tool_dir),
                list(value.items()),
            )
        elif f == "strobe":
            if not isinstance(value, Mapping):
                raise ValueError(
                    f"strobe op value must be {{node: {{delta, period, duration}}}}, got {value!r}"
                )

            def go(kv):
                node, spec = kv
                strobe_time(
                    sessions[node],
                    spec["delta"],
                    spec["period"],
                    spec["duration"],
                    self.tool_dir,
                )

            real_pmap(go, list(value.items()))
        elif f == "check-offsets":
            pass
        else:
            raise ValueError(f"clock nemesis doesn't understand :f {f!r}")
        return {**op, "type": "info", "clock-offsets": clock_offsets(test)}

    def teardown(self, test):
        try:
            real_pmap(lambda n: reset_time(test["sessions"][n]), list(test["nodes"]))
        except Exception:  # noqa: BLE001 - teardown is best-effort
            logger.warning("clock reset on teardown failed", exc_info=True)

    def fs(self):
        return {"reset", "bump", "strobe", "check-offsets"}


def clock_nemesis() -> Nemesis:
    return ClockNemesis()


# ---------------------------------------------------------------------------
# Generators (time.clj:148-197): exponentially distributed skews, random
# node subsets.
# ---------------------------------------------------------------------------


def _random_subset(nodes):
    nodes = list(nodes)
    k = random.randint(1, len(nodes))
    return random.sample(nodes, k)


def reset_gen(test, ctx):
    """Reset a random subset of nodes (time.clj:148-153)."""
    return {"type": "info", "f": "reset", "value": _random_subset(test["nodes"])}


def bump_gen(test, ctx):
    """Bump a random subset by exponentially distributed ±2^2..2^18 ms
    skews (time.clj:155-165)."""
    value = {
        n: random.choice([1, -1]) * (2 ** random.uniform(2, 18))
        for n in _random_subset(test["nodes"])
    }
    return {"type": "info", "f": "bump", "value": {n: int(v) for n, v in value.items()}}


def strobe_gen(test, ctx):
    """Strobe a random subset: delta 2^-1..2^10 ms, period 2^0..2^10 ms,
    duration 0-32 s (time.clj:167-178)."""
    value = {
        n: {
            "delta": max(1, int(2 ** random.uniform(-1, 10))),
            "period": max(1, int(2 ** random.uniform(0, 10))),
            "duration": random.randint(0, 32),
        }
        for n in _random_subset(test["nodes"])
    }
    return {"type": "info", "f": "strobe", "value": value}


def clock_gen():
    """The full clock schedule: a reset to establish sanity, then a mix of
    resets, bumps, strobes, and offset checks (time.clj:180-197)."""
    from jepsen_tpu import generator as gen

    return gen.phases(
        gen.once({"type": "info", "f": "reset", "value": None}),
        gen.mix([reset_gen, bump_gen, strobe_gen, lambda t, c: {"type": "info", "f": "check-offsets"}]),
    )
