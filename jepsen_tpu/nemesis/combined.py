"""Nemesis packages: composable fault + schedule bundles.

Mirrors ``jepsen.nemesis.combined`` (reference: jepsen/src/jepsen/nemesis/
combined.clj).  A *package* bundles everything one fault family needs
(combined.clj:8-15):

  nemesis          — handles the family's :f vocabulary
  generator        — emits its fault schedule ops forever
  final_generator  — heals/recovers at the end of the test
  perf             — {name, start, stop, fs, color} hints for plot shading

Packages compose: ``nemesis_package(faults={"partition", "kill"})`` builds
one nemesis + generator pair that ``core.run_test`` can drop straight into
a test map (combined.clj:328-374).

Node specs (combined.clj:38-61) name *which* nodes a fault hits, resolved
fresh on every op: "one", "minority", "majority", "minority-third",
"primaries", "all".
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Mapping, Sequence

from jepsen_tpu import db as jdb
from jepsen_tpu import generator as gen
from jepsen_tpu import nemesis as nem
from jepsen_tpu.utils import majority, real_pmap

DEFAULT_INTERVAL = 10  # seconds between fault ops (combined.clj:27-29)

NODE_SPECS = ("one", "minority", "majority", "minority-third", "primaries", "all")


def db_nodes(test: Mapping, spec) -> list:
    """Resolve a node spec to concrete nodes (combined.clj:38-61)."""
    nodes = list(test["nodes"])
    n = len(nodes)
    if spec is None or spec == "all":
        return nodes
    if isinstance(spec, (list, tuple, set)):
        return [x for x in nodes if x in set(spec)]
    if spec == "one":
        return random.sample(nodes, 1)
    if spec == "minority":
        return random.sample(nodes, max(1, (n - 1) // 2))
    if spec == "majority":
        return random.sample(nodes, majority(n))
    if spec == "minority-third":
        return random.sample(nodes, max(1, n // 3))
    if spec == "primaries":
        db = test.get("db")
        if db is not None and jdb.supports(db, "primaries"):
            return list(db.primaries(test))
        return []
    raise ValueError(f"unknown node spec {spec!r}")


@dataclass
class Package:
    """One fault family's bundle (combined.clj:8-15)."""

    nemesis: nem.Nemesis
    generator: Any = None
    final_generator: Any = None
    perf: dict = field(default_factory=dict)


def compose_packages(packages: Sequence[Package]) -> Package:
    """Combine packages: one routing nemesis, schedules interleaved with
    ``gen.any``, finals run in sequence (combined.clj:305-326)."""
    packages = [p for p in packages if p is not None]
    gens = [p.generator for p in packages if p.generator is not None]
    finals = [p.final_generator for p in packages if p.final_generator is not None]
    return Package(
        nemesis=nem.compose([p.nemesis for p in packages]),
        generator=gen.any_gen(*gens) if gens else None,
        final_generator=finals if finals else None,
        perf={"nemeses": [p.perf for p in packages if p.perf]},
    )


# ---------------------------------------------------------------------------
# Partition package (combined.clj:226-246)
# ---------------------------------------------------------------------------

PARTITION_SPECS = ("one", "majority", "majorities-ring", "minority-third")


def _grudge_for(spec, nodes: list) -> dict:
    """Translate a partition target spec into a grudge (combined.clj:
    205-224 partition-specs)."""
    xs = list(nodes)
    if spec == "one":
        return nem.complete_grudge(nem.split_one(xs))
    if spec == "majority":
        random.shuffle(xs)
        return nem.complete_grudge(nem.bisect(xs))
    if spec == "majorities-ring":
        return nem.majorities_ring(xs)
    if spec == "minority-third":
        random.shuffle(xs)
        k = max(1, len(xs) // 3)
        return nem.complete_grudge([xs[:k], xs[k:]])
    raise ValueError(f"unknown partition spec {spec!r}")


class _PartitionNemesis(nem.Nemesis):
    """Partitioner speaking {:f :start-partition, :value spec}
    (combined.clj:226-236)."""

    def __init__(self):
        self.inner = nem.Partitioner(None, "start-partition", "stop-partition")

    def setup(self, test):
        self.inner.setup(test)
        return self

    def invoke(self, test, op):
        if op.get("f") == "start-partition":
            grudge = _grudge_for(op.get("value") or "majority", list(test["nodes"]))
            return {**self.inner.invoke(test, {**op, "value": grudge}), "value": op.get("value")}
        return self.inner.invoke(test, op)

    def teardown(self, test):
        self.inner.teardown(test)

    def fs(self):
        return {"start-partition", "stop-partition"}


def partition_package(opts: Mapping | None = None) -> Package:
    """Network-partition fault package (combined.clj:226-246)."""
    opts = dict(opts or {})
    interval = opts.get("interval", DEFAULT_INTERVAL)
    targets = list(opts.get("targets", PARTITION_SPECS))

    def start(test, ctx):
        return {"type": "info", "f": "start-partition", "value": random.choice(targets)}

    stop = {"type": "info", "f": "stop-partition", "value": None}
    schedule = gen.flip_flop(start, gen.repeat(stop))
    return Package(
        nemesis=_PartitionNemesis(),
        generator=gen.stagger(interval, schedule),
        final_generator=gen.once(stop),
        perf={
            "name": "partition",
            "start": {"start-partition"},
            "stop": {"stop-partition"},
            "color": "#E9A4A0",
        },
    )


# ---------------------------------------------------------------------------
# DB process package: kill / pause via db capabilities (combined.clj:70-152)
# ---------------------------------------------------------------------------


class DBNemesis(nem.Nemesis):
    """Start/kill/pause/resume the DB's processes on spec'd nodes via the
    db's Process/Pause capabilities (combined.clj:70-98)."""

    def __init__(self, fset: set | None = None):
        self._fs = set(fset) if fset else {"start", "kill", "pause", "resume"}

    def invoke(self, test, op):
        f = op.get("f")
        if f not in self._fs:
            raise ValueError(f"db nemesis doesn't understand :f {f!r}")
        db: jdb.DB = test["db"]
        method = {"start": "start", "kill": "kill", "pause": "pause", "resume": "resume"}[f]
        if not jdb.supports(db, method):
            raise ValueError(f"db {db!r} doesn't support {method}")
        nodes = db_nodes(test, op.get("value"))
        sessions = test["sessions"]

        def go(node):
            return node, getattr(db, method)(test, node, sessions[node])

        res = dict(real_pmap(go, nodes))
        return {**op, "type": "info", "value": {n: (r if r is not None else f) for n, r in res.items()}}

    def fs(self):
        return set(self._fs)


def _fault_subpackage(fset, degrade_f, restore_f, targets, interval, color) -> Package:
    def degrade(test, ctx):
        return {"type": "info", "f": degrade_f, "value": random.choice(list(targets))}

    restore = {"type": "info", "f": restore_f, "value": "all"}
    schedule = gen.flip_flop(degrade, gen.repeat(restore))
    return Package(
        nemesis=DBNemesis(fset),
        generator=gen.stagger(interval, schedule),
        final_generator=gen.once(restore),
        perf={"name": degrade_f, "start": {degrade_f}, "stop": {restore_f}, "color": color},
    )


def db_package(opts: Mapping | None = None, db: jdb.DB | None = None) -> Package | None:
    """Process kill/pause faults, gated on what the DB supports
    (combined.clj:100-152).  ``faults`` in opts may narrow to {"kill"} or
    {"pause"}."""
    opts = dict(opts or {})
    interval = opts.get("interval", DEFAULT_INTERVAL)
    targets = list(opts.get("targets", ("one", "minority", "majority", "all")))
    faults = set(opts.get("faults", {"kill", "pause"}))
    subs = []
    if "kill" in faults and (db is None or (jdb.supports(db, "kill") and jdb.supports(db, "start"))):
        subs.append(
            _fault_subpackage({"start", "kill"}, "kill", "start", targets, interval, "#E9A0E6")
        )
    if "pause" in faults and (db is None or (jdb.supports(db, "pause") and jdb.supports(db, "resume"))):
        subs.append(
            _fault_subpackage({"pause", "resume"}, "pause", "resume", targets, interval, "#A0B1E9")
        )
    if not subs:
        return None
    return compose_packages(subs)


# ---------------------------------------------------------------------------
# Clock package (combined.clj:248-280)
# ---------------------------------------------------------------------------


def clock_package(opts: Mapping | None = None) -> Package:
    """Clock skew faults via the on-node C tools (combined.clj:248-280).
    Op vocabulary is f-mapped to *-clock so it composes with other
    packages."""
    from jepsen_tpu.nemesis import time as nt

    opts = dict(opts or {})
    interval = opts.get("interval", DEFAULT_INTERVAL)
    mapping = {
        "reset": "reset-clock",
        "bump": "bump-clock",
        "strobe": "strobe-clock",
        "check-offsets": "check-clock-offsets",
    }
    nemesis = nem.f_map(mapping, nt.clock_nemesis())

    def rename(g):
        return gen.f_map(mapping, g)

    schedule = gen.mix(
        [
            rename(nt.reset_gen),
            rename(nt.bump_gen),
            rename(nt.strobe_gen),
            rename(lambda t, c: {"type": "info", "f": "check-offsets"}),
        ]
    )
    return Package(
        nemesis=nemesis,
        generator=gen.stagger(interval, schedule),
        final_generator=gen.once({"type": "info", "f": "reset-clock", "value": None}),
        perf={
            "name": "clock",
            "start": {"bump-clock", "strobe-clock"},
            "stop": {"reset-clock"},
            "color": "#A0E9DB",
        },
    )


# ---------------------------------------------------------------------------
# Entry point (combined.clj:328-374)
# ---------------------------------------------------------------------------

FAULTS = ("partition", "kill", "pause", "clock")


def nemesis_package(opts: Mapping | None = None) -> Package:
    """Build the composite package for ``opts["faults"]``
    (combined.clj:328-374).  Opts:

      faults    — iterable of fault names (default: all of FAULTS)
      db        — the test's DB (gates kill/pause on its capabilities)
      interval  — seconds between fault ops (default 10)
      partition/kill/pause/clock — per-family opt maps (targets, interval)
    """
    opts = dict(opts or {})
    faults = set(opts.get("faults", FAULTS))
    unknown = faults - set(FAULTS)
    if unknown:
        raise ValueError(f"unknown faults {sorted(unknown)}; known: {FAULTS}")
    interval = opts.get("interval", DEFAULT_INTERVAL)
    db = opts.get("db")
    pkgs: list[Package | None] = []
    if "partition" in faults:
        pkgs.append(partition_package({"interval": interval, **opts.get("partition", {})}))
    # one db_package call per family so each honors ITS OWN opt map —
    # a single call fed opts["kill"] silently applied kill's targets to
    # pause too, making the "pause" opt map dead config
    if "kill" in faults:
        pkgs.append(
            db_package(
                {"interval": interval, "faults": {"kill"}, **opts.get("kill", {})},
                db=db,
            )
        )
    if "pause" in faults:
        pkgs.append(
            db_package(
                {"interval": interval, "faults": {"pause"}, **opts.get("pause", {})},
                db=db,
            )
        )
    if "clock" in faults:
        pkgs.append(clock_package({"interval": interval, **opts.get("clock", {})}))
    return compose_packages([p for p in pkgs if p is not None])
