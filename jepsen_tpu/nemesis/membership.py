"""Membership nemesis: grow and shrink the cluster under test.

Mirrors ``jepsen.nemesis.membership`` (reference: jepsen/src/jepsen/
nemesis/membership.clj + membership/state.clj): a user-supplied *state
machine* describes how to observe and change cluster membership; the
nemesis runs it — refreshing per-node views of the cluster on an
interval, emitting join/leave ops, applying them, and waiting for them to
resolve before moving on.

The ``MembershipState`` protocol (membership/state.clj):

  setup(test)                 → initialized state
  node_view(test, node)       → this node's view of the cluster (or None)
  merge_views(test, views)    → canonical view from {node: view}
  fs()                        → the :f vocabulary this machine emits
  op(test)                    → next fault op dict, or None (nothing to do)
  invoke(test, op)            → apply the op; returns the completion value
  resolve_op(test, op, view)  → has the op taken effect in ``view``?
  teardown(test)

State carries ``view`` (the merged cluster view) and ``pending`` (ops
applied but not yet resolved) — the nemesis maintains both.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Mapping

from jepsen_tpu.nemesis import Nemesis
from jepsen_tpu.nemesis.combined import DEFAULT_INTERVAL, Package
from jepsen_tpu.utils import real_pmap

logger = logging.getLogger(__name__)


class MembershipState:
    """Base state machine; subclass per database (membership/state.clj)."""

    view: Any = None

    def setup(self, test) -> "MembershipState":
        return self

    def node_view(self, test, node):
        """One node's opinion of the membership (None = unreachable)."""
        return None

    def merge_views(self, test, views: Mapping):
        """Collapse {node: view} into the canonical view (e.g. the most
        common one, or the union)."""
        for v in views.values():
            if v is not None:
                return v
        return None

    def fs(self) -> set:
        return {"grow", "shrink"}

    def op(self, test):
        """The next membership fault to inject, or None."""
        return None

    def invoke(self, test, op):
        raise NotImplementedError

    def resolve_op(self, test, op, view) -> bool:
        """Has ``op`` taken effect, judging by ``view``?  Resolved ops
        leave the pending set."""
        return True

    def teardown(self, test):
        pass


class MembershipNemesis(Nemesis):
    """Drive a MembershipState: background view refresh + op application
    (membership.clj's nemesis wrapper)."""

    def __init__(self, state: MembershipState, interval: float = 5.0):
        self.state = state
        self.interval = interval
        self.pending: list = []
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- view refresh -------------------------------------------------------

    def refresh_view(self, test):
        views = dict(
            real_pmap(
                lambda n: (n, self._safe_view(test, n)), list(test["nodes"])
            )
        )
        merged = self.state.merge_views(test, views)
        with self._lock:
            self.state.view = merged
            self.pending = [
                op for op in self.pending if not self.state.resolve_op(test, op, merged)
            ]
        return merged

    def _safe_view(self, test, node):
        try:
            return self.state.node_view(test, node)
        except Exception:  # noqa: BLE001 - unreachable nodes are normal
            return None

    def _refresher(self, test):
        while not self._stop.wait(self.interval):
            try:
                self.refresh_view(test)
            except Exception:  # noqa: BLE001
                logger.warning("membership view refresh failed", exc_info=True)

    # -- nemesis protocol ---------------------------------------------------

    def setup(self, test):
        self.state = self.state.setup(test)
        self.refresh_view(test)
        self._thread = threading.Thread(
            target=self._refresher, args=(test,), daemon=True
        )
        self._thread.start()
        return self

    def invoke(self, test, op):
        value = self.state.invoke(test, op)
        with self._lock:
            self.pending.append(op)
        return {**op, "type": "info", "value": value, "view": self.state.view}

    def teardown(self, test):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        self.state.teardown(test)

    def fs(self):
        return self.state.fs()


def membership_gen(nemesis: MembershipNemesis):
    """Generator fn: emit the state machine's next op; None ops come back
    as pending-style skips (the interpreter treats None as exhausted, so
    wrap with gen.stagger + repeat upstream)."""

    def gen_fn(test, ctx):
        with nemesis._lock:
            waiting = bool(nemesis.pending)
        if waiting:
            # An applied change hasn't resolved in the view yet: emit a
            # sleep (handled in-worker, excluded from history) instead of
            # stacking another membership change on top.
            return {"type": "sleep", "value": 1.0}
        op = nemesis.state.op(test)
        return op if op is not None else {"type": "sleep", "value": 1.0}

    return gen_fn


def membership_package(
    state: MembershipState, opts: Mapping | None = None
) -> Package:
    """A nemesis package wrapping a membership state machine
    (membership.clj → combined.clj integration)."""
    from jepsen_tpu import generator as gen

    opts = dict(opts or {})
    interval = opts.get("interval", DEFAULT_INTERVAL)
    nemesis = MembershipNemesis(state, interval=opts.get("view-interval", 5.0))
    return Package(
        nemesis=nemesis,
        generator=gen.stagger(interval, gen.repeat(membership_gen(nemesis))),
        final_generator=None,
        perf={
            "name": "membership",
            "start": set(state.fs()),
            "stop": set(),
            "color": "#E9DCA0",
        },
    )
