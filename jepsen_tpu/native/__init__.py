"""Native runtime components (C, built on demand).

``blockio`` — CRC32-tracked positioned block appends for the run.jepsen
store format: the role of the reference's Java FileOffsetOutputStream
(jepsen/src/jepsen/store/FileOffsetOutputStream.java).  Built lazily with
the system compiler into this package directory; every consumer falls
back to the pure-Python implementation when the extension is missing, so
nothing depends on the toolchain at runtime.
"""

from __future__ import annotations

import importlib
import logging
import subprocess
import sysconfig
from pathlib import Path

logger = logging.getLogger(__name__)

_HERE = Path(__file__).resolve().parent


def build_blockio(force: bool = False):
    """Compile _blockio.c into this directory (gcc, one translation
    unit).  Returns the imported module or None."""
    so = _HERE / "_blockio.so"
    src = _HERE / "blockio.c"
    if force or not so.exists() or so.stat().st_mtime < src.stat().st_mtime:
        include = sysconfig.get_paths()["include"]
        cmd = [
            "gcc", "-O2", "-shared", "-fPIC",
            f"-I{include}",
            str(src), "-o", str(so),
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True, timeout=120)
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired, FileNotFoundError) as e:
            logger.debug("blockio build failed (%s); using the Python path", e)
            return None
    return _import_blockio()


def _import_blockio():
    try:
        import sys

        if str(_HERE) not in sys.path:
            sys.path.insert(0, str(_HERE))
        return importlib.import_module("_blockio")
    except ImportError:
        return None


_blockio = None
_tried = False


def blockio():
    """The extension module, building it on first use; None when no
    toolchain is available (callers use the Python fallback)."""
    global _blockio, _tried
    if not _tried:
        _tried = True
        _blockio = build_blockio()
    return _blockio
