/* blockio: CRC32-tracked positioned block writes for the run.jepsen
 * format.
 *
 * The native sibling of jepsen_tpu/store/format.py's block framing — the
 * role the reference implements in Java (jepsen/src/jepsen/store/
 * FileOffsetOutputStream.java: an output stream over a FileChannel at an
 * offset, tracking CRC32).  A CPython extension rather than a subprocess:
 * the hot path is appending multi-megabyte packed history chunks, where
 * Python-level crc32+write costs two extra buffer traversals.
 *
 * Exposes:
 *   append_block(fd, type, payload) -> (offset, total_len)
 *       write [u32 len | u32 crc32 | u8 type | payload] at EOF
 *   crc32(payload) -> u32
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <stdint.h>
#include <string.h>
#include <unistd.h>

/* CRC-32 (IEEE 802.3, zlib-compatible), slice-by-1 with a lazily built
 * table — matching Python's zlib.crc32 so files stay interchangeable
 * between the C and Python writers. */
static uint32_t crc_table[256];
static int crc_table_ready = 0;

static void build_crc_table(void) {
  uint32_t c;
  int n, k;
  for (n = 0; n < 256; n++) {
    c = (uint32_t)n;
    for (k = 0; k < 8; k++)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[n] = c;
  }
  crc_table_ready = 1;
}

static uint32_t crc32_buf(const unsigned char *buf, Py_ssize_t len) {
  uint32_t c = 0xFFFFFFFFu;
  Py_ssize_t i;
  if (!crc_table_ready)
    build_crc_table();
  for (i = 0; i < len; i++)
    c = crc_table[(c ^ buf[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

static int write_all(int fd, const unsigned char *buf, Py_ssize_t len) {
  while (len > 0) {
    ssize_t w = write(fd, buf, (size_t)len);
    if (w < 0) {
      if (errno == EINTR)
        continue;
      return -1;
    }
    buf += w;
    len -= w;
  }
  return 0;
}

static PyObject *py_crc32(PyObject *self, PyObject *args) {
  Py_buffer view;
  uint32_t c;
  if (!PyArg_ParseTuple(args, "y*", &view))
    return NULL;
  c = crc32_buf((const unsigned char *)view.buf, view.len);
  PyBuffer_Release(&view);
  return PyLong_FromUnsignedLong((unsigned long)c);
}

static PyObject *py_append_block(PyObject *self, PyObject *args) {
  int fd, btype;
  Py_buffer view;
  unsigned char header[9];
  uint32_t crc;
  off_t off;
  PyObject *result = NULL;

  if (!PyArg_ParseTuple(args, "iiy*", &fd, &btype, &view))
    return NULL;
  if (view.len > 0xFFFFFFFFLL - 9) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "payload too large for a u32-framed block");
    return NULL;
  }
  crc = crc32_buf((const unsigned char *)view.buf, view.len);
  header[0] = (unsigned char)(view.len & 0xFF);
  header[1] = (unsigned char)((view.len >> 8) & 0xFF);
  header[2] = (unsigned char)((view.len >> 16) & 0xFF);
  header[3] = (unsigned char)((view.len >> 24) & 0xFF);
  header[4] = (unsigned char)(crc & 0xFF);
  header[5] = (unsigned char)((crc >> 8) & 0xFF);
  header[6] = (unsigned char)((crc >> 16) & 0xFF);
  header[7] = (unsigned char)((crc >> 24) & 0xFF);
  header[8] = (unsigned char)(btype & 0xFF);

  Py_BEGIN_ALLOW_THREADS
  off = lseek(fd, 0, SEEK_END);
  if (off >= 0)
    if (write_all(fd, header, 9) != 0 ||
        write_all(fd, (const unsigned char *)view.buf, view.len) != 0)
      off = -2;
  Py_END_ALLOW_THREADS

  if (off == -1) {
    PyErr_SetFromErrno(PyExc_OSError);
  } else if (off == -2) {
    PyErr_SetFromErrno(PyExc_OSError);
  } else {
    result = Py_BuildValue("Ln", (long long)off, view.len);
  }
  PyBuffer_Release(&view);
  return result;
}

static PyMethodDef methods[] = {
    {"crc32", py_crc32, METH_VARARGS, "zlib-compatible CRC-32 of a buffer"},
    {"append_block", py_append_block, METH_VARARGS,
     "append_block(fd, type, payload) -> (offset, payload_len)"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_blockio",
    "CRC32-tracked block appends for the run.jepsen format", -1, methods,
};

PyMODINIT_FUNC PyInit__blockio(void) { return PyModule_Create(&moduledef); }
