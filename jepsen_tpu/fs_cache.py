"""Control-node filesystem cache for expensive setup artifacts.

Mirrors ``jepsen.fs-cache`` (reference: jepsen/src/jepsen/fs_cache.clj
docstring 1-44): cache strings / data / whole files on the control node,
keyed by structured paths (e.g. ``["etcd", "3.5.0", "tarball"]``), with
atomic writes and per-key locks — then push cached files out to db nodes
(``deploy_remote``) so a 10-minute build happens once, not once per node
per run.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import urllib.parse
from collections import defaultdict
from pathlib import Path
from typing import Any, Sequence

DEFAULT_DIR = Path("/tmp/jepsen/cache")

_locks: dict = defaultdict(threading.RLock)  # reentrant: locking(key) wraps save_*
_locks_guard = threading.Lock()


def _lock_for(key: tuple) -> threading.Lock:
    with _locks_guard:
        return _locks[key]


def encode_path(key: Sequence) -> str:
    """A cache key (sequence of printables) → a relative filesystem path,
    URL-escaped so arbitrary strings are safe (fs_cache.clj's
    path encoding)."""
    return "/".join(urllib.parse.quote(str(part), safe="") for part in key)


class Cache:
    def __init__(self, root: str | Path = DEFAULT_DIR):
        self.root = Path(root)

    def path(self, key: Sequence) -> Path:
        return self.root / encode_path(key)

    def exists(self, key: Sequence) -> bool:
        return self.path(key).exists()

    # -- writes (atomic: tmp + rename) --------------------------------------

    def _prepare(self, key: Sequence) -> tuple[Path, Path]:
        p = self.path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        return p, p.with_name(p.name + ".tmp")

    def save_string(self, key: Sequence, s: str) -> Path:
        with _lock_for(tuple(key)):
            p, tmp = self._prepare(key)
            tmp.write_text(s)
            os.replace(tmp, p)
        return p

    def save_data(self, key: Sequence, data: Any) -> Path:
        return self.save_string(key, json.dumps(data))

    def save_file(self, key: Sequence, local_path: str | Path) -> Path:
        with _lock_for(tuple(key)):
            p, tmp = self._prepare(key)
            shutil.copyfile(local_path, tmp)
            os.replace(tmp, p)
        return p

    # -- reads ---------------------------------------------------------------

    def load_string(self, key: Sequence) -> str | None:
        p = self.path(key)
        return p.read_text() if p.exists() else None

    def load_data(self, key: Sequence):
        s = self.load_string(key)
        return None if s is None else json.loads(s)

    def clear(self, key: Sequence | None = None):
        target = self.path(key) if key else self.root
        if target.is_dir():
            shutil.rmtree(target, ignore_errors=True)
        elif target.exists():
            target.unlink()

    # -- node deployment (fs_cache.clj deploy-remote!) -----------------------

    def deploy_remote(self, session, key: Sequence, remote_path: str):
        """Push a cached file to a node (upload + move into place)."""
        p = self.path(key)
        if not p.exists():
            raise FileNotFoundError(f"cache key {list(key)!r} not populated")
        session.exec("mkdir", "-p", str(Path(remote_path).parent))
        session.upload([str(p)], remote_path)


#: module-level default cache (the reference's cache is a singleton dir)
cache = Cache()

save_string = cache.save_string
save_data = cache.save_data
save_file = cache.save_file
load_string = cache.load_string
load_data = cache.load_data


def locking(key: Sequence):
    """Context lock for compound check-then-populate sections
    (fs_cache.clj's locking)."""
    return _lock_for(tuple(key))
