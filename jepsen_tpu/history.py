"""Operation histories: the central data model.

A *history* is the ordered record of everything clients and the nemesis did
during a test.  The reference represents it as a vector of Clojure maps with
keys ``:type :process :f :value :time`` plus a post-hoc ``:index``
(jepsen/src/jepsen/generator.clj:330-343, core.clj:228 — which calls
``knossos.history/index``).  This rebuild keeps that record view for the
host-side harness, but makes a dense packed struct-of-arrays form
(``PackedHistory``) a first-class citizen, because the TPU checker kernels
(jepsen_tpu.ops) consume `(type, process, f, value, time)` int tensors, not
Python dicts.

Op ``type`` life-cycle (client.clj:9-27, generator/interpreter.clj:142-157):

  invoke  — a client began an operation
  ok      — it definitely happened
  fail    — it definitely did not happen
  info    — indeterminate (client crashed / timed out); the op may take
            effect at *any* later time, so it stays concurrent with the
            entire remainder of the history.  Unmatched invokes at the end
            of a history are implicitly indeterminate too.

Processes are integers; the nemesis is the special process ``NEMESIS``
(reference uses the keyword ``:nemesis``).
"""

from __future__ import annotations

import dataclasses
from collections.abc import Mapping, Sequence
from typing import Any, Callable, Iterable, Iterator

import numpy as np

# ---------------------------------------------------------------------------
# Op records
# ---------------------------------------------------------------------------

INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"

#: Sentinel process id for nemesis ops (reference: the keyword :nemesis).
NEMESIS = "nemesis"

#: Packed uint8 codes for op types.
TYPE_CODES = {INVOKE: 0, OK: 1, FAIL: 2, INFO: 3}
TYPE_NAMES = [INVOKE, OK, FAIL, INFO]

#: Packed int32 for "no value" (reference: nil).  Chosen far outside any
#: realistic register value so model kernels can branch on it.
NIL = np.int32(np.iinfo(np.int32).min)
#: Packed int32 process id for the nemesis.
NEMESIS_PID = np.int32(-1)
#: Packed int32 "no partner" marker in pair indices.
NO_PAIR = np.int32(-1)


def op(type: str, process, f, value=None, time: int | None = None, **extra):
    """Construct an op dict. Mirrors the reference's op maps."""
    o = {"type": type, "process": process, "f": f, "value": value}
    if time is not None:
        o["time"] = time
    o.update(extra)
    return o


def invoke_op(process, f, value=None, **kw):
    return op(INVOKE, process, f, value, **kw)


def is_invoke(o) -> bool:
    """knossos.op/invoke? equivalent."""
    return o["type"] == INVOKE


def is_ok(o) -> bool:
    """knossos.op/ok? equivalent."""
    return o["type"] == OK


def is_fail(o) -> bool:
    """knossos.op/fail? equivalent."""
    return o["type"] == FAIL


def is_info(o) -> bool:
    """knossos.op/info? equivalent."""
    return o["type"] == INFO


def is_client_op(o) -> bool:
    """True iff this op was performed by a client process (an integer), not
    the nemesis (control.clj worker model; checkers usually filter on this)."""
    return isinstance(o["process"], int)


# ---------------------------------------------------------------------------
# Indexing & pairing
# ---------------------------------------------------------------------------


def index(history: Sequence[dict]) -> list[dict]:
    """Add a monotone ``index`` key to each op, returning a new list.

    Equivalent to ``knossos.history/index`` as called by the orchestrator
    before checking (core.clj:228).  Idempotent: ops that already carry an
    index keep it if the whole history is consistently indexed.  A
    positional ColumnHistory is already indexed and passes through
    untouched (no dict materialization).
    """
    if isinstance(history, ColumnHistory) and history.positional():
        return history
    out = []
    for i, o in enumerate(history):
        if o.get("index") != i:
            o = {**o, "index": i}
        out.append(o)
    return out


def pair_index(history: Sequence[dict]) -> np.ndarray:
    """``pair[i]`` = index of op i's invoke/completion partner, or NO_PAIR.

    Equivalent to ``knossos.history/pair-index`` (used by e.g. the counter
    checker, checker.clj:759).  Matching walks per-process: an invoke by
    process p pairs with the next non-invoke op by p.  Nemesis ops pair the
    same way (start/stop style ops often don't pair; unmatched → NO_PAIR).
    """
    n = len(history)
    pair = np.full(n, NO_PAIR, dtype=np.int32)
    open_by_process: dict[Any, int] = {}
    for i, o in enumerate(history):
        p = o["process"]
        if is_invoke(o):
            open_by_process[p] = i
        else:
            j = open_by_process.pop(p, None)
            if j is not None:
                pair[j] = i
                pair[i] = j
    return pair


def complete(history: Sequence[dict]) -> list[dict]:
    """Fill invoke ops' values from their ok completions.

    Equivalent to ``knossos.history/complete``: a read is invoked with value
    nil and completes with the observed value; checkers that fold over
    invocations want the completed value on the invoke.  Ops whose completion
    is ``info`` get ``{"indeterminate": True}`` semantics — we leave the
    invoke value as-is and do not alter types.
    """
    pairs = pair_index(history)
    out = list(history)
    for i, o in enumerate(history):
        j = int(pairs[i])
        if is_invoke(o) and j != -1 and history[j]["type"] == OK:
            comp_v = history[j].get("value")
            if comp_v is not None and o.get("value") != comp_v:
                out[i] = {**o, "value": comp_v}
    return out


def crashed_invokes(history: Sequence[dict]) -> list[int]:
    """Indices of invoke ops that never definitively completed: their
    completion is ``info`` or missing.  These stay concurrent with the whole
    rest of the history (the worst-case branching driver — SURVEY.md §5
    'long-context' note)."""
    pairs = pair_index(history)
    out = []
    for i, o in enumerate(history):
        if is_invoke(o) and is_client_op(o):
            j = int(pairs[i])
            if j == -1 or history[j]["type"] == INFO:
                out.append(i)
    return out


# ---------------------------------------------------------------------------
# Value encoding
# ---------------------------------------------------------------------------


def encode_register_value(f, value) -> tuple[int, int]:
    """Default value encoder for register-family workloads.

    read/write values are ints (or None → NIL); cas carries ``[old, new]``.
    Returns an ``(v1, v2)`` int pair for the packed columns.
    """
    if value is None:
        return int(NIL), int(NIL)
    if isinstance(value, (list, tuple)):
        a = int(NIL) if value[0] is None else int(value[0])
        b = int(NIL) if len(value) < 2 or value[1] is None else int(value[1])
        return a, b
    if isinstance(value, (int, np.integer)):
        return int(value), int(NIL)
    raise TypeError(f"register value encoder can't pack {value!r}")


def decode_register_value(f, v1: int, v2: int):
    if v1 == NIL and v2 == NIL:
        return None
    if v2 == NIL:
        return int(v1)
    return [None if v1 == NIL else int(v1), None if v2 == NIL else int(v2)]


# ---------------------------------------------------------------------------
# Packed (SoA) histories
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class PackedHistory:
    """Dense struct-of-arrays history — the TPU-native representation.

    Columns (all length n, aligned with op index):

      type_    uint8   TYPE_CODES
      process  int32   client pid, or NEMESIS_PID
      f        int32   index into ``f_names``
      v1, v2   int32   encoded value columns (NIL = absent)
      time     int64   relative nanoseconds (0 if the op had no time)
      pair     int32   partner index (NO_PAIR if none)

    ``f_names`` maps f codes back to names.  Checker kernels take these
    arrays directly; jnp.asarray is zero-copy from the numpy columns on CPU
    and a single H2D transfer on TPU.
    """

    type_: np.ndarray
    process: np.ndarray
    f: np.ndarray
    v1: np.ndarray
    v2: np.ndarray
    time: np.ndarray
    pair: np.ndarray
    f_names: list[str]

    def __len__(self) -> int:
        return len(self.type_)

    @property
    def n(self) -> int:
        return len(self.type_)

    def f_code(self, name) -> int:
        return self.f_names.index(name)

    def unpack(self, decode_value: Callable = decode_register_value) -> list[dict]:
        """Inverse of ``pack`` (loses non-standard op keys)."""
        out = []
        for i in range(len(self)):
            fname = self.f_names[int(self.f[i])]
            p = int(self.process[i])
            out.append(
                {
                    "index": i,
                    "type": TYPE_NAMES[int(self.type_[i])],
                    "process": NEMESIS if p == NEMESIS_PID else p,
                    "f": fname,
                    "value": decode_value(fname, int(self.v1[i]), int(self.v2[i])),
                    "time": int(self.time[i]),
                }
            )
        return out


def pack(
    history: Sequence[dict],
    encode_value: Callable = encode_register_value,
    f_names: Sequence[str] | None = None,
) -> PackedHistory:
    """Pack a record history into a ``PackedHistory``.

    ``f_names`` fixes the f-code vocabulary (useful to share codes across a
    batch of histories); by default it is built in order of first appearance.
    """
    n = len(history)
    type_ = np.zeros(n, dtype=np.uint8)
    process = np.zeros(n, dtype=np.int32)
    f = np.zeros(n, dtype=np.int32)
    v1 = np.full(n, NIL, dtype=np.int32)
    v2 = np.full(n, NIL, dtype=np.int32)
    time = np.zeros(n, dtype=np.int64)
    names = list(f_names) if f_names is not None else []
    codes: dict[Any, int] = {nm: i for i, nm in enumerate(names)}
    for i, o in enumerate(history):
        type_[i] = TYPE_CODES[o["type"]]
        p = o["process"]
        process[i] = NEMESIS_PID if p == NEMESIS else p
        fv = o["f"]
        if fv not in codes:
            if f_names is not None:
                raise KeyError(f"op f {fv!r} not in fixed f_names {names}")
            codes[fv] = len(names)
            names.append(fv)
        f[i] = codes[fv]
        a, b = encode_value(fv, o.get("value"))
        v1[i], v2[i] = a, b
        time[i] = o.get("time", 0) or 0
    return PackedHistory(
        type_=type_,
        process=process,
        f=f,
        v1=v1,
        v2=v2,
        time=time,
        pair=pair_index(history),
        f_names=names,
    )


# ---------------------------------------------------------------------------
# Derived metrics
# ---------------------------------------------------------------------------


def history_to_latencies(history: Sequence[dict]) -> list[dict]:
    """Annotate completions with ``latency`` (ns between invoke and
    completion).  Mirrors ``jepsen.util/history->latencies``
    (util.clj:700-735) but keyed off the pair index rather than a scan."""
    pairs = pair_index(history)
    out = list(history)
    for i, o in enumerate(history):
        j = int(pairs[i])
        if not is_invoke(o) and j != -1:
            inv = history[j]
            if "time" in inv and "time" in o:
                out[i] = {**o, "latency": o["time"] - inv["time"]}
    return out


def processes(history: Sequence[dict]) -> list:
    """Distinct client processes in order of first appearance."""
    seen = {}
    for o in history:
        p = o["process"]
        if isinstance(p, int) and p not in seen:
            seen[p] = True
    return list(seen)


def iter_pairs(history: Sequence[dict]) -> Iterator[tuple[dict, dict | None]]:
    """Yield (invoke, completion-or-None) pairs in invoke order."""
    pairs = pair_index(history)
    for i, o in enumerate(history):
        if is_invoke(o):
            j = int(pairs[i])
            yield o, (history[j] if j != -1 else None)


class ColumnHistory(Sequence):
    """A stored history as lazy ops over SoA columns.

    The zero-copy analyze path (VERDICT r3 item 9): ``store.format.
    read_columns`` hands the ``.jepsen`` file's packed int64 columns
    straight here — no per-op dict is built at load time.  Checkers that
    iterate dict ops get them materialized one at a time on access;
    vectorized consumers read ``.cols`` / ``.fs`` directly.  Positions
    double as indices (the stored history is the indexed history), so
    ``index()`` is a no-op over this type.
    """

    _TYPE_NAMES = (INVOKE, OK, FAIL, INFO)

    def __init__(self, cols: Mapping, fs: Sequence[str], extras: Mapping):
        self.cols = cols
        self.fs = list(fs)
        self.extras = dict(extras)
        self._py: dict | None = None  # plain-int column cache, built lazily
        self._ops: list | None = None  # memoized op dicts (one build each)
        self._complete = False  # _ops fully materialized?

    def __len__(self) -> int:
        return len(self.cols["index"])

    def _pycols(self) -> dict:
        # One tolist() per column on first dict access: per-op numpy
        # scalar conversions otherwise dominate lazy materialization
        # (measured 2x on pack from columns).
        if self._py is None:
            self._py = {k: v.tolist() for k, v in self.cols.items()}
        return self._py

    def _op(self, i: int) -> dict:
        c = self._pycols()
        extra = self.extras.get(i, {})
        if "value" in extra:
            value = extra["value"]
        else:
            value = decode_register_value(None, c["value1"][i], c["value2"][i])
            if extra.get("value-tuple?") and isinstance(value, list):
                value = tuple(value)
        p = c["process"][i]
        op = {
            "index": c["index"][i],
            "type": extra.get("type", self._TYPE_NAMES[c["type"][i]]),
            "process": extra.get("process", NEMESIS if p == -1 else p),
            "f": self.fs[c["f"][i]],
            "value": value,
            "time": c["time"][i],
        }
        for k, v in extra.items():
            if k not in ("value", "value-tuple?", "type", "process"):
                op[k] = v
        return op

    def __getitem__(self, i):
        if self._ops is None:
            self._ops = [None] * len(self)
        if isinstance(i, slice):
            return [self[j] for j in range(*i.indices(len(self)))]
        n = len(self)
        if i < 0:
            i += n
        if not 0 <= i < n:
            raise IndexError(i)
        op = self._ops[i]
        if op is None:
            op = self._ops[i] = self._op(i)
        return op

    def __iter__(self):
        # Full scans (prepare/pack/checker folds) materialize in one
        # tight batch: per-access laziness costs more than the loop.
        yield from self.materialized()

    def materialized(self) -> list:
        """All ops as dicts, built once and memoized (ops already built
        by __getitem__ keep their identity)."""
        if not self._complete:
            prior = self._ops
            self._ops = [
                (prior[i] if prior is not None and prior[i] is not None else self._op(i))
                for i in range(len(self))
            ]
            self._complete = True
        return self._ops

    def positional(self) -> bool:
        """True when stored indices equal positions (an indexed history)."""
        idx = self.cols["index"]
        return bool((idx == np.arange(len(idx))).all())

    def __eq__(self, other):
        if other is self:
            return True
        try:
            n = len(other)
        except TypeError:
            return NotImplemented
        return n == len(self) and all(a == b for a, b in zip(self, other))

    def __repr__(self) -> str:
        return f"ColumnHistory({len(self)} ops)"


def materialize(history):
    """A plain-list view of a history: ColumnHistory batch-materializes
    once (memoized); anything else passes through.  Hot consumers (pack,
    the CPU engines) normalize through this so their inner-loop indexing
    runs at list speed instead of paying per-access Sequence overhead."""
    if isinstance(history, ColumnHistory):
        return history.materialized()
    return history
