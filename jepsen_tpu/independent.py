"""Independent: scale a single-key workload across a whole keyspace.

Mirrors ``jepsen.independent`` (reference:
jepsen/src/jepsen/independent.clj).  Linearizability checking is NP-hard in
history length, so instead of one long history the workload is sharded into
many independent keys with bounded per-key op counts
(independent.clj:2-7) — and the checker splits the history back out per key
(independent.clj:240-317).  This keyspace axis is exactly what the TPU
backend turns into the vmapped batch dimension (SURVEY.md §2.5 item 4;
jepsen_tpu.parallel.batch_analysis).

Values are tagged as ``(key, value)`` tuples (the reference uses a
MapEntry, independent.clj:21-29); ``tuple_/is_tuple/ktuple`` handle the
tagging.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Callable, Mapping, Sequence

from jepsen_tpu import generator as gen
from jepsen_tpu import history as h
from jepsen_tpu import store
from jepsen_tpu.checker import Checker, merge_valid, check_safe
from jepsen_tpu.utils import bounded_pmap

KEY_SENTINEL = "__independent-key__"


def tuple_(key, value) -> list:
    """Tag a value with its key (independent.clj:21-25).  JSON-friendly
    2-lists, round-tripping through history.jsonl."""
    return [KEY_SENTINEL, key, value]


def is_tuple(v) -> bool:
    return isinstance(v, (list, tuple)) and len(v) == 3 and v[0] == KEY_SENTINEL


def tuple_key(v):
    return v[1]


def tuple_value(v):
    return v[2]


# ---------------------------------------------------------------------------
# Generators
# ---------------------------------------------------------------------------


def _key_wrap(key, sub: gen.Gen) -> gen.Gen:
    """Ops from sub get values tagged with key."""
    return gen.map_gen(lambda o: {**o, "value": tuple_(key, o.get("value"))}, sub)


def sequential_generator(keys: Sequence, gen_fn: Callable[[Any], Any]) -> gen.Gen:
    """One key at a time: run gen_fn(k) to exhaustion for each k in order
    (independent.clj:31-66)."""
    return gen._Seq(tuple(_key_wrap(k, gen.to_gen(gen_fn(k))) for k in keys))


@dataclasses.dataclass(frozen=True)
class ConcurrentGenerator(gen.Gen):
    """Partition client threads into groups of n; each group works one key's
    generator, pulling a fresh key whenever its generator is exhausted
    (independent.clj:103-238).

    ``keys`` is consumed lazily; when it runs dry and every group's
    generator is exhausted, the whole generator is done.
    """

    n: int  # threads per group
    gen_fn: Callable
    keys: tuple
    groups: Mapping  # group_id -> (key, Gen) | None (None = retired)

    def _group_of(self, thread) -> int | None:
        if thread == gen.NEMESIS:
            return None
        return thread // self.n

    def _group_threads(self, ctx, gid):
        return frozenset(
            t for t in ctx.all_threads() if t != gen.NEMESIS and t // self.n == gid
        )

    def op(self, test, ctx):
        gids = sorted({g for g in (self._group_of(t) for t in ctx.all_threads()) if g is not None})
        candidates = []
        keys = self.keys
        groups = dict(self.groups)
        for gid in gids:
            state = groups.get(gid, "unset")
            if state is None:
                continue
            if state == "unset":
                if not keys:
                    groups[gid] = None
                    continue
                state = (keys[0], _key_wrap(keys[0], gen.to_gen(self.gen_fn(keys[0]))))
                keys = keys[1:]
                groups[gid] = state
            k, g = state
            sub = ctx.restrict(lambda t, gid=gid: self._group_of(t) == gid)
            r = g.op(test, sub)
            if r is None:
                # Exhausted: draw the next key for this group, if any.
                if keys:
                    nk = keys[0]
                    keys = keys[1:]
                    groups[gid] = (nk, _key_wrap(nk, gen.to_gen(self.gen_fn(nk))))
                    r = groups[gid][1].op(test, sub)
                    if r is None:
                        groups[gid] = None
                        continue
                else:
                    groups[gid] = None
                    continue
            o, g2 = r
            candidates.append({"op": o, "gen": g2, "gid": gid, "key": groups[gid][0]})
        live = ConcurrentGenerator(self.n, self.gen_fn, keys, groups)
        if not candidates:
            if any(v is not None for v in groups.values()) or keys:
                return (gen.PENDING, live)
            return None
        best = gen.soonest_op_map(candidates)
        groups[best["gid"]] = (best["key"], best["gen"])
        return (best["op"], ConcurrentGenerator(self.n, self.gen_fn, keys, groups))

    def update(self, test, ctx, event):
        thread = ctx.thread_of(event.get("process"))
        gid = self._group_of(thread) if thread is not None else None
        if gid is None:
            return self
        state = self.groups.get(gid)
        if not state:
            return self
        k, g = state
        sub = ctx.restrict(lambda t, gid=gid: self._group_of(t) == gid)
        groups = dict(self.groups)
        groups[gid] = (k, g.update(test, sub, event))
        return ConcurrentGenerator(self.n, self.gen_fn, self.keys, groups)


def concurrent_generator(n: int, keys: Sequence, gen_fn: Callable) -> gen.Gen:
    """(independent.clj:103-238).  n = threads per key-group; the test's
    concurrency should be a multiple of n."""
    return ConcurrentGenerator(n, gen_fn, tuple(keys), {})


# ---------------------------------------------------------------------------
# History surgery (independent.clj:240-264)
# ---------------------------------------------------------------------------


def history_keys(history: Sequence[Mapping]) -> list:
    """Distinct keys, in order of first appearance."""
    seen = {}
    for o in history:
        v = o.get("value")
        if is_tuple(v):
            seen.setdefault(tuple_key(v), True)
    return list(seen)


def subhistory(key, history: Sequence[Mapping]) -> list[dict]:
    """Ops for one key, values untagged; non-tuple ops (e.g. nemesis) are
    kept with their value intact (independent.clj:251-264)."""
    out = []
    for o in history:
        v = o.get("value")
        if is_tuple(v):
            if tuple_key(v) == key:
                out.append({**o, "value": tuple_value(v)})
        else:
            out.append(o)
    return out


# ---------------------------------------------------------------------------
# Checker (independent.clj:266-317)
# ---------------------------------------------------------------------------


class IndependentChecker(Checker):
    """Split the history per key, run the wrapped checker on each, merge
    validity; per-key results land in ``independent/<key>/``."""

    def __init__(self, checker: Checker):
        self.checker = checker

    def check(self, test, history, opts):
        keys = history_keys(history)
        opts = dict(opts or {})

        def save_key(k, sub, res, from_batch=False):
            try:
                d = store.test_dir(test) / "independent" / str(k)
                d.mkdir(parents=True, exist_ok=True)
                store._write_json(d / "results.json", res)
                store.write_history(d, sub)
            except (KeyError, OSError, TypeError):
                return  # no store configured (bare unit tests)
            # Checkers with extra artifact output (e.g. elle's anomaly
            # explanation dir) render per key too.  Only the batch path
            # needs the hook: it skips the per-key check(), which writes
            # its own artifacts on the fallback path.
            if from_batch:
                write = getattr(self.checker, "write_artifacts", None)
                if write is not None:
                    write(test, res, {**opts, "subdirectory": f"independent/{k}"})

        batch = None
        if hasattr(self.checker, "check_batch"):
            # Batch-capable checkers (TPU elle / linearizable) take every
            # per-key subhistory in ONE call and bucket them into vmapped
            # kernel launches — the reference's bounded-pmap scale-out
            # (independent.clj:285-307) as a device batch axis.  A batch
            # failure falls back to the per-key path below so one key's
            # exception can't mask another key's real violation.
            subs = [h.index(subhistory(k, history)) for k in keys]
            try:
                batch = self.checker.check_batch(test, subs, opts)
            except Exception:  # noqa: BLE001 — per-key path isolates it
                batch = None
        if batch is not None:
            results = {}
            for k, sub, res in zip(keys, subs, batch):
                results[k] = res
                save_key(k, sub, res, from_batch=True)
        else:

            def check_key(k):
                sub = h.index(subhistory(k, history))
                sub_opts = {**opts, "subdirectory": f"independent/{k}"}
                res = check_safe(self.checker, test, sub, sub_opts)
                save_key(k, sub, res)
                return k, res

            results = dict(bounded_pmap(check_key, keys))
        valid = merge_valid([r.get("valid?") for r in results.values()] or [True])
        failures = [k for k, r in results.items() if r.get("valid?") is not True]
        return {
            "valid?": valid,
            "results": results,
            "failures": failures,
        }


def checker(inner: Checker) -> Checker:
    return IndependentChecker(inner)
