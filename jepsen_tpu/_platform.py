"""Platform selection override for the checker kernels.

Some platform plugins (the axon TPU tunnel) override the JAX_PLATFORMS
env var by injecting themselves into the ``jax_platforms`` config flag
at import time — so a user exporting ``JAX_PLATFORMS=cpu`` still gets
the plugin, and an unreachable TPU hangs every checker import.  The
plugin-injected flag value is indistinguishable from one set
deliberately, so this shim honors a framework-owned variable instead:

    JEPSEN_TPU_PLATFORM=cpu python -m examples.toydb test --local ...

``honor_env_platform()`` is called from the modules whose import
triggers backend initialization (module-level ``jnp`` constants), NOT
from the package __init__: store/history/web paths stay jax-free and
import fast.  It sets the config flag unconditionally when the variable
is present — the variable exists only to express user intent, so there
is nothing to defer to.
"""

from __future__ import annotations

import os

ENV_VAR = "JEPSEN_TPU_PLATFORM"


def shard_map(fn, *, mesh, in_specs, out_specs, check_vma: bool | None = None):
    """jax.shard_map across the jax API move: ``jax.shard_map`` (>=0.6,
    ``check_vma``) vs ``jax.experimental.shard_map.shard_map`` (0.4/0.5,
    ``check_rep`` — same meaning).  Every mesh kernel builds through this
    one shim so an interpreter upgrade is a one-line fix."""
    import jax

    if hasattr(jax, "shard_map"):
        kw = {} if check_vma is None else {"check_vma": check_vma}
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _sm

    kw = {} if check_vma is None else {"check_rep": check_vma}
    return _sm(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw)


def honor_env_platform() -> None:
    want = os.environ.get(ENV_VAR)
    if not want:
        return
    try:
        import jax

        if getattr(jax.config, "jax_platforms", None) != want:
            jax.config.update("jax_platforms", want)
    except Exception:  # pragma: no cover — jax absent or config renamed
        pass
