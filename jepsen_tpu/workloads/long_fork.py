"""Long-fork workload: detects the parallel-snapshot-isolation anomaly.

Mirrors ``jepsen.tests.long-fork`` (reference: jepsen/tests/long_fork.clj,
332 LoC).  Keys come in groups of n; each key is written *exactly once*
(value 1), and readers snapshot a whole group in one txn
(long_fork.clj:117+).  Under PSI, two reads may observe the writes of a
group in contradictory orders — read A sees x but not y while read B sees
y but not x.  Since writes are unique and monotone per group, all reads of
a group must be totally ordered by their seen-write *sets*; any
⊆-incomparable pair is a long fork (the linear-time verifier of
long_fork.clj:62-88).

Ops (txn micro-op form, like Elle workloads):
  write: {"f": "txn", "value": [["w", k, 1]]}
  read:  {"f": "txn", "value": [["r", k1, None], ..., ["r", kn, None]]}
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Mapping, Sequence

from jepsen_tpu import generator as gen
from jepsen_tpu import history as h
from jepsen_tpu.checker import Checker

DEFAULT_GROUP_SIZE = 3


def group_of(k: int, n: int) -> int:
    return k // n


def group_keys(g: int, n: int) -> list[int]:
    return list(range(g * n, (g + 1) * n))


def _write_key(o) -> int | None:
    v = o.get("value")
    if o.get("type") == "invoke" and o.get("f") == "txn" and v and len(v) == 1 and v[0][0] == "w":
        return v[0][1]
    return None


@dataclasses.dataclass(frozen=True)
class _LongForkGen(gen.Gen):
    """Mix single-key writes with whole-group reads.  The write-key cursor
    advances only when a write invocation is actually dispatched (seen as
    an invoke event), never from op() side effects — the interpreter
    speculatively calls op() and may discard the result, so impure
    closures would burn keys (long_fork.clj:117-160 keeps the cursor in
    generator state the same way).  Mixing is internal because ``gen.mix``
    does not route updates to its children."""

    n: int
    next_key: int = 0

    def op(self, test, ctx):
        if gen._rng.random() < 0.5:
            val = [["w", self.next_key, 1]]
        else:
            g = group_of(max(0, self.next_key - 1), self.n)
            val = [["r", k, None] for k in group_keys(g, self.n)]
        o = gen.fill_in_op({"f": "txn", "value": val}, ctx)
        return (o, self)

    def update(self, test, ctx, event):
        k = _write_key(event)
        if k is not None and k >= self.next_key:
            return dataclasses.replace(self, next_key=k + 1)
        return self


def generator(n: int = DEFAULT_GROUP_SIZE) -> gen.Gen:
    """Interleave single-key writes with whole-group reads
    (long_fork.clj:117-160), advanced by invoke events only."""
    return _LongForkGen(n)


def read_sets(history: Sequence[Mapping], n: int) -> dict:
    """{group: [set-of-keys-seen-written, ...]} from ok group reads."""
    out: dict = {}
    for o in history:
        if not (h.is_ok(o) and o.get("f") == "txn"):
            continue
        mops = o.get("value") or []
        rs = [(m[1], m[2]) for m in mops if m[0] == "r"]
        if len(rs) < 2:
            continue
        g = group_of(rs[0][0], n)
        if any(group_of(k, n) != g for k, _ in rs):
            continue
        seen = frozenset(k for k, v in rs if v is not None)
        out.setdefault(g, []).append({"op": o, "seen": seen})
    return out


class LongForkChecker(Checker):
    """All reads of a group must be ⊆-comparable (long_fork.clj:62-88)."""

    def __init__(self, n: int = DEFAULT_GROUP_SIZE):
        self.n = n

    def check(self, test, history, opts):
        groups = read_sets(history, self.n)
        forks = []
        for g, reads in groups.items():
            # Sort by |seen|; incomparable pairs can only occur between
            # reads whose set sizes are equal or where neither contains the
            # other.
            reads = sorted(reads, key=lambda r: len(r["seen"]))
            for a, b in itertools.combinations(reads, 2):
                sa, sb = a["seen"], b["seen"]
                if not (sa <= sb or sb <= sa):
                    forks.append(
                        {
                            "group": g,
                            "read-a": a["op"],
                            "read-b": b["op"],
                            "only-a": sorted(sa - sb),
                            "only-b": sorted(sb - sa),
                        }
                    )
        return {
            "valid?": not forks,
            "group-count": len(groups),
            "long-forks": forks[:10],
            "fork-count": len(forks),
        }


def checker(n: int = DEFAULT_GROUP_SIZE) -> Checker:
    return LongForkChecker(n)


def workload(opts: Mapping | None = None) -> dict:
    opts = dict(opts or {})
    n = opts.get("group-size", DEFAULT_GROUP_SIZE)
    return {"generator": generator(n), "checker": checker(n)}
