"""Causal-consistency workloads.

Mirrors ``jepsen.tests.causal`` and ``jepsen.tests.causal-reverse``
(reference: jepsen/tests/causal.clj 131 LoC, causal_reverse.clj 114 LoC):

* ``causal``: a single register driven by one logical session performing
  write 1 → read → write 2 → read; causal consistency requires
  read-your-writes and monotonic reads within the session, so the first
  read must see 1 and the second 2 (causal.clj's CO ops).
* ``causal_reverse``: sequentially-ordered inserts whose order must not be
  observed reversed — a read that sees a *later* insert but misses an
  *earlier* one violates the prefix property (causal_reverse.clj's
  lost-update ordering check).

Ops:
  causal:          {"f": "write"|"read", "value": int|None}
  causal_reverse:  {"f": "insert", "value": k} and
                   {"f": "read", "value": None -> [k...]}
"""

from __future__ import annotations

import itertools
from typing import Mapping

from jepsen_tpu import generator as gen
from jepsen_tpu import history as h
from jepsen_tpu.checker import Checker


def generator() -> gen.Gen:
    """One session's CO chain (causal.clj ops)."""
    return gen.on_threads(
        lambda t: t == 0,
        [
            {"f": "write", "value": 1},
            {"f": "read", "value": None},
            {"f": "write", "value": 2},
            {"f": "read", "value": None},
        ],
    )


class CausalChecker(Checker):
    """Per-process read-your-writes + monotonic reads on a register
    (causal.clj:40-100)."""

    def check(self, test, history, opts):
        errors = []
        last_write: dict = {}
        last_read: dict = {}
        pairs = h.pair_index(history)
        for i, o in enumerate(history):
            if not h.is_invoke(o) or not h.is_client_op(o):
                continue
            j = int(pairs[i])
            comp = history[j] if j != -1 else None
            if comp is None or comp["type"] != h.OK:
                continue
            p = o["process"]
            if o["f"] == "write":
                last_write[p] = o["value"]
            elif o["f"] == "read":
                v = comp.get("value")
                if p in last_write and v != last_write[p] and (
                    last_read.get(p) is None or v == last_read.get(p)
                ):
                    # Saw neither our write nor progress past it.
                    if v is None or (
                        isinstance(v, int)
                        and isinstance(last_write[p], int)
                        and v < last_write[p]
                    ):
                        errors.append(
                            {
                                "op": comp,
                                "error": f"read {v!r} but process {p} wrote {last_write[p]!r}",
                            }
                        )
                if (
                    p in last_read
                    and isinstance(v, int)
                    and isinstance(last_read[p], int)
                    and v < last_read[p]
                ):
                    errors.append(
                        {"op": comp, "error": f"non-monotonic read {v!r} after {last_read[p]!r}"}
                    )
                last_read[p] = v
        return {"valid?": not errors, "errors": errors[:10]}


def checker() -> Checker:
    return CausalChecker()


def workload(opts: Mapping | None = None) -> dict:
    return {"generator": generator(), "checker": checker()}


# ---------------------------------------------------------------------------
# causal-reverse (causal_reverse.clj)
# ---------------------------------------------------------------------------


def reverse_generator() -> gen.Gen:
    counter = itertools.count()
    return gen.mix(
        [
            gen.repeat(lambda: {"f": "insert", "value": next(counter)}),
            gen.repeat({"f": "read", "value": None}),
        ]
    )


class CausalReverseChecker(Checker):
    """Inserts are issued in increasing order; a read seeing k but missing
    some acknowledged j<k (j inserted before k began) observed them out of
    order (causal_reverse.clj:40-100)."""

    def check(self, test, history, opts):
        pairs = h.pair_index(history)
        # insert value -> (invoke index, ok?)
        acked = {}
        for i, o in enumerate(history):
            if h.is_invoke(o) and o["f"] == "insert":
                j = int(pairs[i])
                if j != -1 and history[j]["type"] == h.OK:
                    acked[o["value"]] = (i, j)
        errors = []
        for i, o in enumerate(history):
            if not (h.is_ok(o) and o["f"] == "read"):
                continue
            seen = set(o.get("value") or [])
            inv_i = int(pairs[i])
            for k in seen:
                if k not in acked:
                    continue
                for jv, (ji, jj) in acked.items():
                    # j's ok came before k's invoke → j happens-before k.
                    if jv < k and jj < acked[k][0] and jv not in seen:
                        errors.append(
                            {
                                "op": o,
                                "error": f"read saw {k} but missed earlier acked {jv}",
                            }
                        )
        return {"valid?": not errors, "errors": errors[:10]}


def reverse_checker() -> Checker:
    return CausalReverseChecker()


def reverse_workload(opts: Mapping | None = None) -> dict:
    return {"generator": reverse_generator(), "checker": reverse_checker()}
