"""Workload bundles: reusable generator+checker packages.

Mirrors the reference's ``jepsen.tests.*`` namespaces (SURVEY.md §2.1): each
module exposes a ``workload(opts) -> dict`` with at least ``generator`` and
``checker`` keys (plus ``final_generator`` where the workload needs a
read-back phase), ready to merge into a test map — the same bundle shape as
e.g. ``jepsen.tests.bank/test`` (tests/bank.clj:179-192).
"""

from jepsen_tpu.workloads import (  # noqa: F401
    adya,
    append,
    bank,
    causal,
    linearizable_register,
    long_fork,
    monotonic,
    sequential,
    sets,
    wr,
)
