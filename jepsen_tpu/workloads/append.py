"""Elle list-append workload: append/read transactions + cycle checking.

Mirrors ``jepsen.tests.cycle.append`` (reference:
jepsen/tests/cycle/append.clj): the generator streams transactions of
``["append", k, unique-v]`` / ``["r", k, None]`` micro-ops
(cycle/append.clj:24-28 re-exports elle's generator; ours is
jepsen_tpu.txn.append_txns), and the checker is the Elle-equivalent
list-append dependency-graph analysis (jepsen_tpu.checker.elle).

Ops: {"f": "txn", "value": [[mop-f, key, value], ...]}
"""

from __future__ import annotations

import random
from typing import Mapping

from jepsen_tpu import generator as gen
from jepsen_tpu import txn as jtxn
from jepsen_tpu.checker import elle


def generator(opts: Mapping | None = None) -> gen.Gen:
    opts = dict(opts or {})
    rng = random.Random(opts.get("seed"))
    txns = jtxn.append_txns(
        rng,
        key_count=opts.get("key-count", 3),
        min_txn_length=opts.get("min-txn-length", 1),
        max_txn_length=opts.get("max-txn-length", 4),
        max_writes_per_key=opts.get("max-writes-per-key", 32),
    )
    return gen.repeat(lambda: {"f": "txn", "value": next(txns)})


def workload(opts: Mapping | None = None) -> dict:
    """(cycle/append.clj:30-55)."""
    opts = dict(opts or {})
    kw = {}
    if "anomalies" in opts:
        kw["anomalies"] = opts["anomalies"]
    if "additional-graphs" in opts:
        kw["additional_graphs"] = opts["additional-graphs"]
    return {
        "generator": generator(opts),
        "checker": elle.list_append(**kw),
    }
