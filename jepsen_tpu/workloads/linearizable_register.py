"""Linearizable register workload over an independent keyspace.

Mirrors ``jepsen.tests.linearizable-register`` (reference:
jepsen/tests/linearizable_register.clj): a concurrent-generator of
read/write/cas per key, each key's subhistory checked with the
cas-register model + timeline (linearizable_register.clj:26-53).  Per-key
op and process budgets keep the NP-hard search tractable
(per-key-limit ~20, process-limit 20, linearizable_register.clj:30-33) —
and give the TPU backend its vmap batch axis.
"""

from __future__ import annotations

import random
from typing import Mapping

from jepsen_tpu import generator as gen
from jepsen_tpu import independent, models
from jepsen_tpu.checker import compose
from jepsen_tpu.checker.linearizable import linearizable
from jepsen_tpu.checker.timeline import timeline_checker


def r(test=None, ctx=None):
    return {"f": "read", "value": None}


def w(test=None, ctx=None):
    return {"f": "write", "value": random.randint(0, 4)}


def cas(test=None, ctx=None):
    return {"f": "cas", "value": [random.randint(0, 4), random.randint(0, 4)]}


def workload(opts: Mapping | None = None) -> dict:
    opts = dict(opts or {})
    n = opts.get("concurrency", 10)
    per_key_limit = opts.get("per-key-limit", 20)
    process_limit = opts.get("process-limit", 20)
    algorithm = opts.get("algorithm", "competition")
    threads_per_key = max(1, min(n, opts.get("threads-per-key", n)))
    n_keys = opts.get("key-count", 64)

    def per_key(k):
        return gen.process_limit(
            process_limit,
            gen.limit(per_key_limit, gen.mix([gen.repeat(r), gen.repeat(w), gen.repeat(cas)])),
        )

    return {
        "generator": independent.concurrent_generator(
            threads_per_key, list(range(n_keys)), per_key
        ),
        "checker": independent.checker(
            compose(
                {
                    "linear": linearizable(
                        {"model": models.CASRegister(None), "algorithm": algorithm}
                    ),
                    "timeline": timeline_checker(),
                }
            )
        ),
    }
