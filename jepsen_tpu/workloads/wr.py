"""Elle rw-register workload: write/read transactions + cycle checking.

Mirrors ``jepsen.tests.cycle.wr`` (reference: jepsen/tests/cycle/wr.clj):
transactions of ``["w", k, unique-v]`` / ``["r", k, None]`` micro-ops
(generator: jepsen_tpu.txn.wr_txns), checked by the Elle-equivalent
rw-register analysis with the G0/G1a/G1b/G1c/G-single/G2 anomaly
vocabulary (cycle/wr.clj:30-46).

Ops: {"f": "txn", "value": [[mop-f, key, value], ...]}
"""

from __future__ import annotations

import random
from typing import Mapping

from jepsen_tpu import generator as gen
from jepsen_tpu import txn as jtxn
from jepsen_tpu.checker import elle


def generator(opts: Mapping | None = None) -> gen.Gen:
    opts = dict(opts or {})
    rng = random.Random(opts.get("seed"))
    txns = jtxn.wr_txns(
        rng,
        key_count=opts.get("key-count", 2),
        min_txn_length=opts.get("min-txn-length", 1),
        max_txn_length=opts.get("max-txn-length", 2),
        max_writes_per_key=opts.get("max-writes-per-key", 32),
    )
    return gen.repeat(lambda: {"f": "txn", "value": next(txns)})


def workload(opts: Mapping | None = None) -> dict:
    """(cycle/wr.clj:48-54)."""
    opts = dict(opts or {})
    kw = {}
    if "anomalies" in opts:
        kw["anomalies"] = opts["anomalies"]
    if "additional-graphs" in opts:
        kw["additional_graphs"] = opts["additional-graphs"]
    if opts.get("sequential-keys?"):
        kw["sequential_keys"] = True
    if opts.get("linearizable-keys?"):
        kw["linearizable_keys"] = True
    return {
        "generator": generator(opts),
        "checker": elle.wr_register(**kw),
    }
