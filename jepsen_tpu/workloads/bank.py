"""Bank workload: concurrent transfers must conserve total money.

Mirrors ``jepsen.tests.bank`` (reference: jepsen/tests/bank.clj): a set of
accounts with a fixed total; transfer ops move money between two accounts,
read ops snapshot all balances (bank.clj:20-44).  Under snapshot isolation
or weaker, write skew lets reads observe totals drifting — the checker
asserts every ok read sums to ``total-amount`` and (optionally) that no
balance goes negative (bank.clj:57-121).

Ops:
  {"f": "read",     "value": None -> {account: balance}}
  {"f": "transfer", "value": {"from": a, "to": b, "amount": n}}
"""

from __future__ import annotations

import random
from typing import Mapping

from jepsen_tpu import generator as gen
from jepsen_tpu import history as h
from jepsen_tpu.checker import Checker

DEFAULT_ACCOUNTS = list(range(8))
DEFAULT_TOTAL = 100
DEFAULT_MAX_TRANSFER = 5


def read_op(test, ctx):
    return {"f": "read", "value": None}


def transfer_op(accounts, max_transfer):
    def f(test, ctx):
        a, b = random.sample(list(accounts), 2)
        return {
            "f": "transfer",
            "value": {"from": a, "to": b, "amount": random.randint(1, max_transfer)},
        }

    return f


def generator(opts: Mapping | None = None) -> gen.Gen:
    """Roughly even mix of reads and transfers (bank.clj:36-44)."""
    opts = dict(opts or {})
    accounts = opts.get("accounts", DEFAULT_ACCOUNTS)
    max_transfer = opts.get("max-transfer", DEFAULT_MAX_TRANSFER)
    return gen.mix([gen.repeat(read_op), gen.repeat(transfer_op(accounts, max_transfer))])


class BankChecker(Checker):
    """(bank.clj:57-121)."""

    def __init__(self, negative_balances_ok: bool = False):
        self.negative_balances_ok = negative_balances_ok

    def check(self, test, history, opts):
        total = test.get("total-amount", DEFAULT_TOTAL)
        accounts = set(test.get("accounts", DEFAULT_ACCOUNTS))
        bad_reads = []
        read_count = 0
        for o in history:
            if not (h.is_ok(o) and o["f"] == "read"):
                continue
            read_count += 1
            balances = o.get("value") or {}
            errs = []
            got_total = sum(balances.values())
            if set(balances) != accounts:
                errs.append(f"accounts {sorted(balances)} != expected {sorted(accounts)}")
            if got_total != total:
                errs.append(f"total {got_total} != expected {total}")
            if not self.negative_balances_ok:
                neg = {a: v for a, v in balances.items() if v < 0}
                if neg:
                    errs.append(f"negative balances {neg}")
            if errs:
                bad_reads.append({"op": o, "errors": errs})
        return {
            "valid?": not bad_reads,
            "read-count": read_count,
            "bad-reads": bad_reads[:10],
            "bad-read-count": len(bad_reads),
        }


def checker(negative_balances_ok: bool = False) -> Checker:
    return BankChecker(negative_balances_ok)


def workload(opts: Mapping | None = None) -> dict:
    """Bundle (bank.clj:179-192)."""
    opts = dict(opts or {})
    return {
        "accounts": opts.get("accounts", DEFAULT_ACCOUNTS),
        "total-amount": opts.get("total-amount", DEFAULT_TOTAL),
        "max-transfer": opts.get("max-transfer", DEFAULT_MAX_TRANSFER),
        "generator": generator(opts),
        "checker": checker(opts.get("negative-balances?", False)),
    }
