"""Adya G2: anti-dependency cycles via predicate reads (write skew).

Mirrors ``jepsen.tests.adya`` (reference: jepsen/tests/adya.clj, 87 LoC):
pairs of transactions each read a predicate over two rows ``(key, a)`` and
``(key, b)`` and insert their own row only if the *other* row is absent.
Serializability forbids both from committing — if both do, each read
missed the other's write: a G2 anomaly (two rw anti-dependency edges
forming a cycle).

Ops: {"f": "txn", "value": {"key": k, "id": 1|2, "read": [row-a?, row-b?]}}
— the client fills "read" with what the predicate observed and sets type
ok iff its insert committed.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

from jepsen_tpu import generator as gen
from jepsen_tpu import history as h
from jepsen_tpu.checker import Checker


@dataclasses.dataclass(frozen=True)
class _AdyaGen(gen.Gen):
    """Emit id-1 then id-2 for each key, advancing ONLY on dispatched
    invoke events (the _LongForkGen idiom): the interpreter peeks op()
    speculatively and may discard the result, so stateful closures drop
    ops — the original list/queue forms silently emitted only id-1 per
    key, which the live toydb adya harness caught (no key ever had both
    transactions, so write skew was undetectable by construction)."""

    key: int = 0
    rid: int = 1

    def op(self, test, ctx):
        o = gen.fill_in_op(
            {"f": "txn", "value": {"key": self.key, "id": self.rid}}, ctx
        )
        return (o, self)

    def update(self, test, ctx, event):
        v = event.get("value") if isinstance(event.get("value"), dict) else None
        if (
            event.get("type") == "invoke"
            and event.get("f") == "txn"
            and v is not None
            and v.get("key") == self.key
            and v.get("id") == self.rid
        ):
            if self.rid == 1:
                return dataclasses.replace(self, rid=2)
            return dataclasses.replace(self, key=self.key + 1, rid=1)
        return self


def generator() -> gen.Gen:
    """Two ops per key, one for each row id (adya.clj:30-60), advanced
    by invoke events only."""
    return _AdyaGen()


class G2Checker(Checker):
    """Both inserts for a key committing, each having read the other row as
    absent, is write skew (adya.clj:62-87)."""

    def check(self, test, history, opts):
        by_key: dict = {}
        for o in history:
            if h.is_ok(o) and o.get("f") == "txn":
                v = o.get("value") or {}
                by_key.setdefault(v.get("key"), []).append(o)
        anomalies = []
        for k, ops in by_key.items():
            ids = {(o["value"] or {}).get("id") for o in ops}
            if {1, 2} <= ids:
                committed = [o for o in ops if (o["value"] or {}).get("id") in (1, 2)]
                saw_other = [
                    o
                    for o in committed
                    if not any((o["value"] or {}).get("read") or [])
                ]
                if len(saw_other) >= 2:
                    anomalies.append({"key": k, "ops": committed[:2]})
        return {
            "valid?": not anomalies,
            "anomaly-count": len(anomalies),
            "anomalies": anomalies[:10],
        }


def checker() -> Checker:
    return G2Checker()


def workload(opts: Mapping | None = None) -> dict:
    return {"generator": generator(), "checker": checker()}
