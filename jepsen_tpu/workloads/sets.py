"""Set workloads: unique adds followed (or interleaved) with reads.

Mirrors the reference's set tests (checker.clj:240-291 for the
final-read form; checker.clj:294-592 set-full for the read-throughout
element-lifecycle form).

Ops:
  {"f": "add",  "value": unique int}
  {"f": "read", "value": None -> collection of ints}
"""

from __future__ import annotations

import itertools
from typing import Mapping

from jepsen_tpu import generator as gen
from jepsen_tpu.checker import basic


def add_gen():
    counter = itertools.count()
    return gen.repeat(lambda: {"f": "add", "value": next(counter)})


def workload(opts: Mapping | None = None) -> dict:
    """Adds throughout; one final read after a barrier (the classic set
    test)."""
    return {
        "generator": add_gen(),
        "final_generator": gen.once(gen.repeat({"f": "read", "value": None})),
        "checker": basic.set_checker(),
    }


def workload_full(opts: Mapping | None = None) -> dict:
    """Adds and reads interleaved; set-full lifecycle analysis
    (checker.clj:294-592)."""
    opts = dict(opts or {})
    return {
        "generator": gen.mix([add_gen(), gen.repeat({"f": "read", "value": None})]),
        "checker": basic.set_full(linearizable=opts.get("linearizable?", False)),
    }
