"""Monotonic workload: an increment-only counter whose observed values
must never run backwards.

The pattern three of the reference's biggest harnesses carry
(cockroachdb/src/jepsen/cockroach/monotonic.clj, tidb, faunadb): clients
increment a counter and read it; a database that reorders or loses
increments shows a read going backwards in real time or a value the
increments can't explain.

Ops:
  {"f": "inc",  "value": None -> the post-increment count}
  {"f": "read", "value": None -> the current count}

Checker verdict:
  nonmonotonic — a read completed before another read began, yet the
                 later read observed a SMALLER value (real-time
                 regression)
  impossible   — a read observed more than the number of increments
                 INVOKED by its completion (an invoked op may take
                 effect before its ack arrives, so invocations — not
                 completions — bound what a read may see)
"""

from __future__ import annotations

from typing import Mapping, Sequence

from jepsen_tpu import generator as gen
from jepsen_tpu import history as h
from jepsen_tpu.checker import Checker


class MonotonicChecker(Checker):
    def check(self, test, history: Sequence[Mapping], opts) -> dict:
        reads = []  # (invoke_time, complete_time, value, op)
        pair = h.pair_index(history)
        attempted_incs = 0  # incs INVOKED so far: the committable bound
        errors: list = []
        for i, o in enumerate(history):
            if o.get("process") == h.NEMESIS:
                continue
            if o["type"] == h.INVOKE:
                if o["f"] == "inc":
                    attempted_incs += 1
                continue
            j = int(pair[i])
            inv = history[j] if j >= 0 else None
            if o["f"] == "read" and o["type"] == h.OK and inv is not None:
                v = o.get("value")
                if not isinstance(v, int):
                    errors.append({"type": "non-integer-read", "op": o})
                    continue
                if v > attempted_incs:
                    errors.append(
                        {
                            "type": "impossible",
                            "op": o,
                            "observed": v,
                            "max-possible": attempted_incs,
                        }
                    )
                reads.append((inv["time"], o["time"], v, o))
        # Real-time monotonicity: if read A completed before read B began,
        # B must not observe LESS than A.  Sweep in invocation order,
        # carrying the max value among reads already completed (O(n log n)).
        by_completion = sorted(reads, key=lambda r: r[1])
        by_invocation = sorted(reads, key=lambda r: r[0])
        ci = 0
        hi = None  # (value, op) with max value among completed reads
        for inv_b, _comp_b, vb, ob in by_invocation:
            while ci < len(by_completion) and by_completion[ci][1] < inv_b:
                _ia, _ca, va, oa = by_completion[ci]
                if hi is None or va > hi[0]:
                    hi = (va, oa)
                ci += 1
            if hi is not None and vb < hi[0]:
                errors.append(
                    {
                        "type": "nonmonotonic",
                        "earlier": hi[1],
                        "later": ob,
                        "went": [hi[0], vb],
                    }
                )
        out: dict = {"valid?": not errors, "reads": len(reads), "incs": attempted_incs}
        if errors:
            out["errors"] = errors[:8]
            out["error-count"] = len(errors)
        return out


def checker() -> Checker:
    return MonotonicChecker()


def workload(opts: Mapping | None = None) -> dict:
    return {
        "generator": gen.mix(
            [gen.repeat({"f": "inc", "value": None}), gen.repeat({"f": "read", "value": None})]
        ),
        "checker": checker(),
    }
