"""Sequential-consistency workload: ordered key chains with prefix reads.

The pattern the cockroach/tidb/dgraph harnesses share (reference:
cockroachdb/src/jepsen/cockroach/sequential.clj and kin): each writer
owns a chain of keys it writes strictly in order (key 0, then key 1, …);
a reader scanning a chain in REVERSE key order must observe a suffix
whose presence implies every earlier key — seeing key i written but key
i-1 missing means the later write became visible before the earlier one,
a sequential-consistency (per-session order) violation.

Ops:
  {"f": "write", "value": [chain, i]}        write key i of a chain
  {"f": "read",  "value": [chain, observed]} observed = sorted key
                                             indices seen (completion)

Checker verdict per chain: the observed set of every read must be a
PREFIX of 0..n (no holes below the maximum seen).
"""

from __future__ import annotations

from typing import Mapping, Sequence

from jepsen_tpu import generator as gen
from jepsen_tpu import history as h
from jepsen_tpu.checker import Checker


class SequentialChecker(Checker):
    def check(self, test, history: Sequence[Mapping], opts) -> dict:
        errors: list = []
        reads = 0
        for o in history:
            if o.get("process") == h.NEMESIS or o["type"] != h.OK or o["f"] != "read":
                continue
            chain, observed = o["value"]
            observed = sorted(observed or [])
            reads += 1
            if observed and observed != list(range(observed[-1] + 1)):
                missing = sorted(set(range(observed[-1] + 1)) - set(observed))
                errors.append(
                    {
                        "type": "hole",
                        "chain": chain,
                        "observed": observed,
                        "missing": missing,
                        "op": o,
                    }
                )
        out: dict = {"valid?": not errors, "reads": reads}
        if errors:
            out["errors"] = errors[:8]
            out["error-count"] = len(errors)
        return out


def checker() -> Checker:
    return SequentialChecker()


def writes(chain: int, n_keys: int):
    """The chain's ordered writes, one op per key."""
    return [{"f": "write", "value": [chain, i]} for i in range(n_keys)]


def workload(opts: Mapping | None = None) -> dict:
    """Writers walk their chains in order while readers scan chains; the
    client contract: a read returns [chain, observed-key-indices] with
    the scan performed in reverse key order.

    Each chain is OWNED by one writer thread (``on_threads`` binding): a
    thread never has two ops in flight, so a chain's writes are serialized
    by construction — without that, consecutive writes of one chain could
    race and a correct system would show spurious holes.
    """
    import random as _random

    opts = dict(opts or {})
    n_chains = opts.get("chain-count", 8)
    n_keys = opts.get("keys-per-chain", 5)
    conc = opts.get("concurrency", 4)
    rng = _random.Random(opts.get("seed"))

    n_writers = max(1, min(n_chains, conc - 1 if conc > 1 else 1))
    chains = list(range(n_chains))
    rng.shuffle(chains)
    by_writer: list[list] = [[] for _ in range(n_writers)]
    for k, c in enumerate(chains):
        by_writer[k % n_writers].extend(writes(c, n_keys))
    writer_gens = [
        gen.on_threads(lambda t, w=w: t == w, gen.stagger(0.01, gen.to_gen(ops)))
        for w, ops in enumerate(by_writer)
    ]

    def read_gen(test=None, ctx=None):
        return {"f": "read", "value": [rng.randrange(n_chains), None]}

    readers = gen.on_threads(
        lambda t: isinstance(t, int) and t >= n_writers,
        gen.stagger(0.02, gen.repeat(read_gen)),
    )
    return {
        "generator": gen.any_gen(*writer_gens, readers),
        "checker": checker(),
    }
