"""Lock discipline: the ``# guarded-by:`` annotation convention.

The CheckService is a multi-thread scheduler (admission threads + the
scheduler loop + the fast-path thread + watchdog workers + the graph
pool) whose invariants were previously enforced only by review.  This
analyzer makes the locking contract CHECKED:

Annotate a shared-mutable field at its ``__init__`` assignment::

    self._totals = {...}          # guarded-by: _lock
    self._inflight = []           # guarded-by: _lock [rw]
    self.queues = {...}           # guarded-by: caller

  * ``guarded-by: <lock>`` — every WRITE to the field (assignment,
    augmented assignment, ``del``, subscript store, or a mutating
    method call: ``append``/``pop``/``update``/…) anywhere in the class
    must be lexically inside ``with self.<lock>:``.
  * ``[rw]`` — reads are checked too (for fields where a stale read is
    itself a bug: iteration during mutation, check-then-act).
  * ``caller`` — the field is serialized by the OWNING object's lock
    (documented-external); nothing is checked locally.

``threading.Condition(self._lock)`` aliasing is understood: holding
``self._cond`` IS holding ``self._lock`` (same underlying lock), so
either satisfies a ``guarded-by: _lock`` (or ``_cond``) annotation.

Escape hatches, each lexical and explicit:

  * ``__init__``/``__del__`` bodies are exempt (construction
    happens-before publication);
  * a method that runs with the lock held by contract declares it with
    a ``# holds: <lock>`` comment on (or directly above) its ``def``;
  * ``# graftlint: disable=lock-guard`` on the flagged line.

The check is lexical on purpose: a write inside a nested function
defined under ``with self._lock:`` does NOT inherit the guard (the
closure runs later, on whatever thread calls it).
"""

from __future__ import annotations

import ast
import re

from jepsen_tpu.lint import Finding, SourceFile

RULES = ("lock-guard", "lock-unknown")

_GUARD_RE = re.compile(
    r"#\s*guarded-by:\s*(?P<lock>[A-Za-z_][\w]*)\s*(?P<rw>\[rw\])?"
)
_HOLDS_RE = re.compile(r"#\s*holds:\s*(?P<lock>[A-Za-z_][\w]*)")

#: method calls that mutate their receiver in place.
_MUTATORS = {
    "append", "extend", "insert", "remove", "pop", "popleft", "appendleft",
    "clear", "update", "setdefault", "add", "discard", "sort", "reverse",
    "__setitem__",
}

#: constructors recognised as locks for the annotation's target.
_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


class _Field:
    def __init__(self, name: str, lock: str, rw: bool, line: int):
        self.name = name
        self.lock = lock          # attr name, or "caller"
        self.rw = rw
        self.line = line


def _self_attr(node: ast.AST) -> str | None:
    """'x' for a ``self.x`` attribute node, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class LockChecker:
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []
        #: (rel, line) of guarded-by comments a field actually consumed
        self._consumed: set[tuple] = set()

    def run(self) -> list[Finding]:
        for node in ast.walk(self.src.tree):
            if isinstance(node, ast.ClassDef):
                self._check_class(node)
        self._flag_unattached()
        return self.findings

    def _flag_unattached(self) -> None:
        """A guarded-by comment nothing consumed is a DEAD annotation:
        the developer believes the field is checked, nothing is — fail
        loud instead of open."""
        for ln, c in self.src.comments.items():
            if not _GUARD_RE.search(c):
                continue
            if (self.src.rel, ln) in self._consumed:
                continue
            if self.src.is_disabled("lock-unknown", ln):
                continue
            self.findings.append(Finding(
                rule="lock-unknown", path=self.src.rel, line=ln,
                scope="mod-level", slug=f"unattached@{ln}",
                message=(
                    "guarded-by annotation is attached to no __init__ "
                    "field assignment (place it trailing the assignment, "
                    "trailing its last line, or directly above it) — as "
                    "written it checks NOTHING"
                ),
            ))

    # -- annotation collection --------------------------------------------

    def _collect(self, cls: ast.ClassDef):
        """(fields, lock_aliases, declared_locks) from ``__init__``."""
        fields: dict[str, _Field] = {}
        aliases: dict[str, set[str]] = {}   # lock name -> equivalence set
        declared: set[str] = set()
        init = next(
            (n for n in cls.body
             if isinstance(n, ast.FunctionDef) and n.name == "__init__"),
            None,
        )
        stmts = ast.walk(init) if init is not None else iter(())
        for stmt in stmts:
            if not isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                continue
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            attr = next((a for t in targets
                         if (a := _self_attr(t)) is not None), None)
            if attr is None:
                continue
            # lock declarations + Condition aliasing
            v = stmt.value
            if isinstance(v, ast.Call):
                ctor = v.func.attr if isinstance(v.func, ast.Attribute) \
                    else (v.func.id if isinstance(v.func, ast.Name) else "")
                if ctor in _LOCK_CTORS:
                    declared.add(attr)
                    wrapped = next(
                        (w for a in v.args
                         if (w := _self_attr(a)) is not None), None)
                    if ctor == "Condition" and wrapped is not None:
                        group = (aliases.get(wrapped)
                                 or aliases.get(attr) or set())
                        group |= {attr, wrapped}
                        for name in group:
                            aliases[name] = group
            # annotation placements accepted: trailing the assignment's
            # first line, trailing its LAST line (multi-line literals),
            # or on its OWN line directly above — a comment trailing the
            # PREVIOUS statement must not leak onto this field
            candidates = [stmt.lineno, stmt.end_lineno or stmt.lineno]
            above = stmt.lineno - 1
            if (0 < above <= len(self.src.lines)
                    and self.src.lines[above - 1].lstrip().startswith("#")):
                candidates.append(above)
            for ln in candidates:
                m = _GUARD_RE.search(self.src.comments.get(ln, ""))
                if m:
                    self._consumed.add((self.src.rel, ln))
                    fields[attr] = _Field(
                        attr, m.group("lock"), bool(m.group("rw")),
                        stmt.lineno,
                    )
                    break
        for name in declared:
            aliases.setdefault(name, {name})
        return fields, aliases, declared

    def _holds(self, fn: ast.FunctionDef) -> set[str]:
        """Locks a method declares held by contract (``# holds:``)."""
        out: set[str] = set()
        for ln in (fn.lineno, fn.lineno - 1):
            m = _HOLDS_RE.search(self.src.comments.get(ln, ""))
            if m:
                out.add(m.group("lock"))
        return out

    # -- per-class walk ----------------------------------------------------

    def _check_class(self, cls: ast.ClassDef) -> None:
        fields, aliases, declared = self._collect(cls)
        if not fields:
            return
        for f in fields.values():
            if f.lock != "caller" and f.lock not in declared:
                if not self.src.is_disabled("lock-unknown", f.line):
                    self.findings.append(Finding(
                        rule="lock-unknown", path=self.src.rel, line=f.line,
                        scope=f"{cls.name}.{f.name}", slug=f.lock,
                        message=(
                            f"guarded-by names `{f.lock}`, but __init__ "
                            "declares no such lock on self"
                        ),
                    ))
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name in ("__init__", "__del__"):
                continue
            self._check_method(cls, fn, fields, aliases)

    def _check_method(self, cls, fn, fields, aliases) -> None:
        held0 = frozenset(self._holds(fn))
        self._walk(fn.body, held0, cls, fn, fields, aliases, nested=False)

    def _walk(self, stmts, held: frozenset, cls, fn, fields, aliases,
              nested: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a closure runs later, on an arbitrary thread: it does
                # NOT inherit the lexically-enclosing guard (but a
                # `# holds:` on the nested def still applies)
                inner = frozenset(self._holds(stmt))
                self._walk(stmt.body, inner, cls, fn, fields, aliases,
                           nested=True)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                got = set(held)
                for item in stmt.items:
                    attr = _self_attr(item.context_expr)
                    if attr is not None:
                        got |= aliases.get(attr, {attr})
                for item in stmt.items:
                    self._exprs(item.context_expr, held, cls, fn, fields,
                                aliases)
                self._walk(stmt.body, frozenset(got), cls, fn, fields,
                           aliases, nested)
                continue
            # statement-level write detection
            self._stmt_accesses(stmt, held, cls, fn, fields, aliases)
            for body_attr in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, body_attr, None)
                if isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    self._walk(sub, held, cls, fn, fields, aliases, nested)
            for h in getattr(stmt, "handlers", []) or []:
                self._walk(h.body, held, cls, fn, fields, aliases, nested)
            for case in getattr(stmt, "cases", []) or []:  # match stmts
                self._walk(case.body, held, cls, fn, fields, aliases,
                           nested)

    def _stmt_accesses(self, stmt, held, cls, fn, fields, aliases) -> None:
        wrote: set[int] = set()  # id()s of attribute nodes already judged
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            flat: list[ast.expr] = []

            def _flatten(t: ast.expr) -> None:
                # tuple unpacking writes every element, recursively:
                # `a, (b, self.x) = ...` is a write to self.x
                if isinstance(t, (ast.Tuple, ast.List)):
                    for el in t.elts:
                        _flatten(el)
                else:
                    flat.append(t)

            for tgt in targets:
                _flatten(tgt)
            for tgt in flat:
                node = tgt
                if isinstance(node, ast.Starred):
                    node = node.value
                while isinstance(node, ast.Subscript):
                    node = node.value
                attr = _self_attr(node)
                if attr in fields:
                    wrote.add(id(node))
                    self._judge(fields[attr], "write", node, held, cls, fn,
                                aliases)
        if isinstance(stmt, ast.Delete):
            for tgt in stmt.targets:
                node = tgt
                while isinstance(node, ast.Subscript):
                    node = node.value
                attr = _self_attr(node)
                if attr in fields:
                    wrote.add(id(node))
                    self._judge(fields[attr], "write", node, held, cls, fn,
                                aliases)
        # mutating method calls + flagged reads, over every expression
        # hanging off this statement (but not nested statements)
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._exprs(child, held, cls, fn, fields, aliases,
                            skip=wrote)

    def _exprs(self, e: ast.expr, held, cls, fn, fields, aliases,
               skip: set | None = None) -> None:
        skip = skip or set()
        for node in ast.walk(e):
            if id(node) in skip:
                continue
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATORS:
                recv = node.func.value
                while isinstance(recv, ast.Subscript):
                    recv = recv.value
                attr = _self_attr(recv)
                if attr in fields:
                    skip.add(id(recv))
                    self._judge(fields[attr], "write", node, held, cls, fn,
                                aliases)
            elif isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if (attr in fields and fields[attr].rw
                        and isinstance(node.ctx, ast.Load)):
                    self._judge(fields[attr], "read", node, held, cls, fn,
                                aliases)

    def _judge(self, field: _Field, access: str, node, held, cls, fn,
               aliases) -> None:
        if field.lock == "caller":
            return  # documented-external: serialized by the owner
        ok_locks = aliases.get(field.lock, {field.lock})
        if held & ok_locks:
            return
        if self.src.is_disabled("lock-guard", node.lineno):
            return
        self.findings.append(Finding(
            rule="lock-guard", path=self.src.rel, line=node.lineno,
            scope=f"{cls.name}.{fn.name}", slug=f"{access}:{field.name}",
            message=(
                f"unguarded {access} of `self.{field.name}` (guarded-by: "
                f"{field.lock}) outside `with self.{field.lock}:`"
            ),
        ))


def check_source(src: SourceFile) -> list[Finding]:
    return LockChecker(src).run()
