"""graftlint — static analysis for the checker stack (stdlib ``ast`` only).

Three analyzers, run as a CI gate by ``tools/graftlint.py`` (stage in
``docker/bin/test``):

  * **trace discipline** (``lint.tracecheck``) — walks the call graph
    rooted at every ``jax.jit`` / ``shard_map`` / Pallas launch site and
    flags host-sync and retrace hazards inside traced code: ``.item()``
    / ``float()`` / ``np.asarray`` on traced values, Python ``if`` /
    ``while`` / ``for`` on traced values, ``time`` / ``random`` calls,
    implicit (weak-type-breaking) dtypes, jitted Python config args
    missing from ``static_argnames``, and launch entry points that
    bypass the padded-geometry helpers (each such site is a hidden
    compile bucket).
  * **lock discipline** (``lint.lockcheck``) — a ``# guarded-by:
    <lock>`` annotation convention on shared-mutable fields, with an
    intraprocedural checker that every write (and, for ``[rw]``
    fields, every read) of a guarded attribute is lexically inside
    ``with self.<lock>:`` — a real race detector for the CheckService
    scheduler threads.
  * **telemetry drift** (``lint.telemetry``) — statically collects
    every obs span/counter/gauge name and every metrics-registry
    series, and diffs them against the documented inventories
    (``obs/summary.py``, README, ``doc/tutorial.md``): undocumented or
    orphaned names fail the build.

Suppression is two-layer: an inline ``# graftlint: disable=<rule>``
comment on (or directly above) the flagged line, and a checked-in
triaged baseline (``.graftlint-baseline.json``) keyed on stable finding
keys (rule + file + enclosing scope + hazard slug — never line numbers,
so unrelated edits don't churn it).  Every baseline entry carries a
one-line ``why``.

The package imports nothing heavyweight (no jax, no numpy): linting the
whole repo is a sub-second pure-AST pass, cheap enough for tier-1.
"""

from __future__ import annotations

import json
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from pathlib import Path

__all__ = [
    "BASELINE_NAME", "Baseline", "Finding", "SourceFile", "load_baseline",
]

BASELINE_NAME = ".graftlint-baseline.json"

#: ``# graftlint: disable=rule1,rule2`` (or ``disable=all``).
_DISABLE_RE = re.compile(r"#\s*graftlint:\s*disable=([\w\-,*]+)")


@dataclass
class Finding:
    """One analyzer finding.

    ``key`` is the stable suppression identity: ``rule:path:scope:slug``
    (+ ``#n`` when the same hazard repeats in one scope) — line numbers
    stay out of it so baselines survive unrelated edits."""

    rule: str
    path: str          # repo-relative, forward slashes
    line: int
    scope: str         # enclosing qualname ("mod-level" when none)
    slug: str          # short stable hazard identifier
    message: str
    key: str = field(default="")

    def finalize_key(self, n: int = 0, total: int = 1) -> None:
        base = f"{self.rule}:{self.path}:{self.scope}:{self.slug}"
        # duplicates carry index AND total: a NEW identical hazard in
        # the scope changes every sibling's key, so the whole set
        # resurfaces unsuppressed (fail closed) instead of the newcomer
        # silently inheriting a baselined key
        self.key = base if total == 1 else f"{base}#{n}/{total}"

    def as_dict(self) -> dict:
        return {
            "rule": self.rule, "path": self.path, "line": self.line,
            "scope": self.scope, "message": self.message, "key": self.key,
        }

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def assign_keys(findings: list[Finding]) -> list[Finding]:
    """Finalize stable keys, disambiguating repeats of the same hazard
    inside one scope by occurrence order (source order) plus the repeat
    count — see ``Finding.finalize_key`` for why the count is in the
    key."""
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.slug))
    totals: dict[tuple, int] = {}
    for f in findings:
        base = (f.rule, f.path, f.scope, f.slug)
        totals[base] = totals.get(base, 0) + 1
    seen: dict[tuple, int] = {}
    for f in findings:
        base = (f.rule, f.path, f.scope, f.slug)
        n = seen.get(base, 0)
        f.finalize_key(n, totals[base])
        seen[base] = n + 1
    return findings


class SourceFile:
    """A parsed source file plus the comment-level facts the analyzers
    need (AST drops comments; one ``tokenize`` pass recovers them)."""

    def __init__(self, path: Path, rel: str, text: str | None = None):
        import ast

        self.path = path
        self.rel = rel
        self.text = (text if text is not None
                     else path.read_text(encoding="utf-8"))
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=rel)
        #: line -> comment text (without leading '#'), from tokenize
        self.comments: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:  # pragma: no cover — ast parsed, so
            pass                     # tokenize failing is near-impossible
        #: line -> set of disabled rules ("*" = all)
        self.disabled: dict[int, set[str]] = {}
        for ln, c in self.comments.items():
            m = _DISABLE_RE.search(c)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                if "all" in rules:
                    rules = {"*"}
                self.disabled[ln] = rules

    def is_disabled(self, rule: str, line: int) -> bool:
        """Inline suppression: a disable comment on the flagged line or
        the line directly above it."""
        for ln in (line, line - 1):
            rules = self.disabled.get(ln)
            if rules and ("*" in rules or rule in rules):
                return True
        return False

    def comment_on(self, node) -> str:
        """The trailing comment on a node's (first) line, '' when none —
        how ``# guarded-by:`` annotations are attached."""
        return self.comments.get(node.lineno, "")


@dataclass
class Baseline:
    """The checked-in triaged suppression file."""

    path: Path | None
    entries: dict[str, str]      # key -> one-line justification

    def split(self, findings: list[Finding]):
        """(unsuppressed, suppressed, stale_keys)."""
        live, supp = [], []
        hit: set[str] = set()
        for f in findings:
            if f.key in self.entries:
                hit.add(f.key)
                supp.append(f)
            else:
                live.append(f)
        stale = sorted(set(self.entries) - hit)
        return live, supp, stale


def load_baseline(path: Path | None) -> Baseline:
    if path is None or not path.is_file():
        return Baseline(path, {})
    data = json.loads(path.read_text(encoding="utf-8"))
    entries: dict[str, str] = {}
    for e in data.get("suppressions", []):
        entries[str(e["key"])] = str(e.get("why", ""))
    return Baseline(path, entries)
