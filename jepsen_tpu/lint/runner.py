"""The graftlint run: file discovery, analyzer dispatch, baseline.

``run_lint(repo_root)`` is the whole gate — ``tools/graftlint.py`` is a
thin argparse shell around it and ``tests/test_graftlint.py`` calls it
directly for the repo self-scan.

Scopes:

  * trace discipline runs over the compiled-kernel modules
    (``TRACE_FILES`` — the ``jax.jit``/``shard_map`` launch surface);
  * lock discipline runs over every package file (it is annotation-
    driven: files without ``# guarded-by:`` cost one regex scan);
  * telemetry drift reads every package file for emit sites and diffs
    against ``DOC_SURFACES``.
"""

from __future__ import annotations

import time
from pathlib import Path

from jepsen_tpu.lint import (
    BASELINE_NAME, Baseline, Finding, SourceFile, assign_keys,
    load_baseline,
)
from jepsen_tpu.lint import lockcheck, telemetry, tracecheck

#: the jit/shard_map/pallas launch surface (repo-relative).
TRACE_FILES = (
    "jepsen_tpu/ops/wgl.py",
    "jepsen_tpu/ops/hashing.py",
    "jepsen_tpu/ops/spill.py",
    "jepsen_tpu/ops/closure.py",
    "jepsen_tpu/parallel/batch.py",
    "jepsen_tpu/parallel/sharded.py",
)

#: documented-inventory surfaces for the telemetry-drift diff.
DOC_SURFACES = (
    "README.md",
    "doc/tutorial.md",
    "jepsen_tpu/obs/summary.py",
    "jepsen_tpu/obs/metrics.py",
)

ALL_RULES = tracecheck.RULES + lockcheck.RULES + telemetry.RULES


class LintResult:
    def __init__(self, findings, suppressed, stale, stages, files):
        self.findings: list[Finding] = findings    # unsuppressed
        self.suppressed: list[Finding] = suppressed
        self.stale_baseline: list[str] = stale
        self.stages: dict[str, float] = stages     # analyzer -> seconds
        self.files = files
        self.wall_s = sum(stages.values())

    @property
    def ok(self) -> bool:
        return not self.findings

    def as_dict(self) -> dict:
        return {
            "version": 1,
            "ok": self.ok,
            "files": self.files,
            "wall_s": round(self.wall_s, 3),
            "stages": {k: round(v, 3) for k, v in self.stages.items()},
            "findings": [f.as_dict() for f in self.findings],
            "suppressed": [f.as_dict() for f in self.suppressed],
            "stale_baseline": self.stale_baseline,
        }


def _load_sources(repo_root: Path) -> list[SourceFile]:
    out = []
    pkg = repo_root / "jepsen_tpu"
    for p in sorted(pkg.rglob("*.py")):
        rel = p.relative_to(repo_root).as_posix()
        try:
            out.append(SourceFile(p, rel))
        except SyntaxError:
            # a file that doesn't parse fails tier-1 long before lint;
            # don't double-report it here
            continue
    return out


def run_lint(repo_root: str | Path, *, rules: set[str] | None = None,
             baseline: Baseline | None = None) -> LintResult:
    """Run every analyzer over the repo; apply the baseline; return the
    result.  ``rules`` filters to a subset (rule-name match)."""
    repo_root = Path(repo_root)
    if baseline is None:
        baseline = load_baseline(repo_root / BASELINE_NAME)
    findings: list[Finding] = []
    stages: dict[str, float] = {}

    # parse is a real stage: ast.parse + tokenize over the whole
    # package, and the ledger's wall_s must see its creep too
    t0 = time.monotonic()
    sources = _load_sources(repo_root)
    by_rel = {s.rel: s for s in sources}
    stages["parse"] = time.monotonic() - t0

    t0 = time.monotonic()
    for rel in TRACE_FILES:
        src = by_rel.get(rel)
        if src is not None:
            findings.extend(tracecheck.check_source(src))
    stages["trace"] = time.monotonic() - t0

    t0 = time.monotonic()
    for src in sources:
        if "guarded-by:" in src.text:
            findings.extend(lockcheck.check_source(src))
    stages["lock"] = time.monotonic() - t0

    t0 = time.monotonic()
    doc_paths = [(repo_root / d, d) for d in DOC_SURFACES]
    findings.extend(telemetry.check(sources, doc_paths,
                                    repo_root / "jepsen_tpu"))
    stages["telemetry"] = time.monotonic() - t0

    assign_keys(findings)
    # baseline split runs on the UNFILTERED findings: a --rules subset
    # must not report the other rules' live suppressions as stale
    live, supp, stale = baseline.split(findings)
    if rules:
        live = [f for f in live if f.rule in rules]
        supp = [f for f in supp if f.rule in rules]
    return LintResult(live, supp, stale, stages, len(sources))
