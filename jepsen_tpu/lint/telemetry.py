"""Telemetry drift: emitted names vs the documented inventories.

Metric/doc drift has been patched by hand three PRs running (7–9) and a
double-counted mirror series survived review in PR 8 — the inventory is
exactly the kind of bookkeeping a machine should hold.  This analyzer:

  * statically collects every obs span/counter/gauge/event name (string
    literal first argument of ``obs.span`` / ``obs.span_event`` /
    ``obs.counter`` / ``obs.gauge`` / ``obs.event``) and every live
    metrics-registry series (``metrics.inc`` / ``metrics.set_gauge`` /
    ``metrics.observe``) across the package;
  * collects the *documented* inventory from the doc surfaces — README,
    ``doc/tutorial.md``, and the ``obs/summary.py`` + ``obs/metrics.py``
    tables/docstrings (backtick-quoted tokens, ``jepsen_tpu_*`` words,
    and ``family.*`` wildcards);
  * diffs the two:

      - ``telemetry-undocumented`` — an emitted name no doc surface
        mentions (operators can't find what they can't look up);
      - ``telemetry-orphan`` — a documented telemetry name nothing
        emits (the docs promise a series that doesn't exist).

Names are canonicalised before comparison (``serve.queue_depth`` ≡
``jepsen_tpu_serve_queue_depth`` ≡ ``serve_queue_depth_total``'s base),
so either spelling documents a series.  A ``family.*`` wildcard in a doc
documents every name under that prefix.  Dynamically-built names
(f-strings with a literal prefix) register their prefix, so members are
neither flagged undocumented nor their docs orphaned.

Module paths (``serve.health``, ``jepsen_tpu.ops.spill``) are excluded
from orphan detection by checking tokens against the package's actual
module tree.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from jepsen_tpu.lint import Finding, SourceFile

RULES = ("telemetry-undocumented", "telemetry-orphan")

#: method names that emit a telemetry series, by the qualifier they
#: must hang off (``obs.counter``, ``_metrics.inc``, ``obs_metrics.
#: set_gauge`` — alias imports are common).
_OBS_METHODS = {"span", "span_event", "counter", "gauge", "event"}
_METRIC_METHODS = {"inc", "set_gauge", "observe"}

#: telemetry name families (first dotted component / leading word) —
#: the namespace the orphan check patrols in the doc surfaces.
FAMILIES = {
    "serve", "fault", "frontier", "elle", "dedup", "ladder", "device",
    "checker", "phase", "wgl", "sharded", "durable", "provenance", "fleet",
    "stream",
}

_TOKEN_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_.*-]*[A-Za-z0-9_*]")
_BACKTICK_RE = re.compile(r"`([^`\n]+)`")


def canon(name: str) -> str:
    """Comparison key: ``serve.queue_depth`` ==
    ``jepsen_tpu_serve_queue_depth`` == its ``_total`` counter form."""
    n = re.sub(r"[^a-z0-9]+", "_", str(name).lower())
    if n.startswith("jepsen_tpu_"):
        n = n[len("jepsen_tpu_"):]
    n = n.strip("_")
    for suffix in ("_total", "_bucket", "_sum", "_count"):
        if n.endswith(suffix):
            n = n[: -len(suffix)]
            break
    return n


class EmitSite:
    def __init__(self, name: str, kind: str, path: str, line: int,
                 prefix: bool = False):
        self.name = name
        self.kind = kind
        self.path = path
        self.line = line
        self.prefix = prefix  # dynamically-built: name is a literal prefix


def collect_emitted(sources: list[SourceFile]) -> list[EmitSite]:
    out: list[EmitSite] = []
    for src in sources:
        if src.rel.endswith(("obs/__init__.py", "obs/metrics.py")):
            continue  # the emit API itself, not an instrumented call site
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            kind = _emit_kind(node)
            if kind is None:
                continue
            for name, prefix in _name_literals(node.args[0]):
                out.append(EmitSite(name, kind, src.rel, node.lineno,
                                    prefix=prefix))
    return out


def _name_literals(arg: ast.expr) -> list[tuple[str, bool]]:
    """(name, is_prefix) pairs a name argument can statically produce:
    a constant, both arms of a conditional expression, or the literal
    head of an f-string / ``"lit" + x`` concatenation (a prefix)."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return [(arg.value, False)]
    if isinstance(arg, ast.IfExp):
        return _name_literals(arg.body) + _name_literals(arg.orelse)
    if isinstance(arg, ast.JoinedStr) and arg.values \
            and isinstance(arg.values[0], ast.Constant):
        return [(str(arg.values[0].value), True)]
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add) \
            and isinstance(arg.left, ast.Constant) \
            and isinstance(arg.left.value, str):
        return [(arg.left.value, True)]
    return []


def _emit_kind(node: ast.Call) -> str | None:
    """'counter'/'inc'/… when this call is a telemetry emission."""
    f = node.func
    if not isinstance(f, ast.Attribute):
        return None
    method = f.attr
    qual = f.value
    qual_name = qual.id if isinstance(qual, ast.Name) else (
        qual.attr if isinstance(qual, ast.Attribute) else ""
    )
    if method in _OBS_METHODS and (
            qual_name == "obs" or qual_name.endswith("obs")):
        return method
    if method in _METRIC_METHODS and (
            qual_name.endswith("metrics") or qual_name == "REGISTRY"):
        return method
    return None


class DocToken:
    def __init__(self, token: str, path: str, line: int):
        self.token = token
        self.path = path
        self.line = line

    @property
    def wildcard(self) -> bool:
        return self.token.endswith(".*") or self.token.endswith("_*")


def collect_documented(doc_paths: list[tuple[Path, str]]) -> list[DocToken]:
    """Telemetry-name tokens from the doc surfaces: backtick spans in
    markdown (plus bare ``jepsen_tpu_*`` words — metric names in fenced
    blocks); for ``.py`` surfaces only string constants and docstrings
    count (code identifiers like local variables are not documentation).
    A token immediately followed by ``(`` is a function reference, not a
    telemetry name."""
    out: list[DocToken] = []
    for path, rel in doc_paths:
        try:
            text = path.read_text(encoding="utf-8")
        except OSError:
            continue
        if rel.endswith(".py"):
            try:
                tree = ast.parse(text, filename=rel)
            except SyntaxError:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str):
                    _span_tokens(node.value, rel, node.lineno, out)
            continue
        for i, ln in enumerate(text.splitlines(), start=1):
            for span in (_BACKTICK_RE.findall(ln)
                         + [t for t in ln.split() if "jepsen_tpu_" in t]):
                _span_tokens(span, rel, i, out)
    return out


def _span_tokens(span: str, rel: str, line: int,
                 out: list[DocToken]) -> None:
    for m in _TOKEN_RE.finditer(span):
        end = m.end()
        if end < len(span) and span[end] in "(=":
            continue  # `wgl.device_buffer_bytes()` / `frontier_budget_mb=`
            # — function and kwarg references, not telemetry names
        out.append(DocToken(m.group(0), rel, line))


def _module_paths(package_root: Path) -> set[str]:
    """Dotted module paths under the package (``serve.health``,
    ``obs.metrics``, …) — doc tokens matching one are code references,
    not telemetry names."""
    out: set[str] = set()
    for p in package_root.rglob("*.py"):
        rel = p.relative_to(package_root)
        parts = list(rel.parts[:-1])
        if rel.stem != "__init__":
            parts.append(rel.stem)
        for k in range(1, len(parts) + 1):
            out.add(".".join(parts[:k]))
    return out


def _namelike(tok: str) -> bool:
    """Does a doc token claim to be a telemetry name?"""
    if tok.endswith(".py") or not canon(tok):
        return False  # file references / bare prefixes
    if tok.startswith("jepsen_tpu_"):
        return True
    if "." in tok:
        return tok.split(".", 1)[0] in FAMILIES
    m = re.match(r"^(serve|fault|frontier|elle|dedup)_\w+$", tok)
    return bool(m)


def check(sources: list[SourceFile], doc_paths: list[tuple[Path, str]],
          package_root: Path) -> list[Finding]:
    emitted = collect_emitted(sources)
    docs = collect_documented(doc_paths)
    modules = _module_paths(package_root)

    emitted_canon = {canon(e.name) for e in emitted if not e.prefix}
    emitted_prefixes = {canon(e.name) for e in emitted if e.prefix}

    doc_canon: set[str] = set()
    doc_prefixes: set[str] = set()
    for t in docs:
        if t.wildcard:
            doc_prefixes.add(canon(t.token[:-1]))
        else:
            doc_canon.add(canon(t.token))

    findings: list[Finding] = []

    def _documented(name: str) -> bool:
        c = canon(name)
        if c in doc_canon:
            return True
        return any(c.startswith(p) for p in doc_prefixes if p)

    by_rel = {s.rel: s for s in sources}
    seen_undoc: set[str] = set()
    for e in emitted:
        if e.prefix:
            continue  # dynamic families are documented by wildcard or not
        c = canon(e.name)
        if _documented(e.name) or c in seen_undoc:
            continue
        src = by_rel.get(e.path)
        if src is not None and src.is_disabled("telemetry-undocumented",
                                               e.line):
            continue
        seen_undoc.add(c)
        findings.append(Finding(
            rule="telemetry-undocumented", path=e.path, line=e.line,
            scope=e.kind, slug=e.name,
            message=(
                f"{e.kind} `{e.name}` is emitted but appears in no doc "
                "surface (README / doc/tutorial.md / obs summary tables) "
                "— document it or delete it"
            ),
        ))

    def _is_module(tok: str) -> bool:
        # ≥2 components on purpose: "serve.health" is a module path,
        # but a bare package name must not exempt its whole family
        # ("serve" is a package AND the serve.* telemetry namespace)
        t = tok[len("jepsen_tpu."):] if tok.startswith("jepsen_tpu.") else tok
        parts = t.split(".")
        return any(".".join(parts[:k]) in modules
                   for k in range(2, min(len(parts), 3) + 1))

    seen_orphan: set[str] = set()
    for t in docs:
        if t.wildcard or not _namelike(t.token) or _is_module(t.token):
            continue
        c = canon(t.token)
        if c in emitted_canon or c in seen_orphan:
            continue
        if any(c.startswith(p) for p in emitted_prefixes if p):
            continue
        seen_orphan.add(c)
        findings.append(Finding(
            rule="telemetry-orphan", path=t.path, line=t.line,
            scope="doc", slug=t.token,
            message=(
                f"documented telemetry name `{t.token}` is emitted "
                "nowhere in the package — fix the doc or restore the "
                "series"
            ),
        ))
    return findings
