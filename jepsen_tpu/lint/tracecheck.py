"""Trace discipline: host-sync and retrace hazards inside traced code.

The hot paths are compiled JAX ladders; their perf collapses silently
when a launch re-traces (a hidden compile bucket) or when traced values
leak back to the host mid-program (an implicit device sync serializing
the pipeline).  The compile-cache hit counters only report AFTER the
chip hours are burned — this analyzer flags the hazards at review time.

Mechanics (pure ``ast``, no jax import): every ``jax.jit`` /
``jax.vmap`` / ``_platform.shard_map`` / ``pl.pallas_call`` site whose
target resolves to a module-local function becomes a *traced root*.
Parameters are **static** when named in ``static_argnames`` or bound to
host values via ``functools.partial``; every other parameter is
**tainted** (a tracer at trace time).  An intraprocedural taint walk —
descending into module-local callees with the call-site taint mapped
onto their parameters — then flags:

  * ``trace-host-sync`` — ``.item()`` / ``.tolist()`` /
    ``.block_until_ready()`` / ``float()``/``int()``/``bool()`` /
    ``np.*`` / ``jax.device_get`` applied to a tainted value;
  * ``trace-host-control`` — Python ``if`` / ``while`` / ``assert`` /
    ``for`` over a tainted value (each distinct host value seen here is
    a fresh trace; the fix is ``static_argnames`` for config args,
    ``lax.cond``/``jnp.where`` for data);
  * ``trace-nondeterminism`` — ``time.*`` / ``random.*`` /
    ``np.random.*`` inside traced code (baked in at trace time, stale
    ever after);
  * ``trace-implicit-dtype`` — ``jnp.zeros``/``full``/``array``/…
    without an explicit ``dtype``: the weak-type default shifts with
    operand promotion, and a shifted dtype is a new compile bucket;
  * ``trace-raw-geometry`` — a function calling a jit-runner factory
    (``batched_runner`` & co.) without deriving its shapes from the
    padded-geometry helpers (``bucket_geometry``/``padded_batch``/
    ``pad_*``): every distinct raw shape is a hidden compile bucket.

Functions whose callees can't be resolved module-locally are left
alone — the analyzer under-reports rather than guessing.
"""

from __future__ import annotations

import ast

from jepsen_tpu.lint import Finding, SourceFile

RULES = (
    "trace-host-sync", "trace-host-control", "trace-nondeterminism",
    "trace-implicit-dtype", "trace-raw-geometry",
)

#: attribute reads on a traced value that yield HOST (static) values.
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size"}

#: method calls that force a device sync / host transfer.
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}

#: builtins that coerce a traced value to host.
_HOST_COERCE = {"float", "int", "bool", "complex"}

#: dotted-name prefixes whose call results are traced values.
_TRACED_ROOTS = ("jnp.", "lax.", "jax.")

#: dotted-name prefixes that are nondeterministic at trace time.
_NONDET_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.")

#: jnp constructors and the positional index their dtype lands at.
_DTYPE_CTORS = {
    "jnp.zeros": 1, "jnp.ones": 1, "jnp.empty": 1, "jnp.array": 1,
    "jnp.asarray": 1, "jnp.full": 2, "jnp.arange": 3,
}

#: lax/jax combinators whose function-valued arguments are traced with
#: fully-tainted parameters.
_COMBINATORS = {
    "lax.scan", "lax.cond", "lax.while_loop", "lax.fori_loop", "lax.map",
    "lax.switch", "jax.lax.scan", "jax.lax.cond", "jax.lax.while_loop",
    "jax.lax.fori_loop", "jax.checkpoint", "jax.remat",
}

#: jit-runner factories (compiled-launch entry points) for the
#: raw-geometry audit ...
_RUNNER_FACTORIES = {
    "batched_runner", "exact_batched_runner", "async_runner",
    "greedy_runner", "lane_shard", "_sharded_runner",
}

#: ... and the padded-geometry helpers that legitimize their shapes.
_GEOMETRY_HELPERS = {
    "bucket_geometry", "padded_batch", "pad_packed", "pad_B", "pad_resume",
}

_MAX_DEPTH = 10


_DTYPE_CALL_RE = None  # compiled lazily (module import stays trivial)


def _explicit_dtype(node: ast.AST) -> bool:
    """Whether a value expression pins its own dtype: a ``jnp.uint32(x)``
    -style constructor or an ``.astype(...)`` call."""
    import re as _re

    global _DTYPE_CALL_RE
    if _DTYPE_CALL_RE is None:
        _DTYPE_CALL_RE = _re.compile(
            r"^(jnp|np|numpy)\.(u?int\d+|float\d+|bool_?|bfloat16)$"
        )
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name and _DTYPE_CALL_RE.match(name):
            return True
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype":
            return True
    return False


def _dotted(node: ast.AST) -> str | None:
    """'jnp.zeros' for Attribute/Name chains rooted at a Name; None for
    anything dynamic (method calls on expressions)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _const_names(node: ast.AST | None) -> set[str]:
    """static_argnames as a name set ('x' or ('x', 'y'))."""
    out: set[str] = set()
    if node is None:
        return out
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        out.add(node.value)
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.add(el.value)
    return out


def _param_names(fn: ast.FunctionDef) -> list[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args, *a.kwonlyargs)]


class _Target:
    """A resolved trace target: the function plus which of its params
    are STATIC at trace time (static_argnames + partial-bound)."""

    def __init__(self, fn: ast.FunctionDef, static: set[str]):
        self.fn = fn
        self.static = static

    @property
    def tainted(self) -> frozenset:
        return frozenset(p for p in _param_names(self.fn)
                         if p not in self.static)


class TraceChecker:
    def __init__(self, src: SourceFile):
        self.src = src
        self.findings: list[Finding] = []
        #: module-level (and class-level) defs by bare name
        self.fns: dict[str, ast.FunctionDef] = {}
        self._collect_fns(src.tree, prefix="")
        #: (qualname, tainted) -> returns_tainted, for memoized descent
        self._memo: dict[tuple, bool] = {}
        self._in_progress: set[tuple] = set()

    # -- indexing ----------------------------------------------------------

    def _collect_fns(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.fns.setdefault(child.name, child)
                child._qualname = prefix + child.name  # type: ignore
            elif isinstance(child, ast.ClassDef):
                self._collect_fns(child, prefix=child.name + ".")

    def _qual(self, fn: ast.FunctionDef) -> str:
        return getattr(fn, "_qualname", fn.name)

    # -- root discovery ----------------------------------------------------

    def run(self) -> list[Finding]:
        for target in self._find_roots():
            self._analyze(target.fn, target.tainted, depth=0)
        self._audit_geometry(self._jitted_names())
        return self.findings

    def _jitted_names(self) -> set[str]:
        """Module-level names bound to jit-wrapped callables (``_run =
        jax.jit(...)`` / ``x = functools.partial(jax.jit, ...)(f)``) —
        calling one IS a compiled launch, so the geometry audit treats
        them like runner factories."""
        out: set[str] = set()
        for stmt in self.src.tree.body:
            if not isinstance(stmt, ast.Assign) \
                    or not isinstance(stmt.value, ast.Call):
                continue
            call = stmt.value
            is_jit = (_dotted(call.func) in ("jax.jit", "jit")
                      or (isinstance(call.func, ast.Call)
                          and self._jit_static(call.func) is not None))
            if is_jit:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        out.add(tgt.id)
        return out

    def _find_roots(self) -> list[_Target]:
        roots: list[_Target] = []
        # decorated defs
        for fn in set(self.fns.values()):
            for deco in fn.decorator_list:
                static = self._jit_static(deco)
                if static is not None:
                    roots.append(_Target(fn, static))
        # call-site wrapping: jax.jit(f, ...) / shard_map(f, ...) /
        # jax.vmap(f) / pl.pallas_call(f) anywhere in the module
        for call in ast.walk(self.src.tree):
            if not isinstance(call, ast.Call):
                continue
            name = _dotted(call.func)
            wrap = None
            if name in ("jax.jit", "jit"):
                wrap = "jit"
            elif name and (name.endswith("shard_map")
                           or name.endswith("pallas_call")):
                wrap = "shard"
            elif name in ("jax.vmap", "vmap"):
                wrap = "vmap"
            # functools.partial(jax.jit, static_argnames=...)(f)
            elif (isinstance(call.func, ast.Call)
                  and self._jit_static(call.func) is not None):
                static0 = self._jit_static(call.func)
                for t in self._resolve_targets(call.args[0] if call.args
                                               else None, call):
                    t.static |= static0
                    roots.append(t)
                continue
            if wrap is None or not call.args:
                continue
            static0 = _const_names(next(
                (k.value for k in call.keywords
                 if k.arg in ("static_argnames", "static_argnums")), None))
            for t in self._resolve_targets(call.args[0], call):
                t.static |= static0
                roots.append(t)
        # a vmap nested directly inside a jit(...) shows up twice: once
        # via the jit (with its static_argnames) and once as a bare vmap
        # root with none — keep only the maximal static sets per fn
        out: list[_Target] = []
        for t in roots:
            if any(o.fn is t.fn and o.static > t.static for o in roots):
                continue
            if any(o.fn is t.fn and o.static == t.static and o is not t
                   for o in out):
                continue
            out.append(t)
        return out

    def _jit_static(self, node: ast.AST) -> set[str] | None:
        """None unless ``node`` IS a jit wrapper (bare ``jax.jit`` or
        ``[functools.]partial(jax.jit, static_argnames=...)``); else its
        static-argname set."""
        if _dotted(node) in ("jax.jit", "jit"):
            return set()
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            if fname in ("functools.partial", "partial") and node.args:
                if _dotted(node.args[0]) in ("jax.jit", "jit"):
                    return _const_names(next(
                        (k.value for k in node.keywords
                         if k.arg in ("static_argnames", "static_argnums")),
                        None))
        return None

    def _resolve_targets(self, node: ast.AST | None,
                         site: ast.AST) -> list[_Target]:
        """Resolve a function-valued expression to module-local defs,
        tracking partial-bound (static) parameters.  Unresolvable
        expressions resolve to nothing — under-report, never guess."""
        if node is None:
            return []
        if isinstance(node, ast.Name):
            fn = self._local_value(node, site) or self.fns.get(node.id)
            if isinstance(fn, ast.FunctionDef):
                return [_Target(fn, set())]
            if isinstance(fn, ast.AST):
                return self._resolve_targets(fn, site)
            return []
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            if fname in ("functools.partial", "partial") and node.args:
                inner = self._resolve_targets(node.args[0], site)
                for t in inner:
                    params = _param_names(t.fn)
                    bound = set(params[: len(node.args) - 1])
                    bound |= {k.arg for k in node.keywords if k.arg}
                    t.static |= bound
                return inner
            if fname in ("jax.vmap", "vmap") or (
                    fname and (fname.endswith("shard_map")
                               or fname.endswith("pallas_call"))):
                return (self._resolve_targets(node.args[0], site)
                        if node.args else [])
        return []

    def _local_value(self, name: ast.Name, site: ast.AST) -> ast.AST | None:
        """The expression last assigned to ``name`` in the function
        enclosing ``site`` (resolves ``core = functools.partial(...)``
        bindings inside runner factories)."""
        encl = self._enclosing_fn(site)
        if encl is None:
            return None
        value = None
        best_line = -1
        for stmt in ast.walk(encl):
            # SOURCE order, not ast.walk visit order: a later top-level
            # rebinding must shadow an earlier nested one
            if isinstance(stmt, ast.Assign) \
                    and best_line < stmt.lineno < site.lineno:
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name) and tgt.id == name.id:
                        value = stmt.value
                        best_line = stmt.lineno
        return value

    def _enclosing_fn(self, node: ast.AST) -> ast.FunctionDef | None:
        best = None
        for fn in set(self.fns.values()):
            if fn.lineno <= node.lineno <= (fn.end_lineno or fn.lineno):
                if best is None or fn.lineno > best.lineno:
                    best = fn
        return best

    # -- taint walk --------------------------------------------------------

    def _analyze(self, fn: ast.FunctionDef, tainted: frozenset,
                 depth: int) -> bool:
        """Walk ``fn`` with ``tainted`` parameter names; returns whether
        its return value is tainted.  Memoized per (fn, taint-set) so
        shared helpers report each hazard once."""
        key = (self._qual(fn), tainted)
        if key in self._memo:
            return self._memo[key]
        if key in self._in_progress or depth > _MAX_DEPTH:
            return True  # cycle/limit: assume traced, stop descending
        self._in_progress.add(key)
        env = set(tainted)
        returns = [False]
        for stmt in fn.body:
            self._stmt(stmt, env, fn, depth, returns)
        self._in_progress.discard(key)
        self._memo[key] = returns[0]
        return returns[0]

    def _taint_target(self, tgt: ast.expr, env: set) -> None:
        """Taint the names a tainted assignment actually writes: the
        root container of a subscript/attribute store, every element of
        a tuple — but never index expressions (``scratch[i] = x`` must
        not taint the host int ``i``)."""
        if isinstance(tgt, ast.Name):
            env.add(tgt.id)
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for el in tgt.elts:
                self._taint_target(el, env)
        elif isinstance(tgt, ast.Starred):
            self._taint_target(tgt.value, env)
        elif isinstance(tgt, (ast.Subscript, ast.Attribute)):
            self._taint_target(tgt.value, env)

    def _flag(self, rule: str, node: ast.AST, fn: ast.FunctionDef,
              slug: str, message: str) -> None:
        if self.src.is_disabled(rule, node.lineno):
            return
        self.findings.append(Finding(
            rule=rule, path=self.src.rel, line=node.lineno,
            scope=self._qual(fn), slug=slug, message=message,
        ))

    def _stmt(self, stmt: ast.stmt, env: set, fn: ast.FunctionDef,
              depth: int, returns: list) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # nested defs run only when called (combinators resolve)
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            t = self._expr(value, env, fn, depth) if value is not None \
                else False
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for tgt in targets:
                if isinstance(stmt, ast.AugAssign):
                    t = t or self._expr(tgt, env, fn, depth)
                if t:
                    self._taint_target(tgt, env)
                else:
                    self._expr(tgt, env, fn, depth)  # subscript hazards
            return
        if isinstance(stmt, (ast.If, ast.While)):
            if self._expr(stmt.test, env, fn, depth):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                names = sorted(
                    n.id for n in ast.walk(stmt.test)
                    if isinstance(n, ast.Name) and n.id in env
                )
                hint = (
                    "add it to static_argnames if it is host config, or "
                    "use lax.cond/jnp.where if it is data"
                )
                self._flag(
                    "trace-host-control", stmt, fn, f"{kind}:{','.join(names) or '?'}",
                    f"Python `{kind}` on traced value(s) "
                    f"{', '.join(names) or '<expr>'} re-traces per distinct "
                    f"host value — {hint}",
                )
            for s in (*stmt.body, *stmt.orelse):
                self._stmt(s, env, fn, depth, returns)
            return
        if isinstance(stmt, ast.For):
            it = stmt.iter
            hazard = False
            if (isinstance(it, ast.Call) and _dotted(it.func) == "range"
                    and any(self._expr(a, env, fn, depth) for a in it.args)):
                hazard = True
            elif self._expr(it, env, fn, depth):
                hazard = True
            if hazard:
                self._flag(
                    "trace-host-control", stmt, fn, "for",
                    "Python `for` over a traced value unrolls/re-traces — "
                    "use lax.scan/fori_loop, or make the bound static",
                )
                # loop targets are traced only when the iterable is —
                # `for i in range(4)` yields host ints
                for n in ast.walk(stmt.target):
                    if isinstance(n, ast.Name):
                        env.add(n.id)
            for s in (*stmt.body, *stmt.orelse):
                self._stmt(s, env, fn, depth, returns)
            return
        if isinstance(stmt, ast.Assert):
            if self._expr(stmt.test, env, fn, depth):
                self._flag(
                    "trace-host-control", stmt, fn, "assert",
                    "`assert` on a traced value forces a host sync — use "
                    "checkify or drop it from traced code",
                )
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None and self._expr(stmt.value, env, fn,
                                                     depth):
                returns[0] = True
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr, env, fn, depth)
            for s in stmt.body:
                self._stmt(s, env, fn, depth, returns)
            return
        if isinstance(stmt, ast.Try):
            for s in (*stmt.body, *stmt.orelse, *stmt.finalbody):
                self._stmt(s, env, fn, depth, returns)
            for h in stmt.handlers:
                for s in h.body:
                    self._stmt(s, env, fn, depth, returns)
            return
        if isinstance(stmt, ast.Expr):
            self._expr(stmt.value, env, fn, depth)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, env, fn, depth)
            elif isinstance(child, ast.stmt):
                self._stmt(child, env, fn, depth, returns)

    def _expr(self, e: ast.expr, env: set, fn: ast.FunctionDef,
              depth: int) -> bool:
        if isinstance(e, ast.Name):
            return e.id in env
        if isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Lambda):
            return False  # bodies run via combinators, resolved there
        if isinstance(e, ast.Attribute):
            if e.attr in _STATIC_ATTRS:
                self._expr(e.value, env, fn, depth)
                return False  # .shape/.dtype of a tracer are host values
            return self._expr(e.value, env, fn, depth)
        if isinstance(e, ast.Call):
            return self._call(e, env, fn, depth)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.DictComp,
                          ast.GeneratorExp)):
            # taint the generator targets BEFORE walking the element,
            # or hazards inside the element go unseen
            out = False
            for gen in e.generators:
                if self._expr(gen.iter, env, fn, depth):
                    out = True
                    for n in ast.walk(gen.target):
                        if isinstance(n, ast.Name):
                            env.add(n.id)
                for cond in gen.ifs:
                    self._expr(cond, env, fn, depth)
            for part in ((e.key, e.value) if isinstance(e, ast.DictComp)
                         else (e.elt,)):
                out = self._expr(part, env, fn, depth) or out
            return out
        out = False
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.expr):
                out = self._expr(child, env, fn, depth) or out
        return out

    def _call(self, call: ast.Call, env: set, fn: ast.FunctionDef,
              depth: int) -> bool:
        name = _dotted(call.func)
        arg_nodes = list(call.args) + [k.value for k in call.keywords]
        arg_taints = [self._expr(a, env, fn, depth) for a in arg_nodes]
        any_tainted = any(arg_taints)

        # method call on a traced receiver (covers bare names too:
        # `x.item()` resolves to the dotted "x.item", but x is tainted)
        if isinstance(call.func, ast.Attribute):
            recv_tainted = self._expr(call.func.value, env, fn, depth)
            if recv_tainted:
                if call.func.attr in _SYNC_METHODS:
                    self._flag(
                        "trace-host-sync", call, fn, call.func.attr,
                        f"`.{call.func.attr}()` on a traced value is a "
                        "device sync inside traced code",
                    )
                    return False
                return True

        # nondeterminism: baked in at trace time regardless of args
        if name and (name.startswith(_NONDET_PREFIXES)
                     or name in ("time", "perf_counter")):
            self._flag(
                "trace-nondeterminism", call, fn, name,
                f"`{name}()` inside traced code is evaluated once at "
                "trace time and baked into the program — hoist it to the "
                "host caller",
            )
            return False

        # host coercion / sync
        if name in _HOST_COERCE and any_tainted:
            self._flag(
                "trace-host-sync", call, fn, name,
                f"`{name}()` on a traced value blocks on the device "
                "(implicit sync) — keep it as an array op, or make the "
                "operand static",
            )
            return False
        if name == "jax.device_get" and any_tainted:
            self._flag(
                "trace-host-sync", call, fn, "device_get",
                "`jax.device_get` inside traced code syncs the device — "
                "move it to the host caller",
            )
            return False
        if (name and (name.startswith(("np.", "numpy."))
                      and not name.startswith(_NONDET_PREFIXES))
                and any_tainted):
            self._flag(
                "trace-host-sync", call, fn, name,
                f"`{name}` on a traced value forces a host transfer — "
                "use the jnp equivalent inside traced code",
            )
            return False

        # implicit dtype on jnp constructors
        if name in _DTYPE_CTORS:
            pos = _DTYPE_CTORS[name]
            has_dtype = (len(call.args) > pos
                         or any(k.arg == "dtype" for k in call.keywords))
            if not has_dtype and name in ("jnp.full", "jnp.array",
                                          "jnp.asarray"):
                # an explicitly-dtyped fill/source value carries the
                # dtype itself: jnp.full(shape, jnp.uint32(x))
                vpos = pos - 1
                if vpos < len(call.args):
                    has_dtype = _explicit_dtype(call.args[vpos])
            if not has_dtype:
                self._flag(
                    "trace-implicit-dtype", call, fn, name,
                    f"`{name}` without an explicit dtype weak-types by "
                    "promotion — a shifted operand dtype silently becomes "
                    "a new compile bucket; pass dtype=",
                )
            return True

        # combinators trace their function-valued args with full taint
        if name in _COMBINATORS:
            for a in call.args:
                for t in self._resolve_targets(a, call):
                    self._analyze(t.fn, t.tainted | frozenset(), depth + 1)
            return True

        if name and name.startswith(_TRACED_ROOTS):
            return True
        if name == "len":
            return False  # length of a traced array is static shape info

        # module-local descent: map call-site taint onto callee params
        if name and "." not in name and name in self.fns:
            callee = self.fns[name]
            params = _param_names(callee)
            callee_taint: set[str] = set()
            for i, a in enumerate(call.args):
                if i < len(params) and arg_taints[i]:
                    callee_taint.add(params[i])
            for k, kt in zip(call.keywords,
                             arg_taints[len(call.args):]):
                if k.arg and kt:
                    callee_taint.add(k.arg)
            return self._analyze(callee, frozenset(callee_taint), depth + 1)

        if isinstance(call.func, ast.expr):
            self._expr(call.func, env, fn, depth)
        return any_tainted

    # -- raw-geometry audit ------------------------------------------------

    def _audit_geometry(self, jitted_names: set[str]) -> None:
        """Every function that calls a jit-runner factory (or a module-
        level jitted callable) must also call a padded-geometry helper
        (or a local ``pad*`` helper) — a launch whose shapes come
        straight from input sizes mints one compile bucket per distinct
        size."""
        for fn in set(self.fns.values()):
            factory_calls: list[ast.Call] = []
            has_geometry = False
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                callee = _dotted(node.func)
                tail = callee.rsplit(".", 1)[-1] if callee else None
                if tail in _RUNNER_FACTORIES or tail in jitted_names:
                    factory_calls.append(node)
                if tail and (tail in _GEOMETRY_HELPERS
                             or tail.startswith("pad")):
                    has_geometry = True
            if not factory_calls or has_geometry:
                continue
            # one finding per (function, launch callee): the fix — or
            # the triage — is per launch path, not per call expression
            seen: set[str] = set()
            for call in factory_calls:
                callee = (_dotted(call.func) or "?").rsplit(".", 1)[-1]
                if callee in seen:
                    continue
                seen.add(callee)
                self._flag(
                    "trace-raw-geometry", call, fn, callee,
                    f"`{callee}` launch site in a function that never "
                    "touches the padded-geometry helpers "
                    "(bucket_geometry/padded_batch/pad_*) — raw shapes "
                    "mint a hidden compile bucket per distinct size",
                )


def check_source(src: SourceFile) -> list[Finding]:
    out = TraceChecker(src).run()
    # one root may reach a helper under several taint sets; the hazard
    # is the same source line — report it once
    seen: set[tuple] = set()
    uniq: list[Finding] = []
    for f in out:
        k = (f.rule, f.path, f.line, f.slug)
        if k not in seen:
            seen.add(k)
            uniq.append(f)
    return uniq
