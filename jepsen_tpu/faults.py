"""Fault tolerance for the checker pipeline: retry, degrade, deadline.

Long accelerator jobs die — preemption, OOM, transient XLA runtime
errors, dead worker pools — and a multi-minute checker ladder must
survive them the way a production training job does.  This module is the
shared policy layer the launch sites thread through:

  * ``error_kind(e)`` classifies an exception for the retry policy:
    ``"oom"`` (RESOURCE_EXHAUSTED — halve the work and relaunch),
    ``"transient"`` (backoff and retry the same launch), or ``None``
    (not a recognized device fault — the caller re-raises, a code bug
    must stay loud).
  * ``call_with_retry(fn, ctx)`` runs one device launch under that
    policy: transient faults retry with exponential backoff (env knobs
    below); OOM and still-failing launches raise ``LaunchFailure`` for
    the CALLER to handle — ``parallel.batch`` halves the sub-batch on
    OOM and degrades only the failing lanes to ``"unknown"``;
    ``ops.wgl.chunked_analysis`` degrades the single history.  Retries
    and degradations all emit ``fault.*`` telemetry (the "faults" table
    in telemetry.json).
  * ``Deadline`` is the wall-clock check budget (CLI
    ``--check-deadline``, opts key ``"deadline"`` threaded through
    ``checker.check_safe``/``Compose``): stage boundaries poll
    ``expired()``; on expiry the ladder checkpoints
    (jepsen_tpu.store.checkpoint) and marks the remaining packs
    ``unknown`` instead of running past the budget.

Env knobs (read per call so tests and operators can adjust live):

  JEPSEN_TPU_LAUNCH_RETRIES   transient retries per launch (default 3)
  JEPSEN_TPU_RETRY_BASE_S     first backoff delay (default 0.25)
  JEPSEN_TPU_RETRY_MAX_S      backoff cap (default 8.0)

``INJECT`` is the fault-injection seam: when set to a callable it runs
as ``INJECT(ctx, attempt)`` before every launch attempt and may raise a
synthetic fault — tests and tools/chaos_check.py drive OOM/transient
scenarios through it without monkeypatching kernel internals.  For
LIVE-service chaos use ``inject_scope`` (thread-safe install/restore,
composable nesting) with a ``seeded_injector`` (a deterministic
per-seed fault schedule) instead of assigning ``INJECT`` directly —
assignment is a process-global mutation two concurrent harnesses would
clobber.

The serving layer's hung-launch watchdog derives its per-launch
wall-clock caps from ``launch_seconds_ewma()`` — an EWMA over every
recorded device-launch wall time, fed by ``record_launch_seconds``
at the ladder's instrumented launch sites (``parallel.batch._launch``).

Import-light by design (stdlib + obs only): the spawn-based confirmation
workers and the control layer can import it without dragging in jax.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import threading
import time
from typing import Callable, Mapping

from jepsen_tpu import obs
from jepsen_tpu.obs import metrics as _metrics

#: fault-injection hook: ``INJECT(ctx, attempt)`` runs before each launch
#: attempt and may raise (classified exactly like a real launch error).
#: Beyond launch sites, the DURABLE-WRITE seams also announce themselves
#: here — ``store._atomic_write`` (ctx ``what="store.atomic_write"``,
#: ``step`` in post-tmp / post-fsync / post-rename / pre-dir-fsync) and
#: the perf-ledger append (``what="ledger.append"``) — so the
#: crashpoint audit (tools/crashpoint.py) can die at any write step.
#: Injectors targeting launches must FILTER on ctx ``what``: a raise in
#: a write seam faults an operation no retry policy covers.
INJECT: Callable[[dict, int], None] | None = None


class CrashPoint(BaseException):
    """A simulated process death at a durable-write step.

    Raised by a crashpoint injector inside a write seam;
    ``store._atomic_write`` performs NO cleanup for it (unlike ordinary
    exceptions, whose tmp file is unlinked), so the on-disk state is
    exactly what a SIGKILL at that step leaves — tmp present, target
    old.  A ``BaseException`` on purpose: the best-effort ``except
    Exception`` guards around checkpoint/journal writes must not
    swallow a simulated death, it must unwind to the crashpoint
    harness like the real signal would."""

    def __init__(self, step: str, path: str = "?"):
        self.step = step
        self.path = path
        super().__init__(f"simulated crash at {step} writing {path}")

#: serializes INJECT install/restore (inject_scope); RLock so a scope
#: may nest inside another on the same thread.
_INJECT_LOCK = threading.RLock()

#: the live inject_scope entries, oldest first.  The installed INJECT
#: hook is REBUILT from this stack on every enter/exit, and an exiting
#: scope removes only ITS OWN entry — so overlapping scopes on
#: different threads (the concurrent-harness case) tear down in any
#: order without disabling each other or resurrecting a dead injector
#: (a naive save/restore pairing breaks exactly there).
_INJECT_STACK: list = []
#: whatever was assigned to INJECT directly before the first scope
#: entered (legacy call sites); restored when the last scope exits.
_INJECT_BASE: Callable[[dict, int], None] | None = None


def _rebuild_inject() -> None:
    entries = (
        [(_INJECT_BASE, True)] if _INJECT_BASE is not None else []
    ) + list(_INJECT_STACK)
    start = 0
    for i, (_fn, comp) in enumerate(entries):
        if not comp:
            start = i  # a shadowing scope hides everything before it
    chain = [fn for fn, _comp in entries[start:]]
    if not chain:
        _set_inject(None)
    elif len(chain) == 1:
        _set_inject(chain[0])
    else:
        def chained(ctx, attempt, _fns=tuple(chain)):
            for f in _fns:
                f(ctx, attempt)
        _set_inject(chained)


@contextlib.contextmanager
def inject_scope(injector: Callable[[dict, int], None], *,
                 compose: bool = True):
    """Install a fault injector for the duration of the scope —
    thread-safe and re-entrant, unlike assigning ``INJECT`` directly.

    With ``compose`` (the default) injectors from enclosing scopes keep
    running FIRST, then this one: scopes stack, so a chaos harness can
    layer a poison schedule over a transient/OOM schedule.
    ``compose=False`` shadows the earlier injectors for the scope
    instead.  Each exit removes only its own layer and the remaining
    stack is re-composed — overlapping scopes on different threads may
    therefore exit in any order, and a pre-scope direct ``INJECT``
    assignment is restored once the last scope exits (even if a body
    raises)."""
    entry = [injector, bool(compose)]  # list: unique identity per enter
    global _INJECT_BASE
    with _INJECT_LOCK:
        if not _INJECT_STACK:
            _INJECT_BASE = INJECT
        _INJECT_STACK.append(entry)
        _rebuild_inject()
    try:
        yield injector
    finally:
        with _INJECT_LOCK:
            for i in range(len(_INJECT_STACK) - 1, -1, -1):
                if _INJECT_STACK[i] is entry:
                    del _INJECT_STACK[i]
                    break
            if not _INJECT_STACK:
                _set_inject(_INJECT_BASE)
                _INJECT_BASE = None
            else:
                _rebuild_inject()


def _set_inject(fn) -> None:
    global INJECT
    INJECT = fn


def seeded_injector(
    seed: int,
    *,
    transient_rate: float = 0.25,
    oom_rate: float = 0.15,
    what: str | None = None,
) -> Callable[[dict, int], None]:
    """A DETERMINISTIC randomized fault schedule for ``inject_scope``.

    Decisions are a pure function of ``(seed, ctx identity, attempt)``
    — a hash, not a shared RNG stream — so the same seed reproduces the
    same fault plan even when launches interleave across service
    threads (a shared ``random.Random`` would make the schedule depend
    on thread timing).  First attempts fail transiently at
    ``transient_rate`` (retries then succeed: the attempt number is in
    the hash); multi-lane first attempts OOM at ``oom_rate`` on top
    (exercising the halving path).  ``what`` restricts the schedule to
    launch sites whose ctx ``what`` starts with it (e.g. ``"ladder."``
    keeps service-level seams like ``serve.batch`` clean for a
    composed poison injector)."""

    def _roll(ctx: Mapping, attempt: int) -> float:
        key = "|".join((
            str(seed), str(ctx.get("what")), str(ctx.get("stage")),
            str(ctx.get("engine")), str(ctx.get("capacity")),
            str(ctx.get("lanes")), str(attempt),
        ))
        h = hashlib.sha256(key.encode()).digest()
        return int.from_bytes(h[:8], "big") / 2.0**64

    def inject(ctx, attempt):
        w = str(ctx.get("what") or "")
        if what is not None and not w.startswith(what):
            return
        if what is None and w.startswith(("store.", "ledger.")):
            # The durable-write seams are crashpoint territory: a
            # rate-based transient/OOM schedule raising inside
            # _atomic_write / the ledger append would fault writes no
            # retry policy covers (a checkpoint save is best-effort, a
            # journal write is counted-and-swallowed — either way the
            # injected fault would test nothing this schedule means to).
            # Target them explicitly via ``what=`` to opt in.
            return
        if attempt != 0:
            return  # retries always succeed: the plan tests recovery
        r = _roll(ctx, attempt)
        if r < transient_rate:
            raise RuntimeError(
                "INTERNAL: injected transient fault (seeded_injector "
                f"seed={seed})"
            )
        if r < transient_rate + oom_rate and int(ctx.get("lanes") or 0) > 1:
            raise RuntimeError(
                "RESOURCE_EXHAUSTED: injected OOM (seeded_injector "
                f"seed={seed})"
            )

    return inject


#: launch-wall EWMA (record_launch_seconds / launch_seconds_ewma): the
#: smoothed device-launch wall time the serving layer's hung-launch
#: watchdog derives its per-launch caps from.  None until the first
#: launch is recorded.
_LAUNCH_EWMA_ALPHA = 0.2
_launch_ewma_s: float | None = None
_retry_launch_count = 0
_launch_ewma_lock = threading.Lock()


def record_launch_seconds(seconds: float, *, retry: bool = False) -> None:
    """Fold one device launch's wall clock into the process-wide launch
    EWMA (called by the ladder's instrumented launch wrapper).

    ``retry=True`` marks an OOM-halved or spill-retry sub-launch: those
    run at a REDUCED size, so folding them in would drag the EWMA down
    and make the watchdog's ``factor × EWMA`` wall caps trip HEALTHY
    full-size launches right after an OOM episode (round-8 satellite).
    Retry launches are counted (``retry_launch_count``) but excluded
    from the baseline."""
    global _launch_ewma_s, _retry_launch_count
    with _launch_ewma_lock:
        if retry:
            _retry_launch_count += 1
            return
        if _launch_ewma_s is None:
            _launch_ewma_s = float(seconds)
        else:
            _launch_ewma_s = (
                (1 - _LAUNCH_EWMA_ALPHA) * _launch_ewma_s
                + _LAUNCH_EWMA_ALPHA * float(seconds)
            )


def launch_seconds_ewma() -> float | None:
    """The smoothed per-launch wall clock (None before any launch).
    Fed only by FULL-SIZE launches — see record_launch_seconds."""
    with _launch_ewma_lock:
        return _launch_ewma_s


def retry_launch_count() -> int:
    """How many reduced-size (OOM-halved / spill-retry) launches were
    excluded from the EWMA baseline (tests and telemetry)."""
    with _launch_ewma_lock:
        return _retry_launch_count


# ---------------------------------------------------------------------------
# OOM spill policy: free device memory before shrinking the work
# ---------------------------------------------------------------------------

#: registered spillers, called in order by try_oom_spill.  A spiller
#: takes the launch ctx and returns truthy when it actually freed
#: something (e.g. parallel.batch registers ops.wgl.evict_runner_caches
#: on non-CPU backends).
_OOM_SPILLERS: list[Callable[[Mapping], object]] = []
_OOM_SPILLERS_LOCK = threading.Lock()


def register_oom_spiller(fn: Callable[[Mapping], object]) -> None:
    """Register a device-memory spiller for the OOM policy (idempotent
    per function object).  Spillers must be safe to call from any
    launch site and return truthy iff they freed device memory."""
    with _OOM_SPILLERS_LOCK:
        if fn not in _OOM_SPILLERS:
            _OOM_SPILLERS.append(fn)


def unregister_oom_spiller(fn: Callable[[Mapping], object]) -> None:
    with _OOM_SPILLERS_LOCK:
        if fn in _OOM_SPILLERS:
            _OOM_SPILLERS.remove(fn)


def try_oom_spill(ctx: Mapping | None = None) -> bool:
    """The OOM ladder's FIRST rung (round 8): before halving the
    sub-batch — which costs verdict lanes and probes the fault again —
    ask the registered spillers to free device memory so the SAME
    launch can retry at full size.  Returns True iff any spiller
    reported freeing something; the caller then retries once and only
    falls back to halving if the retry OOMs too.  A broken spiller is
    swallowed: the spill rung is an optimization, halving still
    backstops it."""
    ctx = dict(ctx or {})
    with _OOM_SPILLERS_LOCK:
        spillers = list(_OOM_SPILLERS)
    freed = False
    for fn in spillers:
        try:
            freed = bool(fn(ctx)) or freed
        except Exception:  # noqa: BLE001 — see docstring
            continue
    if freed:
        # mirrors to /metrics as jepsen_tpu_fault_oom_spill_total
        obs.counter("fault.oom.spill", what=str(ctx.get("what") or "launch"))
    return freed

#: substrings that mark an exception as out-of-memory (halve, don't retry
#: the same shape — the same launch would OOM again).
OOM_MARKERS = (
    "RESOURCE_EXHAUSTED",
    "Out of memory",
    "out of memory",
    "OOM",
    "Attempting to allocate",
)

#: substrings that mark an exception as transient (retry with backoff:
#: tunnel drops, preempted/restarted workers, momentary runtime errors).
TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "ABORTED",
    "INTERNAL",
    "DEADLINE_EXCEEDED",
    "worker process crashed",
    "restarted",
    "Socket closed",
    "connection reset",
    "failed to connect",
    "Unable to initialize backend",
)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def describe(e: BaseException) -> str:
    """One-line, bounded rendering of an exception for ``:cause`` strings
    and telemetry attributes."""
    s = f"{type(e).__name__}: {e}"
    return s if len(s) <= 300 else s[:297] + "..."


def error_kind(e: BaseException) -> str | None:
    """Classify ``e`` for the launch retry policy (module doc).

    Only RuntimeError/OSError lineages qualify — XlaRuntimeError (and
    jax's JaxRuntimeError alias) subclass RuntimeError, and transport
    errors ride OSError — so a ValueError from bad arguments is never
    silently retried or degraded."""
    if not isinstance(e, (RuntimeError, OSError)):
        return None
    msg = f"{type(e).__name__}: {e}"
    if any(m in msg for m in OOM_MARKERS):
        return "oom"
    if any(m in msg for m in TRANSIENT_MARKERS):
        return "transient"
    return None


class LaunchFailure(Exception):
    """A device launch failed under the retry policy.

    ``kind`` is ``"oom"`` (raised immediately — retrying the same shape
    would OOM again; the caller halves the work) or ``"transient"`` (the
    backoff retries are exhausted; the caller degrades the affected
    lanes).  ``cause`` is the final underlying exception."""

    def __init__(self, kind: str, cause: BaseException, what: str = "launch"):
        self.kind = kind
        self.cause = cause
        self.what = what
        super().__init__(f"{what} failed ({kind}): {describe(cause)}")


def call_with_retry(
    fn: Callable,
    ctx: Mapping | None = None,
    *,
    retries: int | None = None,
    base_s: float | None = None,
    max_s: float | None = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Run one device launch under the retry policy (module doc).

    ``ctx`` annotates telemetry and the injection hook; recognized keys:
    ``what`` (telemetry label), ``stage``/``engine``/``capacity``/
    ``lanes`` (whatever the call site knows).  Returns ``fn()``'s value;
    raises ``LaunchFailure`` on OOM or exhausted retries, and re-raises
    unclassified exceptions untouched."""
    ctx = dict(ctx or {})
    what = str(ctx.get("what") or "launch")
    retries = _env_int("JEPSEN_TPU_LAUNCH_RETRIES", 3) if retries is None else retries
    base_s = _env_float("JEPSEN_TPU_RETRY_BASE_S", 0.25) if base_s is None else base_s
    max_s = _env_float("JEPSEN_TPU_RETRY_MAX_S", 8.0) if max_s is None else max_s
    attempt = 0
    while True:
        try:
            hook = INJECT
            if hook is not None:
                hook(ctx, attempt)
            return fn()
        except Exception as e:  # noqa: BLE001 — classified below
            kind = error_kind(e)
            if kind is None:
                raise
            # Live fault metrics (obs.metrics, the /metrics endpoint):
            # exhausted launches get a series labeled by kind + launch
            # site, so an operator watching a serving process sees WHERE
            # faults cluster without opening any run's telemetry.
            # (Retries need no explicit series — the obs.counter below
            # already mirrors as fault_launch_retry_total; a second
            # explicit one would double-count the same event.)
            if kind == "oom":
                _metrics.inc("fault.launch_failures", kind="oom", what=what)
                raise LaunchFailure("oom", e, what) from e
            if attempt >= retries:
                _metrics.inc("fault.launch_failures", kind="transient",
                             what=what)
                raise LaunchFailure("transient", e, what) from e
            delay = min(max_s, base_s * (2 ** attempt))
            attempt += 1
            obs.counter(
                "fault.launch.retry", what=what, attempt=attempt,
                delay_s=round(delay, 3), error=describe(e),
                **{k: ctx[k] for k in ("stage", "engine", "capacity", "lanes")
                   if k in ctx},
            )
            sleep(delay)


class Deadline:
    """A wall-clock check budget, shared by every checker in a compose.

    Constructed once (``checker.resolve_opts`` wraps the raw
    ``"check-deadline"`` seconds value exactly once per check) so that
    parallel checkers and nested engines all see ONE budget."""

    __slots__ = ("seconds", "_t0")

    def __init__(self, seconds: float, *, start: float | None = None):
        self.seconds = float(seconds)
        self._t0 = time.monotonic() if start is None else start

    @classmethod
    def coerce(cls, v) -> "Deadline | None":
        """None passes through; a Deadline passes through; a number
        becomes a fresh Deadline starting now."""
        if v is None or isinstance(v, cls):
            return v
        return cls(float(v))

    def remaining(self) -> float:
        return self.seconds - (time.monotonic() - self._t0)

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def clamp(self, timeout: float) -> float:
        """Bound a wait by the budget: min(timeout, remaining), floored
        at 0 — the serving layer's blocking HTTP result waits must never
        outlive the request's own deadline (web.py POST /check wait)."""
        return max(0.0, min(timeout, self.remaining()))

    def __repr__(self):
        return f"Deadline({self.seconds}s, {self.remaining():.3f}s left)"
