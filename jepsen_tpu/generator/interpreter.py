"""Interpreter: executes a generator against real clients and a nemesis.

Mirrors ``jepsen.generator.interpreter`` (reference:
jepsen/src/jepsen/generator/interpreter.clj): one OS thread per worker
(concurrency client workers + the nemesis), each fed by a 1-slot input
queue, all completing into a shared completion queue; a single-threaded
scheduling loop asks the generator for ops, dispatches them at their
scheduled times, and folds completions back into the generator state
(interpreter.clj:181-310).

Key semantics preserved:

  * completions are polled *before* new ops — they're latency-sensitive
    (interpreter.clj:206-241)
  * any Throwable from a client becomes an :info completion with an
    "indeterminate" error — the op may or may not have taken effect
    (interpreter.clj:142-157)
  * a client thread whose op crashed gets a fresh process id, and its
    client is close!/open!-cycled unless reusable (interpreter.clj:33-67,
    233-236)
  * :sleep and :log ops are executed in-worker and excluded from the
    history (interpreter.clj:172-179)
  * PENDING polls at 1 ms (max-pending-interval, interpreter.clj:166-170)
"""

from __future__ import annotations

import logging
import queue
import threading
from typing import Any, Mapping

from jepsen_tpu import client as jclient
from jepsen_tpu import generator as gen
from jepsen_tpu.generator import NEMESIS, PENDING, Context
from jepsen_tpu.utils import relative_time_nanos

logger = logging.getLogger(__name__)

#: interpreter.clj:166-170 — how long to block on the completion queue while
#: the generator is pending.
MAX_PENDING_INTERVAL_S = 0.001

_EXIT = {"type": "exit"}

#: Op types executed in-worker but excluded from history and generator
#: updates (interpreter.clj:172-179).
_SPECIAL_TYPES = ("sleep", "log", "sleep-done", "log-done")


def goes_in_history(op: Mapping) -> bool:
    return op.get("type") not in _SPECIAL_TYPES


class Worker:
    """Worker protocol (interpreter.clj:19-31)."""

    def open(self, test, wid):
        return self

    def invoke(self, test, op) -> Mapping:
        raise NotImplementedError

    def close(self, test):
        pass


class ClientWorker(Worker):
    """Wraps a Client; reopens it when its process changes, unless the
    client is reusable (interpreter.clj:33-67)."""

    def __init__(self, node: str, client: jclient.Client):
        self.node = node
        self.base = client
        self.conn: jclient.Client | None = None
        self.process: Any = None

    def open(self, test, wid):
        self.conn = self.base.open(test, self.node)
        return self

    def invoke(self, test, op):
        if self.process != op["process"]:
            if not self.base.reusable and self.process is not None:
                try:
                    if self.conn is not None:
                        self.conn.close(test)
                except Exception:  # noqa: BLE001
                    logger.exception("error closing crashed client on %s", self.node)
                self.conn = None
            if self.conn is None:
                self.conn = self.base.open(test, self.node)
            self.process = op["process"]
        return self.conn.invoke(test, op)

    def close(self, test):
        if self.conn is not None:
            self.conn.close(test)
            self.conn = None


class NemesisWorker(Worker):
    """The nemesis is shared state set up by the orchestrator; the worker
    just routes ops to it (interpreter.clj:69-97)."""

    def __init__(self, nemesis):
        self.nemesis = nemesis

    def invoke(self, test, op):
        return self.nemesis.invoke(test, op)


def client_nodes(test: Mapping) -> list:
    nodes = list(test.get("nodes") or ["local"])
    return nodes


def _spawn_worker(test, wid, worker: Worker, completions: queue.Queue):
    """Worker thread: take an op, run it, put the completion
    (interpreter.clj:99-164).  Any Throwable becomes an :info completion
    with an indeterminate error."""
    inq: queue.Queue = queue.Queue(maxsize=1)

    def loop():
        try:
            worker.open(test, wid)
        except Exception:  # noqa: BLE001
            logger.exception("worker %s failed to open", wid)
        while True:
            op = inq.get()
            if op is _EXIT:
                try:
                    worker.close(test)
                except Exception:  # noqa: BLE001
                    logger.exception("worker %s failed to close", wid)
                return
            t = op.get("type")
            if t == "sleep":
                import time as _t

                _t.sleep(op.get("value") or 0)
                completions.put({**op, "type": "sleep-done"})
            elif t == "log":
                logger.info("%s", op.get("value"))
                completions.put({**op, "type": "log-done"})
            else:
                try:
                    comp = worker.invoke(test, op)
                except Exception as e:  # noqa: BLE001 - op is indeterminate
                    logger.debug("worker %s crashed on %s", wid, op, exc_info=True)
                    comp = {
                        **op,
                        "type": "info",
                        "error": f"indeterminate: {type(e).__name__}: {e}",
                    }
                completions.put(comp)

    thread = threading.Thread(target=loop, name=f"jepsen-worker-{wid}", daemon=True)
    thread.start()
    return inq, thread


def run(test: Mapping) -> list[dict]:
    """Run the test's generator to completion against its client and
    nemesis; returns the history (interpreter.clj:181-310).

    Requires an active ``utils.relative_time`` scope (the orchestrator
    establishes one; tests may use ``with relative_time():``).
    """
    ctx: Context = gen.context(test)
    g = gen.validate(gen.friendly_exceptions(gen.to_gen(test.get("generator"))))
    nodes = client_nodes(test)
    completions: queue.Queue = queue.Queue()
    # ``test["op-sink"]``: a callable tee'd every op that lands in the
    # history, in history order (core.py's live streaming mode feeds it
    # into a checker.streaming.StreamingChecker).  A monitor must never
    # be able to kill the run it watches, so sink errors are logged and
    # the sink is dropped for the rest of the run.
    sink = test.get("op-sink")

    def tee(op):
        nonlocal sink
        if sink is None:
            return
        try:
            sink(op)
        except Exception:  # noqa: BLE001 — see comment above
            logger.exception("op-sink failed; disabling for this run")
            sink = None

    workers: dict[Any, tuple[queue.Queue, threading.Thread]] = {}
    for thread_id in sorted(ctx.all_threads(), key=gen._thread_sort_key):
        if thread_id == NEMESIS:
            w: Worker = NemesisWorker(test.get("nemesis") or _noop_nemesis())
        else:
            w = ClientWorker(
                nodes[thread_id % len(nodes)],
                test.get("client") or jclient.noop(),
            )
        workers[thread_id] = _spawn_worker(test, thread_id, w, completions)

    history: list[dict] = []
    outstanding = 0

    def process_completion(comp):
        nonlocal ctx, g, outstanding
        comp = dict(comp)
        comp["time"] = relative_time_nanos()
        thread_id = ctx.thread_of(comp["process"])
        # The generator must see the completion-time context with the
        # completing thread already freed (but the old process mapping
        # intact) — interpreter.clj:215-231.
        ctx = ctx.with_time(comp["time"])
        if thread_id is not None:
            ctx = ctx.free_thread(thread_id)
        if goes_in_history(comp):
            history.append(comp)
            tee(comp)
            g = g.update(test, ctx, comp)
        if (
            comp.get("type") == "info"
            and thread_id is not None
            and thread_id != NEMESIS
        ):
            # Crashed: the thread continues under a fresh process id
            # (interpreter.clj:233-236).
            ctx = ctx.with_next_process(thread_id)
        outstanding -= 1

    try:
        while True:
            # Priority 1: completions (interpreter.clj:206-241).
            try:
                comp = completions.get_nowait()
            except queue.Empty:
                comp = None
            if comp is not None:
                process_completion(comp)
                continue

            ctx = ctx.with_time(relative_time_nanos())
            r = g.op(test, ctx)
            if r is None:
                if outstanding == 0:
                    break
                process_completion(completions.get())
                continue
            op, g2 = r
            if op is PENDING:
                try:
                    process_completion(completions.get(timeout=MAX_PENDING_INTERVAL_S))
                except queue.Empty:
                    pass
                continue
            now = relative_time_nanos()
            due = op.get("time", now)
            if due > now:
                # Not yet due: wait, but service completions meanwhile
                # (interpreter.clj:268-275).  Discard the speculative g2.
                try:
                    process_completion(
                        completions.get(timeout=min((due - now) / 1e9, 0.01))
                    )
                except queue.Empty:
                    pass
                continue
            # Dispatch.
            op = dict(op)
            op["time"] = now
            thread_id = ctx.thread_of(op["process"])
            inq, _ = workers[thread_id]
            ctx = ctx.busy_thread(thread_id)
            if goes_in_history(op):
                history.append(op)
                tee(op)
                g = g2.update(test, ctx, op)
            else:
                g = g2
            inq.put(op)
            outstanding += 1
    finally:
        for inq, _ in workers.values():
            inq.put(_EXIT)
        for _, t in workers.values():
            t.join(timeout=10)

    return history


def _noop_nemesis():
    from jepsen_tpu import nemesis as nem

    return nem.noop()
