"""Generator DSL: a pure, functional scheduling language for test workloads.

Mirrors the semantics of ``jepsen.generator`` (reference:
jepsen/src/jepsen/generator.clj, 1,581 LoC).  A generator is an immutable
value with two operations (generator.clj:382-390):

  ``op(gen, test, ctx)``     -> ``(op, gen')`` | ``(PENDING, gen)`` | ``None``
  ``update(gen, test, ctx, event)`` -> ``gen'``

``op`` asks "what would you like to do next?"; ``None`` means exhausted,
``PENDING`` means "nothing *right now*, ask again later".  ``update`` feeds
every history event (invocations and completions) back into the generator so
stateful combinators (synchronize, until-ok, flip-flop) can react.

The *context* tracks logical time (nanoseconds) and which worker threads are
free (generator.clj:428-464).  Threads are ints ``0..concurrency-1`` plus the
special ``NEMESIS`` thread; each thread is mapped to a *process* (an
incrementing id — crashed processes are replaced, generator.clj:330-343).

Everything here is pure Python over immutable dataclasses: no I/O, no wall
clock, no threads — exactly like the reference, which is why the
deterministic simulator (jepsen_tpu.generator.testing) can unit-test every
combinator with exact op sequences (generator/test.clj).

Python values are coerced to generators like the reference's protocol
extensions (generator.clj:545-590):

  None          -> exhausted generator
  dict          -> emit that op once (fill in process/time/type)
  callable      -> call it (with (test, ctx), (test,), or ()); treat the
                   result as a generator, then repeat the function forever
  list / tuple  -> each element in turn
  Gen instance  -> itself
"""

from __future__ import annotations

import dataclasses
import inspect
import types
import logging
import random
from typing import Any, Callable, Iterable, Mapping, Sequence

logger = logging.getLogger(__name__)

#: Sentinel: generator has nothing to do *right now* (generator.clj:382-390).
PENDING = "pending"

#: The nemesis's thread/process name (reference keyword :nemesis).
NEMESIS = "nemesis"


def s_to_ns(seconds: float) -> int:
    return int(seconds * 1_000_000_000)


def ns_to_s(ns: int) -> float:
    return ns / 1_000_000_000


# ---------------------------------------------------------------------------
# RNG — deterministic under the simulator (generator/test.clj:31-48)
# ---------------------------------------------------------------------------

#: Module RNG used for free-thread choice, mix, stagger jitter.  The
#: scheduler (interpreter or simulator) is single-threaded, so a shared
#: instance is safe; tests seed it via rand_seed (reference seed 45100).
_rng = random.Random()

DEFAULT_RAND_SEED = 45100


def rand_seed(seed: int = DEFAULT_RAND_SEED) -> None:
    """Reset the generator RNG — gives byte-identical schedules
    (generator/test.clj:44)."""
    _rng.seed(seed)


# ---------------------------------------------------------------------------
# Context (generator.clj:428-464)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Context:
    """Scheduling context: logical time, free threads, thread->process map.

    ``workers`` maps every thread (int or NEMESIS) to its current process;
    ``free_threads`` is the subset not currently executing an op.  The
    reference uses a Bifurcan Set for O(1) fair nth (generator.clj:440-449);
    a Python frozenset + sorted tuple choice is equivalent here.
    """

    time: int
    free_threads: frozenset
    workers: Mapping[Any, Any]  # thread -> process

    # -- queries ------------------------------------------------------------

    def all_threads(self) -> frozenset:
        return frozenset(self.workers)

    def free_processes(self) -> list:
        return [self.workers[t] for t in self._sorted_free()]

    def all_processes(self) -> list:
        return list(self.workers.values())

    def process_of(self, thread):
        return self.workers[thread]

    def thread_of(self, process):
        """Invert the worker map, O(1) amortized via a per-instance memo
        (the reference keeps a Bifurcan inverse; generator.clj:506-515).
        Contexts are immutable, so the memo can never go stale."""
        try:
            inv = self._thread_of_memo
        except AttributeError:
            inv = {p: t for t, p in self.workers.items()}
            object.__setattr__(self, "_thread_of_memo", inv)
        return inv.get(process)

    def _sorted_free(self) -> list:
        return sorted(self.free_threads, key=_thread_sort_key)

    def some_free_process(self):
        """A uniformly random free process (fair scheduling,
        generator.clj:440-449), or None."""
        free = self._sorted_free()
        if not free:
            return None
        return self.workers[free[_rng.randrange(len(free))]]

    # -- transitions --------------------------------------------------------

    def with_time(self, time: int) -> "Context":
        return dataclasses.replace(self, time=time)

    def busy_thread(self, thread) -> "Context":
        return dataclasses.replace(self, free_threads=self.free_threads - {thread})

    def free_thread(self, thread) -> "Context":
        return dataclasses.replace(self, free_threads=self.free_threads | {thread})

    def with_next_process(self, thread) -> "Context":
        """Assign a fresh process id to a crashed thread's slot
        (generator.clj:330-343; interpreter.clj:233-236)."""
        workers = dict(self.workers)
        workers[thread] = next_process(self, thread)
        return dataclasses.replace(self, workers=workers)

    def restrict(self, pred: Callable[[Any], bool]) -> "Context":
        """Restrict to threads satisfying pred — both workers and
        free_threads, so barrier combinators see only the subset
        (generator.clj:864-883 on-threads)."""
        workers = {t: p for t, p in self.workers.items() if pred(t)}
        return Context(
            time=self.time,
            free_threads=frozenset(t for t in self.free_threads if pred(t)),
            workers=workers,
        )


def _thread_sort_key(t):
    return (1, 0) if t == NEMESIS else (0, t)


def context(test: Mapping) -> Context:
    """Fresh context for a test map: threads 0..concurrency-1 + nemesis,
    all free, process ids = thread ids (generator.clj:453-464)."""
    n = int(test.get("concurrency", 1))
    workers = {t: t for t in range(n)}
    workers[NEMESIS] = NEMESIS
    return Context(time=0, free_threads=frozenset(workers), workers=workers)


def next_process(ctx: Context, thread):
    """The process id that replaces a crashed one: current + number of client
    threads, so ids never collide (generator.clj:330-343)."""
    if thread == NEMESIS:
        return NEMESIS
    n_clients = sum(1 for t in ctx.workers if t != NEMESIS)
    return ctx.workers[thread] + n_clients


# ---------------------------------------------------------------------------
# Op filling (generator.clj:531-543)
# ---------------------------------------------------------------------------


def fill_in_op(op: Mapping, ctx: Context):
    """Fill missing :time, :process, :type on a partial op.  Returns PENDING
    when no free thread can run it (generator.clj:531-543)."""
    o = dict(op)
    if "process" not in o:
        p = ctx.some_free_process()
        if p is None:
            return PENDING
        o["process"] = p
    elif o["process"] not in ctx.free_processes():
        # Explicit process that isn't free: can't run yet.
        return PENDING
    o.setdefault("time", ctx.time)
    o.setdefault("type", "invoke")
    o.setdefault("f", None)
    o.setdefault("value", None)
    return o


# ---------------------------------------------------------------------------
# Generator protocol & coercion (generator.clj:545-590)
# ---------------------------------------------------------------------------


class Gen:
    """Base generator.  Subclasses override op/update; both must be pure
    (return new instances, never mutate)."""

    def op(self, test, ctx):
        raise NotImplementedError

    def update(self, test, ctx, event):
        return self


class _Nil(Gen):
    """The exhausted generator (None coerces here)."""

    def op(self, test, ctx):
        return None

    def __repr__(self):
        return "nil-gen"


NIL_GEN = _Nil()


@dataclasses.dataclass(frozen=True)
class _OpMap(Gen):
    """A raw op map emits itself exactly once (generator.clj:560-567 — use
    repeat() to emit it forever)."""

    m: Mapping

    def op(self, test, ctx):
        o = fill_in_op(self.m, ctx)
        if o is PENDING:
            return (PENDING, self)
        return (o, NIL_GEN)


@dataclasses.dataclass(frozen=True)
class _Fn(Gen):
    """A function is called to produce an op/generator; the function itself
    repeats forever (generator.clj:569-584).  Accepts arities (test, ctx),
    (test,), or ()."""

    f: Callable

    def op(self, test, ctx):
        # Iterative, not recursive: an fn may return an immediately-exhausted
        # generator (e.g. []), in which case we just call it again.
        for _ in range(100_000):
            x = _call_flex(self.f, test, ctx)
            if x is None:
                return None
            r = to_gen(x).op(test, ctx)
            if r is None:
                continue
            o, g2 = r
            # The result runs to completion first, then this fn again.
            return (o, _Seq((g2, self)))
        raise RuntimeError(
            f"function generator {self.f!r} keeps returning exhausted generators"
        )

    def update(self, test, ctx, event):
        return self


def _positional_arity(f) -> int | None:
    """Number of required positional params, or None if uninspectable /
    varargs (meaning: pass everything).  Memoized on the function object —
    signature introspection showed up at ~10% of interpreter time."""
    # Cache on plain functions only: a bound method shares its
    # function's __dict__, and its signature differs by self.
    if type(f) is types.FunctionType:
        cached = f.__dict__.get("__jepsen_arity__")
        if cached is not None:
            return cached
    try:
        sig = inspect.signature(f)
    except (TypeError, ValueError):
        return None
    n = 0
    for p in sig.parameters.values():
        if p.kind is p.VAR_POSITIONAL:
            return None
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD) and p.default is p.empty:
            n += 1
    if type(f) is types.FunctionType:
        f.__dict__["__jepsen_arity__"] = n
    return n


def _call_flex(f, test, ctx):
    n = _positional_arity(f)
    if n is None or n >= 2:
        return f(test, ctx)
    if n == 1:
        return f(test)
    return f()


@dataclasses.dataclass(frozen=True)
class _Seq(Gen):
    """A sequence of generators, run one after another
    (generator.clj:586-590)."""

    gens: tuple

    def op(self, test, ctx):
        gens = self.gens
        while gens:
            head = to_gen(gens[0])
            r = head.op(test, ctx)
            if r is None:
                gens = gens[1:]
                continue
            o, g2 = r
            return (o, _Seq((g2,) + gens[1:]))
        return None

    def update(self, test, ctx, event):
        if not self.gens:
            return self
        head = to_gen(self.gens[0]).update(test, ctx, event)
        return _Seq((head,) + self.gens[1:])


def to_gen(x) -> Gen:
    """Coerce a Python value to a generator (see module docstring)."""
    if x is None:
        return NIL_GEN
    if isinstance(x, Gen):
        return x
    if isinstance(x, Mapping):
        return _OpMap(x)
    if callable(x):
        return _Fn(x)
    if isinstance(x, (list, tuple)):
        return _Seq(tuple(x))
    raise TypeError(f"can't coerce {x!r} to a generator")


# ---------------------------------------------------------------------------
# soonest-op-map (generator.clj:885-927)
# ---------------------------------------------------------------------------


def soonest_op_map(candidates: Sequence[dict | None]):
    """Pick the candidate map {'op','gen','weight'?} whose op occurs first.

    Pending beats nothing; a real op beats pending; earlier time beats later;
    ties break weighted-random (generator.clj:885-927).  Returns the chosen
    map (with merged weight) or None.
    """
    best = None
    for c in candidates:
        if c is None:
            continue
        if best is None:
            best = c
            continue
        a, b = best["op"], c["op"]
        if a is PENDING and b is PENDING:
            continue
        if a is PENDING:
            best = c
            continue
        if b is PENDING:
            continue
        ta, tb = a.get("time", 0), b.get("time", 0)
        if tb < ta:
            best = c
        elif tb == ta:
            wa = best.get("weight", 1)
            wb = c.get("weight", 1)
            if _rng.random() < wb / (wa + wb):
                best = {**c, "weight": wa + wb}
            else:
                best = {**best, "weight": wa + wb}
    return best


# ---------------------------------------------------------------------------
# Combinators
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Validate(Gen):
    """Assert emitted ops are well-formed maps with a free process and
    non-decreasing times (generator.clj:622-671)."""

    gen: Gen

    def op(self, test, ctx):
        r = to_gen(self.gen).op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        if o is not PENDING:
            problems = []
            if not isinstance(o, Mapping):
                problems.append(f"should be a map, but was {o!r}")
            else:
                if o.get("type") not in ("invoke", "sleep", "log", "info"):
                    problems.append(f"bad :type {o.get('type')!r}")
                if "time" not in o:
                    problems.append("no :time")
                if o.get("type") == "invoke" and o.get("process") not in ctx.free_processes():
                    problems.append(
                        f"process {o.get('process')!r} is not free "
                        f"(free: {ctx.free_processes()})"
                    )
            if problems:
                raise ValueError(f"invalid op {o!r} from {self.gen!r}: {problems}")
        return (o, Validate(g2))

    def update(self, test, ctx, event):
        return Validate(to_gen(self.gen).update(test, ctx, event))


@dataclasses.dataclass(frozen=True)
class FriendlyExceptions(Gen):
    """Wrap op/update so exceptions carry which generator threw
    (generator.clj:678-718)."""

    gen: Gen

    def op(self, test, ctx):
        try:
            r = to_gen(self.gen).op(test, ctx)
        except Exception as e:
            raise RuntimeError(f"generator {self.gen!r} threw in op()") from e
        if r is None:
            return None
        o, g2 = r
        return (o, FriendlyExceptions(g2))

    def update(self, test, ctx, event):
        try:
            return FriendlyExceptions(to_gen(self.gen).update(test, ctx, event))
        except Exception as e:
            raise RuntimeError(f"generator {self.gen!r} threw in update()") from e


@dataclasses.dataclass(frozen=True)
class Trace(Gen):
    """Log every op/update passing through, tagged with k
    (generator.clj:720-763)."""

    k: Any
    gen: Gen

    def op(self, test, ctx):
        r = to_gen(self.gen).op(test, ctx)
        logger.info("trace %s op -> %s", self.k, None if r is None else r[0])
        if r is None:
            return None
        o, g2 = r
        return (o, Trace(self.k, g2))

    def update(self, test, ctx, event):
        logger.info("trace %s update <- %s", self.k, event)
        return Trace(self.k, to_gen(self.gen).update(test, ctx, event))


@dataclasses.dataclass(frozen=True)
class Map(Gen):
    """Apply f to every emitted op (generator.clj:782-788)."""

    f: Callable
    gen: Gen

    def op(self, test, ctx):
        r = to_gen(self.gen).op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        if o is not PENDING:
            o = self.f(o)
        return (o, Map(self.f, g2))

    def update(self, test, ctx, event):
        return Map(self.f, to_gen(self.gen).update(test, ctx, event))


def f_map(m: Mapping, gen) -> Gen:
    """Rename op :f keys via map m — both on the way out and (inverse) on
    update events, so composed nemeses see their own vocabulary
    (generator.clj:790-810)."""
    inv = {v: k for k, v in m.items()}
    return _FMap(dict(m), inv, to_gen(gen))


@dataclasses.dataclass(frozen=True)
class _FMap(Gen):
    m: Mapping
    inv: Mapping
    gen: Gen

    def op(self, test, ctx):
        r = to_gen(self.gen).op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        if o is not PENDING:
            o = {**o, "f": self.m.get(o.get("f"), o.get("f"))}
        return (o, _FMap(self.m, self.inv, g2))

    def update(self, test, ctx, event):
        ev = {**event, "f": self.inv.get(event.get("f"), event.get("f"))}
        return _FMap(self.m, self.inv, to_gen(self.gen).update(test, ctx, ev))


@dataclasses.dataclass(frozen=True)
class Filter(Gen):
    """Only emit ops satisfying pred (generator.clj:812-862).  Skipped ops
    advance the inner generator."""

    pred: Callable
    gen: Gen

    def op(self, test, ctx):
        gen = to_gen(self.gen)
        while True:
            r = gen.op(test, ctx)
            if r is None:
                return None
            o, g2 = r
            if o is PENDING or self.pred(o):
                return (o, Filter(self.pred, g2))
            gen = g2

    def update(self, test, ctx, event):
        return Filter(self.pred, to_gen(self.gen).update(test, ctx, event))


@dataclasses.dataclass(frozen=True)
class OnThreads(Gen):
    """Restrict a generator to threads satisfying pred: it sees a filtered
    context and only receives updates for its own threads
    (generator.clj:864-883)."""

    pred: Callable
    gen: Gen

    def op(self, test, ctx):
        sub = ctx.restrict(self.pred)
        if not sub.workers:
            return None
        r = to_gen(self.gen).op(test, sub)
        if r is None:
            return None
        o, g2 = r
        return (o, OnThreads(self.pred, g2))

    def update(self, test, ctx, event):
        thread = ctx.thread_of(event.get("process"))
        if thread is not None and self.pred(thread):
            sub = ctx.restrict(self.pred)
            return OnThreads(self.pred, to_gen(self.gen).update(test, sub, event))
        return self


def on_threads(pred, gen) -> Gen:
    return OnThreads(pred, to_gen(gen))


on = on_threads


def clients(gen, final_gen=None) -> Gen:
    """Run gen on client threads only (generator.clj:864-883 via
    on-threads).  The optional ``final_gen`` is a convenience this rebuild
    adds (the reference's 2-arity routes a *nemesis* gen instead and final
    phases go through then/phases): it runs after a synchronize barrier on
    the *client* threads, so every outstanding client op completes before
    the final phase begins (nemesis ops may still be in flight)."""
    if final_gen is not None:
        # Barrier inside the restriction: waits for the *client* threads
        # only, not the nemesis (Context.restrict filters free_threads).
        return on_threads(lambda t: t != NEMESIS, phases(gen, final_gen))
    return on_threads(lambda t: t != NEMESIS, gen)


def nemesis(gen, final_gen=None) -> Gen:
    """Run gen on the nemesis thread only.  ``final_gen`` (rebuild
    convenience, see ``clients``) runs after a synchronize barrier."""
    if final_gen is not None:
        return on_threads(lambda t: t == NEMESIS, phases(gen, final_gen))
    return on_threads(lambda t: t == NEMESIS, gen)


@dataclasses.dataclass(frozen=True)
class Any(Gen):
    """Emit whichever child generator's op comes soonest; updates go to all
    children (generator.clj:929-953)."""

    gens: tuple

    def op(self, test, ctx):
        candidates = []
        for i, g in enumerate(self.gens):
            r = to_gen(g).op(test, ctx)
            if r is None:
                continue
            o, g2 = r
            candidates.append({"op": o, "gen": g2, "i": i})
        best = soonest_op_map(candidates)
        if best is None:
            return None
        gens = tuple(
            best["gen"] if i == best["i"] else g for i, g in enumerate(self.gens)
        )
        return (best["op"], Any(gens))

    def update(self, test, ctx, event):
        return Any(tuple(to_gen(g).update(test, ctx, event) for g in self.gens))


def any_gen(*gens) -> Gen:
    return Any(tuple(to_gen(g) for g in gens))


@dataclasses.dataclass(frozen=True)
class EachThread(Gen):
    """An independent copy of gen runs on every thread
    (generator.clj:955-1007).  Exhausted when every thread's copy is."""

    fresh: Gen
    copies: Mapping  # thread -> Gen | None (None = exhausted)

    def _copy_for(self, t):
        if t in self.copies:
            return self.copies[t]
        return self.fresh

    def op(self, test, ctx):
        candidates = []
        exhausted = []
        for t in ctx.all_threads():
            g = self._copy_for(t)
            if g is None:
                continue
            sub = ctx.restrict(lambda x, t=t: x == t)
            r = to_gen(g).op(test, sub)
            if r is None:
                exhausted.append(t)
                continue
            o, g2 = r
            candidates.append({"op": o, "gen": g2, "t": t})
        if not candidates:
            return None
        best = soonest_op_map(candidates)
        copies = dict(self.copies)
        for t in exhausted:
            copies[t] = None
        copies[best["t"]] = best["gen"]
        return (best["op"], EachThread(self.fresh, copies))

    def update(self, test, ctx, event):
        t = ctx.thread_of(event.get("process"))
        if t is None:
            return self
        g = self._copy_for(t)
        if g is None:
            return self
        sub = ctx.restrict(lambda x, t=t: x == t)
        copies = dict(self.copies)
        copies[t] = to_gen(g).update(test, sub, event)
        return EachThread(self.fresh, copies)


def each_thread(gen) -> Gen:
    return EachThread(to_gen(gen), {})


@dataclasses.dataclass(frozen=True)
class Reserve(Gen):
    """Partition client threads into fixed-size groups, each running its own
    generator; remaining threads (and the nemesis) run the default
    (generator.clj:1009-1089)."""

    ranges: tuple  # ((frozenset_of_threads, Gen), ...)
    default: Gen
    default_pred: Callable

    def op(self, test, ctx):
        candidates = []
        for i, (threads, g) in enumerate(self.ranges):
            sub = ctx.restrict(lambda t, s=threads: t in s)
            r = to_gen(g).op(test, sub)
            if r is None:
                continue
            o, g2 = r
            candidates.append({"op": o, "gen": g2, "i": i, "weight": len(threads)})
        sub = ctx.restrict(self.default_pred)
        if sub.workers:
            r = to_gen(self.default).op(test, sub)
            if r is not None:
                o, g2 = r
                candidates.append(
                    {"op": o, "gen": g2, "i": -1, "weight": max(1, len(sub.workers))}
                )
        best = soonest_op_map(candidates)
        if best is None:
            return None
        if best["i"] == -1:
            return (best["op"], Reserve(self.ranges, best["gen"], self.default_pred))
        ranges = tuple(
            (s, best["gen"] if i == best["i"] else g)
            for i, (s, g) in enumerate(self.ranges)
        )
        return (best["op"], Reserve(ranges, self.default, self.default_pred))

    def update(self, test, ctx, event):
        t = ctx.thread_of(event.get("process"))
        if t is None:
            return self
        for i, (threads, g) in enumerate(self.ranges):
            if t in threads:
                sub = ctx.restrict(lambda x, s=threads: x in s)
                ranges = tuple(
                    (s, to_gen(g).update(test, sub, event) if j == i else gg)
                    for j, (s, gg) in enumerate(self.ranges)
                )
                return Reserve(ranges, self.default, self.default_pred)
        if self.default_pred(t):
            sub = ctx.restrict(self.default_pred)
            return Reserve(
                self.ranges, to_gen(self.default).update(test, sub, event), self.default_pred
            )
        return self


def reserve(*args) -> Gen:
    """``reserve(n1, g1, n2, g2, ..., default)`` — first n1 client threads run
    g1, next n2 run g2, …; all other threads run default
    (generator.clj:1009-1089)."""
    *pairs, default = args
    if len(pairs) % 2 != 0:
        raise ValueError("reserve takes count/gen pairs followed by a default")
    ranges = []
    start = 0
    for i in range(0, len(pairs), 2):
        n, g = pairs[i], pairs[i + 1]
        threads = frozenset(range(start, start + n))
        ranges.append((threads, to_gen(g)))
        start += n
    reserved = frozenset().union(*[s for s, _ in ranges]) if ranges else frozenset()
    return Reserve(tuple(ranges), to_gen(default), lambda t: t not in reserved)


@dataclasses.dataclass(frozen=True)
class Mix(Gen):
    """Random choice among generators on each op; exhausted children are
    dropped; updates are not routed (matching the reference, which keeps mix
    stateless across updates — generator.clj:1124-1155)."""

    gens: tuple

    def op(self, test, ctx):
        gens = list(self.gens)
        order = list(range(len(gens)))
        _rng.shuffle(order)
        saw_pending = False
        dropped = set()
        for i in order:
            r = to_gen(gens[i]).op(test, ctx)
            if r is None:
                dropped.add(i)
                continue
            o, g2 = r
            if o is PENDING:
                saw_pending = True
                continue
            remaining = tuple(
                g2 if j == i else g for j, g in enumerate(gens) if j not in dropped
            )
            return (o, Mix(remaining))
        remaining = tuple(g for j, g in enumerate(gens) if j not in dropped)
        if saw_pending:
            return (PENDING, Mix(remaining))
        return None


def mix(gens: Iterable) -> Gen:
    return Mix(tuple(to_gen(g) for g in gens))


@dataclasses.dataclass(frozen=True)
class Limit(Gen):
    """At most n ops (generator.clj:1156-1170)."""

    remaining: int
    gen: Gen

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        r = to_gen(self.gen).op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        n = self.remaining - (0 if o is PENDING else 1)
        return (o, Limit(n, g2))

    def update(self, test, ctx, event):
        return Limit(self.remaining, to_gen(self.gen).update(test, ctx, event))


def limit(n: int, gen) -> Gen:
    return Limit(n, to_gen(gen))


def once(gen) -> Gen:
    """Exactly one op (generator.clj:1172-1175)."""
    return Limit(1, to_gen(gen))


@dataclasses.dataclass(frozen=True)
class Repeat(Gen):
    """Emit gen's next op over and over *without advancing gen* — like
    clojure.core/repeat of a value (generator.clj:1183-1210).  With a count,
    stops after n ops."""

    remaining: int | None
    gen: Gen

    def op(self, test, ctx):
        if self.remaining is not None and self.remaining <= 0:
            return None
        r = to_gen(self.gen).op(test, ctx)
        if r is None:
            return None
        o, _g2 = r
        if o is PENDING:
            return (PENDING, self)
        n = None if self.remaining is None else self.remaining - 1
        return (o, Repeat(n, self.gen))

    def update(self, test, ctx, event):
        return Repeat(self.remaining, to_gen(self.gen).update(test, ctx, event))


def repeat(gen, n: int | None = None) -> Gen:
    return Repeat(n, to_gen(gen))


@dataclasses.dataclass(frozen=True)
class Cycle(Gen):
    """Restart gen from pristine when exhausted, forever or n times
    (generator.clj:1212-1238)."""

    remaining: int | None
    fresh: Gen
    gen: Gen

    def op(self, test, ctx):
        r = to_gen(self.gen).op(test, ctx)
        if r is not None:
            o, g2 = r
            return (o, Cycle(self.remaining, self.fresh, g2))
        if self.remaining is not None and self.remaining <= 1:
            return None
        n = None if self.remaining is None else self.remaining - 1
        r = to_gen(self.fresh).op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        return (o, Cycle(n, self.fresh, g2))

    def update(self, test, ctx, event):
        return Cycle(self.remaining, self.fresh, to_gen(self.gen).update(test, ctx, event))


def cycle(gen, n: int | None = None) -> Gen:
    g = to_gen(gen)
    return Cycle(n, g, g)


@dataclasses.dataclass(frozen=True)
class ProcessLimit(Gen):
    """Allow ops from at most n distinct client processes — crashed processes
    burn budget, bounding the search frontier for checkers
    (generator.clj:1240-1265)."""

    n: int
    seen: frozenset
    gen: Gen

    def _eligible(self, ctx: Context):
        budget = self.n - len(self.seen)

        def ok(t):
            if t == NEMESIS:
                return True
            p = ctx.workers[t]
            return p in self.seen or budget > 0

        return ok

    def op(self, test, ctx):
        sub = ctx.restrict(self._eligible(ctx))
        free_clients = [t for t in sub.free_threads if t != NEMESIS]
        if not free_clients and len(self.seen) >= self.n:
            # All in-budget processes are done/crashed-over-budget.
            live = {p for p in ctx.all_processes() if p in self.seen}
            if not live:
                return None
        r = to_gen(self.gen).op(test, sub)
        if r is None:
            return None
        o, g2 = r
        seen = self.seen
        if o is not PENDING and isinstance(o.get("process"), int):
            seen = seen | {o["process"]}
        return (o, ProcessLimit(self.n, seen, g2))

    def update(self, test, ctx, event):
        return ProcessLimit(self.n, self.seen, to_gen(self.gen).update(test, ctx, event))


def process_limit(n: int, gen) -> Gen:
    return ProcessLimit(n, frozenset(), to_gen(gen))


@dataclasses.dataclass(frozen=True)
class TimeLimit(Gen):
    """Stop emitting once logical time exceeds the deadline; the deadline is
    fixed on first call (generator.clj:1267-1291)."""

    dt: int  # ns
    deadline: int | None
    gen: Gen

    def op(self, test, ctx):
        deadline = self.deadline if self.deadline is not None else ctx.time + self.dt
        if ctx.time >= deadline:
            return None
        r = to_gen(self.gen).op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        if o is not PENDING and o.get("time", ctx.time) >= deadline:
            return None
        return (o, TimeLimit(self.dt, deadline, g2))

    def update(self, test, ctx, event):
        return TimeLimit(self.dt, self.deadline, to_gen(self.gen).update(test, ctx, event))


def time_limit(seconds: float, gen) -> Gen:
    return TimeLimit(s_to_ns(seconds), None, to_gen(gen))


@dataclasses.dataclass(frozen=True)
class Stagger(Gen):
    """Introduce uniform-random [0, 2dt) spacing between ops — *total* rate
    across all threads, not per-thread (generator.clj:1293-1330)."""

    dt: int  # ns (mean interval)
    next_time: int | None
    gen: Gen

    def op(self, test, ctx):
        r = to_gen(self.gen).op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        if o is PENDING:
            return (PENDING, Stagger(self.dt, self.next_time, g2))
        nt = self.next_time if self.next_time is not None else ctx.time
        t = max(o.get("time", ctx.time), nt)
        o = {**o, "time": t}
        return (o, Stagger(self.dt, t + int(_rng.random() * 2 * self.dt), g2))

    def update(self, test, ctx, event):
        return Stagger(self.dt, self.next_time, to_gen(self.gen).update(test, ctx, event))


def stagger(seconds: float, gen) -> Gen:
    return Stagger(s_to_ns(seconds), None, to_gen(gen))


@dataclasses.dataclass(frozen=True)
class Delay(Gen):
    """Exactly dt between emitted ops — total rate 1/dt
    (generator.clj:1369-1395)."""

    dt: int
    next_time: int | None
    gen: Gen

    def op(self, test, ctx):
        r = to_gen(self.gen).op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        if o is PENDING:
            return (PENDING, Delay(self.dt, self.next_time, g2))
        nt = self.next_time if self.next_time is not None else ctx.time
        t = max(o.get("time", ctx.time), nt)
        o = {**o, "time": t}
        return (o, Delay(self.dt, t + self.dt, g2))

    def update(self, test, ctx, event):
        return Delay(self.dt, self.next_time, to_gen(self.gen).update(test, ctx, event))


def delay(seconds: float, gen) -> Gen:
    return Delay(s_to_ns(seconds), None, to_gen(gen))


def sleep(seconds: float) -> Gen:
    """One special op telling its worker to do nothing for dt; excluded from
    the history by the interpreter (generator.clj:1397-1401,
    interpreter.clj:172-179)."""
    return once({"type": "sleep", "value": seconds, "f": None})


def log(message) -> Gen:
    """One special op logging a message in-worker; excluded from the history
    (generator.clj:1177-1181)."""
    return once({"type": "log", "value": message, "f": None})


@dataclasses.dataclass(frozen=True)
class Synchronize(Gen):
    """A barrier: PENDING until every thread in the context is free, then
    becomes gen (generator.clj:1403-1423)."""

    gen: Gen
    released: bool = False

    def op(self, test, ctx):
        if self.released or ctx.free_threads == ctx.all_threads():
            g = to_gen(self.gen)
            r = g.op(test, ctx)
            if r is None:
                return None
            o, g2 = r
            return (o, Synchronize(g2, True))
        return (PENDING, self)

    def update(self, test, ctx, event):
        return Synchronize(to_gen(self.gen).update(test, ctx, event), self.released)


def synchronize(gen) -> Gen:
    return Synchronize(to_gen(gen))


def phases(*gens) -> Gen:
    """Each generator runs to completion, with a full barrier before the
    next begins (generator.clj:1425-1430)."""
    return _Seq(tuple(synchronize(g) for g in gens))


def then(a, b) -> Gen:
    """b, then (after a barrier) a — argument order matches the reference's
    threading-macro convention ``(->> a (then b))`` (generator.clj:1432-1441)."""
    return _Seq((to_gen(b), synchronize(a)))


@dataclasses.dataclass(frozen=True)
class UntilOk(Gen):
    """Pass through until one of *our* ops completes :ok.  Tracks the
    processes of invocations this generator emitted so sibling generators'
    :ok completions don't count (generator.clj:1443-1473 tracks
    active-processes the same way)."""

    gen: Gen
    done: bool = False
    active: frozenset = frozenset()

    def op(self, test, ctx):
        if self.done:
            return None
        r = to_gen(self.gen).op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        active = self.active
        if (
            o is not PENDING
            and isinstance(o, Mapping)
            and "process" in o
            # sleep/log ops never produce update events (the interpreter
            # keeps them out of history), so tracking them would leave a
            # stale process entry behind.
            and o.get("type", "invoke") == "invoke"
        ):
            active = active | {o["process"]}
        return (o, UntilOk(g2, False, active))

    def update(self, test, ctx, event):
        p = event.get("process")
        ours = p in self.active
        done = self.done or (event.get("type") == "ok" and ours)
        active = self.active - {p} if ours and event.get("type") in ("ok", "info", "fail") else self.active
        return UntilOk(to_gen(self.gen).update(test, ctx, event), done, active)


def until_ok(gen) -> Gen:
    return UntilOk(to_gen(gen))


@dataclasses.dataclass(frozen=True)
class FlipFlop(Gen):
    """Alternate ops between generators: a, b, a, b, … Exhausted when the
    current one is (generator.clj:1475-1489)."""

    gens: tuple
    i: int

    def op(self, test, ctx):
        g = to_gen(self.gens[self.i])
        r = g.op(test, ctx)
        if r is None:
            return None
        o, g2 = r
        if o is PENDING:
            gens = tuple(g2 if j == self.i else x for j, x in enumerate(self.gens))
            return (PENDING, FlipFlop(gens, self.i))
        gens = tuple(g2 if j == self.i else x for j, x in enumerate(self.gens))
        return (o, FlipFlop(gens, (self.i + 1) % len(gens)))

    def update(self, test, ctx, event):
        gens = tuple(to_gen(g).update(test, ctx, event) for g in self.gens)
        return FlipFlop(gens, self.i)


def flip_flop(*gens) -> Gen:
    return FlipFlop(tuple(to_gen(g) for g in gens), 0)


@dataclasses.dataclass(frozen=True)
class CycleTimes(Gen):
    """Rotate between generators on a repeating schedule of durations:
    t1 of g1, t2 of g2, …, looping (generator.clj:1491-1563)."""

    periods: tuple  # (ns, ...)
    gens: tuple
    origin: int | None

    def _window(self, time: int, origin: int):
        """(index, abs_start, abs_end) of the window containing `time`."""
        total = sum(self.periods)
        phase = (time - origin) % total
        acc = 0
        for i, p in enumerate(self.periods):
            if phase < acc + p:
                start = time - phase + acc
                return i, start, start + p
            acc += p
        raise AssertionError("unreachable")

    def op(self, test, ctx):
        origin = self.origin if self.origin is not None else ctx.time
        t_ask = ctx.time
        # Fix-point: if the asked window's op lands in a later window,
        # re-ask the generator that owns that later window
        # (the reference achieves this by slicing gens into time-capped
        # pieces, generator.clj:1491-1563).
        for _ in range(4 * len(self.gens) + 4):
            i, start, end = self._window(max(t_ask, ctx.time), origin)
            sub_ctx = ctx.with_time(max(ctx.time, start))
            r = to_gen(self.gens[i]).op(test, sub_ctx)
            if r is None:
                return None
            o, g2 = r
            if o is PENDING:
                return (PENDING, CycleTimes(self.periods, self.gens, origin))
            t_op = o.get("time", sub_ctx.time)
            if t_op < end:
                gens = tuple(g2 if j == i else g for j, g in enumerate(self.gens))
                return (o, CycleTimes(self.periods, gens, origin))
            t_ask = t_op
        gens = tuple(g2 if j == i else g for j, g in enumerate(self.gens))
        return (o, CycleTimes(self.periods, gens, origin))

    def update(self, test, ctx, event):
        # Broadcast: a completion may arrive in a different window than its
        # invocation, so routing by the event's window would update the
        # wrong child (the reference slices gens per window instead).
        origin = self.origin if self.origin is not None else ctx.time
        gens = tuple(to_gen(g).update(test, ctx, event) for g in self.gens)
        return CycleTimes(self.periods, gens, origin)


def cycle_times(*args) -> Gen:
    """cycle_times(t1_seconds, g1, t2_seconds, g2, ...)."""
    if len(args) % 2 != 0:
        raise ValueError("cycle_times takes duration/gen pairs")
    periods = tuple(s_to_ns(args[i]) for i in range(0, len(args), 2))
    gens = tuple(to_gen(args[i]) for i in range(1, len(args), 2))
    return CycleTimes(periods, gens, None)


def validate(gen) -> Gen:
    return Validate(to_gen(gen))


def friendly_exceptions(gen) -> Gen:
    return FriendlyExceptions(to_gen(gen))


def trace(k, gen) -> Gen:
    return Trace(k, to_gen(gen))


def map_gen(f, gen) -> Gen:
    return Map(f, to_gen(gen))


def filter_gen(pred, gen) -> Gen:
    return Filter(pred, to_gen(gen))
