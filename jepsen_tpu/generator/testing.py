"""Deterministic generator simulation — no threads, no wall clock.

A pure re-implementation of the interpreter's scheduling loop with a
pluggable *completion function* deciding how invocations complete, mirroring
``jepsen.generator.test`` (reference: jepsen/src/jepsen/generator/test.clj,
shipped in src/ precisely so downstream generator logic can be tested
without hardware — SURVEY.md §4.2).

``simulate(test, gen, completion_fn)`` returns the full simulated history.
Completion functions map an invocation to its completion op (or None for
invoke-only simulation):

  quick         — invocations only; threads free immediately
  perfect       — every op completes :ok exactly 10 ms later
  perfect_info  — every op completes :info 10 ms later
  imperfect     — rotates ok/info/fail with latencies 10/20/30 ms

All randomness flows through the generator-module RNG, seeded with 45100
(generator/test.clj:31-48) so schedules are byte-identical across runs.
"""

from __future__ import annotations

import heapq
from typing import Callable, Mapping

from jepsen_tpu import generator as gen_mod
from jepsen_tpu.generator import (
    NEMESIS,
    PENDING,
    Context,
    Gen,
    context,
    rand_seed,
    s_to_ns,
    to_gen,
)

LATENCY_NS = 10_000_000  # 10 ms, the reference's perfect latency


def quick_completion(invoke_op: Mapping) -> Mapping | None:
    """Invoke-only simulation (generator/test.clj:110-120)."""
    return None


def perfect_completion(invoke_op: Mapping) -> Mapping:
    """Complete :ok after exactly 10 ms (generator/test.clj:122-138)."""
    return {
        **invoke_op,
        "type": "ok",
        "time": invoke_op["time"] + LATENCY_NS,
    }


def perfect_info_completion(invoke_op: Mapping) -> Mapping:
    """Complete :info after 10 ms — worst-case for checkers
    (generator/test.clj:140-152)."""
    return {
        **invoke_op,
        "type": "info",
        "time": invoke_op["time"] + LATENCY_NS,
    }


class ImperfectCompletion:
    """Rotate ok → info → fail with latencies 10/20/30 ms
    (generator/test.clj:154-182)."""

    TYPES = ("ok", "info", "fail")

    def __init__(self):
        self.i = 0

    def __call__(self, invoke_op: Mapping) -> Mapping:
        t = self.TYPES[self.i % 3]
        latency = LATENCY_NS * (1 + self.i % 3)
        self.i += 1
        return {**invoke_op, "type": t, "time": invoke_op["time"] + latency}


def simulate(
    test: Mapping,
    gen,
    completion_fn: Callable[[Mapping], Mapping | None] = perfect_completion,
    ctx: Context | None = None,
    max_ops: int = 100_000,
    seed: int | None = gen_mod.DEFAULT_RAND_SEED,
) -> list[dict]:
    """Run the generator to exhaustion against a simulated perfect worker
    pool; returns the history (generator/test.clj:50-108).

    The loop mirrors interpreter scheduling: completions are processed
    before any invocation scheduled at a later time; generators are pure so
    "peeking" at an op and deciding to process a completion first simply
    discards the speculative successor state.
    """
    if seed is not None:
        rand_seed(seed)
    g: Gen = to_gen(gen)
    ctx = ctx if ctx is not None else context(test)
    history: list[dict] = []
    # Pending completions: heap of (time, tiebreak, completion_op)
    pending: list[tuple] = []
    tiebreak = 0

    def process_completion():
        nonlocal ctx, g
        t, _, comp = heapq.heappop(pending)
        ctx = ctx.with_time(max(ctx.time, t))
        thread = ctx.thread_of(comp["process"])
        # Mirror the interpreter: the generator sees the completion-time
        # context with the completing thread already freed.
        if thread is not None:
            ctx = ctx.free_thread(thread)
        if comp.get("type") != "sleep-wake":
            history.append(comp)
            g = g.update(test, ctx, comp)
            if comp.get("type") == "info" and thread != NEMESIS:
                # Crashed process: assign a fresh process id
                # (interpreter.clj:233-236).
                ctx = ctx.with_next_process(thread)

    while len(history) < max_ops:
        r = g.op(test, ctx)
        if r is None:
            while pending:
                process_completion()
            break
        op, g2 = r
        if op is PENDING:
            if not pending:
                raise RuntimeError(
                    f"deadlock: generator {g!r} is pending with no outstanding ops"
                )
            process_completion()
            continue
        t = op.get("time", ctx.time)
        if pending and pending[0][0] <= t:
            # A completion comes first; discard the speculative op.
            process_completion()
            continue
        # Emit the invocation.  sleep/log are excluded from history and
        # updates, exactly like the interpreter (interpreter.clj:172-179).
        ctx = ctx.with_time(max(ctx.time, t))
        thread = ctx.thread_of(op["process"])
        ctx = ctx.busy_thread(thread)
        if op.get("type") in ("sleep", "log"):
            g = g2
        else:
            g = g2.update(test, ctx, op)
            history.append(op)
        if op.get("type") == "sleep":
            wake = {
                "type": "sleep-wake",
                "process": op["process"],
                "time": t + s_to_ns(op.get("value") or 0),
            }
            heapq.heappush(pending, (wake["time"], tiebreak, wake))
            tiebreak += 1
        elif op.get("type") == "log":
            ctx = ctx.free_thread(thread)
        else:
            comp = completion_fn(op)
            if comp is None:
                ctx = ctx.free_thread(thread)
            else:
                heapq.heappush(pending, (comp["time"], tiebreak, comp))
                tiebreak += 1
    return history


def quick(test, gen, **kw) -> list[dict]:
    """Invocations only (generator/test.clj:110-120)."""
    return simulate(test, gen, quick_completion, **kw)


def perfect(test, gen, **kw) -> list[dict]:
    """Every op completes ok in 10 ms (generator/test.clj:122-138)."""
    return simulate(test, gen, perfect_completion, **kw)


def perfect_info(test, gen, **kw) -> list[dict]:
    return simulate(test, gen, perfect_info_completion, **kw)


def imperfect(test, gen, **kw) -> list[dict]:
    return simulate(test, gen, ImperfectCompletion(), **kw)
