"""Transaction micro-op utilities.

A *transaction* op is a history op whose ``value`` is a list of micro-ops
(mops), each ``[f, k, v]`` — e.g. ``["r", "x", [1, 2]]`` or
``["append", "x", 3]``.  Mirrors the reference's vendored ``jepsen.txn``
library (txn/src/jepsen/txn.clj) which backs the Elle-style workloads.

Mops are plain 3-element lists/tuples; accessors below mirror
``jepsen.txn.micro-op``.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Iterable, Iterator, Sequence

# ---------------------------------------------------------------------------
# Micro-op accessors (jepsen.txn.micro-op)
# ---------------------------------------------------------------------------


def mop_f(mop) -> Any:
    """The function of a micro-op: "r", "w", "append", ..."""
    return mop[0]


def mop_key(mop) -> Any:
    return mop[1]


def mop_value(mop) -> Any:
    return mop[2]


def is_read(mop) -> bool:
    return mop[0] == "r"


def is_write(mop) -> bool:
    return mop[0] != "r"


# ---------------------------------------------------------------------------
# Transaction folds (txn/src/jepsen/txn.clj:5-76)
# ---------------------------------------------------------------------------


def reduce_mops(f: Callable, init, history: Iterable[dict]):
    """Fold ``f(state, op, mop)`` over every micro-op of every op in history
    (txn.clj:5-17)."""
    state = init
    for op in history:
        for mop in op["value"] or ():
            state = f(state, op, mop)
    return state


def op_mops(history: Iterable[dict]) -> Iterator[tuple[dict, Sequence]]:
    """All (op, mop) pairs from a history (txn.clj:19-23)."""
    for op in history:
        for mop in op["value"] or ():
            yield op, mop


def ext_reads(txn: Sequence) -> dict:
    """Keys → values this transaction *externally* read: observed values it
    did not itself write earlier in the txn (txn.clj:25-41)."""
    ext: dict = {}
    ignore: set = set()
    for mop in txn:
        f, k, v = mop[0], mop[1], mop[2]
        if f == "r" and k not in ignore:
            ext[k] = v
        ignore.add(k)
    return ext


def ext_writes(txn: Sequence) -> dict:
    """Keys → final values written by this transaction (txn.clj:43-54)."""
    ext: dict = {}
    for mop in txn:
        if mop[0] != "r":
            ext[mop[1]] = mop[2]
    return ext


def int_write_mops(txn: Sequence) -> dict:
    """Keys → list of *non-final* write mops to that key (txn.clj:56-76).
    These are the writes whose observation constitutes a G1b intermediate
    read."""
    writes: dict = {}
    for mop in txn:
        if mop[0] != "r":
            writes.setdefault(mop[1], []).append(list(mop))
    return {k: vs[:-1] for k, vs in writes.items() if len(vs) > 1}


# ---------------------------------------------------------------------------
# Transaction generators (mirroring elle's gen / wr-txns defaults, which the
# reference re-exports at tests/cycle/append.clj:24-28)
# ---------------------------------------------------------------------------


def wr_txns(
    rng: random.Random,
    key_count: int = 2,
    min_txn_length: int = 1,
    max_txn_length: int = 2,
    max_writes_per_key: int = 32,
) -> Iterator[list]:
    """Infinite stream of write/read transactions over a sliding window of
    integer keys, with globally unique writes per key.  Mirrors elle's
    ``wr-txns`` defaults (key-count 2, txn length 1-2, max-writes-per-key
    32)."""
    active = list(range(key_count))
    next_key = key_count
    writes: dict[int, int] = {}
    while True:
        length = rng.randint(min_txn_length, max_txn_length)
        txn = []
        for _ in range(length):
            k = rng.choice(active)
            if rng.random() < 0.5:
                txn.append(["r", k, None])
            else:
                w = writes.get(k, 0) + 1
                if w > max_writes_per_key:
                    # Retire this key, open a fresh one.
                    active[active.index(k)] = next_key
                    k = next_key
                    next_key += 1
                    w = 1
                writes[k] = w
                txn.append(["w", k, w])
        yield txn


def append_txns(
    rng: random.Random,
    key_count: int = 2,
    min_txn_length: int = 1,
    max_txn_length: int = 2,
    max_writes_per_key: int = 32,
) -> Iterator[list]:
    """Like :func:`wr_txns` but writes are ``append`` mops (elle
    list-append generator semantics)."""
    for txn in wr_txns(rng, key_count, min_txn_length, max_txn_length, max_writes_per_key):
        yield [["append", k, v] if f == "w" else [f, k, v] for f, k, v in txn]
