"""Orchestrator: the full test lifecycle.

Mirrors ``jepsen.core`` (reference: jepsen/src/jepsen/core.clj): set up the
OS and DB on every node over the control layer, run the generator through
the interpreter against real clients and the nemesis, record the history,
download logs, tear everything down, then analyze — in exactly the
reference's order (core.clj:327-406, call stack in SURVEY.md §3.1):

  run_test(test)
  ├─ prepare_test                      core.clj:311
  ├─ store.save_0                      store.clj:375
  ├─ sessions to all nodes             core.clj:275-295
  ├─ os.setup on all nodes             core.clj:93-100
  ├─ db.cycle (teardown→setup)         core.clj:172-181, db.clj:117-158
  ├─ relative-time origin              util.clj:337
  ├─ run_case: client/nemesis setup → interpreter.run   core.clj:190-214
  ├─ store.save_1 (history, pre-analysis)               core.clj:401
  ├─ snarf_logs (download db logs)     core.clj:102-136
  ├─ teardown (reverse order)          core.clj:202-212
  ├─ analyze                           core.clj:221-237
  └─ results logged + saved            core.clj:239-252
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Any, Mapping

from jepsen_tpu import checker as chk
from jepsen_tpu import client as jclient
from jepsen_tpu import control, db as jdb, history as h, net as jnet, obs, store
from jepsen_tpu.generator import interpreter
from jepsen_tpu.utils import real_pmap, relative_time

logger = logging.getLogger(__name__)


def prepare_test(test: Mapping) -> dict:
    """Fill defaults: start time, concurrency (= node count), net, name
    (core.clj:311-325)."""
    t = dict(test)
    t.setdefault("name", "jepsen-tpu")
    t.setdefault("nodes", ["n1", "n2", "n3", "n4", "n5"])
    if not t.get("start-time-str"):
        t["start-time-str"] = store.time_str()
    c = t.get("concurrency", "1n")
    if isinstance(c, str):
        # "3n" syntax: multiplier of node count (cli.clj:90-93,150-168).
        mult = int(c[:-1] or 1) if c.endswith("n") else None
        t["concurrency"] = (
            mult * len(t["nodes"]) if mult is not None else int(c)
        )
    # Real iptables only over real remote transports: a dummy run has no
    # network, and a --local run must NEVER touch the host's firewall.
    ssh_opts = t.get("ssh") or {}
    harmless = ssh_opts.get("dummy?") or ssh_opts.get("local?")
    t.setdefault("net", jnet.noop() if harmless else jnet.iptables())
    t.setdefault("client", jclient.noop())
    t.setdefault("checker", None)
    return t


def setup_nemesis(test: Mapping):
    nem = test.get("nemesis")
    if nem is None:
        return None
    return nem.setup(test)


def _with_clients(test: Mapping, method: str):
    """Open a client per node and run setup/teardown on it
    (core.clj:190-212)."""
    client = test.get("client")
    if client is None:
        return

    def one(node):
        c = client.open(test, node)
        try:
            getattr(c, method)(test)
        finally:
            try:
                c.close(test)
            except Exception:  # noqa: BLE001
                logger.exception("error closing %s client on %s", method, node)

    real_pmap(one, list(test["nodes"]))


def snarf_logs(test: Mapping):
    """Download DB log files into the store dir, one subdir per node
    (core.clj:102-136)."""
    database = test.get("db")
    if database is None:
        return
    sess = control.sessions(test)
    d = store.test_dir(test)

    def one(node):
        files = list(database.log_files(test, node) or [])
        if not files:
            return
        dest = d / node
        dest.mkdir(parents=True, exist_ok=True)
        for f in files:
            try:
                sess[node].download(f, str(dest))
            except Exception:  # noqa: BLE001
                logger.warning("couldn't download %s from %s", f, node, exc_info=True)

    real_pmap(one, list(test["nodes"]))


def run_case(test: Mapping) -> list[dict]:
    """Nemesis + client setup, then the interpreter loop
    (core.clj:190-214)."""
    nem = setup_nemesis(test)
    t = {**test, "nemesis": nem}
    try:
        _with_clients(test, "setup")
        return interpreter.run(t)
    finally:
        try:
            _with_clients(test, "teardown")
        except Exception:  # noqa: BLE001
            logger.exception("client teardown failed")
        if nem is not None:
            try:
                nem.teardown(test)
            except Exception:  # noqa: BLE001
                logger.exception("nemesis teardown failed")


class _LiveStream:
    """``test["stream?"]``: tee the interpreter's op log into a running
    ``checker.streaming.StreamingChecker`` so a linearizability
    violation is reported WHILE the test runs, not minutes later when
    ``analyze`` gets the stored history (ISSUE 19: check latency
    measured from the offending op, not from end-of-run).

    The live verdict is advisory — ``analyze`` still runs the test's
    checker post-hoc and its results stay authoritative — but on the
    same history the streaming verdict is identical by construction
    (the differential suite pins that).  Ops buffer and feed in
    ``test["stream-every"]``-op epochs (default 32: each epoch re-packs
    the current prefix, so per-op feeding would be quadratic host
    work)."""

    def __init__(self, test: Mapping, model):
        from jepsen_tpu.checker.streaming import StreamingChecker

        self.every = max(1, int(test.get("stream-every") or 32))
        self.checker = StreamingChecker(
            model,
            capacity=tuple(test.get("stream-capacity") or (64, 256)),
        )
        self._buf: list[dict] = []
        self._announced = False

    def sink(self, op: Mapping) -> None:
        """The interpreter's ``op-sink`` callable (history order)."""
        self._buf.append(dict(op))
        if len(self._buf) >= self.every:
            self._flush()

    def _flush(self) -> None:
        buf, self._buf = self._buf, []
        if buf:
            self.checker.feed(buf)
        self._announce()

    def _announce(self) -> None:
        if not self.checker.terminal or self._announced:
            return
        self._announced = True
        res = self.checker.result or {}
        det = self.checker.detection or {}
        if res.get("valid?") is False:
            logger.warning(
                "STREAMING: linearizability violation detected while the "
                "test runs — op position %s, %s ops seen (analysis will "
                "confirm post-hoc)", det.get("op-position"), det.get("ops"),
            )
        else:
            logger.info("streaming verdict: valid?=%s", res.get("valid?"))

    def finish(self) -> dict:
        """End of run: flush, finalize, return the stream's status doc
        (recorded as ``test["streaming"]``)."""
        self._flush()
        self.checker.finalize()
        self._announce()
        return self.checker.status()


def _stream_model(test: Mapping):
    """The model a live stream checks against: ``test["model"]`` or the
    test checker's ``.model`` (the Linearizable checker carries one)."""
    model = test.get("model")
    if model is None:
        model = getattr(test.get("checker"), "model", None)
    if model is None:
        # a composed checker hides its linearizable child's model
        children = getattr(test.get("checker"), "checker_map", None) or {}
        for child in children.values():
            model = getattr(child, "model", None)
            if model is not None:
                break
    if isinstance(model, str):
        from jepsen_tpu import models

        model = models.model(model)
    return model


def _live_stream(test: Mapping) -> "_LiveStream | None":
    """Build the live streaming monitor when ``test["stream?"]`` asks
    for one.  Never raises — a broken monitor must not cost the run."""
    if not test.get("stream?"):
        return None
    model = _stream_model(test)
    if model is None:
        logger.warning(
            "stream? is set but the test names no model (set "
            "test['model'] or use a checker with .model); "
            "live streaming disabled")
        return None
    try:
        return _LiveStream(test, model)
    except Exception:  # noqa: BLE001 — monitor, not the run
        logger.exception("couldn't start live streaming; disabled")
        return None


def analyze(test: Mapping, *, capture: bool = True) -> dict:
    """Index the history, run the checker, store the results — the TPU
    insertion point (core.clj:221-237, SURVEY.md §3.3).

    ``capture`` tees the harness log to the run's jepsen.log
    (store.clj:436-464); run_test passes False because its own capture
    already spans the analysis.  A standalone analyze (CLI ``analyze``)
    opens its own telemetry recording into the store dir; under run_test
    the spans nest into the run's already-open recording.

    Fault-tolerance keys flow from the test map into the checker opts:
    ``"check-deadline"`` (seconds; CLI ``--check-deadline``) becomes the
    shared wall-clock budget, ``"checkpoint-dir"`` (default: the run's
    store dir; env ``JEPSEN_TPU_CHECKPOINT`` overrides) is where the
    TPU ladder persists its per-stage checkpoint, and ``"resume?"``
    (CLI ``analyze --resume <run-dir>``; implied by the env var) re-
    enters an interrupted ladder at the saved rung.  A deadline expiry
    degrades the remaining work to attributable unknowns — results.json
    is ALWAYS written complete."""
    test = dict(test)
    cm = (
        store.capture_logging(test) if capture else contextlib.nullcontext()
    )
    with cm, obs.recording(store.test_dir(test), enabled=obs.enabled_for(test)):
        with obs.span("phase.analyze") as sp:
            test["history"] = h.index(test.get("history") or [])
            checker = test.get("checker")
            if checker is not None:
                results = chk.check_safe(
                    checker, test, test["history"], _checker_opts(test)
                )
            else:
                results = {"valid?": True}
            sp.set(valid=results.get("valid?"))
            test["results"] = results
        _write_checker_times(test)
        with obs.span("phase.save-results"):
            store.save_2(test)
    return test


def _checker_opts(test: Mapping) -> dict:
    """The checker-opts fragment analyze derives from the test map (see
    analyze's docstring for the key semantics)."""
    opts: dict = {}
    if test.get("check-deadline") is not None:
        opts["check-deadline"] = test["check-deadline"]
    ck_env = os.environ.get("JEPSEN_TPU_CHECKPOINT")
    ck = test.get("checkpoint-dir") or ck_env
    try:
        opts["checkpoint-dir"] = str(ck) if ck else str(store.test_dir(test))
    except KeyError:  # no name/start-time in the map: no store, no checkpoint
        if ck:
            opts["checkpoint-dir"] = str(ck)
    if test.get("resume?") or ck_env:
        opts["resume?"] = True
    return opts


def _write_checker_times(test: Mapping) -> None:
    """Telemetry-backed checker-time artifact, next to the latency graphs
    (checker/perf.py renders it from the recording's checker.check spans)."""
    rec = obs.active()
    if rec is None:
        return
    try:
        from jepsen_tpu.checker import perf

        perf.write_checker_times(test, rec.events)
    except Exception:  # noqa: BLE001 — an observability artifact must
        # never fail the analysis that produced the verdict
        logger.debug("couldn't write checker-times artifact", exc_info=True)


def log_results(test: Mapping):
    """(core.clj:239-252)."""
    v = (test.get("results") or {}).get("valid?")
    name = test.get("name")
    if v is True:
        logger.info("Everything looks good! ヽ(‘ー`)ノ — %s", name)
    elif v == "unknown":
        logger.warning("Errors occurred during analysis; validity unknown — %s", name)
    else:
        logger.warning("Analysis invalid! (ノಥ益ಥ）ノ ┻━┻ — %s", name)


def run_test(test: Mapping) -> dict:
    """The whole lifecycle; returns the completed test map with :history
    and :results (core.clj:327-406)."""
    test = prepare_test(test)
    with contextlib.ExitStack() as stack:
        # Tee the whole run's log — setup through analysis — into the
        # store dir (store.clj:436-464), and open the run's telemetry
        # recording next to it (telemetry.jsonl + rolled-up
        # telemetry.json on close).
        stack.enter_context(store.capture_logging(test))
        stack.enter_context(
            obs.recording(store.test_dir(test), enabled=obs.enabled_for(test))
        )
        return _run_test_captured(test)


def _run_test_captured(test: dict) -> dict:
    store.save_0(test)
    logger.info("Running test %s/%s", test["name"], test["start-time-str"])
    with control.with_sessions(test):
        os_ = test.get("os")
        database = test.get("db")
        try:
            with obs.span("phase.db-cycle", nodes=len(test.get("nodes") or [])):
                if os_ is not None:
                    control.on_nodes(test, os_.setup)
                if database is not None:
                    jdb.cycle_db(test)
            live = _live_stream(test)
            with relative_time(), obs.span("phase.run-case") as sp:
                # the sink rides a COPY so the callable never lands in
                # the persisted test map
                history = run_case(
                    test if live is None
                    else {**test, "op-sink": live.sink})
                sp.set(ops=len(history))
            test = dict(test)
            test["history"] = history
            if live is not None:
                with obs.span("phase.stream-finalize"):
                    test["streaming"] = live.finish()
            with obs.span("phase.save-history"):
                store.save_1(test)
        finally:
            # Logs are snarfed even when the run crashed — debugging a
            # crash needs them most (core.clj:150-166 shutdown hook).
            try:
                with obs.span("phase.snarf-logs"):
                    snarf_logs(test)
            except Exception:  # noqa: BLE001
                logger.exception("log download failed")
            with obs.span("phase.teardown"):
                try:
                    if database is not None and not test.get("leave-db-running?"):
                        control.on_nodes(test, database.teardown)
                except Exception:  # noqa: BLE001
                    logger.exception("db teardown failed")
                try:
                    if os_ is not None:
                        control.on_nodes(test, os_.teardown)
                except Exception:  # noqa: BLE001
                    logger.exception("os teardown failed")
    test = analyze(test, capture=False)
    log_results(test)
    return test
