"""Small reporting/REPL/codec conveniences.

Mirrors the reference's ``jepsen.report`` (stdout-to-file macro,
report.clj), ``jepsen.repl`` (latest-test helper, repl.clj), and
``jepsen.codec`` (data <-> bytes, codec.clj) — deliberately tiny, as in
the reference (16 + 9 + 29 LoC).
"""

from __future__ import annotations

import contextlib
import io
import json
import sys
from pathlib import Path


@contextlib.contextmanager
def to_file(path: str | Path):
    """Redirect stdout into a file for the duration (report.clj's
    ``to`` macro) — e.g. rendering an analysis summary into the store."""
    Path(path).parent.mkdir(parents=True, exist_ok=True)
    buf = io.StringIO()
    old = sys.stdout
    sys.stdout = buf
    try:
        yield
    finally:
        sys.stdout = old
        Path(path).write_text(buf.getvalue())


def latest_test(store_dir=None) -> dict | None:
    """The most recently run test, loaded (repl.clj:5-9)."""
    from jepsen_tpu import store

    return store.latest(store_dir=store_dir)


def encode(obj) -> bytes:
    """Data → bytes (codec.clj:12-20; JSON where the reference uses
    EDN)."""
    from jepsen_tpu.store import _jsonable

    return json.dumps(_jsonable(obj), separators=(",", ":")).encode()


def decode(data: bytes):
    """Bytes → data (codec.clj:22-29)."""
    if not data:
        return None
    return json.loads(data.decode())
