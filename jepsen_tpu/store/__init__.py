"""Persistence: where test runs live on disk.

Mirrors ``jepsen.store`` (reference: jepsen/src/jepsen/store.clj): each run
gets ``store/<name>/<timestamp>/`` (store.clj:33-68) holding the test map,
the history, the results, and downloaded node logs, with ``latest``
symlinks maintained at both levels (store.clj:282-319).  Writes happen in
three phases, exactly like the reference's crash-safety story
(store.clj:375-420, rationale in store/format.clj:141-150):

  save_0 — initial test map, before anything runs
  save_1 — the history, as soon as the run ends (pre-analysis: a crash in
           a checker must never lose the history)
  save_2 — the results

Formats: the test map and results are JSON (non-serializable values
stringified, mirroring store.clj:92-104's nonserializable-key stripping);
the history is JSON-lines (one op per line, like history.edn) plus the
human-readable ``history.txt``.  All writes go through tmp + fsync +
rename (+ directory fsync) so a crash never leaves a torn file and a
completed write survives a hard power cut — the same path the checker's
``checker-checkpoint.json``/``.npz`` ride (store/checkpoint.py).
"""

from __future__ import annotations

import contextlib as _contextlib
import datetime as _dt
import json
import logging
import os
import shutil
import tempfile
from pathlib import Path
from typing import Any, Mapping, Sequence

from jepsen_tpu import history as _h

logger = logging.getLogger(__name__)

BASE_DIR = Path("store")

#: Test-map keys that can't/shouldn't be serialized (functions, live
#: objects) — store.clj:92-104.
NONSERIALIZABLE_KEYS = (
    "db", "os", "net", "client", "nemesis", "checker", "generator", "remote",
    "sessions", "barrier", "store",
)


def base_dir(test_or_opts: Mapping | None = None) -> Path:
    if test_or_opts and test_or_opts.get("store-dir"):
        return Path(test_or_opts["store-dir"])
    return BASE_DIR


def time_str(t: _dt.datetime | None = None) -> str:
    """Directory-name timestamp (store.clj:45-50)."""
    t = t or _dt.datetime.now()
    return t.strftime("%Y%m%dT%H%M%S.%f")[:-3] + "Z"


def test_dir(test: Mapping) -> Path:
    return base_dir(test) / str(test["name"]) / str(test["start-time-str"])


def _jsonable(x: Any):
    if isinstance(x, Mapping):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple, set, frozenset)):
        return [_jsonable(v) for v in x]
    if isinstance(x, _h.ColumnHistory):
        return [_jsonable(v) for v in x]
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    try:
        import numpy as np

        if isinstance(x, np.integer):
            return int(x)
        if isinstance(x, np.floating):
            return float(x)
        if isinstance(x, np.ndarray):
            return x.tolist()
    except ImportError:  # pragma: no cover
        pass
    return str(x)


def serializable_test(test: Mapping) -> dict:
    """The test map minus live objects (store.clj:92-104)."""
    return _jsonable({k: v for k, v in test.items() if k not in NONSERIALIZABLE_KEYS})


def _fsync_dir(d: Path) -> None:
    """fsync a directory so a just-renamed entry survives a power cut
    (rename atomicity alone only orders the rename against the crash,
    not against the disk).  Platforms without directory fds skip."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:  # pragma: no cover - e.g. Windows
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - fs without dir fsync
        pass
    finally:
        os.close(fd)


def _write_seam(step: str, path) -> None:
    """The crashpoint-audit seam: every ``_atomic_write`` step announces
    itself through ``faults.INJECT`` so ``tools/crashpoint.py`` can kill
    (SIGKILL in a child) or simulate a death (``faults.CrashPoint``) at
    exactly post-tmp / post-fsync / post-rename / pre-dir-fsync.
    Injectors MUST filter on ``ctx["what"] == "store.atomic_write"`` —
    a rate-based launch-fault schedule raising here would fault writes
    no retry policy covers (``faults.seeded_injector`` skips these
    seams unless explicitly targeted).  Lazy import: the store package
    stays importable without the faults layer resolved first."""
    from jepsen_tpu import faults

    hook = faults.INJECT
    if hook is not None:
        hook({"what": "store.atomic_write", "step": step,
              "path": str(path)}, 0)


def _atomic_write(path: Path, data: str | bytes):
    """tmp + fsync + rename + dir fsync: a reader never sees a torn
    file (rename atomicity), and a completed write survives a hard
    power cut (the data AND the directory entry are durable before the
    tmp name disappears).  Checkpoints and results both ride this.
    Torn-by-other-means (bit rot, hand edits, partial copies) is the
    durable-record layer's job — see ``store.durable``, whose checksums
    wrap every artifact that outlives a process.

    The tmp name is UNIQUE per writer (mkstemp), not ``<path>.tmp``:
    composed checkers write into one run dir concurrently, and two
    writers sharing a fixed tmp name could publish a torn mix of both.
    Concurrent same-path writers thus stay last-writer-wins, each write
    atomic.  (Crashed writers leave their unique ``*.tmp`` behind;
    ``durable.sweep_tmp`` reclaims them at store open / service start.)

    Each write step runs the ``faults.INJECT`` crashpoint seam
    (``_write_seam``).  A ``faults.CrashPoint`` raised there simulates
    the process dying at that step: NO cleanup runs, so the on-disk
    state is exactly what a SIGKILL at that instant leaves."""
    path = Path(path)
    fd, tmp = tempfile.mkstemp(
        dir=str(path.parent or "."), prefix=path.name + ".", suffix=".tmp"
    )
    binary = isinstance(data, (bytes, bytearray))
    try:
        with os.fdopen(fd, "wb" if binary else "w") as f:
            f.write(data)
            f.flush()
            _write_seam("post-tmp", path)
            os.fsync(f.fileno())
        _write_seam("post-fsync", path)
        os.chmod(tmp, 0o644)  # mkstemp's 0600 would hide artifacts from the web UI user
        os.replace(tmp, path)
    except BaseException as e:
        from jepsen_tpu import faults as _faults

        if not isinstance(e, _faults.CrashPoint):
            with _contextlib.suppress(OSError):
                os.unlink(tmp)
        raise
    _write_seam("post-rename", path)
    _write_seam("pre-dir-fsync", path)
    _fsync_dir(path.parent)


def _write_json(path: Path, obj):
    _atomic_write(path, json.dumps(_jsonable(obj), indent=1))


def write_history(d: Path, history: Sequence[Mapping]):
    """history.jsonl (machine) + history.txt (human) — store.clj:384-399
    writes both forms in parallel futures; sequential is fine here."""
    lines = [json.dumps(_jsonable(o), separators=(",", ":")) for o in history]
    _atomic_write(d / "history.jsonl", "\n".join(lines) + ("\n" if lines else ""))
    txt = []
    for o in history:
        txt.append(
            f"{o.get('index', ''):>8} {str(o.get('process', '')):>8} "
            f"{o.get('type', ''):<8} {str(o.get('f', '')):<16} {o.get('value', '')!r}"
        )
    _atomic_write(d / "history.txt", "\n".join(txt) + ("\n" if txt else ""))


def _run_file(d: Path) -> Path:
    return d / "run.jepsen"


def save_0(test: Mapping) -> Mapping:
    """Write the initial test map; returns test with paths filled
    (store.clj:375-382)."""
    from jepsen_tpu.store import format as fmt

    d = test_dir(test)
    d.mkdir(parents=True, exist_ok=True)
    # Store open is the sweep point for ``*.tmp`` orphans a crashed
    # writer left in this run dir (age-gated: a concurrently-writing
    # composed checker's live tmp survives).
    from jepsen_tpu.store import durable as _durable

    _durable.sweep_tmp(d, what="store")
    _write_json(d / "test.json", serializable_test(test))
    w = fmt.Writer(_run_file(d))
    w.write_test(test)
    update_symlinks(test)
    return test


def save_1(test: Mapping) -> Mapping:
    """Write the history immediately after the run — BEFORE analysis, so a
    crash in a checker can never lose it (store.clj:384-399)."""
    from jepsen_tpu.store import format as fmt

    d = test_dir(test)
    d.mkdir(parents=True, exist_ok=True)
    _write_json(d / "test.json", serializable_test(test))
    write_history(d, test.get("history") or [])
    w = fmt.Writer(_run_file(d))
    if not any(b["type"] == fmt.T_TEST for b in w.index["blocks"]):
        w.write_test(test)
    w.write_history(test.get("history") or [])
    return test


def save_2(test: Mapping) -> Mapping:
    """Write the results and seal the block file (store.clj:401-419)."""
    from jepsen_tpu.store import format as fmt

    d = test_dir(test)
    d.mkdir(parents=True, exist_ok=True)
    _write_json(d / "results.json", test.get("results") or {})
    w = fmt.Writer(_run_file(d))
    # A dir stored before the block format (or whose file was lost) gets a
    # complete file, not a results-only one that would shadow the JSON
    # artifacts in load_dir.
    if not any(b["type"] == fmt.T_TEST for b in w.index["blocks"]):
        w.write_test(test)
    if not any(b["type"] == fmt.T_HISTORY for b in w.index["blocks"]) and test.get("history"):
        w.write_history(test["history"])
    w.write_results(test.get("results") or {})
    w.close()
    update_symlinks(test)
    return test


def update_symlinks(test: Mapping):
    """Maintain <name>/latest and store/latest (store.clj:282-319)."""
    d = test_dir(test)
    for link in (d.parent / "latest", base_dir(test) / "latest"):
        try:
            if link.is_symlink() or link.exists():
                link.unlink()
            link.parent.mkdir(parents=True, exist_ok=True)
            link.symlink_to(os.path.relpath(d, link.parent))
        except OSError:  # pragma: no cover - symlinks may be unsupported
            logger.debug("couldn't update symlink %s", link, exc_info=True)


# ---------------------------------------------------------------------------
# Reading (store.clj:121-246)
# ---------------------------------------------------------------------------


def iter_runs(store_dir=None):
    """Yield ``(name, timestamp, run_dir, mtime_ns)`` for every stored
    run — the ONE store-directory enumeration (dir, non-symlink, two
    levels) that ``tests()`` and the web dashboard's cached run index
    both consume, so what counts as "a run" can never diverge between
    the API and the UI."""
    base = base_dir({"store-dir": store_dir} if store_dir else None)
    if not base.exists():
        return
    for name_dir in sorted(base.iterdir()):
        if not name_dir.is_dir() or name_dir.is_symlink():
            continue
        for run in sorted(name_dir.iterdir()):
            if not run.is_dir() or run.is_symlink():
                continue
            try:
                mt = run.stat().st_mtime_ns
            except OSError:
                continue
            yield name_dir.name, run.name, run, mt


def tests(name: str | None = None, store_dir=None) -> dict:
    """{name: {timestamp: path}} of stored runs (store.clj:121-160)."""
    out: dict = {}
    for n, ts, run, _mt in iter_runs(store_dir=store_dir):
        if name is not None and n != name:
            continue
        out.setdefault(n, {})[ts] = run
    return out


def load(name: str, timestamp: str, store_dir=None) -> dict:
    """Load a stored test (test map + history + results)
    (store.clj:196-246)."""
    base = base_dir({"store-dir": store_dir} if store_dir else None)
    d = base / name / timestamp
    return load_dir(d)


def load_dir(d: Path) -> dict:
    d = Path(d)
    run = d / "run.jepsen"
    if run.exists():
        from jepsen_tpu.store import format as fmt

        try:
            idx = fmt.read_index(run)
            test = fmt.read(run, index=idx, history=False)
            cols, fs, extras = fmt.read_columns(run, index=idx)
            if len(cols["index"]):
                # the zero-copy path: ops materialize lazily; kernels and
                # vectorized consumers read the columns directly
                test["history"] = _h.ColumnHistory(cols, fs, extras)
            test["dir"] = str(d)
            return test
        except fmt.CorruptFile:
            logger.warning("corrupt %s; falling back to JSON artifacts", run)
    test = json.loads((d / "test.json").read_text()) if (d / "test.json").exists() else {}
    hist_path = d / "history.jsonl"
    if hist_path.exists():
        test["history"] = [
            json.loads(line) for line in hist_path.read_text().splitlines() if line
        ]
    res_path = d / "results.json"
    if res_path.exists():
        test["results"] = json.loads(res_path.read_text())
    test["dir"] = str(d)
    return test


def peek_dir(d: Path) -> dict:
    """Cheap metadata read: name / start-time / valid? / op-count WITHOUT
    loading history or results — the block file footer when present
    (store/format.py read_index), else the small JSON artifacts.  This is
    what the web test table and `test-all` summaries use."""
    d = Path(d)
    run = d / "run.jepsen"
    if run.exists():
        from jepsen_tpu.store import format as fmt

        try:
            idx = fmt.read_index(run)
            return {
                "name": idx.get("name"),
                "start-time-str": idx.get("start-time"),
                "valid?": idx.get("valid?"),
                "op-count": idx.get("op-count"),
                "dir": str(d),
            }
        except fmt.CorruptFile:
            pass
    out: dict = {"dir": str(d)}
    try:
        t = json.loads((d / "test.json").read_text())
        out["name"] = t.get("name")
        out["start-time-str"] = t.get("start-time-str")
    except (OSError, ValueError):
        pass
    try:
        out["valid?"] = json.loads((d / "results.json").read_text()).get("valid?")
    except (OSError, ValueError):
        out.setdefault("valid?", None)
    return out


def latest(store_dir=None) -> dict | None:
    """The most recent run across all tests (store.clj:282-291)."""
    base = base_dir({"store-dir": store_dir} if store_dir else None)
    link = base / "latest"
    if link.exists():
        return load_dir(link.resolve())
    newest = None
    for name, runs in tests(store_dir=store_dir).items():
        for ts, path in runs.items():
            if newest is None or ts > newest[0]:
                newest = (ts, path)
    return load_dir(newest[1]) if newest else None


def delete(name: str | None = None, store_dir=None):
    """Delete stored runs (store.clj:248-266)."""
    base = base_dir({"store-dir": store_dir} if store_dir else None)
    target = base / name if name else base
    if target.exists():
        shutil.rmtree(target)


@_contextlib.contextmanager
def capture_logging(test: Mapping, filename: str = "jepsen.log"):
    """Tee the harness log to ``store/<name>/<time>/<filename>`` for the
    duration (reference: jepsen/src/jepsen/store.clj:436-464 — unilog
    writes the run's console log to jepsen.log so a stored run carries
    its own post-mortem record; jepsen.web serves it).

    The file captures INFO+ regardless of the console level; existing
    handlers keep their previous effective threshold so console output
    is unchanged.
    """
    d = test_dir(test)
    d.mkdir(parents=True, exist_ok=True)
    handler = logging.FileHandler(d / filename, encoding="utf-8")
    handler.setLevel(logging.INFO)
    handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname)-7s [%(name)s] %(message)s")
    )
    root = logging.getLogger()
    old_level = root.level
    bumped: list[tuple[logging.Handler, int]] = []
    if root.getEffectiveLevel() > logging.INFO:
        # Lower the root gate so INFO records reach our handler, but pin
        # the previous threshold onto the other handlers.
        for hh in root.handlers:
            if hh.level < root.getEffectiveLevel():
                bumped.append((hh, hh.level))
                hh.setLevel(root.getEffectiveLevel())
        root.setLevel(logging.INFO)
    root.addHandler(handler)
    try:
        yield d / filename
    finally:
        root.removeHandler(handler)
        handler.close()
        root.setLevel(old_level)
        for hh, lvl in bumped:
            hh.setLevel(lvl)
