"""`.jepsen` block file: single-file runs with cheap partial reads.

Mirrors the *objectives* of ``jepsen.store.format`` (reference:
jepsen/src/jepsen/store/format.clj:36-176) with a tensor-native layout
instead of Fressian:

  - one append-only file per run: magic ``JTPU1`` + version + an 8-byte
    footer-index offset patched in last (format.clj:36-53's
    block-index-offset header);
  - self-delimiting blocks ``[u32 len | u32 crc32 | u8 type | payload]``
    (format.clj:66-81) so a crash mid-write never corrupts earlier
    blocks, and a file without a footer is recovered by scanning
    (format.clj:141-150's crash-safe history recovery);
  - history chunks store the PACKED SoA int64 columns
    (jepsen_tpu.history.pack's layout: the kernels' native form) plus a
    JSON sidecar for op fields the columns can't hold — loading a stored
    run for re-checking costs one mmap-friendly read, no per-op parsing;
  - the footer index carries ``{name, start-time, valid?, op-count,
    block offsets}`` so ``valid?``/name/time reads never touch history
    blocks — the reference's PartialMap trick (format.clj:113-129), which
    the web UI's test table depends on.

Write lifecycle matches the reference's crash-safety story
(store.clj:375-420): save-0 appends the test map, save-1 appends history
chunks the moment the run ends, save-2 appends results + footer.
"""

from __future__ import annotations

import io
import json
import struct
import zlib
from pathlib import Path
from typing import Any, Mapping, Sequence

import numpy as np

MAGIC = b"JTPU1\x00"
VERSION = 1
HEADER_LEN = len(MAGIC) + 2 + 8  # magic + u16 version + u64 footer offset

# Block types
T_TEST = 1
T_HISTORY = 2
T_RESULTS = 3
T_INDEX = 4

#: ops per history chunk — large enough to amortize, small enough to
#: stream (reference chunks history similarly for lazy loads).
CHUNK_OPS = 8192

#: op fields with dedicated SoA columns; everything else rides the JSON
#: sidecar.
_COLS = ("index", "type", "process", "f", "time", "value1", "value2")


class CorruptFile(Exception):
    pass


def _col_packable(v, nil) -> bool:
    """Can the value ride the int columns and round-trip exactly?  Bools,
    >2-element sequences, NIL-colliding ints, and anything non-integer go
    to the JSON sidecar instead."""

    def ok_int(x):
        return (
            x is None
            or (isinstance(x, (int, np.integer)) and not isinstance(x, bool) and int(x) != int(nil) and -(2**62) < int(x) < 2**62)
        )

    if ok_int(v):
        return True
    # Pairs round-trip via (v1, v2) — except a None second element, which
    # decodes back as a bare int (the columns can't tell them apart).
    if isinstance(v, (list, tuple)) and len(v) == 2:
        return ok_int(v[0]) and ok_int(v[1]) and v[1] is not None
    return False


def _pack_chunk(ops: Sequence[Mapping]) -> bytes:
    """One history chunk: packed int64 columns + JSON sidecar.

    Columns: index, type-code, process (NEMESIS → -1), f interned id,
    time, value1/value2 (register encoding when packable).  The sidecar
    holds the f vocabulary and, per op, any fields the columns can't
    carry (non-integer values, extra keys like clock-offsets).
    """
    from jepsen_tpu import history as h

    n = len(ops)
    cols = {c: np.zeros(n, np.int64) for c in _COLS}
    f_ids: dict[str, int] = {}
    extras: dict[int, dict] = {}
    type_codes = {h.INVOKE: 0, h.OK: 1, h.FAIL: 2, h.INFO: 3}
    for i, o in enumerate(ops):
        cols["index"][i] = o.get("index", i)
        cols["type"][i] = type_codes.get(o.get("type"), 3)
        p = o.get("process")
        p_packable = isinstance(p, (int, np.integer)) and not isinstance(p, bool)
        cols["process"][i] = int(p) if p_packable else -1
        fname = str(o.get("f"))
        cols["f"][i] = f_ids.setdefault(fname, len(f_ids))
        cols["time"][i] = int(o.get("time") or 0)
        extra = {
            k: v
            for k, v in o.items()
            if k not in ("index", "type", "process", "f", "time", "value")
        }
        v = o.get("value")
        if _col_packable(v, h.NIL):
            v1, v2 = h.encode_register_value(None, list(v) if isinstance(v, tuple) else v)
            cols["value1"][i], cols["value2"][i] = v1, v2
            if isinstance(v, tuple):
                extra["value-tuple?"] = True
        else:
            cols["value1"][i] = cols["value2"][i] = int(h.NIL)
            extra["value"] = v
        if o.get("type") not in type_codes:
            extra["type"] = o.get("type")
        if not p_packable:
            extra["process"] = p
        if extra:
            extras[i] = extra
    buf = io.BytesIO()
    np.savez(buf, **cols)
    sidecar = json.dumps(
        {"fs": list(f_ids), "extras": {str(k): _jsonable(v) for k, v in extras.items()}},
        separators=(",", ":"),
    ).encode()
    # (op-count, sidecar-len) prefix: scans recover op counts without
    # touching the npz payload.
    return struct.pack("<II", n, len(sidecar)) + sidecar + buf.getvalue()


def _unpack_chunk(payload: bytes) -> list[dict]:
    # One decoder for both read paths: ColumnHistory's batch
    # materializer IS the dict decode (history.py), so the eager read
    # and the zero-copy read can never diverge.
    from jepsen_tpu import history as h

    cols, fs, extras = _chunk_columns(payload)
    return h.ColumnHistory(cols, fs, extras).materialized()


def _jsonable(x: Any):
    from jepsen_tpu import store

    return store._jsonable(x)


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------


class Writer:
    """Append blocks to a run file; call close() (or save_2 path) to seal
    with the footer index (format.clj:131-158 write lifecycle)."""

    def __init__(self, path: str | Path):
        self.path = Path(path)
        self.index: dict = {"blocks": []}
        if not self.path.exists():
            with open(self.path, "wb") as f:
                f.write(MAGIC + struct.pack("<HQ", VERSION, 0))
        else:
            # Re-opening an existing file (save_1 after save_0): recover
            # its block table, drop any footer (we'll rewrite it).
            self.index = scan(self.path)

    def _append(self, btype: int, payload: bytes) -> dict:
        try:
            from jepsen_tpu.native import blockio

            ext = blockio()
        except ImportError:
            ext = None
        with open(self.path, "r+b") as f:
            if ext is not None:
                # C fast path: CRC + framed append in one buffer pass
                # (the FileOffsetOutputStream role).
                off, _n = ext.append_block(f.fileno(), btype, payload)
            else:
                f.seek(0, 2)
                off = f.tell()
                f.write(struct.pack("<IIB", len(payload), zlib.crc32(payload), btype))
                f.write(payload)
        entry = {"type": btype, "offset": off, "len": len(payload)}
        self.index["blocks"].append(entry)
        return entry

    def write_test(self, test: Mapping):
        from jepsen_tpu import store

        self._append(T_TEST, json.dumps(store.serializable_test(test)).encode())
        self.index["name"] = str(test.get("name"))
        self.index["start-time"] = str(test.get("start-time-str"))

    def write_history(self, history: Sequence[Mapping]):
        for lo in range(0, len(history), CHUNK_OPS):
            self._append(T_HISTORY, _pack_chunk(history[lo : lo + CHUNK_OPS]))
        self.index["op-count"] = len(history)

    def write_results(self, results: Mapping):
        self._append(T_RESULTS, json.dumps(_jsonable(results)).encode())
        self.index["valid?"] = results.get("valid?")

    def close(self):
        """Append the footer index and patch its offset into the header —
        the last write; a crash before this leaves a scannable file."""
        payload = json.dumps(_jsonable(self.index)).encode()
        entry = self._append(T_INDEX, payload)
        with open(self.path, "r+b") as f:
            f.seek(len(MAGIC) + 2)
            f.write(struct.pack("<Q", entry["offset"]))


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------


def _read_block(f, off: int) -> tuple[int, bytes]:
    f.seek(off)
    hdr = f.read(9)
    if len(hdr) < 9:
        raise CorruptFile(f"truncated block header at {off}")
    length, crc, btype = struct.unpack("<IIB", hdr)
    payload = f.read(length)
    if len(payload) < length:
        raise CorruptFile(f"truncated block payload at {off}")
    if zlib.crc32(payload) != crc:
        raise CorruptFile(f"crc mismatch at {off}")
    return btype, payload


def _peek_block(f, off: int, end: int) -> tuple[int, int, bytes]:
    """Block type, total size, and a small payload PREFIX — without
    reading (or CRC-checking) the whole payload.  Truncation is detected
    by bounds; a torn tail within the final block is caught by the full
    read path when that block is actually loaded."""
    f.seek(off)
    hdr = f.read(9)
    if len(hdr) < 9:
        raise CorruptFile(f"truncated block header at {off}")
    length, _crc, btype = struct.unpack("<IIB", hdr)
    if off + 9 + length > end:
        raise CorruptFile(f"truncated block payload at {off}")
    prefix = f.read(min(length, 4096))
    return btype, length, prefix


def scan(path: str | Path) -> dict:
    """Walk every block; rebuild the index (crash recovery — a file
    without a footer still yields everything fully written,
    format.clj:141-150)."""
    index: dict = {"blocks": []}
    with open(path, "rb") as f:
        head = f.read(HEADER_LEN)
        if head[: len(MAGIC)] != MAGIC:
            raise CorruptFile("bad magic")
        if len(head) < HEADER_LEN:
            raise CorruptFile("truncated header")
        off = HEADER_LEN
        end = f.seek(0, 2)
        while off < end:
            try:
                btype, length, prefix = _peek_block(f, off, end)
            except CorruptFile:
                break  # torn tail from a crash: keep what's whole
            if btype == T_INDEX:
                _bt, payload = _read_block(f, off)
                base = json.loads(payload.decode())
                base["blocks"] = index["blocks"]
                index = base
            else:
                index["blocks"].append({"type": btype, "offset": off, "len": length})
                if btype == T_HISTORY:
                    (n_ops,) = struct.unpack_from("<I", prefix)
                    index["op-count"] = index.get("op-count", 0) + n_ops
                elif btype in (T_TEST, T_RESULTS):
                    _bt, payload = _read_block(f, off)
                    data = json.loads(payload.decode())
                    if btype == T_TEST:
                        index["name"] = data.get("name")
                        index["start-time"] = data.get("start-time-str")
                    else:
                        index["valid?"] = data.get("valid?")
            off += 9 + length
    return index


def read_index(path: str | Path) -> dict:
    """The cheap read: footer only — name/start-time/valid?/op-count
    without touching history blocks (the PartialMap role,
    format.clj:113-129).  Falls back to a scan for unsealed files."""
    with open(path, "rb") as f:
        head = f.read(HEADER_LEN)
        if head[: len(MAGIC)] != MAGIC:
            raise CorruptFile("bad magic")
        if len(head) < HEADER_LEN:
            raise CorruptFile("truncated header")
        (version, footer_off) = struct.unpack("<HQ", head[len(MAGIC) :])
        if footer_off:
            try:
                btype, payload = _read_block(f, footer_off)
                if btype == T_INDEX:
                    return json.loads(payload.decode())
            except (CorruptFile, ValueError):
                pass  # torn footer: recover by scanning below
    return scan(path)


def read(path: str | Path, index: dict | None = None, history: bool = True) -> dict:
    """Load the full run: test map + history + results.  ``history=False``
    skips the history blocks (callers on the zero-copy path read them as
    columns via ``read_columns`` instead)."""
    index = index or read_index(path)
    out: dict = {}
    hist: list = []
    with open(path, "rb") as f:
        for entry in index["blocks"]:
            if not history and entry["type"] == T_HISTORY:
                continue
            btype, payload = _read_block(f, entry["offset"])
            if btype == T_TEST:
                out.update(json.loads(payload.decode()))
            elif btype == T_HISTORY:
                hist.extend(_unpack_chunk(payload))
            elif btype == T_RESULTS:
                out["results"] = json.loads(payload.decode())
    if hist:
        out["history"] = hist
    return out


def _chunk_columns(payload: bytes):
    """One history chunk's raw columns without materializing op dicts."""
    _n, side_len = struct.unpack_from("<II", payload)
    sidecar = json.loads(payload[8 : 8 + side_len].decode())
    npz = np.load(io.BytesIO(payload[8 + side_len :]))
    cols = {c: npz[c] for c in _COLS}
    return cols, sidecar["fs"], {int(k): v for k, v in sidecar["extras"].items()}


def read_columns(path: str | Path, index: dict | None = None):
    """The stored history as concatenated SoA columns — the zero-copy
    analyze path: no per-op dict is built at load time (ops materialize
    lazily through jepsen_tpu.history.ColumnHistory, and vectorized
    consumers read the arrays directly).

    Returns ``(cols, fs, extras)``: int64 column arrays over the whole
    history, the merged ``f`` vocabulary (per-chunk ids remapped), and
    ``{position: extra-fields}`` for ops the columns can't fully carry.
    """
    index = index or read_index(path)
    parts: list = []
    with open(path, "rb") as f:
        for entry in index["blocks"]:
            if entry["type"] != T_HISTORY:
                continue
            btype, payload = _read_block(f, entry["offset"])
            parts.append(_chunk_columns(payload))
    if not parts:
        return {c: np.zeros(0, np.int64) for c in _COLS}, [], {}
    fs: list[str] = []
    f_ids: dict[str, int] = {}
    extras: dict[int, dict] = {}
    off = 0
    all_cols: dict[str, list] = {c: [] for c in _COLS}
    for cols, chunk_fs, chunk_extras in parts:
        remap = np.array(
            [f_ids.setdefault(name, len(f_ids)) for name in chunk_fs], np.int64
        )
        for c in _COLS:
            all_cols[c].append(remap[cols[c]] if c == "f" else cols[c])
        for k, v in chunk_extras.items():
            extras[off + k] = v
        off += len(cols["index"])
    fs = [name for name, _ in sorted(f_ids.items(), key=lambda kv: kv[1])]
    return {c: np.concatenate(all_cols[c]) for c in _COLS}, fs, extras
