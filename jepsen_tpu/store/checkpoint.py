"""Checker checkpoint/resume: the ladder's durable state on disk.

A multi-minute ``parallel.batch_analysis`` ladder run used to live only
in process memory — a preemption lost everything.  This module persists
the ladder's durable state after every stage so a killed run resumes at
the saved rung with the saved frontiers and produces verdicts identical
to an uninterrupted run:

  ``checker-checkpoint.json``  the control state: the ladder config
      (engine, capacity ladders, rounds, dedup backend, confirmation
      mode — RNG-free by construction) plus a history fingerprint, the
      stage cursor, per-history verdicts so far, the pending set,
      in-flight confirmation descriptors, and queued device
      confirmations.
  ``checker-checkpoint.npz``   the pending lanes' carried-frontier
      resume snapshots (the round-5 snapshot machinery's
      (bsnap, state, fok, fcr, alive) tuples), keyed by history index.

Both files ride ``store._atomic_write`` (tmp + fsync + rename + dir
fsync) and the ``store.durable`` envelope: the json carries a CRC32
over its payload plus a per-file digest MANIFEST of the npz it belongs
to, npz written BEFORE json.  A crash between the two (or bit rot,
truncation, hand-editing on either file) is therefore *detected* at
load — the mismatched pair is quarantined aside
(``<name>.corrupt-<n>``) and the raised ``CheckpointError`` carries a
machine-readable corruption report (``.report``); the consumer runs
fresh, which reproduces uninterrupted verdicts, never resumes a
mixed-generation pair.  Old pre-envelope checkpoints load through the
``durable`` migration registry instead of being rejected for their
version.

Resume semantics: ``load()`` hands the saved state back;
``batch_analysis(resume=True)`` verifies the fingerprint against the
histories it was given (a mismatch is IGNORED with a warning — resuming
against different inputs can only produce wrong verdicts, running fresh
never can) and re-enters the ladder at the saved rung.  The saved
CONFIG wins over the caller's arguments on resume: the CLI resume path
cannot know the original kwargs, and verdict identity requires the
original ladder.
"""

from __future__ import annotations

import hashlib
import io
import json
from pathlib import Path
from typing import Mapping, Sequence

import numpy as np

from jepsen_tpu import store as _store
from jepsen_tpu.store import durable as _durable

CKPT_JSON = "checker-checkpoint.json"
CKPT_NPZ = "checker-checkpoint.npz"

#: payload version 2 = the durable-envelope era (checksummed json with
#: an npz digest manifest); version 1 was the bare pre-envelope doc,
#: readable through the migration below.
VERSION = 2

#: chunked-scan (single-history) checkpoint pair: the carried — possibly
#: HOST-SPILLED, so row count is unbounded — frontier between chunk
#: scans, plus the scan cursor.  Separate files from the ladder
#: checkpoint: the two can coexist in one run directory (a ladder's
#: unsafe-shape fallback runs chunked scans inside a checkpointed
#: ladder).
CHUNK_JSON = "chunk-checkpoint.json"
CHUNK_NPZ = "chunk-checkpoint.npz"

CHUNK_VERSION = 2

#: per-stream incremental checkpoint pair (checker.streaming): the op
#: stream consumed so far, the settled-scan cursor, and the carried
#: frontier between epochs.  Unlike the chunk pair the OPS THEMSELVES
#: ride the json — a streaming resume has no stored history to re-read,
#: the checkpoint IS the source of truth for what was fed, so the
#: feeder only needs the consumed-op count to continue.
STREAM_JSON = "stream-checkpoint.json"
STREAM_NPZ = "stream-checkpoint.npz"

STREAM_VERSION = 1

KIND_LADDER = "ladder-checkpoint"
KIND_CHUNK = "chunk-checkpoint"
KIND_STREAM = "stream-checkpoint"

_durable.register_kind(KIND_LADDER, VERSION)
_durable.register_kind(KIND_CHUNK, CHUNK_VERSION)
_durable.register_kind(KIND_STREAM, STREAM_VERSION)


@_durable.register_migration(KIND_LADDER, 1)
def _ladder_v1_to_v2(payload):
    # v1 was the bare doc with its own "version" key and no checksums;
    # the field shapes are otherwise identical.
    payload = {k: v for k, v in dict(payload).items() if k != "version"}
    return payload, 2


@_durable.register_migration(KIND_CHUNK, 1)
def _chunk_v1_to_v2(payload):
    payload = {k: v for k, v in dict(payload).items() if k != "version"}
    return payload, 2


class CheckpointError(Exception):
    """Missing, torn, corrupt, or version-incompatible checkpoint.

    ``report`` (when present) is the durable layer's machine-readable
    corruption report — consumers embed it in their ``cause`` / fault
    telemetry instead of a bare string."""

    def __init__(self, message: str, report: dict | None = None):
        self.report = report
        super().__init__(message)


def json_path(d) -> Path:
    return Path(d) / CKPT_JSON


def exists(d) -> bool:
    return json_path(d).exists()


def fingerprint(histories: Sequence[Sequence[Mapping]]) -> str:
    """A stable identity for the checked inputs: sha256 over every op's
    (type, process, f, value) in order, per history.

    A stored ``ColumnHistory`` hashes its SoA columns DIRECTLY —
    iterating it would materialize every op dict, defeating the store's
    zero-copy path on 50k-op runs.  The two paths therefore fingerprint
    the same content differently; that is fine — the fingerprint only
    has to be stable for the same input source (a resume re-reads the
    same stored run), and a spurious mismatch merely means a fresh run,
    never a wrong resume."""
    h = hashlib.sha256()
    for hist in histories:
        h.update(b"\x00")
        cols = getattr(hist, "cols", None)
        if cols is not None and hasattr(hist, "fs"):
            for name in ("type", "process", "f", "value1", "value2"):
                if name in cols:
                    h.update(np.ascontiguousarray(np.asarray(cols[name])).tobytes())
            h.update(json.dumps(list(hist.fs), default=str).encode())
            extras = getattr(hist, "extras", None) or {}
            if extras:
                h.update(
                    json.dumps(_store._jsonable(extras), sort_keys=True,
                               default=str).encode()
                )
            continue
        for o in hist:
            h.update(
                json.dumps(
                    [
                        _store._jsonable(o.get("type")),
                        _store._jsonable(o.get("process")),
                        _store._jsonable(o.get("f")),
                        _store._jsonable(o.get("value")),
                    ],
                    separators=(",", ":"),
                    default=str,
                ).encode()
            )
    return h.hexdigest()


def save(
    d,
    *,
    config: Mapping,
    stage: int,
    results: Mapping[int, Mapping],
    pending: Sequence[int],
    confirms: Mapping[int, Mapping] | None = None,
    device_confirms: Sequence[Mapping] | None = None,
    resumes: Mapping[int, tuple] | None = None,
    rungs: Mapping[int, int] | None = None,
    complete: bool = False,
) -> Path:
    """Atomically persist one stage boundary's state; returns the json
    path.  ``resumes`` maps history index -> (bsnap, state, fok, fcr,
    alive); ``confirms`` maps history index -> {"res", "op_pos"} for
    in-flight worker confirmations (resubmitted on resume);
    ``device_confirms`` is the queued device-confirmation descriptors
    [{"i", "failed_at", "cap", "res"}].  ``rungs`` optionally maps a
    pending history index -> its NEXT ladder-stage index — continuous
    batching admits members at rung boundaries, so pending members may
    sit at different rungs; a member absent from the map resumes at
    ``stage`` (the pre-continuous behavior, and what old checkpoints
    decode to).  ``complete`` marks a finished run — resuming it
    returns the saved results without device work."""
    d = Path(d)
    d.mkdir(parents=True, exist_ok=True)
    resumes = dict(resumes or {})
    files = None
    if resumes:
        arrays = {}
        for i, (bsnap, st, fo, fc, al) in resumes.items():
            arrays[f"{i}_bsnap"] = np.asarray(bsnap, np.int32)
            arrays[f"{i}_st"] = np.asarray(st)
            arrays[f"{i}_fo"] = np.asarray(fo)
            arrays[f"{i}_fc"] = np.asarray(fc)
            arrays[f"{i}_al"] = np.asarray(al)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        data = buf.getvalue()
        _store._atomic_write(d / CKPT_NPZ, data)
        # The json's manifest digests THIS npz: load() can prove the
        # pair belongs together (a crash between the two writes, or a
        # corrupted sibling, is detected instead of assumed away).
        files = {CKPT_NPZ: _durable.digest_bytes(data)}
    doc = {
        "complete": bool(complete),
        "config": config,
        "stage": int(stage),
        "results": {str(i): r for i, r in (results or {}).items()},
        "pending": [int(i) for i in pending],
        "confirms": {str(i): c for i, c in (confirms or {}).items()},
        "device_confirms": list(device_confirms or ()),
        "resumes": sorted(int(i) for i in resumes),
        "rungs": {str(i): int(r) for i, r in (rungs or {}).items()},
    }
    _durable.write_record(
        json_path(d), KIND_LADDER, _store._jsonable(doc), files=files
    )
    return json_path(d)


def chunk_json_path(d) -> Path:
    return Path(d) / CHUNK_JSON


def chunked_exists(d) -> bool:
    return chunk_json_path(d).exists()


def save_chunked(
    d,
    *,
    config: Mapping,
    barrier: int,
    cap_idx: int,
    frontier: tuple,
    lossy: bool,
    verified: int,
    launches: int,
    spill_rows: int = 0,
    spill_bytes: int = 0,
    spill_spent: int = 0,
    result: Mapping | None = None,
) -> Path:
    """Persist one chunk boundary of a spill-capable chunked scan
    (ops.wgl.chunked_analysis).  ``frontier`` is the carried
    (state, fok, fcr) host arrays — spilled rows included, so the row
    axis is unbounded; a kill -9 between chunks (or mid-spill: the
    merge happens before the save) then a resume reproduces
    uninterrupted verdicts.  ``config`` must carry the history
    fingerprint plus the scan parameters verdict identity depends on.
    ``result`` marks a FINISHED run (idempotent resume: the saved
    verdict returns without device work).  npz before json, atomically,
    same torn-write reasoning as the ladder checkpoint."""
    d = Path(d)
    d.mkdir(parents=True, exist_ok=True)
    st, fo, fc = frontier
    buf = io.BytesIO()
    np.savez(buf, st=np.asarray(st), fo=np.asarray(fo), fc=np.asarray(fc))
    data = buf.getvalue()
    _store._atomic_write(d / CHUNK_NPZ, data)
    doc = {
        "config": config,
        "barrier": int(barrier),
        "cap_idx": int(cap_idx),
        "lossy": bool(lossy),
        "verified": int(verified),
        "launches": int(launches),
        "spill_rows": int(spill_rows),
        "spill_bytes": int(spill_bytes),
        "spill_spent": int(spill_spent),
        "result": result,
    }
    _durable.write_record(
        chunk_json_path(d), KIND_CHUNK, _store._jsonable(doc),
        files={CHUNK_NPZ: _durable.digest_bytes(data)},
    )
    return chunk_json_path(d)


def stream_json_path(d) -> Path:
    return Path(d) / STREAM_JSON


def stream_exists(d) -> bool:
    return stream_json_path(d).exists()


def save_stream(
    d,
    *,
    config: Mapping,
    ops: Sequence[Mapping],
    advanced: int,
    cap_idx: int,
    frontier: tuple,
    group_keys: Sequence[Sequence[int]],
    lossy: bool,
    verified: int,
    launches: int,
    epochs: int,
    result: Mapping | None = None,
) -> Path:
    """Persist one stream epoch boundary (checker.streaming).  ``ops``
    is the FULL op stream consumed so far (the resume source of truth);
    ``advanced`` is the settled-barrier cursor the carried ``frontier``
    (state, fok, fcr) sits at; ``group_keys`` are the (f_code, v1, v2)
    triples naming the frontier's fcr columns, so a resume can remap
    them onto the re-packed vocabulary.  ``config`` must carry the scan
    parameters verdict identity depends on (model name, capacity
    ladder, rounds, chunk size, dedup backend, fast flag).  ``result``
    marks a TERMINAL stream (verdict already emitted): resuming it
    returns the saved verdict without device work.  npz before json,
    atomically, same torn-write reasoning as the chunk pair."""
    d = Path(d)
    d.mkdir(parents=True, exist_ok=True)
    st, fo, fc = frontier
    buf = io.BytesIO()
    np.savez(buf, st=np.asarray(st), fo=np.asarray(fo), fc=np.asarray(fc))
    data = buf.getvalue()
    _store._atomic_write(d / STREAM_NPZ, data)
    doc = {
        "config": config,
        "ops": [dict(o) for o in ops],
        "advanced": int(advanced),
        "cap_idx": int(cap_idx),
        "group_keys": [[int(x) for x in k] for k in group_keys],
        "lossy": bool(lossy),
        "verified": int(verified),
        "launches": int(launches),
        "epochs": int(epochs),
        "result": result,
    }
    _durable.write_record(
        stream_json_path(d), KIND_STREAM, _store._jsonable(doc),
        files={STREAM_NPZ: _durable.digest_bytes(data)},
    )
    return stream_json_path(d)


def load_stream(d) -> dict:
    """Load a stream checkpoint; raises CheckpointError (with the
    durable layer's ``.report`` when applicable) on a missing, torn,
    corrupt, or unmigratable pair.  Corrupt pairs are quarantined aside
    by the durable layer before the raise."""
    p = stream_json_path(d)
    try:
        rr = _durable.read_verified(p, KIND_STREAM)
    except _durable.DurableError as e:
        raise CheckpointError(str(e), e.report) from e
    doc = rr.payload
    npz = Path(d) / STREAM_NPZ
    if not npz.exists():
        raise CheckpointError(
            f"{p} references missing {STREAM_NPZ}",
            {"artifact": KIND_STREAM, "path": str(npz),
             "reason": "missing-sibling"})
    try:
        with np.load(npz) as a:
            frontier = (a["st"], a["fo"], a["fc"])
    except (OSError, ValueError, KeyError) as e:
        q = _durable.quarantine_file(npz, reason="npz-unreadable",
                                     kind=KIND_STREAM)
        raise CheckpointError(
            f"unreadable {npz}: {e}",
            {"artifact": KIND_STREAM, "path": str(npz),
             "reason": "npz-unreadable", "quarantined_to": q}) from e
    return {
        "config": doc.get("config") or {},
        "ops": list(doc.get("ops") or ()),
        "advanced": int(doc.get("advanced") or 0),
        "cap_idx": int(doc.get("cap_idx") or 0),
        "group_keys": [tuple(int(x) for x in k)
                       for k in (doc.get("group_keys") or ())],
        "lossy": bool(doc.get("lossy")),
        "verified": int(doc.get("verified") or 0),
        "launches": int(doc.get("launches") or 0),
        "epochs": int(doc.get("epochs") or 0),
        "result": doc.get("result"),
        "frontier": frontier,
        "path": str(p),
    }


def _quarantine_pair(d, names, kind: str, reason: str) -> list[str]:
    out = []
    for name in names:
        p = Path(d) / name
        if p.exists():
            q = _durable.quarantine_file(p, reason=reason, kind=kind)
            if q:
                out.append(q)
    return out


def quarantine(d, *, reason: str = "stale") -> list[str]:
    """Move the ladder checkpoint pair in ``d`` aside
    (``<name>.corrupt-<n>``) — the fingerprint-mismatch / corruption
    path: the files must leave the resume glob so a LATER ``--resume``
    can't pick the stale state back up, but they stay on disk as
    evidence.  Returns the quarantine paths."""
    return _quarantine_pair(d, (CKPT_JSON, CKPT_NPZ), KIND_LADDER, reason)


def quarantine_chunked(d, *, reason: str = "stale") -> list[str]:
    """``quarantine`` for the chunked-scan checkpoint pair."""
    return _quarantine_pair(d, (CHUNK_JSON, CHUNK_NPZ), KIND_CHUNK, reason)


def quarantine_stream(d, *, reason: str = "stale") -> list[str]:
    """``quarantine`` for the per-stream checkpoint pair."""
    return _quarantine_pair(d, (STREAM_JSON, STREAM_NPZ), KIND_STREAM,
                            reason)


def load_chunked(d) -> dict:
    """Load a chunked-scan checkpoint; raises CheckpointError (with the
    durable layer's ``.report`` when applicable) on a missing, torn,
    corrupt, or unmigratable file.  Corrupt pairs are quarantined
    aside by the durable layer before the raise."""
    p = chunk_json_path(d)
    try:
        rr = _durable.read_verified(p, KIND_CHUNK)
    except _durable.DurableError as e:
        raise CheckpointError(str(e), e.report) from e
    doc = rr.payload
    npz = Path(d) / CHUNK_NPZ
    if not npz.exists():
        # legacy pairs carry no manifest; enveloped ones already proved
        # the sibling exists with matching digest
        raise CheckpointError(
            f"{p} references missing {CHUNK_NPZ}",
            {"artifact": KIND_CHUNK, "path": str(npz),
             "reason": "missing-sibling"})
    try:
        with np.load(npz) as a:
            frontier = (a["st"], a["fo"], a["fc"])
    except (OSError, ValueError, KeyError) as e:
        q = _durable.quarantine_file(npz, reason="npz-unreadable",
                                     kind=KIND_CHUNK)
        raise CheckpointError(
            f"unreadable {npz}: {e}",
            {"artifact": KIND_CHUNK, "path": str(npz),
             "reason": "npz-unreadable", "quarantined_to": q}) from e
    return {
        "config": doc.get("config") or {},
        "barrier": int(doc.get("barrier") or 0),
        "cap_idx": int(doc.get("cap_idx") or 0),
        "lossy": bool(doc.get("lossy")),
        "verified": int(doc.get("verified") or 0),
        "launches": int(doc.get("launches") or 0),
        "spill_rows": int(doc.get("spill_rows") or 0),
        "spill_bytes": int(doc.get("spill_bytes") or 0),
        "spill_spent": int(doc.get("spill_spent") or 0),
        "result": doc.get("result"),
        "frontier": frontier,
        "path": str(p),
    }


def load(d) -> dict:
    """Load a checkpoint back into live shapes: int-keyed results/
    confirms, resume tuples rebuilt from the npz.  Raises
    CheckpointError (with the durable layer's ``.report`` when
    applicable) on a missing, torn, corrupt, or unmigratable file;
    corrupt json/npz pairs are quarantined aside before the raise."""
    p = json_path(d)
    try:
        rr = _durable.read_verified(p, KIND_LADDER)
    except _durable.DurableError as e:
        raise CheckpointError(str(e), e.report) from e
    doc = rr.payload
    out = {
        "complete": bool(doc.get("complete")),
        "config": doc.get("config") or {},
        "stage": int(doc.get("stage") or 0),
        "results": {int(i): r for i, r in (doc.get("results") or {}).items()},
        "pending": [int(i) for i in doc.get("pending") or ()],
        "confirms": {int(i): c for i, c in (doc.get("confirms") or {}).items()},
        "device_confirms": list(doc.get("device_confirms") or ()),
        "resumes": {},
        "rungs": {int(i): int(r) for i, r in (doc.get("rungs") or {}).items()},
        "path": str(p),
    }
    want = [int(i) for i in doc.get("resumes") or ()]
    if want:
        npz = Path(d) / CKPT_NPZ
        if not npz.exists():
            raise CheckpointError(
                f"{p} references missing {CKPT_NPZ}",
                {"artifact": KIND_LADDER, "path": str(npz),
                 "reason": "missing-sibling"})
        try:
            with np.load(npz) as a:
                for i in want:
                    try:
                        out["resumes"][i] = (
                            int(a[f"{i}_bsnap"]),
                            a[f"{i}_st"],
                            a[f"{i}_fo"],
                            a[f"{i}_fc"],
                            a[f"{i}_al"],
                        )
                    except KeyError as e:
                        raise CheckpointError(
                            f"{CKPT_NPZ} is missing frontier arrays for "
                            f"lane {i}",
                            {"artifact": KIND_LADDER, "path": str(npz),
                             "reason": "missing-lane", "lane": i},
                        ) from e
        except (OSError, ValueError) as e:
            # torn legacy npz (enveloped pairs already passed the digest)
            q = _durable.quarantine_file(npz, reason="npz-unreadable",
                                         kind=KIND_LADDER)
            raise CheckpointError(
                f"unreadable {npz}: {e}",
                {"artifact": KIND_LADDER, "path": str(npz),
                 "reason": "npz-unreadable", "quarantined_to": q}) from e
    return out
