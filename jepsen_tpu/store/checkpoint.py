"""Checker checkpoint/resume: the ladder's durable state on disk.

A multi-minute ``parallel.batch_analysis`` ladder run used to live only
in process memory — a preemption lost everything.  This module persists
the ladder's durable state after every stage so a killed run resumes at
the saved rung with the saved frontiers and produces verdicts identical
to an uninterrupted run:

  ``checker-checkpoint.json``  the control state: the ladder config
      (engine, capacity ladders, rounds, dedup backend, confirmation
      mode — RNG-free by construction) plus a history fingerprint, the
      stage cursor, per-history verdicts so far, the pending set,
      in-flight confirmation descriptors, and queued device
      confirmations.
  ``checker-checkpoint.npz``   the pending lanes' carried-frontier
      resume snapshots (the round-5 snapshot machinery's
      (bsnap, state, fok, fcr, alive) tuples), keyed by history index.

Both files ride ``store._atomic_write`` (tmp + fsync + rename + dir
fsync), npz BEFORE json — the json names the stage the npz belongs to,
so a crash between the two leaves a json that simply predates the npz's
extra rows (never the reverse: a json pointing at missing frontiers).

Resume semantics: ``load()`` hands the saved state back;
``batch_analysis(resume=True)`` verifies the fingerprint against the
histories it was given (a mismatch is IGNORED with a warning — resuming
against different inputs can only produce wrong verdicts, running fresh
never can) and re-enters the ladder at the saved rung.  The saved
CONFIG wins over the caller's arguments on resume: the CLI resume path
cannot know the original kwargs, and verdict identity requires the
original ladder.
"""

from __future__ import annotations

import hashlib
import io
import json
from pathlib import Path
from typing import Mapping, Sequence

import numpy as np

from jepsen_tpu import store as _store

CKPT_JSON = "checker-checkpoint.json"
CKPT_NPZ = "checker-checkpoint.npz"

VERSION = 1

#: chunked-scan (single-history) checkpoint pair: the carried — possibly
#: HOST-SPILLED, so row count is unbounded — frontier between chunk
#: scans, plus the scan cursor.  Separate files from the ladder
#: checkpoint: the two can coexist in one run directory (a ladder's
#: unsafe-shape fallback runs chunked scans inside a checkpointed
#: ladder).
CHUNK_JSON = "chunk-checkpoint.json"
CHUNK_NPZ = "chunk-checkpoint.npz"

CHUNK_VERSION = 1


class CheckpointError(Exception):
    """Missing, torn, or version-incompatible checkpoint."""


def json_path(d) -> Path:
    return Path(d) / CKPT_JSON


def exists(d) -> bool:
    return json_path(d).exists()


def fingerprint(histories: Sequence[Sequence[Mapping]]) -> str:
    """A stable identity for the checked inputs: sha256 over every op's
    (type, process, f, value) in order, per history.

    A stored ``ColumnHistory`` hashes its SoA columns DIRECTLY —
    iterating it would materialize every op dict, defeating the store's
    zero-copy path on 50k-op runs.  The two paths therefore fingerprint
    the same content differently; that is fine — the fingerprint only
    has to be stable for the same input source (a resume re-reads the
    same stored run), and a spurious mismatch merely means a fresh run,
    never a wrong resume."""
    h = hashlib.sha256()
    for hist in histories:
        h.update(b"\x00")
        cols = getattr(hist, "cols", None)
        if cols is not None and hasattr(hist, "fs"):
            for name in ("type", "process", "f", "value1", "value2"):
                if name in cols:
                    h.update(np.ascontiguousarray(np.asarray(cols[name])).tobytes())
            h.update(json.dumps(list(hist.fs), default=str).encode())
            extras = getattr(hist, "extras", None) or {}
            if extras:
                h.update(
                    json.dumps(_store._jsonable(extras), sort_keys=True,
                               default=str).encode()
                )
            continue
        for o in hist:
            h.update(
                json.dumps(
                    [
                        _store._jsonable(o.get("type")),
                        _store._jsonable(o.get("process")),
                        _store._jsonable(o.get("f")),
                        _store._jsonable(o.get("value")),
                    ],
                    separators=(",", ":"),
                    default=str,
                ).encode()
            )
    return h.hexdigest()


def save(
    d,
    *,
    config: Mapping,
    stage: int,
    results: Mapping[int, Mapping],
    pending: Sequence[int],
    confirms: Mapping[int, Mapping] | None = None,
    device_confirms: Sequence[Mapping] | None = None,
    resumes: Mapping[int, tuple] | None = None,
    rungs: Mapping[int, int] | None = None,
    complete: bool = False,
) -> Path:
    """Atomically persist one stage boundary's state; returns the json
    path.  ``resumes`` maps history index -> (bsnap, state, fok, fcr,
    alive); ``confirms`` maps history index -> {"res", "op_pos"} for
    in-flight worker confirmations (resubmitted on resume);
    ``device_confirms`` is the queued device-confirmation descriptors
    [{"i", "failed_at", "cap", "res"}].  ``rungs`` optionally maps a
    pending history index -> its NEXT ladder-stage index — continuous
    batching admits members at rung boundaries, so pending members may
    sit at different rungs; a member absent from the map resumes at
    ``stage`` (the pre-continuous behavior, and what old checkpoints
    decode to).  ``complete`` marks a finished run — resuming it
    returns the saved results without device work."""
    d = Path(d)
    d.mkdir(parents=True, exist_ok=True)
    resumes = dict(resumes or {})
    if resumes:
        arrays = {}
        for i, (bsnap, st, fo, fc, al) in resumes.items():
            arrays[f"{i}_bsnap"] = np.asarray(bsnap, np.int32)
            arrays[f"{i}_st"] = np.asarray(st)
            arrays[f"{i}_fo"] = np.asarray(fo)
            arrays[f"{i}_fc"] = np.asarray(fc)
            arrays[f"{i}_al"] = np.asarray(al)
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        _store._atomic_write(d / CKPT_NPZ, buf.getvalue())
    doc = {
        "version": VERSION,
        "complete": bool(complete),
        "config": config,
        "stage": int(stage),
        "results": {str(i): r for i, r in (results or {}).items()},
        "pending": [int(i) for i in pending],
        "confirms": {str(i): c for i, c in (confirms or {}).items()},
        "device_confirms": list(device_confirms or ()),
        "resumes": sorted(int(i) for i in resumes),
        "rungs": {str(i): int(r) for i, r in (rungs or {}).items()},
    }
    _store._atomic_write(
        json_path(d), json.dumps(_store._jsonable(doc), indent=1)
    )
    return json_path(d)


def chunk_json_path(d) -> Path:
    return Path(d) / CHUNK_JSON


def chunked_exists(d) -> bool:
    return chunk_json_path(d).exists()


def save_chunked(
    d,
    *,
    config: Mapping,
    barrier: int,
    cap_idx: int,
    frontier: tuple,
    lossy: bool,
    verified: int,
    launches: int,
    spill_rows: int = 0,
    spill_bytes: int = 0,
    spill_spent: int = 0,
    result: Mapping | None = None,
) -> Path:
    """Persist one chunk boundary of a spill-capable chunked scan
    (ops.wgl.chunked_analysis).  ``frontier`` is the carried
    (state, fok, fcr) host arrays — spilled rows included, so the row
    axis is unbounded; a kill -9 between chunks (or mid-spill: the
    merge happens before the save) then a resume reproduces
    uninterrupted verdicts.  ``config`` must carry the history
    fingerprint plus the scan parameters verdict identity depends on.
    ``result`` marks a FINISHED run (idempotent resume: the saved
    verdict returns without device work).  npz before json, atomically,
    same torn-write reasoning as the ladder checkpoint."""
    d = Path(d)
    d.mkdir(parents=True, exist_ok=True)
    st, fo, fc = frontier
    buf = io.BytesIO()
    np.savez(buf, st=np.asarray(st), fo=np.asarray(fo), fc=np.asarray(fc))
    _store._atomic_write(d / CHUNK_NPZ, buf.getvalue())
    doc = {
        "version": CHUNK_VERSION,
        "config": config,
        "barrier": int(barrier),
        "cap_idx": int(cap_idx),
        "lossy": bool(lossy),
        "verified": int(verified),
        "launches": int(launches),
        "spill_rows": int(spill_rows),
        "spill_bytes": int(spill_bytes),
        "spill_spent": int(spill_spent),
        "result": result,
    }
    _store._atomic_write(
        chunk_json_path(d), json.dumps(_store._jsonable(doc), indent=1)
    )
    return chunk_json_path(d)


def load_chunked(d) -> dict:
    """Load a chunked-scan checkpoint; raises CheckpointError on a
    missing/torn/unknown-version file."""
    p = chunk_json_path(d)
    if not p.exists():
        raise CheckpointError(f"no {CHUNK_JSON} in {d}")
    try:
        doc = json.loads(p.read_text())
    except (OSError, ValueError) as e:
        raise CheckpointError(f"unreadable {p}: {e}") from e
    if doc.get("version") != CHUNK_VERSION:
        raise CheckpointError(
            f"unknown chunk-checkpoint version {doc.get('version')!r}")
    npz = Path(d) / CHUNK_NPZ
    if not npz.exists():
        raise CheckpointError(f"{p} references missing {CHUNK_NPZ}")
    try:
        with np.load(npz) as a:
            frontier = (a["st"], a["fo"], a["fc"])
    except (OSError, ValueError, KeyError) as e:
        raise CheckpointError(f"unreadable {npz}: {e}") from e
    return {
        "config": doc.get("config") or {},
        "barrier": int(doc.get("barrier") or 0),
        "cap_idx": int(doc.get("cap_idx") or 0),
        "lossy": bool(doc.get("lossy")),
        "verified": int(doc.get("verified") or 0),
        "launches": int(doc.get("launches") or 0),
        "spill_rows": int(doc.get("spill_rows") or 0),
        "spill_bytes": int(doc.get("spill_bytes") or 0),
        "spill_spent": int(doc.get("spill_spent") or 0),
        "result": doc.get("result"),
        "frontier": frontier,
        "path": str(p),
    }


def load(d) -> dict:
    """Load a checkpoint back into live shapes: int-keyed results/
    confirms, resume tuples rebuilt from the npz.  Raises
    CheckpointError on a missing/torn/unknown-version file."""
    p = json_path(d)
    if not p.exists():
        raise CheckpointError(f"no {CKPT_JSON} in {d}")
    try:
        doc = json.loads(p.read_text())
    except (OSError, ValueError) as e:
        raise CheckpointError(f"unreadable {p}: {e}") from e
    if doc.get("version") != VERSION:
        raise CheckpointError(f"unknown checkpoint version {doc.get('version')!r}")
    out = {
        "complete": bool(doc.get("complete")),
        "config": doc.get("config") or {},
        "stage": int(doc.get("stage") or 0),
        "results": {int(i): r for i, r in (doc.get("results") or {}).items()},
        "pending": [int(i) for i in doc.get("pending") or ()],
        "confirms": {int(i): c for i, c in (doc.get("confirms") or {}).items()},
        "device_confirms": list(doc.get("device_confirms") or ()),
        "resumes": {},
        "rungs": {int(i): int(r) for i, r in (doc.get("rungs") or {}).items()},
        "path": str(p),
    }
    want = [int(i) for i in doc.get("resumes") or ()]
    if want:
        npz = Path(d) / CKPT_NPZ
        if not npz.exists():
            raise CheckpointError(f"{p} references missing {CKPT_NPZ}")
        with np.load(npz) as a:
            for i in want:
                try:
                    out["resumes"][i] = (
                        int(a[f"{i}_bsnap"]),
                        a[f"{i}_st"],
                        a[f"{i}_fo"],
                        a[f"{i}_fc"],
                        a[f"{i}_al"],
                    )
                except KeyError as e:
                    raise CheckpointError(
                        f"{CKPT_NPZ} is missing frontier arrays for lane {i}"
                    ) from e
    return out
