"""Durable-record layer: checksummed, versioned, migratable persistence.

Every durable surface this repo grew — ladder + chunk checkpoints
(store.checkpoint), the admission journal and drain dirs (serve), the
perf ledger (obs.regress) — rides ``store._atomic_write``'s
tmp + fsync + rename + dir-fsync contract, which protects against TORN
writes but says nothing about bit rot, hand-editing, a stray ``cp``
mid-write onto a different filesystem, or a version bump.  Readers used
to assume "a torn file can't exist" and treated any unexpected content
as either fatal or silently skippable.  This module is the one envelope
they all share instead:

  * ``write_record`` wraps a JSON payload in ``{durable, kind, version,
    crc32, payload, files}``: a CRC32 over the canonical payload bytes,
    a schema version, the artifact kind, and (for json/npz pairs) a
    per-sibling-file digest manifest — a checkpoint's json now *proves*
    which npz it belongs to instead of assuming the newest one.
  * ``read_verified`` detects truncation, bit flips, kind confusion and
    stale siblings; a corrupt artifact is QUARANTINED aside
    (``<name>.corrupt-<n>`` — evidence, not deleted) and the raised
    ``DurableError`` carries a machine-readable corruption report the
    consumer can embed in its ``cause``.  Pre-envelope (legacy) files
    read through the migration path below — never rejected for their
    age alone.
  * A **migration registry** keyed by ``(kind, version)`` upgrades old
    formats in memory at read time: a version bump used to mean
    ``CheckpointError`` (ladder/chunk checkpoints) or a fresh run;
    now ``register_migration`` chains old payloads up to the current
    version and the counter ``durable.migrated`` records that it
    happened.
  * ``seal_line``/``check_line`` give append-only JSONL surfaces (the
    perf ledger) a per-record checksum without changing the file shape:
    one extra ``"crc"`` key per line, legacy lines still accepted.
  * ``sweep_tmp`` reclaims ``*.tmp`` orphans a crashed writer left in a
    directory (``_atomic_write``'s crash window), age-gated so a LIVE
    concurrent writer's tmp is never swept, counted as
    ``durable.tmp_swept``.

Telemetry: ``durable.corrupt`` (one per quarantined artifact, with the
corruption reason), ``durable.migrated``, ``durable.tmp_swept``,
``durable.ledger_skipped`` (emitted by the ledger reader).  Import-light
(stdlib + obs): the confirmation workers and the budget gate can import
the store package without dragging jax in.
"""

from __future__ import annotations

import contextlib
import json
import os
import zlib
from pathlib import Path
from typing import Callable, Mapping

from jepsen_tpu import obs

#: envelope schema marker (the OUTER format; payload schemas version
#: independently per kind).
ENVELOPE = 1

#: current payload version per registered kind (``register_kind``).
CURRENT: dict[str, int] = {}

#: ``(kind, from_version) -> fn(payload) -> (payload, to_version)``
#: upgrade steps, chained by ``read_verified`` until the payload reaches
#: ``CURRENT[kind]``.
MIGRATIONS: dict[tuple[str, int], Callable] = {}


def register_kind(kind: str, version: int) -> None:
    """Declare ``kind``'s current payload version (writers write it,
    ``read_verified`` migrates up to it)."""
    CURRENT[str(kind)] = int(version)


def register_migration(kind: str, from_version: int,
                       fn: Callable | None = None):
    """Register an upgrade step for ``(kind, from_version)``.  ``fn``
    takes the old payload dict and returns ``(new_payload,
    new_version)``.  Usable as a decorator."""
    def _reg(f):
        MIGRATIONS[(str(kind), int(from_version))] = f
        return f

    return _reg if fn is None else _reg(fn)


class DurableError(Exception):
    """A durable artifact failed verification or has no migration path.

    ``report`` is the machine-readable corruption/incompatibility
    report (the dict consumers embed in their ``cause``); ``reason``
    is its short code (``missing`` / ``unparseable`` / ``crc-mismatch``
    / ``wrong-kind`` / ``missing-sibling`` / ``sibling-crc-mismatch`` /
    ``no-migration-path``)."""

    def __init__(self, message: str, report: Mapping):
        self.report = dict(report)
        self.reason = str(self.report.get("reason") or "corrupt")
        super().__init__(message)


class ReadResult:
    """What ``read_verified`` hands back: the (possibly migrated)
    payload plus provenance."""

    __slots__ = ("payload", "kind", "version", "migrated", "legacy",
                 "path", "files")

    def __init__(self, *, payload, kind, version, migrated, legacy, path,
                 files):
        self.payload = payload
        self.kind = kind
        self.version = version      # version as read, BEFORE migration
        self.migrated = migrated    # a migration step ran
        self.legacy = legacy        # pre-envelope file (no checksum)
        self.path = path
        self.files = files          # the envelope's sibling manifest


# ---------------------------------------------------------------------------
# Checksums
# ---------------------------------------------------------------------------


def canonical_bytes(payload) -> bytes:
    """The byte string the payload CRC is computed over: sorted-key,
    separator-free canonical JSON (stable across dict insertion order
    and whitespace)."""
    from jepsen_tpu import store as _store

    return json.dumps(
        _store._jsonable(payload), sort_keys=True, separators=(",", ":"),
        default=str,
    ).encode()


def payload_crc(payload) -> int:
    return zlib.crc32(canonical_bytes(payload)) & 0xFFFFFFFF


def digest_bytes(data: bytes) -> dict:
    """The manifest entry for a sibling file written as ``data``."""
    return {"crc32": zlib.crc32(data) & 0xFFFFFFFF, "bytes": len(data)}


def file_digest(path) -> dict:
    """Streamed ``digest_bytes`` of an on-disk file."""
    crc = 0
    n = 0
    with open(path, "rb") as fh:
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
            n += len(chunk)
    return {"crc32": crc & 0xFFFFFFFF, "bytes": n}


# ---------------------------------------------------------------------------
# Writing
# ---------------------------------------------------------------------------


def envelope(kind: str, payload, *, version: int | None = None,
             files: Mapping[str, Mapping] | None = None) -> dict:
    """The envelope dict for ``payload``: checksum + version + kind (+
    the sibling-file digest manifest)."""
    v = CURRENT.get(str(kind)) if version is None else int(version)
    if v is None:
        raise KeyError(f"unregistered durable kind {kind!r}; call "
                       "register_kind first or pass version=")
    doc = {
        "durable": ENVELOPE,
        "kind": str(kind),
        "version": int(v),
        "crc32": payload_crc(payload),
        "payload": payload,
    }
    if files:
        doc["files"] = {str(k): dict(d) for k, d in files.items()}
    return doc


def dumps_record(kind: str, payload, *, version: int | None = None,
                 files: Mapping[str, Mapping] | None = None,
                 indent: int | None = 1) -> str:
    from jepsen_tpu import store as _store

    return json.dumps(
        _store._jsonable(envelope(kind, payload, version=version,
                                  files=files)),
        indent=indent, default=str,
    )


def write_record(path, kind: str, payload, *, version: int | None = None,
                 files: Mapping[str, Mapping] | None = None) -> Path:
    """Atomically persist an enveloped record (``store._atomic_write``:
    tmp + fsync + rename + dir fsync — plus this module's checksum on
    top)."""
    from jepsen_tpu import store as _store

    path = Path(path)
    _store._atomic_write(
        path, dumps_record(kind, payload, version=version, files=files)
    )
    return path


# ---------------------------------------------------------------------------
# Quarantine
# ---------------------------------------------------------------------------


def quarantine_path(path) -> Path:
    """The first free ``<name>.corrupt-<n>`` slot next to ``path``."""
    path = Path(path)
    n = 0
    while True:
        cand = path.with_name(f"{path.name}.corrupt-{n}")
        if not cand.exists():
            return cand
        n += 1


def quarantine_file(path, *, reason: str = "corrupt",
                    kind: str = "?") -> str | None:
    """Move a corrupt artifact aside to ``<name>.corrupt-<n>`` (evidence
    for the operator, out of every reader's glob) and count it.  Returns
    the quarantine path, or None when the move itself failed (the
    original stays; readers keep rejecting it on checksum)."""
    path = Path(path)
    try:
        dest = quarantine_path(path)
        os.replace(path, dest)
    except OSError:
        obs.counter("durable.quarantine_error", kind=kind, reason=reason)
        return None
    obs.counter("durable.corrupt", kind=kind, reason=reason,
                path=str(path), quarantined_to=str(dest))
    return str(dest)


def _report(kind: str, path, reason: str, **extra) -> dict:
    out = {"artifact": str(kind), "path": str(path), "reason": reason}
    out.update(extra)
    return out


def _corrupt(kind: str, path, reason: str, *, quarantine: bool = True,
             siblings: list | None = None, **extra) -> DurableError:
    """Quarantine ``path`` (+ any named siblings) and build the
    DurableError carrying the machine-readable report."""
    quarantined = []
    if quarantine:
        for p in [path] + list(siblings or ()):
            if Path(p).exists():
                q = quarantine_file(p, reason=reason, kind=kind)
                if q:
                    quarantined.append(q)
    rep = _report(kind, path, reason, quarantined_to=quarantined, **extra)
    return DurableError(
        f"corrupt {kind} at {path}: {reason}"
        + (f" (quarantined to {', '.join(quarantined)})" if quarantined
           else ""),
        rep,
    )


# ---------------------------------------------------------------------------
# Reading + migration
# ---------------------------------------------------------------------------


def read_verified(path, kind: str, *, quarantine: bool = True,
                  legacy_version: Callable | int | None = None) -> ReadResult:
    """Read + verify + migrate one enveloped JSON artifact.

    Verification: JSON parses, the envelope names this ``kind``, the
    payload CRC matches, and every sibling in the ``files`` manifest
    exists with matching size + CRC.  Any failure quarantines the
    artifact (and listed siblings) aside and raises ``DurableError``
    with the corruption report.  A file WITHOUT an envelope is a
    pre-durable legacy artifact: its whole doc is the payload and its
    version is ``legacy_version`` (an int, or a callable over the doc;
    default: the doc's own ``"version"`` key, else 0) — the migration
    registry carries it forward, it is never rejected for age alone.
    ``DurableError(reason="no-migration-path")`` means a FUTURE version
    this build can't read (or a gap in the registry); nothing is
    quarantined for that — the file is fine, the reader is old."""
    path = Path(path)
    if not path.exists():
        raise DurableError(f"no {kind} at {path}",
                           _report(kind, path, "missing"))
    try:
        raw = path.read_bytes()
    except OSError as e:
        raise DurableError(f"unreadable {path}: {e}",
                           _report(kind, path, "unreadable",
                                   error=str(e))) from e
    try:
        # strict decode THEN parse: bit rot that lands outside UTF-8 is
        # exactly as corrupt as bad JSON, not an internal error
        doc = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise _corrupt(kind, path, "unparseable", quarantine=quarantine,
                       error=str(e)) from e
    if not isinstance(doc, dict):
        raise _corrupt(kind, path, "unparseable", quarantine=quarantine,
                       error="top-level JSON is not an object")
    legacy = "durable" not in doc or "payload" not in doc
    migrated = False
    files = {}
    if legacy:
        payload = doc
        if callable(legacy_version):
            version = int(legacy_version(doc))
        elif legacy_version is not None:
            version = int(legacy_version)
        else:
            v = doc.get("version")
            version = int(v) if isinstance(v, (int, float)) else 0
    else:
        if doc.get("kind") != kind:
            raise _corrupt(kind, path, "wrong-kind", quarantine=quarantine,
                           found_kind=doc.get("kind"))
        payload = doc.get("payload")
        want = doc.get("crc32")
        got = payload_crc(payload)
        if want != got:
            raise _corrupt(kind, path, "crc-mismatch",
                           quarantine=quarantine,
                           expected_crc=want, actual_crc=got)
        version = int(doc.get("version") or 0)
        files = doc.get("files") or {}
        for name, want_d in files.items():
            sib = path.parent / name
            if not sib.exists():
                raise _corrupt(kind, path, "missing-sibling",
                               quarantine=quarantine, sibling=name)
            got_d = file_digest(sib)
            if (int(want_d.get("bytes", -1)) != got_d["bytes"]
                    or int(want_d.get("crc32", -1)) != got_d["crc32"]):
                raise _corrupt(
                    kind, path, "sibling-crc-mismatch",
                    quarantine=quarantine, siblings=[sib], sibling=name,
                    expected=dict(want_d), actual=got_d,
                )
    current = CURRENT.get(kind)
    read_version = version
    while current is not None and version != current:
        fn = MIGRATIONS.get((kind, version))
        if fn is None:
            raise DurableError(
                f"{kind} at {path} is version {version}; this build "
                f"reads version {current} and has no migration from "
                f"{version}",
                _report(kind, path, "no-migration-path",
                        found_version=version, current_version=current),
            )
        payload, version = fn(payload)
        version = int(version)
        migrated = True
    if migrated:
        obs.counter("durable.migrated", kind=kind,
                    from_version=read_version, to_version=version)
    return ReadResult(payload=payload, kind=kind, version=read_version,
                      migrated=migrated, legacy=legacy, path=str(path),
                      files=files)


# ---------------------------------------------------------------------------
# JSONL per-record checksums (the perf ledger)
# ---------------------------------------------------------------------------


def seal_line(record: Mapping) -> dict:
    """``record`` plus a ``"crc"`` key: CRC32 over the canonical bytes
    of the record WITHOUT the crc key (so sealing is idempotent)."""
    out = {k: v for k, v in dict(record).items() if k != "crc"}
    out["crc"] = payload_crc(out)
    return out


def check_line(record: Mapping) -> tuple[bool, bool]:
    """``(ok, legacy)`` for one parsed JSONL record: legacy lines (no
    ``"crc"``) pass as ok; sealed lines must match their checksum."""
    if not isinstance(record, Mapping):
        return False, False
    if "crc" not in record:
        return True, True
    body = {k: v for k, v in record.items() if k != "crc"}
    return record["crc"] == payload_crc(body), False


# ---------------------------------------------------------------------------
# Cross-process advisory locking
# ---------------------------------------------------------------------------


@contextlib.contextmanager
def file_lock(path, *, timeout_s: float | None = None):
    """Exclusive ``fcntl.flock`` advisory lock on ``path`` (created if
    missing) for the duration of the scope.

    This is the cross-PROCESS companion to the in-process locks the
    durable-dir owners (idempotency map, shared quarantine) already
    hold: two processes sharing one ``--idempotency-dir`` serialize
    their read-modify-write of a key's entry file here, which is what
    makes a claim atomic across a fleet instead of within one service.

    The lock file is a SIDECAR (never the record file itself —
    ``write_record`` replaces records via rename, and a lock taken on a
    renamed-away inode excludes nobody) and is never unlinked: deleting
    a lock file another process is blocked on would hand a third
    process a fresh inode and break mutual exclusion.  One empty
    sidecar per key is the rent.

    ``timeout_s`` bounds the wait (LOCK_NB + backoff); ``TimeoutError``
    after it.  None blocks indefinitely.  On platforms without fcntl
    (not a supported deployment target) the scope degrades to the
    caller's in-process locking."""
    import fcntl  # POSIX-only; imported here so module import never fails

    path = Path(path)
    fd = os.open(str(path), os.O_RDWR | os.O_CREAT, 0o644)
    try:
        if timeout_s is None:
            fcntl.flock(fd, fcntl.LOCK_EX)
        else:
            import time as _time

            deadline = _time.monotonic() + float(timeout_s)
            while True:
                try:
                    fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
                    break
                except OSError:
                    if _time.monotonic() >= deadline:
                        raise TimeoutError(
                            f"file_lock timed out after {timeout_s}s: {path}"
                        ) from None
                    _time.sleep(0.005)
        yield
    finally:
        with contextlib.suppress(OSError):
            import fcntl as _fcntl

            _fcntl.flock(fd, _fcntl.LOCK_UN)
        os.close(fd)


# ---------------------------------------------------------------------------
# Orphaned-tmp sweep
# ---------------------------------------------------------------------------


def sweep_tmp(d, *, min_age_s: float = 60.0, what: str = "store") -> int:
    """Remove ``*.tmp`` orphans a crashed writer left in ``d``
    (``_atomic_write``'s unique-name tmp files).  ``min_age_s`` gates on
    mtime so a LIVE concurrent writer's in-flight tmp is never swept
    (pass 0 for a directory the caller owns exclusively, e.g. a service
    journal dir at startup).  Returns the count, emitted as
    ``durable.tmp_swept``."""
    d = Path(d)
    if not d.is_dir():
        return 0
    import time as _time

    now = _time.time()
    n = 0
    for p in d.glob("*.tmp"):
        try:
            if min_age_s > 0 and now - p.stat().st_mtime < min_age_s:
                continue
        except OSError:
            continue
        with contextlib.suppress(OSError):
            p.unlink()
            n += 1
    if n:
        obs.counter("durable.tmp_swept", n, what=what, dir=str(d))
    return n
