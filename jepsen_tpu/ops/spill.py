"""Bounded-memory frontier management for the exact checker.

The exact engine's hard failure mode is an information-heavy history
whose frontier outgrows every capacity rung: before this module the
checker's only responses to memory pressure were truncation (lossy),
capacity escalation (bounded by the ladder), or exhaustion (a bare
``unknown``).  The paper's rule is "never a wrong verdict, minimize
:unknown" — so memory pressure becomes a managed, recoverable resource
with four pieces, all exact:

  * **Host-spill** (``HostRing`` + the slicing loop in
    ``ops.wgl.chunked_analysis``): the frontier-set sweep is linear in
    the frontier — scanning a chunk of barriers from A ∪ B equals the
    union of scanning from A and from B (each configuration's futures
    are independent; dedup/domination only remove redundant rows).  So
    a carried frontier that exceeds a rung's device capacity is SPLIT:
    slices of ≤ capacity rows stream through the same compiled chunk
    kernel one at a time while the overflow waits in a host ring
    (device→host copies start asynchronously, overlapping the next
    device-bound slice), and the slice outputs recombine by exact
    union.  Rows are never silently dropped; refutation requires EVERY
    slice to die (the union frontier dies at the latest slice death).

  * **LSH-bucketed merge** (``merge_frontiers``): recombining slices
    needs exact dedup + domination over an unbounded host-side row set.
    Rows are bucketed by the 64-bit (state, fok) class hash
    (``ops.hashing.np_class_hash`` — the same packed-key machinery as
    the device bucket backend, per 1806.00588's LSH-for-beam-search),
    and the O(k²) exact compares run only within equal-key runs:
    identical classes always collide into one bucket, so per-bucket
    exact work is globally exact, and cross-bucket rows — provably
    distinct classes — are never compared at all.

  * **Crashed-op group factorization** (``factor_packed``): a crashed
    group whose op is trace-independent of every other op in the
    history (legality-preserving in both directions and commuting, over
    the closed reachable state set — tabulated exactly from the tensor
    model's step function) splits off as its OWN factor of the search
    space.  Crashed ops carry no obligations, so that factor's check is
    closed-form — a witness exists firing none of them — and the factor
    is removed from the device problem entirely: G shrinks, the fcr
    product space shrinks structurally, and the verdict provably equals
    the monolithic one (witnesses map both ways by commuting the
    independent fires to the end and dropping them).

  * **Honest exhaustion** (``undecidability_report``): when fixed
    memory still cannot decide — a single barrier's closure overflows
    the budget ceiling with nothing left to split — the resulting
    ``unknown`` carries a machine-readable report (peak frontier growth
    rate, spill volume, budget at exhaustion, factor count) instead of
    a bare cause string.  The OOM ladder never lies: it either decides,
    or says exactly why it could not.

Telemetry: ``frontier.spill_rows`` / ``frontier.spill_bytes`` counters,
``frontier.factorizations``, ``frontier.spill_merges``, and the
``frontier.undecidable`` event, mirrored to the live /metrics registry
as ``jepsen_tpu_frontier_spill_bytes_total`` and
``jepsen_tpu_frontier_factorizations_total`` for serving processes.
"""

from __future__ import annotations

import json
import os
import threading

import numpy as np

from jepsen_tpu import obs
from jepsen_tpu.obs import metrics as _metrics
from jepsen_tpu.ops import hashing

FRONTIER_BUDGET_ENV = "JEPSEN_TPU_FRONTIER_BUDGET_MB"

#: Working-set multiplier: a rung at capacity F materializes the
#: F·(1+P+G)-row candidate table plus sort scratch and the 2C prune
#: buffer — ~3x the candidate table covers the measured footprint with
#: headroom (conservative by construction: a low estimate only spills
#: earlier, never OOMs later).
_WORKING_SET_FACTOR = 3

#: process-wide spill/factorization totals (service stats read these;
#: the obs counters are per-recording, the /metrics mirror per-process).
_TOTALS = {
    "spill_rows": 0, "spill_bytes": 0, "spill_merges": 0,
    "factorizations": 0, "undecidable_reports": 0,
}
_TOTALS_LOCK = threading.Lock()


def _count(key: str, n: int = 1) -> None:
    with _TOTALS_LOCK:
        _TOTALS[key] += n


def stats_snapshot() -> dict:
    """The process-wide bounded-memory totals (CheckService.stats()'s
    "memory" block)."""
    with _TOTALS_LOCK:
        return dict(_TOTALS)


def row_bytes(W: int, G: int) -> int:
    """Device bytes per frontier row: int32 state + W uint32 fok lanes +
    G int16 fcr counts + the alive bool."""
    return 4 + 4 * W + 2 * G + 1


def resolve_budget_mb(budget_mb: float | None = None) -> float | None:
    """Explicit argument > JEPSEN_TPU_FRONTIER_BUDGET_MB env > None
    (no budget: the capacity ladder alone bounds device rows)."""
    if budget_mb is not None:
        return float(budget_mb)
    v = os.environ.get(FRONTIER_BUDGET_ENV)
    if v:
        try:
            return float(v)
        except ValueError:
            pass
    return None


def budget_rows(budget_mb: float | None, W: int, G: int, P: int) -> int | None:
    """The device frontier-row budget a ``--frontier-budget-mb`` value
    buys at this geometry, under the rung working-set model (candidate
    table is F·(1+P+G) rows; ×_WORKING_SET_FACTOR for scratch).  Never
    below 1 — the smallest rung always runs."""
    if budget_mb is None:
        return None
    per_row = row_bytes(W, G) * (1 + P + G) * _WORKING_SET_FACTOR
    return max(1, int(budget_mb * 1e6) // max(1, per_row))


# ---------------------------------------------------------------------------
# Host spill ring
# ---------------------------------------------------------------------------


class HostRing:
    """A host-side ring of spilled frontier rows.

    ``push`` accepts device (jax) or host (numpy) arrays; device arrays
    start their device→host copies ASYNCHRONOUSLY at push time
    (``copy_to_host_async`` when the backend exposes it), so the copy
    drains while the next device-bound slice launches — the
    streaming-overlap shape of 2010.02164's occupancy math.  Rows
    materialize host-side only at ``pop``.  Nothing is ever dropped:
    the ring is unbounded by design (host RAM is the spill medium), and
    its byte/row counters are the spill-volume telemetry the
    undecidability report and /metrics export."""

    def __init__(self, W: int, G: int):
        self.W = int(W)
        self.G = int(G)
        self._entries: list[tuple] = []  # (state, fok, fcr) pending rows
        self.rows = 0
        self.rows_total = 0
        self.bytes_total = 0

    @staticmethod
    def _start_async(a):
        fn = getattr(a, "copy_to_host_async", None)
        if fn is not None:
            try:
                fn()
            except Exception:  # noqa: BLE001 — the copy is an overlap
                pass  # optimization; np.asarray at pop still works

    def push(self, state, fok, fcr, alive=None) -> int:
        """Spill rows (optionally masked by ``alive``) into the ring;
        returns the row count.  Device inputs begin their host copies
        immediately; masking is deferred to pop so the device isn't
        blocked here."""
        n = int(state.shape[0]) if alive is None else None
        for a in (state, fok, fcr, alive):
            if a is not None:
                self._start_async(a)
        self._entries.append((state, fok, fcr, alive))
        if n is None:
            # the alive count isn't known without materializing; account
            # rows at pop time instead (bytes ride along)
            return 0
        self._account(n)
        return n

    def _account(self, n: int) -> None:
        if n <= 0:
            return
        nbytes = n * row_bytes(self.W, self.G)
        self.rows += n
        self.rows_total += n
        self.bytes_total += nbytes
        _count("spill_rows", n)
        _count("spill_bytes", nbytes)
        # obs.counter mirrors into the live /metrics registry by name
        # when the mirror is on (jepsen_tpu_frontier_spill_bytes_total)
        obs.counter("frontier.spill_rows", n)
        obs.counter("frontier.spill_bytes", nbytes)

    def discard(self) -> None:
        """Drop pending entries WITHOUT accounting them as spill — used
        when a capacity-escalation retry discards an attempt's outputs
        (the rows were never part of an accepted pass)."""
        self._entries = []
        self.rows = 0

    def pop_all(self) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
        """Materialize and drain every spilled row, in push order.
        Returns (state, fok, fcr) host arrays or None when empty."""
        if not self._entries:
            return None
        parts = []
        for state, fok, fcr, alive in self._entries:
            st = np.asarray(state)
            fo = np.asarray(fok)
            fc = np.asarray(fcr)
            if alive is not None:
                sel = np.flatnonzero(np.asarray(alive))
                st, fo, fc = st[sel], fo[sel], fc[sel]
                self._account(int(sel.size))
            parts.append((st, fo, fc))
        self._entries = []
        self.rows = 0
        if len(parts) == 1:
            return parts[0]
        return (
            np.concatenate([p[0] for p in parts]),
            np.concatenate([p[1] for p in parts], axis=0),
            np.concatenate([p[2] for p in parts], axis=0),
        )


# ---------------------------------------------------------------------------
# LSH-bucketed exact merge (dedup + domination on the host)
# ---------------------------------------------------------------------------


def merge_frontiers(parts) -> tuple[np.ndarray, np.ndarray, np.ndarray, dict]:
    """Union spilled/sliced frontier parts into one exact antichain.

    ``parts``: iterable of (state [n], fok [n, W], fcr [n, G]) host
    arrays.  Rows sort by the 64-bit (state, fok) class hash — the LSH
    bucket key (``hashing.np_class_hash``) — then exact duplicate and
    domination kills run ONLY within equal-key runs: identical classes
    always share both hash lanes, so per-run exact work is globally
    exact (a cross-bucket pair is provably a different class, where
    domination cannot apply).  Within a class, the antichain keeps
    pointwise-minimal fcr rows, first copy in input order on ties —
    the same contract as the device ``exact_prune``.

    Returns (state, fok, fcr, stats) with stats = {"rows_in",
    "rows_out", "buckets"}.
    """
    parts = [p for p in parts if p is not None and p[0].shape[0]]
    if not parts:
        z = np.zeros(0, np.int32)
        return z, np.zeros((0, 1), np.uint32), np.zeros((0, 1), np.int16), {
            "rows_in": 0, "rows_out": 0, "buckets": 0}
    state = np.concatenate([np.asarray(p[0]) for p in parts])
    fok = np.concatenate([np.asarray(p[1]) for p in parts], axis=0)
    fcr = np.concatenate([np.asarray(p[2]) for p in parts], axis=0)
    n = state.shape[0]
    h1, h2 = hashing.np_class_hash(state, fok)
    order = np.lexsort((np.arange(n), h2, h1))
    sh1, sh2 = h1[order], h2[order]
    # equal-(h1,h2) run boundaries — the LSH buckets
    new_run = np.ones(n, bool)
    new_run[1:] = (sh1[1:] != sh1[:-1]) | (sh2[1:] != sh2[:-1])
    starts = np.flatnonzero(new_run)
    ends = np.append(starts[1:], n)
    keep = np.ones(n, bool)  # in sorted order
    for lo, hi in zip(starts, ends):
        if hi - lo == 1:
            continue
        idx = order[lo:hi]  # input order within the bucket (lexsort stable)
        bst, bfo, bfc = state[idx], fok[idx], fcr[idx]
        # exact class split inside the bucket (hash collisions between
        # distinct classes are ~1e-13 but kills must stay content-decided)
        same = (bst[:, None] == bst[None, :]) & (
            bfo[:, None, :] == bfo[None, :, :]).all(-1)
        le = (bfc[:, None, :] <= bfc[None, :, :]).all(-1)
        lt = (bfc[:, None, :] < bfc[None, :, :]).any(-1)
        k = hi - lo
        earlier = np.arange(k)[:, None] < np.arange(k)[None, :]
        # kill j when an equal-class i is pointwise ≤ and either strictly
        # smaller or earlier (ties keep the first copy); kills through
        # killed intermediaries are sound by transitivity
        killer = same & le & (lt | earlier)
        np.fill_diagonal(killer, False)
        keep[lo:hi] = ~killer.any(axis=0)
    sel = order[keep]
    sel.sort()  # restore input order (deterministic downstream slicing)
    stats = {"rows_in": int(n), "rows_out": int(sel.size),
             "buckets": int(starts.size)}
    _count("spill_merges")
    obs.counter("frontier.spill_merges")
    return state[sel], fok[sel], fcr[sel], stats


# ---------------------------------------------------------------------------
# Crashed-op group factorization
# ---------------------------------------------------------------------------


def _distinct_ops(packed) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Every distinct (f, v1, v2) op the packed history can fire:
    returning ops (active barriers), open ok movers, and live crashed
    groups."""
    bar_f, bar_v1, bar_v2, _slot = packed["bar"]
    mov_f, mov_v1, mov_v2, mov_open = packed["mov"]
    grp_f, grp_v1, grp_v2 = packed["grp"]
    act = np.asarray(packed["bar_active"], bool)
    mo = np.asarray(mov_open, bool)
    live = np.asarray(packed["grp_open"]).max(axis=0) > 0
    triples = np.concatenate([
        np.stack([np.asarray(bar_f)[act], np.asarray(bar_v1)[act],
                  np.asarray(bar_v2)[act]], axis=1),
        np.stack([np.asarray(mov_f)[mo], np.asarray(mov_v1)[mo],
                  np.asarray(mov_v2)[mo]], axis=1),
        np.stack([np.asarray(grp_f)[live], np.asarray(grp_v1)[live],
                  np.asarray(grp_v2)[live]], axis=1),
    ]).astype(np.int64)
    return np.unique(triples, axis=0).T


def reachable_states(step, init_state: int, ops, max_states: int = 256,
                     depth_cap: int = 64):
    """Tabulate the states reachable from ``init`` in at most
    ``depth_cap`` fires of the distinct ops, via the tensor model's step
    function (host-driven BFS with per-state min-depth).

    A linearization fires each ok op once and each crashed group at
    most its open count, so ``depth_cap`` = the history's total fire
    budget covers every state a witness can visit — models whose state
    space is unbounded under unlimited re-firing (the counter) still
    tabulate finitely.  Returns (states sorted, min_depth aligned,
    closed?) — ``closed`` means a true fixpoint was reached below the
    cap — or None when the table exceeds ``max_states`` (callers skip
    factorization: never a wrong factor, only a missed one)."""
    f, v1, v2 = (np.asarray(a, np.int32) for a in ops)
    depth = {int(init_state): 0}
    frontier = np.array([init_state], np.int32)
    closed = False
    for d in range(1, depth_cap + 1):
        nxt, legal = step(frontier[:, None], f[None, :], v1[None, :],
                          v2[None, :])
        nxt = np.asarray(nxt)[np.asarray(legal)]
        new = [int(s) for s in np.unique(nxt) if int(s) not in depth]
        if not new:
            closed = True
            break
        if len(depth) + len(new) > max_states:
            return None
        for s in new:
            depth[s] = d
        frontier = np.asarray(new, np.int32)
    states = np.array(sorted(depth), np.int32)
    depths = np.array([depth[int(s)] for s in states], np.int32)
    return states, depths, closed


def independent_groups(packed, max_states: int = 256) -> list[int]:
    """Live crashed-group indices that are trace-independent of EVERY
    distinct op in the history (themselves included), over the
    tabulated reachable state set:

      for all reachable s, ops g (the group's) and b:
        (i)  g preserves b's legality and vice versa
             (legal(s·g, b) == legal(s, b) whenever g is legal at s, and
             symmetrically), and
        (ii) they commute where both are legal (s·g·b == s·b·g).

    Such a group's fires can be commuted to the end of any witness and
    dropped (crashed ops carry no obligations), and conversely any
    witness ignoring the group is a witness of the full history — so
    deleting the group preserves the verdict EXACTLY, refutations
    included.  The conditions are checked at every state a witness can
    visit (fire-budget depth); lookup rows one/two fires deeper are in
    the table by construction.  Returns [] when the state space is
    unbounded at the cap or there is no tensor-model structure to
    tabulate."""
    try:
        f, v1, v2 = _distinct_ops(packed)
    except Exception:  # noqa: BLE001 — malformed tables: skip, never fail
        return []
    if f.size == 0 or f.size > 128:
        return []
    # the history's total fire budget: each active barrier's ok op fires
    # once; each crashed group at most its max open count
    fire_budget = int(np.asarray(packed["bar_active"], bool).sum())
    fire_budget += int(np.asarray(packed["grp_open"]).max(axis=0).sum())
    tab = reachable_states(
        packed["step"], int(packed["init_state"]), (f, v1, v2),
        max_states, depth_cap=fire_budget + 2)
    if tab is None:
        return []
    states, depths, closed = tab
    S = states.size
    O = f.size
    nxt, legal = packed["step"](
        states[:, None].astype(np.int32), f[None, :].astype(np.int32),
        v1[None, :].astype(np.int32), v2[None, :].astype(np.int32))
    N = np.asarray(nxt)          # [S, O] next-state values
    L = np.asarray(legal)        # [S, O] legality
    # row index of each next state in the vocabulary; successors of
    # boundary (deepest) states may fall outside — those rows are only
    # dereferenced from interior states, masked below
    Nrow = np.searchsorted(states, N)
    Nrow = np.clip(Nrow, 0, S - 1)
    in_vocab = states[Nrow] == N
    Nrow = np.where(L & in_vocab, Nrow, 0)
    # states a witness can visit: everything when the table closed, else
    # fire-budget depth (lookups at +1/+2 fires stay in the table)
    interior = (
        np.ones(S, bool) if closed else depths <= max(0, fire_budget)
    )
    if closed is False and not (in_vocab | ~L)[interior].all():
        return []  # a witness state's successor left the table: bail

    def independent(a: int, b: int) -> bool:
        La, Lb = L[:, a] & interior, L[:, b] & interior
        # (i) mutual legality preservation
        if not np.array_equal(L[Nrow[:, a], b][La], L[:, b][La]):
            return False
        if not np.array_equal(L[Nrow[:, b], a][Lb], L[:, a][Lb]):
            return False
        # (ii) commutation where both legal
        both = La & Lb
        if not np.array_equal(N[Nrow[:, a], b][both], N[Nrow[:, b], a][both]):
            return False
        return True

    grp_f, grp_v1, grp_v2 = (np.asarray(a) for a in packed["grp"])
    live = np.asarray(packed["grp_open"]).max(axis=0) > 0
    # map each live group to its column in the distinct-op table
    keys = {tuple(t): i for i, t in enumerate(np.stack([f, v1, v2], axis=1))}
    out = []
    for g in np.flatnonzero(live):
        a = keys.get((int(grp_f[g]), int(grp_v1[g]), int(grp_v2[g])))
        if a is None:
            continue
        if all(independent(a, b) for b in range(O)):
            out.append(int(g))
    return out


def factor_packed(packed, max_states: int = 256) -> tuple[dict, int]:
    """Split independent crashed-op groups off the packed problem.

    Each independent group is its own factor of the search space; a
    factor holding only optional crashed ops is decided closed-form
    (valid — fire nothing) and recombines as a no-op under AND, so the
    group is REMOVED: the returned pack has the survivors' grp columns
    only, shrinking G (the fcr product dimension) structurally.  The
    input dict is not mutated.  Returns (packed', factors_dropped)."""
    try:
        drop = independent_groups(packed, max_states)
    except Exception:  # noqa: BLE001 — factorization is an optimization;
        # a tabulation bug must degrade to "no factors", never to a crash
        drop = []
    if not drop:
        return packed, 0
    G0 = packed["G"]
    keep = [g for g in range(G0) if g not in set(drop)]
    grp_f, grp_v1, grp_v2 = (np.asarray(a) for a in packed["grp"])
    grp_open = np.asarray(packed["grp_open"])
    if keep:
        k = np.asarray(keep, np.int64)
        new_grp = (grp_f[k].copy(), grp_v1[k].copy(), grp_v2[k].copy())
        new_open = grp_open[:, k].copy()
    else:  # every group factored away: keep one inert zero column
        new_grp = (np.zeros(1, grp_f.dtype), np.zeros(1, grp_v1.dtype),
                   np.zeros(1, grp_v2.dtype))
        new_open = np.zeros((grp_open.shape[0], 1), grp_open.dtype)
    out = dict(packed)
    out["grp"] = new_grp
    out["grp_open"] = new_open
    out["G"] = new_open.shape[1]
    n = len(drop)
    _count("factorizations", n)
    obs.counter("frontier.factorizations", n)  # mirrors to /metrics
    return out, n


# ---------------------------------------------------------------------------
# Honest exhaustion
# ---------------------------------------------------------------------------


def undecidability_report(
    *,
    capacity: int,
    frontier_rows: int,
    peak_frontier: int,
    barrier: int,
    barriers_total: int,
    budget_mb: float | None = None,
    budget_rows: int | None = None,
    spill_rows: int = 0,
    spill_bytes: int = 0,
    factor_count: int = 0,
    device_buffer_bytes: int | None = None,
    mesh_devices: int | None = None,
    per_device_rows: int | None = None,
    reason: str = "closure-overflow",
) -> dict:
    """The machine-readable record of WHY fixed memory could not decide:
    growth rate (closure output over frontier input at the exhausted
    barrier — how fast the state space outruns any rung), spill volume
    (how much was already moved to host), and the budget in force at
    exhaustion.  Attached by the caller to the final ``unknown`` result
    (``"undecidability"`` key + a json rendering inside ``cause``) —
    the result either decides or says exactly why it could not.

    ``mesh_devices``/``per_device_rows``: set when the exhausted stage
    was the MESH-spanning fused kernel, so the report cites the honest
    mesh capacity (devices × per-device rows) rather than implying a
    single chip was the ceiling — spill engages only after the whole
    mesh's capacity exhausts."""
    rep = {
        "reason": str(reason),
        "capacity": int(capacity),
        "frontier_rows": int(frontier_rows),
        "peak_frontier": int(peak_frontier),
        "growth_rate": round(float(peak_frontier) / max(1, frontier_rows), 3),
        "barrier": int(barrier),
        "barriers_total": int(barriers_total),
        "spill_rows": int(spill_rows),
        "spill_bytes": int(spill_bytes),
        "factor_count": int(factor_count),
    }
    if budget_mb is not None:
        rep["budget_mb"] = float(budget_mb)
    if budget_rows is not None:
        rep["budget_rows"] = int(budget_rows)
    if device_buffer_bytes is not None:
        rep["device_buffer_bytes"] = int(device_buffer_bytes)
    if mesh_devices is not None:
        rep["mesh_devices"] = int(mesh_devices)
        if per_device_rows is not None:
            rep["per_device_rows"] = int(per_device_rows)
            rep["mesh_capacity_rows"] = int(mesh_devices) * int(per_device_rows)
    _count("undecidable_reports")
    obs.event(
        "frontier.undecidable", barrier=rep["barrier"],
        capacity=rep["capacity"], growth_rate=rep["growth_rate"],
        spill_bytes=rep["spill_bytes"], factor_count=rep["factor_count"],
    )
    _metrics.inc("frontier.undecidable")
    return rep


def undecidable_cause(report: dict) -> str:
    """The ``cause`` string for an undecidable unknown: a fixed prefix
    (machine-greppable) + the report as compact json."""
    return "undecidable under fixed memory: " + json.dumps(
        report, sort_keys=True, separators=(",", ":"))
