"""Frontier compaction, dedup and domination pruning for the WGL kernels.

The WGL frontier is a struct-of-arrays table of configurations.  Two
maintenance strategies live here:

  * frontier_update_fast — the production path: single-key hash sort +
    windowed hash-lane dedup with candidate-order compaction.  Kills are
    hash-decided (collision ~1e-13 per compaction), so the batch driver
    confirms every fast-path refutation on the exact CPU sweep before
    reporting it — overlapped with the remaining device stages, which
    makes the confirmation sound and nearly free in wall clock.  (A
    season of sort-free redesigns — pairwise-exact buffers, winner
    buckets, dense slot tables — all measured SLOWER on this TPU than
    the hash sort; the engine notes in PERF.md record the numbers.)
  * frontier_update — the sort-based formulation (hash-ordered 4-key
    lax.sort, windowed kills, two-stage domination), kept as the
    reference implementation and used by the frontier-sharded multi-chip
    path, whose all_to_all routing is hash-based by construction.

Row hashes (murmur3-style mixing) order sorts and fingerprint frontiers;
no kill decision rides on hash identity anywhere.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jepsen_tpu._platform import honor_env_platform

# The module-level constants below initialize the jax backend at import:
# apply the user's JAX_PLATFORMS env choice first (the axon plugin
# ignores the env var; see _platform.py).
honor_env_platform()

_C1 = jnp.uint32(0x85EBCA6B)
_C2 = jnp.uint32(0xC2B2AE35)


def mix32(x):
    """murmur3 fmix32 finalizer (vectorized)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * _C1
    x = x ^ (x >> 13)
    x = x * _C2
    x = x ^ (x >> 16)
    return x


def hash_rows(columns, seed: int):
    """Hash a list of equal-length uint32/int32 column arrays to one uint32
    lane, column-by-column (static unroll; column count is small)."""
    h = jnp.full(columns[0].shape, jnp.uint32(seed ^ 0x9E3779B9))
    for col in columns:
        h = mix32(h ^ col.astype(jnp.uint32))
    return h


def frontier_update_fast(
    state, fok, fcr, alive, cost, capacity: int, window: int = 4,
    n_parents: int | None = None, max_count: int | None = None,
):
    """Frontier dedup + truncation, tuned for the vmapped batch kernel.

    Data movement and op count are the cost on TPU — the exact
    formulation's 4-key lexicographic ``lax.sort`` plus full-table gathers
    measured ~13 ms per round inside the barrier scan on v5e.  Here:

      1. hash each row to 64 bits (2 uint32 lanes; dead rows pinned to the
         max key so they sink to the end);
      2. ONE single-key sort carrying only the hash lanes and a packed
         (alive | index) payload — row data never moves through the sort;
      3. a row is a duplicate when a neighbor within ``window`` sorted
         predecessors has both hash lanes equal — collision probability
         ~1e-13 per compaction.  A collision kills a distinct config
         silently, which is why engines built on this update never
         report ``False`` as final: jepsen_tpu.parallel.batch_analysis
         confirms every fast-path refutation on the exact CPU sweep
         (overlapped with the remaining device stages, so the
         confirmation is sound AND nearly free in wall clock).  Dup runs
         longer than the window survive as bloat;
      4. survivors compact to ``capacity`` by cumsum-rank scatter in
         CANDIDATE order (parents precede children, i.e. fewest-fired
         first, so truncation drops the most-speculative rows and
         witnesses survive longest) — only the ``capacity`` retained
         rows are ever gathered;
      5. dedup survivors compact into a 2*capacity buffer which is
         ``exact_prune``d (content-decided domination) HERE, before
         truncation — the single prune site: the returned frontier is a
         duplicate-free antichain, and every subset the engines take of
         it (truncation, the per-barrier return filter, the uniform
         slot-bit clear) stays one, so no outer prune is needed.

    ``cost`` is accepted for signature parity with frontier_update but
    unused: candidate order already approximates cheapest-first (children
    always carry one more fired op than their parent), so no cost sort is
    needed — and truncation order only affects verdict quality, never
    soundness (overflow flags lossy and the caller escalates).

    ``n_parents``: when the candidate table's first ``n_parents`` rows
    are the previous frontier (parents) and the rest are this round's
    expansions, the returned ``child`` mask marks surviving rows that
    came from an expansion.  ``(alive' & child).any()`` is a no-growth
    closure-fixpoint signal (exact modulo the same hash-dedup caveat as
    step 3 — which is covered by the same refutation confirmation), so
    engines advance a barrier after ONE tick when its closure is already
    complete instead of burning a second fingerprint-compare tick.

    ``max_count``: a static upper bound on any fired-crashed group count
    (callers pass the mover-table size).  When given, the buffer prune
    runs as ``exact_prune_mxu`` — the same content-decided antichain, but
    with the pairwise pointwise-≤ test as one bf16 matmul on the MXU
    instead of O(C²·G) vector compares (the wide-capacity tick's
    dominant cost).

    Returns (state', fok', fcr', alive', overflowed, fp, child) — fp is
    an order-insensitive content fingerprint of the surviving set
    (diagnostic only).
    """
    n = state.shape[0]
    w = fok.shape[1]
    g = fcr.shape[1]
    row_cols = [state] + [fok[:, k] for k in range(w)] + [fcr[:, k] for k in range(g)]
    h1 = hash_rows(row_cols, 0xB00B_135)
    h2 = hash_rows(row_cols, 0x1CEB_00DA)
    iota = jnp.arange(n, dtype=jnp.int32)
    # alive rides in the payload's top bit so a sentinel-colliding hash
    # can't resurrect or kill anything.
    payload = jnp.where(alive, iota, iota + jnp.int32(1 << 30))
    pos = jnp.arange(n)
    key = jnp.where(alive, h1, jnp.uint32(0xFFFFFFFF))
    k1, k2, spay = jax.lax.sort((key, h2, payload), num_keys=1)
    al = spay < (1 << 30)
    sidx = spay & ((1 << 30) - 1)
    dup = jnp.zeros(n, bool)
    for k in range(1, window + 1):
        same = (
            (k1 == jnp.roll(k1, k))
            & (k2 == jnp.roll(k2, k))
            & jnp.roll(al, k)
            & (pos >= k)
        )
        dup = dup | same
    keep = al & ~dup
    # Map the keep mask back to CANDIDATE order before compacting: the
    # candidate table lists parents before children, i.e. fewest-fired
    # first, so truncation under overflow drops the most-speculative rows
    # — witnesses survive longer than under hash-order truncation.
    keep_orig = jnp.zeros(n, bool).at[sidx].set(keep, unique_indices=True)
    # Compact dedup survivors into a 2*capacity buffer, DOMINATION-prune
    # it there ([2C, 2C, G] dense pairwise compares — cheap), and only
    # then truncate: ``overflowed`` counts undominated survivors, not the
    # closure's domination bloat.  Measured on the headline batch at cap
    # 128: 105 → 108 histories resolved for ~0.4 s, and the carried
    # frontier is antichain-minimal so later rounds stay small.
    Cb = min(2 * capacity, n)
    rank = jnp.cumsum(keep_orig) - 1
    n_keep0 = jnp.maximum(rank[-1] + 1, 0)
    pos2 = jnp.where(keep_orig, rank, Cb + pos)
    srcB = (
        jnp.zeros(Cb, jnp.int32)
        .at[pos2]
        .set(iota, mode="drop", unique_indices=True)
    )
    bst = state[srcB]
    bfo = fok[srcB]
    bfc = fcr[srcB]
    balive = jnp.arange(Cb) < jnp.minimum(n_keep0, Cb)
    spill = n_keep0 > Cb
    if max_count is not None:
        # saturating planes: sound at any count (round 5 — wide-mover
        # histories keep the matmul instead of the dense fallback)
        balive = exact_prune_mxu(bst, bfo, bfc, balive, max_count)
    else:
        balive = exact_prune(bst, bfo, bfc, balive)
    rank2 = jnp.cumsum(balive) - 1
    n_keep = jnp.maximum(rank2[-1] + 1, 0)
    pos3 = jnp.where(balive, rank2, capacity + jnp.arange(Cb))
    src2 = (
        jnp.zeros(capacity, jnp.int32)
        .at[pos3]
        .set(jnp.arange(Cb, dtype=jnp.int32), mode="drop", unique_indices=True)
    )
    kst = bst[src2]
    kfo = bfo[src2]
    kfc = bfc[src2]
    new_alive = jnp.arange(capacity) < jnp.minimum(n_keep, capacity)
    overflowed = spill | (n_keep > capacity)
    if n_parents is None:
        child = jnp.zeros(capacity, bool)
    else:
        child = srcB[src2] >= n_parents
    fp = _fingerprint(kst, kfo, kfc, new_alive, w, g)
    return kst, kfo, kfc, new_alive, overflowed, fp, child


#: One-hot plane count for the matmul prune.  Counts at or above the
#: last plane compare SATURATING (see exact_prune_mxu): the test stays
#: sound at any true count, exact below M-1 — so the plane count is a
#: cost/precision knob, not a correctness gate.  64 planes measured
#: fastest on the headline wide stage; round 4's hard gate (dense
#: fallback past mover width 64) is gone.
MXU_PRUNE_MAX_COUNT = 64


def exact_prune_mxu(state, fok, fcr, alive, max_count: int):
    """exact_prune with the pairwise pointwise-≤ test recast as a matmul.

    The dense prune's cost is the [N, N, G] count comparison — vector-unit
    work that dominates wide-capacity ticks (13.6 s vs 4.0 s pruneless on
    the cap-2048 straggler stage).  The MXU formulation: encode each
    row's fired-crashed counts as a cumulative one-hot u[k, c] =
    (fcr[k] ≤ c) and a SATURATING exact one-hot v[k, c] =
    (min(fcr[k], M-1) == c), both [N, G·M] with M = min(``max_count``,
    MXU_PRUNE_MAX_COUNT); then (u @ vᵀ)[i, j] counts the groups where
    fcr_i ≤ min(fcr_j, M-1), and == G ⟹ pointwise fcr_i ≤ fcr_j.  One
    bf16 matmul (values ≤ G, exact in bf16) replaces the O(N²·G)
    compare; class equality and tie-breaking stay content-decided.

    Saturation soundness (round 5, replacing the round-4 dense fallback
    past mover width 64): the computed indicator implies true pointwise
    ≤ at ANY count — min(fcr_j, M-1) ≤ fcr_j, so a kill is always a
    genuine domination/duplicate.  When some count reaches M-1 a true
    domination can be MISSED (u's planes are all-zero for counts ≥ M),
    which only bloats the frontier (overflow → lossy → escalate, never
    a wrong verdict).  Ties stay order-stable: mutual-≤ (equality)
    detected via the saturating test forces every count < M on both
    rows, where the test is exact.  Below M-1 everywhere, the result is
    bit-identical to exact_prune.
    """
    n = state.shape[0]
    g = fcr.shape[1]
    m = min(int(max_count), MXU_PRUNE_MAX_COUNT)
    c = jnp.arange(m, dtype=fcr.dtype)
    sat = jnp.minimum(fcr, m - 1)
    u = (fcr[:, :, None] <= c[None, None, :]).reshape(n, g * m)
    v = (sat[:, :, None] == c[None, None, :]).reshape(n, g * m)
    cnt = jnp.dot(
        u.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16).T,
        preferred_element_type=jnp.float32,
    )
    le = cnt > g - 0.5  # le[i, j]: fcr_i ≤ fcr_j pointwise
    same = state[:, None] == state[None, :]
    for k in range(fok.shape[1]):
        col = fok[:, k]
        same &= col[:, None] == col[None, :]
    idx = jnp.arange(n)
    earlier = idx[:, None] < idx[None, :]
    killer = (
        same & le & (~le.T | earlier) & alive[:, None] & alive[None, :]
    )
    return alive & ~killer.any(axis=0)


def _fingerprint(kst, kfo, kfc, new_alive, w, g):
    out_cols = [kst] + [kfo[:, k] for k in range(w)] + [kfc[:, k] for k in range(g)]
    r1 = hash_rows(out_cols, 0xFEED_0001)
    r2 = hash_rows(out_cols, 0xFEED_0002)
    am = new_alive.astype(jnp.uint32)
    return jnp.stack([(r1 * am).sum(), (r2 * am).sum(), am.sum()])


def frontier_update(state, fok, fcr, alive, cost, capacity: int, window: int = 16):
    """One-pass frontier maintenance: dedup + domination + truncation.

    Sorts candidate rows by (dead, class-hash(state,fok), cost); rows of the
    same (state, fok) class land contiguously, cheapest (fewest-fired)
    first (stable sort by original index).  A row is killed when any of its ``window`` sorted
    predecessors has the same exact (state, fok) and pointwise ≤ fired-
    crashed counts — this removes exact duplicates *and* dominated configs
    in one windowed compare (domination: the cheaper config's futures are a
    superset, see wgl_cpu; kills through killed intermediaries are sound by
    transitivity).  Misses beyond the window only bloat the frontier; they
    never produce wrong kills.

    Returns (state', fok', fcr', alive', overflowed, fp):
      overflowed — undominated survivors exceeded capacity, or the exact-
                   domination buffer spilled (loss);
      fp         — order-insensitive content fingerprint (3 uint32 lanes)
                   of the surviving set.  Callers detect closure fixpoints
                   as fp == previous round's fp; being order-insensitive it
                   is immune to 'livelock' rounds where dominated
                   representatives are regenerated and re-killed without
                   the set actually changing.
    """
    n = state.shape[0]
    w = fok.shape[1]
    g = fcr.shape[1]
    class_cols = [state] + [fok[:, k] for k in range(w)]
    ch1 = hash_rows(class_cols, 0xB00B_135)
    ch2 = hash_rows(class_cols, 0x1CEB_00DA)
    dead = (~alive).astype(jnp.uint32)
    iota = jnp.arange(n, dtype=jnp.int32)
    _sd, _s1, _s2, _sc, sidx = jax.lax.sort(
        (dead, ch1, ch2, cost.astype(jnp.uint32), iota), num_keys=4
    )
    st = state[sidx]
    fo = fok[sidx]
    fc = fcr[sidx]
    al = alive[sidx]
    pos = jnp.arange(n)
    killed = jnp.zeros(n, bool)
    for k in range(1, window + 1):
        pst = jnp.roll(st, k)
        pfo = jnp.roll(fo, k, axis=0)
        pfc = jnp.roll(fc, k, axis=0)
        pal = jnp.roll(al, k)
        same = (pst == st) & (pfo == fo).all(-1) & pal & (pos >= k)
        killed = killed | (same & (pfc <= fc).all(-1))
    aliveD = al & ~killed
    n_w = aliveD.sum()
    # Stage 2: exact pairwise domination on a small buffer.  The windowed
    # pass thins the big candidate table; the buffer pass makes the
    # retained frontier exactly domination-free so bloat can't compound
    # across rounds.
    # The exact pass is quadratic in rows but chunked (dominate), so the
    # buffer only needs to cover the capacity with headroom.
    b2 = min(2 * capacity, n)
    sc2 = cost[sidx].astype(jnp.uint32)
    _k1, _k2, fidx = jax.lax.sort(
        ((~aliveD).astype(jnp.uint32), sc2, jnp.arange(n, dtype=jnp.int32)), num_keys=2
    )
    bsel = fidx[:b2]
    bst, bfo, bfc = st[bsel], fo[bsel], fc[bsel]
    bcost = sc2[bsel]
    balive = jnp.arange(b2) < jnp.minimum(n_w, b2)
    balive = dominate(bst, bfo, bfc, balive)
    n_x = balive.sum()
    # Final truncation to capacity.
    _j1, _j2, ksel = jax.lax.sort(
        ((~balive).astype(jnp.uint32), bcost, jnp.arange(b2, dtype=jnp.int32)),
        num_keys=2,
    )
    keep = ksel[:capacity]
    kst, kfo, kfc = bst[keep], bfo[keep], bfc[keep]
    new_alive = jnp.arange(capacity) < jnp.minimum(n_x, capacity)
    overflowed = (n_w > b2) | (n_x > capacity)
    row_cols = [kst] + [kfo[:, k] for k in range(w)] + [kfc[:, k] for k in range(g)]
    r1 = hash_rows(row_cols, 0xFEED_0001)
    r2 = hash_rows(row_cols, 0xFEED_0002)
    am = new_alive.astype(jnp.uint32)
    fp = jnp.stack([(r1 * am).sum(), (r2 * am).sum(), am.sum()])
    return kst, kfo, kfc, new_alive, overflowed, fp



def exact_prune(state, fok, fcr, alive, chunk_rows: int = 0):
    """Kill duplicate and dominated frontier rows, exactly.

    Row j dies when some alive row i has the same (state, fok) class with
    pointwise ≤ fired-crashed counts AND is either strictly smaller
    somewhere or earlier in the table — ties keep the first copy.  The
    survivor set is the pointwise-minimal antichain with one
    representative per duplicate group — exact pruning, never changes the
    verdict (the survivor's futures are a superset, see wgl_cpu
    domination notes).

    Chunked over the killed axis — via lax.scan, so the program size is
    constant however many chunks a wide buffer needs — to bound the
    [F, C, G] intermediates (under vmap the peak multiplies by the lane
    count, and oversized buffers here have faulted the TPU worker).
    """
    f = state.shape[0]
    g = fcr.shape[1]
    if chunk_rows <= 0:
        chunk_rows = min(f, max(16, (1 << 22) // max(1, f * g)))
    idx = jnp.arange(f, dtype=jnp.int32)

    def part(lo):
        st_c = jax.lax.dynamic_slice_in_dim(state, lo, chunk_rows)
        fo_c = jax.lax.dynamic_slice_in_dim(fok, lo, chunk_rows, axis=0)
        fc_c = jax.lax.dynamic_slice_in_dim(fcr, lo, chunk_rows, axis=0)
        al_c = jax.lax.dynamic_slice_in_dim(alive, lo, chunk_rows)
        idx_c = jax.lax.dynamic_slice_in_dim(idx, lo, chunk_rows)
        same = (state[:, None] == st_c[None, :]) & (
            (fok[:, None, :] == fo_c[None, :, :]).all(-1)
        )
        le = (fcr[:, None, :] <= fc_c[None, :, :]).all(-1)
        lt = (fcr[:, None, :] < fc_c[None, :, :]).any(-1)
        earlier = idx[:, None] < idx_c[None, :]
        dom = same & le & (lt | earlier) & alive[:, None] & al_c[None, :]
        return dom.any(axis=0)

    if f <= chunk_rows:
        return alive & ~part(jnp.int32(0))
    n_chunks = (f + chunk_rows - 1) // chunk_rows
    fpad = n_chunks * chunk_rows
    if fpad != f:
        # pad with dead rows so every dynamic_slice is in bounds (a
        # clamped slice would mis-align the reshape below)
        state = jnp.pad(state, (0, fpad - f))
        fok = jnp.pad(fok, ((0, fpad - f), (0, 0)))
        fcr = jnp.pad(fcr, ((0, fpad - f), (0, 0)))
        alive = jnp.pad(alive, (0, fpad - f))
        idx = jnp.pad(idx, (0, fpad - f))
    starts = jnp.arange(n_chunks, dtype=jnp.int32) * chunk_rows
    _, parts = jax.lax.scan(lambda c, lo: (c, part(lo)), None, starts)
    return alive[:f] & ~parts.reshape(-1)[:f]


def dominate(state, fok, fcr, alive, chunk_rows: int = 0):
    """Kill dominated frontier rows.

    Row j is dominated when some alive row i has the same (state, fok) but
    fired strictly fewer crashed ops pointwise (fcr_i ≤ fcr_j, ≠) — the
    smaller config's futures are a superset (see wgl_cpu domination notes),
    so j is redundant.  Exact pruning: removing dominated rows never
    changes the verdict.  Chunked over the dominated axis to bound the
    [F, C, G] comparison intermediates.
    """
    f = state.shape[0]
    g = fcr.shape[1]
    if chunk_rows <= 0:
        # keep [f, chunk, g] intermediates under ~4M elements (vmap
        # multiplies the peak by the lane count)
        chunk_rows = max(16, min(f, (1 << 22) // max(1, f * g)))
    parts = []
    for lo in range(0, f, chunk_rows):
        hi = min(f, lo + chunk_rows)
        eq_state = state[:, None] == state[None, lo:hi]
        eq_fok = (fok[:, None, :] == fok[None, lo:hi, :]).all(-1)
        le = (fcr[:, None, :] <= fcr[None, lo:hi, :]).all(-1)
        lt = (fcr[:, None, :] < fcr[None, lo:hi, :]).any(-1)
        dom = eq_state & eq_fok & le & lt & alive[:, None] & alive[None, lo:hi]
        parts.append(dom.any(axis=0))
    return alive & ~jnp.concatenate(parts)


def compact(columns, alive, cost, capacity: int):
    """Dedup + truncate a frontier candidate table.

    ``columns``: list of [N] or [N, k] arrays describing rows; ``alive``:
    [N] bool; ``cost``: [N] int32 priority (smaller kept first under
    truncation).  Returns (select_idx [capacity], new_alive [capacity],
    n_unique, overflowed) — callers gather their columns by select_idx.
    """
    n = alive.shape[0]
    flat_cols = []
    for c in columns:
        if c.ndim == 1:
            flat_cols.append(c)
        else:
            for k in range(c.shape[1]):
                flat_cols.append(c[:, k])
    h1 = hash_rows(flat_cols, 0x1234_5678)
    h2 = hash_rows(flat_cols, 0x9ABC_DEF0)
    h3 = hash_rows(flat_cols, 0x0F1E_2D3C)
    dead = (~alive).astype(jnp.uint32)
    iota = jnp.arange(n, dtype=jnp.int32)
    sd, s1, s2, s3, sidx = jax.lax.sort((dead, h1, h2, h3, iota), num_keys=4)
    same_as_prev = (
        (s1 == jnp.roll(s1, 1)) & (s2 == jnp.roll(s2, 1)) & (s3 == jnp.roll(s3, 1))
    )
    same_as_prev = same_as_prev.at[0].set(False)
    uniq = (sd == 0) & ~same_as_prev
    n_unique = uniq.sum()
    # Compact survivors to capacity, cheapest (most-dominating) rows first.
    cost_sorted = cost[sidx]
    not_uniq = (~uniq).astype(jnp.uint32)
    _k1, _k2, fidx = jax.lax.sort(
        (not_uniq, cost_sorted.astype(jnp.uint32), sidx), num_keys=2
    )
    select = fidx[:capacity]
    new_alive = jnp.arange(capacity) < jnp.minimum(n_unique, capacity)
    return select, new_alive, n_unique, n_unique > capacity
