"""Frontier compaction, dedup and domination pruning for the WGL kernels.

The WGL frontier is a struct-of-arrays table of configurations.  Two
maintenance strategies live here:

  * frontier_update_fast — the production path: single-key hash sort +
    windowed hash-lane dedup with candidate-order compaction.  Kills are
    hash-decided (collision ~1e-13 per compaction), so the batch driver
    confirms every fast-path refutation on the exact CPU sweep before
    reporting it — overlapped with the remaining device stages, which
    makes the confirmation sound and nearly free in wall clock.  (A
    season of sort-free redesigns — pairwise-exact buffers, winner
    buckets, dense slot tables — all measured SLOWER on this TPU than
    the hash sort; the engine notes in PERF.md record the numbers.)
  * frontier_update — the sort-based formulation (hash-ordered 4-key
    lax.sort, windowed kills, two-stage domination), kept as the
    reference implementation and used by the frontier-sharded multi-chip
    path, whose all_to_all routing is hash-based by construction.

Row hashes (murmur3-style mixing) order sorts and fingerprint frontiers;
no kill decision rides on hash identity anywhere.

Both maintenance strategies support interchangeable DEDUP BACKENDS
(``dedup_backend="sort"|"bucket"|"pallas"``, selectable per
engine/ladder and via the ``JEPSEN_TPU_DEDUP_BACKEND`` env var, the way
CYCLE_BACKEND selects cycle classification):

  * "sort"   — the original full-width multi-operand ``lax.sort`` over
    the hash lanes (reference behavior).
  * "bucket" — hash-bucketed radix dedup (this module's `_keep_bucket` /
    the packed stage-1 in frontier_update): rows are partitioned by the
    top bits of the row hash into 2^b buckets by packing
    ``[dead-bit | bucket | candidate-index]`` into ONE uint32 and
    running a SINGLE-operand key sort (XLA's specialized single-array
    sort — measured ~6x cheaper than the multi-operand tuple sort that
    is the ladder's per-round floor), then deduping within bucket-local
    windows with full 64-bit hash compares gathered by the packed
    index.  Same kill contract as the sort path (a kill requires both
    hash lanes equal; window misses only bloat), plus two guarantees:
    survivors are always the FIRST copy in candidate order (the packed
    index makes the sort stable; the sort path's tie order is
    unspecified), and bucket overflow NEVER drops a row — an
    undeduplicated row is retained (bloat), caught by the content-
    decided buffer prune, and escalates through the existing
    overflow/lossy ladder if it threatens capacity (see
    ``_keep_bucket``).  When the candidate table is too large for the
    packed-key geometry (``bucket_feasible``), the round statically
    routes to the sort path — never a silent drop.
  * "pallas" — the fused wide-stage Pallas TPU kernel
    (jepsen_tpu.ops.wide_kernel): bucket-backend kill semantics
    WITHOUT the sort, plus the MXU domination prune and cumsum-rank
    compaction, fused into one ``pl.pallas_call`` with every table
    VMEM-resident.  Routed only on statically feasible WIDE geometry
    (``wide_kernel.fused_feasible``); everything else falls back down
    the bucket -> sort ladder at trace time.  Interpret mode executes
    the real kernel body on CPU, so the differential suite gates it
    like any other backend.
"""

from __future__ import annotations

import os
import time
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from jepsen_tpu._platform import honor_env_platform

# The module-level constants below initialize the jax backend at import:
# apply the user's JAX_PLATFORMS env choice first (the axon plugin
# ignores the env var; see _platform.py).
honor_env_platform()

_C1 = jnp.uint32(0x85EBCA6B)
_C2 = jnp.uint32(0xC2B2AE35)

#: Row-hash / fingerprint fold seeds.  Named because ops.wide_kernel
#: recomputes the identical hashes INSIDE its fused Pallas kernel —
#: bit-identical folds are what make the cross-backend differential
#: suite (and the fingerprint fixpoint contract) meaningful.
HASH_SEED_1 = 0xB00B_135
HASH_SEED_2 = 0x1CEB_00DA
FP_SEED_1 = 0xFEED_0001
FP_SEED_2 = 0xFEED_0002

#: Recognized dedup/compaction backends (see module docstring).  A
#: third backend rides beside sort/bucket since round 11:
#:
#:   * "pallas" — the fused wide-stage kernel (ops.wide_kernel): bucket
#:     semantics without the sort, plus the MXU domination prune and
#:     cumsum-rank compaction fused into ONE pl.pallas_call with every
#:     table VMEM-resident.  Routed only on statically feasible WIDE
#:     geometry (wide_kernel.fused_feasible); anything else falls back
#:     to bucket, then sort, at trace time.  On CPU the kernel runs
#:     under Pallas interpret mode, so differential tests execute the
#:     real kernel body.
DEDUP_BACKENDS = ("sort", "bucket", "pallas")

#: Process-wide default backend; the env var below overrides it, an
#: explicit ``dedup_backend=`` argument overrides both.
DEDUP_BACKEND = "sort"

DEDUP_BACKEND_ENV = "JEPSEN_TPU_DEDUP_BACKEND"

#: Fewer bucket bits than this and the radix partition degenerates into
#: a handful of giant buckets whose windowed dedup misses most runs —
#: below it, the bucket backend statically routes to the sort path.
BUCKET_MIN_BITS = 6


def resolve_dedup_backend(backend: str | None = None) -> str:
    """The dedup backend to use: explicit argument, else the
    JEPSEN_TPU_DEDUP_BACKEND env var, else the module default."""
    b = backend or os.environ.get(DEDUP_BACKEND_ENV) or DEDUP_BACKEND
    if b not in DEDUP_BACKENDS:
        raise ValueError(
            f"unknown dedup backend {b!r}; expected one of {DEDUP_BACKENDS}"
        )
    return b


def _bucket_bits(n: int) -> tuple[int, int]:
    """(index_bits, bucket_bits) of the packed radix key for an
    ``n``-row candidate table: 1 dead bit + bucket_bits of hash prefix +
    index_bits of candidate index in one uint32."""
    ibits = max(1, (n - 1).bit_length())
    return ibits, 31 - ibits


def bucket_feasible(n: int) -> bool:
    """Whether the packed bucket geometry is usable at ``n`` candidate
    rows (static, shape-derived): when False the bucket backend routes
    the round to the sort path at trace time — rows are never dropped."""
    return _bucket_bits(n)[1] >= BUCKET_MIN_BITS


def mix32(x):
    """murmur3 fmix32 finalizer (vectorized)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * _C1
    x = x ^ (x >> 13)
    x = x * _C2
    x = x ^ (x >> 16)
    return x


def hash_rows(columns, seed: int):
    """Hash a list of equal-length uint32/int32 column arrays to one uint32
    lane, column-by-column (static unroll; column count is small)."""
    h = jnp.full(columns[0].shape, jnp.uint32(seed ^ 0x9E3779B9))
    # static unroll over a Python list of columns (count is small and
    # shape-determined, never data-dependent)
    for col in columns:  # graftlint: disable=trace-host-control
        h = mix32(h ^ col.astype(jnp.uint32))
    return h


def np_mix32(x: np.ndarray) -> np.ndarray:
    """Host-side mirror of ``mix32`` — bit-identical on the same input, so
    hashes computed on either side of a device→host spill agree (the
    host-spill merge's LSH bucket keys ARE the kernel's class hashes)."""
    x = np.asarray(x).astype(np.uint32)
    x = x ^ (x >> np.uint32(16))
    x = x * np.uint32(0x85EBCA6B)
    x = x ^ (x >> np.uint32(13))
    x = x * np.uint32(0xC2B2AE35)
    x = x ^ (x >> np.uint32(16))
    return x


def np_hash_rows(columns, seed: int) -> np.ndarray:
    """Host-side mirror of ``hash_rows`` (same constants, same fold
    order; differential-tested against the device version)."""
    cols = [np.asarray(c) for c in columns]
    h = np.full(cols[0].shape, np.uint32(seed ^ 0x9E3779B9), np.uint32)
    for col in cols:
        h = np_mix32(h ^ col.astype(np.uint32))
    return h


def np_class_hash(state, fok) -> tuple[np.ndarray, np.ndarray]:
    """Two 32-bit LSH lanes over a frontier's (state, fok) CLASS columns,
    host-side.  Identical classes always share both lanes, so the 64-bit
    key is a locality-sensitive bucket id: the host-spill merge
    (``jepsen_tpu.ops.spill.merge_frontiers``) sorts on it and runs exact
    dedup/domination only within equal-key runs — the near-duplicate
    neighborhoods of the LSH-beam-search literature (PAPERS:
    1806.00588), on the same packed-key machinery the device bucket
    backend uses."""
    state = np.asarray(state)
    fok = np.asarray(fok)
    cols = [state] + [fok[:, k] for k in range(fok.shape[1])]
    return np_hash_rows(cols, HASH_SEED_1), np_hash_rows(cols, HASH_SEED_2)


def _keep_sort(h1, h2, alive, window: int):
    """Hash-dup keep mask, sort formulation: ONE single-key sort carrying
    the hash lanes and a packed (alive | index) payload; a row is a dup
    when a neighbor within ``window`` sorted predecessors has both hash
    lanes equal.  Returns the keep mask in CANDIDATE order."""
    n = h1.shape[0]
    iota = jnp.arange(n, dtype=jnp.int32)
    # alive rides in the payload's top bit so a sentinel-colliding hash
    # can't resurrect or kill anything.
    payload = jnp.where(alive, iota, iota + jnp.int32(1 << 30))
    pos = iota
    key = jnp.where(alive, h1, jnp.uint32(0xFFFFFFFF))
    k1, k2, spay = jax.lax.sort((key, h2, payload), num_keys=1)
    al = spay < (1 << 30)
    sidx = spay & ((1 << 30) - 1)
    dup = jnp.zeros(n, bool)
    for k in range(1, window + 1):
        same = (
            (k1 == jnp.roll(k1, k))
            & (k2 == jnp.roll(k2, k))
            & jnp.roll(al, k)
            & (pos >= k)
        )
        dup = dup | same
    keep = al & ~dup
    # Map the keep mask back to CANDIDATE order before compacting: the
    # candidate table lists parents before children, i.e. fewest-fired
    # first, so truncation under overflow drops the most-speculative rows
    # — witnesses survive longer than under hash-order truncation.
    return jnp.zeros(n, bool).at[sidx].set(keep, unique_indices=True)


def _keep_bucket(h1, h2, alive, window: int):
    """Hash-dup keep mask, bucketed radix formulation.

    Rows partition into 2^b buckets by the TOP b BITS of h1, by packing
    ``[dead:1 | bucket:b | index:i]`` into one uint32 and sorting the
    single packed array — XLA's single-operand sort is the specialized
    fast path (~6x the multi-operand tuple sort on CPU; the tuple sort
    is the per-round floor PERF.md's "Honest limits" names).  The sort
    IS the scatter-by-bucket-rank: bucket-mates land contiguously,
    in candidate order within the bucket (the index bits make the key
    unique and the order deterministic — survivors are always the first
    copy in candidate order, which the unstable tuple sort does not
    guarantee).  Dedup then compares full 64-bit hashes over
    bucket-local windows, gathered through the packed index (gathers
    are cheap where sorts are not).

    Kill contract is the sort path's exactly: a kill requires BOTH hash
    lanes equal on an alive predecessor.  Equal hashes share a bucket
    by construction, so bucketing misses nothing the window would have
    caught; a duplicate beyond ``window`` bucket-mates survives as
    bloat (sound — the content-decided buffer prune downstream kills
    true dups that fit, and capacity pressure escalates through the
    existing overflow/lossy ladder).

    ``overflow`` marks rows whose ENTIRE window was same-bucket alive
    rows yet survived — their duplicates may lie beyond the window
    (possible bloat, never loss).  Rows in overflowed buckets are
    RETAINED, never dropped: soundness needs no fallback, the flag is
    diagnostic (tests and telemetry).

    Returns (keep mask in candidate order, overflow).
    """
    n = h1.shape[0]
    ibits, bbits = _bucket_bits(n)
    assert bbits >= 1, f"bucket geometry infeasible at {n} rows"
    iota = jnp.arange(n, dtype=jnp.int32)
    pos = iota
    bucket = h1 >> jnp.uint32(32 - bbits)
    packed = (
        jnp.where(alive, jnp.uint32(0), jnp.uint32(1) << 31)
        | (bucket << jnp.uint32(ibits))
        | iota.astype(jnp.uint32)
    )
    (spacked,) = jax.lax.sort((packed,), num_keys=1)
    al = spacked < (jnp.uint32(1) << 31)
    sidx = (spacked & jnp.uint32((1 << ibits) - 1)).astype(jnp.int32)
    sh1 = h1[sidx]
    sh2 = h2[sidx]
    sbucket = spacked >> jnp.uint32(ibits)  # dead bit folds into bucket
    dup = jnp.zeros(n, bool)
    full = jnp.ones(n, bool)  # window entirely same-bucket alive rows
    for k in range(1, window + 1):
        pal = jnp.roll(al, k) & (pos >= k)
        dup = dup | (
            (sh1 == jnp.roll(sh1, k)) & (sh2 == jnp.roll(sh2, k)) & pal
        )
        full = full & (sbucket == jnp.roll(sbucket, k)) & pal
    keep = al & ~dup
    overflow = (full & keep).any()
    keep_orig = jnp.zeros(n, bool).at[sidx].set(keep, unique_indices=True)
    return keep_orig, overflow


def frontier_update_fast(
    state, fok, fcr, alive, cost, capacity: int, window: int = 4,
    n_parents: int | None = None, max_count: int | None = None,
    dedup_backend: str = "sort",
):
    """Frontier dedup + truncation, tuned for the vmapped batch kernel.

    Data movement and op count are the cost on TPU — the exact
    formulation's 4-key lexicographic ``lax.sort`` plus full-table gathers
    measured ~13 ms per round inside the barrier scan on v5e.  Here:

      1. hash each row to 64 bits (2 uint32 lanes; dead rows pinned to the
         max key so they sink to the end);
      2. ONE single-key sort carrying only the hash lanes and a packed
         (alive | index) payload — row data never moves through the sort;
      3. a row is a duplicate when a neighbor within ``window`` sorted
         predecessors has both hash lanes equal — collision probability
         ~1e-13 per compaction.  A collision kills a distinct config
         silently, which is why engines built on this update never
         report ``False`` as final: jepsen_tpu.parallel.batch_analysis
         confirms every fast-path refutation on the exact CPU sweep
         (overlapped with the remaining device stages, so the
         confirmation is sound AND nearly free in wall clock).  Dup runs
         longer than the window survive as bloat;
      4. survivors compact to ``capacity`` by cumsum-rank scatter in
         CANDIDATE order (parents precede children, i.e. fewest-fired
         first, so truncation drops the most-speculative rows and
         witnesses survive longest) — only the ``capacity`` retained
         rows are ever gathered;
      5. dedup survivors compact into a 2*capacity buffer which is
         ``exact_prune``d (content-decided domination) HERE, before
         truncation — the single prune site: the returned frontier is a
         duplicate-free antichain, and every subset the engines take of
         it (truncation, the per-barrier return filter, the uniform
         slot-bit clear) stays one, so no outer prune is needed.

    ``cost`` is accepted for signature parity with frontier_update but
    unused: candidate order already approximates cheapest-first (children
    always carry one more fired op than their parent), so no cost sort is
    needed — and truncation order only affects verdict quality, never
    soundness (overflow flags lossy and the caller escalates).

    ``n_parents``: when the candidate table's first ``n_parents`` rows
    are the previous frontier (parents) and the rest are this round's
    expansions, the returned ``child`` mask marks surviving rows that
    came from an expansion.  ``(alive' & child).any()`` is a no-growth
    closure-fixpoint signal (exact modulo the same hash-dedup caveat as
    step 3 — which is covered by the same refutation confirmation), so
    engines advance a barrier after ONE tick when its closure is already
    complete instead of burning a second fingerprint-compare tick.

    ``dedup_backend``: "sort" (the single-key hash sort above) or
    "bucket" (packed radix buckets — see ``_keep_bucket``; identical
    kill contract, ~1.7x cheaper per round on the CPU backend at the
    headline candidate shape, survivor = first copy in candidate order
    deterministically).  Must be a static (trace-time) string; engines
    thread it from their runner caches.  An infeasible bucket geometry
    (``bucket_feasible``) statically routes to the sort path.

    ``max_count``: a static upper bound on any fired-crashed group count
    (callers pass the mover-table size).  When given, the buffer prune
    runs as ``exact_prune_mxu`` — the same content-decided antichain, but
    with the pairwise pointwise-≤ test as one bf16 matmul on the MXU
    instead of O(C²·G) vector compares (the wide-capacity tick's
    dominant cost).

    Returns (state', fok', fcr', alive', overflowed, fp, child) — fp is
    an order-insensitive content fingerprint of the surviving set
    (diagnostic only).
    """
    n = state.shape[0]
    w = fok.shape[1]
    g = fcr.shape[1]
    if dedup_backend not in DEDUP_BACKENDS:
        raise ValueError(f"unknown dedup backend {dedup_backend!r}")
    if dedup_backend == "pallas":
        # The fused wide-stage kernel replaces this WHOLE function body
        # (hash + dedup + buffer prune + compaction) with one
        # pl.pallas_call on feasible wide geometry; otherwise the round
        # statically routes down the bucket -> sort ladder, exactly
        # like an infeasible bucket geometry.  Lazy import: wide_kernel
        # imports this module for the shared hash folds.  w/g engage
        # the VMEM working-set gate: a shape past the budget routes to
        # bucket here — the mesh path (wide_kernel.mesh_frontier_update,
        # routed by the engines when a Placement spans >1 device) is
        # what lifts that ceiling.
        from jepsen_tpu.ops import wide_kernel

        if wide_kernel.fused_feasible(n, capacity, max_count, w=w, g=g):
            return wide_kernel.fused_frontier_update(
                state, fok, fcr, alive, cost, capacity, window=window,
                n_parents=n_parents, max_count=max_count,
            )
    row_cols = [state] + [fok[:, k] for k in range(w)] + [fcr[:, k] for k in range(g)]
    h1 = hash_rows(row_cols, HASH_SEED_1)
    h2 = hash_rows(row_cols, HASH_SEED_2)
    iota = jnp.arange(n, dtype=jnp.int32)
    pos = jnp.arange(n)
    if dedup_backend in ("bucket", "pallas") and bucket_feasible(n):
        keep_orig, _bovf = _keep_bucket(h1, h2, alive, window)
    else:
        keep_orig = _keep_sort(h1, h2, alive, window)
    # Compact dedup survivors into a 2*capacity buffer, DOMINATION-prune
    # it there ([2C, 2C, G] dense pairwise compares — cheap), and only
    # then truncate: ``overflowed`` counts undominated survivors, not the
    # closure's domination bloat.  Measured on the headline batch at cap
    # 128: 105 → 108 histories resolved for ~0.4 s, and the carried
    # frontier is antichain-minimal so later rounds stay small.
    Cb = min(2 * capacity, n)
    rank = jnp.cumsum(keep_orig) - 1
    n_keep0 = jnp.maximum(rank[-1] + 1, 0)
    pos2 = jnp.where(keep_orig, rank, Cb + pos)
    srcB = (
        jnp.zeros(Cb, jnp.int32)
        .at[pos2]
        .set(iota, mode="drop", unique_indices=True)
    )
    bst = state[srcB]
    bfo = fok[srcB]
    bfc = fcr[srcB]
    balive = jnp.arange(Cb) < jnp.minimum(n_keep0, Cb)
    spill = n_keep0 > Cb
    if max_count is not None:
        # saturating planes: sound at any count (round 5 — wide-mover
        # histories keep the matmul instead of the dense fallback)
        balive = exact_prune_mxu(bst, bfo, bfc, balive, max_count)
    else:
        balive = exact_prune(bst, bfo, bfc, balive)
    rank2 = jnp.cumsum(balive) - 1
    n_keep = jnp.maximum(rank2[-1] + 1, 0)
    pos3 = jnp.where(balive, rank2, capacity + jnp.arange(Cb))
    src2 = (
        jnp.zeros(capacity, jnp.int32)
        .at[pos3]
        .set(jnp.arange(Cb, dtype=jnp.int32), mode="drop", unique_indices=True)
    )
    kst = bst[src2]
    kfo = bfo[src2]
    kfc = bfc[src2]
    new_alive = jnp.arange(capacity) < jnp.minimum(n_keep, capacity)
    overflowed = spill | (n_keep > capacity)
    if n_parents is None:
        child = jnp.zeros(capacity, bool)
    else:
        child = srcB[src2] >= n_parents
    fp = _fingerprint(kst, kfo, kfc, new_alive, w, g)
    return kst, kfo, kfc, new_alive, overflowed, fp, child


#: One-hot plane count for the matmul prune.  Counts at or above the
#: last plane compare SATURATING (see exact_prune_mxu): the test stays
#: sound at any true count, exact below M-1 — so the plane count is a
#: cost/precision knob, not a correctness gate.  64 planes measured
#: fastest on the headline wide stage; round 4's hard gate (dense
#: fallback past mover width 64) is gone.
MXU_PRUNE_MAX_COUNT = 64


def exact_prune_mxu(state, fok, fcr, alive, max_count: int):
    """exact_prune with the pairwise pointwise-≤ test recast as a matmul.

    The dense prune's cost is the [N, N, G] count comparison — vector-unit
    work that dominates wide-capacity ticks (13.6 s vs 4.0 s pruneless on
    the cap-2048 straggler stage).  The MXU formulation: encode each
    row's fired-crashed counts as a cumulative one-hot u[k, c] =
    (fcr[k] ≤ c) and a SATURATING exact one-hot v[k, c] =
    (min(fcr[k], M-1) == c), both [N, G·M] with M = min(``max_count``,
    MXU_PRUNE_MAX_COUNT); then (u @ vᵀ)[i, j] counts the groups where
    fcr_i ≤ min(fcr_j, M-1), and == G ⟹ pointwise fcr_i ≤ fcr_j.  One
    bf16 matmul (values ≤ G, exact in bf16) replaces the O(N²·G)
    compare; class equality and tie-breaking stay content-decided.

    Saturation soundness (round 5, replacing the round-4 dense fallback
    past mover width 64): the computed indicator implies true pointwise
    ≤ at ANY count — min(fcr_j, M-1) ≤ fcr_j, so a kill is always a
    genuine domination/duplicate.  When some count reaches M-1 a true
    domination can be MISSED (u's planes are all-zero for counts ≥ M),
    which only bloats the frontier (overflow → lossy → escalate, never
    a wrong verdict).  Ties stay order-stable: mutual-≤ (equality)
    detected via the saturating test forces every count < M on both
    rows, where the test is exact.  Below M-1 everywhere, the result is
    bit-identical to exact_prune.
    """
    n = state.shape[0]
    g = fcr.shape[1]
    m = min(int(max_count), MXU_PRUNE_MAX_COUNT)
    c = jnp.arange(m, dtype=fcr.dtype)
    sat = jnp.minimum(fcr, m - 1)
    u = (fcr[:, :, None] <= c[None, None, :]).reshape(n, g * m)
    v = (sat[:, :, None] == c[None, None, :]).reshape(n, g * m)
    cnt = jnp.dot(
        u.astype(jnp.bfloat16),
        v.astype(jnp.bfloat16).T,
        preferred_element_type=jnp.float32,
    )
    le = cnt > g - 0.5  # le[i, j]: fcr_i ≤ fcr_j pointwise
    same = state[:, None] == state[None, :]
    for k in range(fok.shape[1]):
        col = fok[:, k]
        same &= col[:, None] == col[None, :]
    idx = jnp.arange(n)
    earlier = idx[:, None] < idx[None, :]
    killer = (
        same & le & (~le.T | earlier) & alive[:, None] & alive[None, :]
    )
    return alive & ~killer.any(axis=0)


def _fingerprint(kst, kfo, kfc, new_alive, w, g):
    out_cols = [kst] + [kfo[:, k] for k in range(w)] + [kfc[:, k] for k in range(g)]
    r1 = hash_rows(out_cols, FP_SEED_1)
    r2 = hash_rows(out_cols, FP_SEED_2)
    am = new_alive.astype(jnp.uint32)
    return jnp.stack([(r1 * am).sum(), (r2 * am).sum(), am.sum()])


def frontier_update(
    state, fok, fcr, alive, cost, capacity: int, window: int = 16,
    dedup_backend: str = "sort",
):
    """One-pass frontier maintenance: dedup + domination + truncation.

    Sorts candidate rows by (dead, class-hash(state,fok), cost); rows of the
    same (state, fok) class land contiguously, cheapest (fewest-fired)
    first (stable sort by original index).  A row is killed when any of its ``window`` sorted
    predecessors has the same exact (state, fok) and pointwise ≤ fired-
    crashed counts — this removes exact duplicates *and* dominated configs
    in one windowed compare (domination: the cheaper config's futures are a
    superset, see wgl_cpu; kills through killed intermediaries are sound by
    transitivity).  Misses beyond the window only bloat the frontier; they
    never produce wrong kills.

    ``dedup_backend="bucket"`` replaces the stage-1 multi-key sort with
    the packed radix-bucket partition (bucket = top bits of the CLASS
    hash, so class-mates always share a bucket; single-operand key
    sort; row content gathered through the packed index).  Kills stay
    content-decided — the windowed compare sees exact (state, fok, fcr)
    either way — so this engine's refutations remain final under both
    backends.  Within a bucket rows sit in CANDIDATE order rather than
    cost order (candidate order ≈ fewest-fired-first, the fast path's
    truncation argument); differently-missed dominations are cleaned by
    the stage-2 exact buffer prune, which both backends share.  An
    infeasible geometry routes to the sort stage statically.

    Returns (state', fok', fcr', alive', overflowed, fp):
      overflowed — undominated survivors exceeded capacity, or the exact-
                   domination buffer spilled (loss);
      fp         — order-insensitive content fingerprint (3 uint32 lanes)
                   of the surviving set.  Callers detect closure fixpoints
                   as fp == previous round's fp; being order-insensitive it
                   is immune to 'livelock' rounds where dominated
                   representatives are regenerated and re-killed without
                   the set actually changing.
    """
    n = state.shape[0]
    w = fok.shape[1]
    g = fcr.shape[1]
    class_cols = [state] + [fok[:, k] for k in range(w)]
    ch1 = hash_rows(class_cols, HASH_SEED_1)
    ch2 = hash_rows(class_cols, HASH_SEED_2)
    iota = jnp.arange(n, dtype=jnp.int32)
    if dedup_backend not in DEDUP_BACKENDS:
        raise ValueError(f"unknown dedup backend {dedup_backend!r}")
    # The exact engine's kills are content-decided under every backend;
    # the pallas kernel is the FAST stage's fusion, so here "pallas"
    # rides the bucket stage-1 partition (same class-hash buckets).
    if dedup_backend in ("bucket", "pallas") and bucket_feasible(n):
        ibits, bbits = _bucket_bits(n)
        packed = (
            jnp.where(alive, jnp.uint32(0), jnp.uint32(1) << 31)
            | ((ch1 >> jnp.uint32(32 - bbits)) << jnp.uint32(ibits))
            | iota.astype(jnp.uint32)
        )
        (spacked,) = jax.lax.sort((packed,), num_keys=1)
        al = spacked < (jnp.uint32(1) << 31)
        sidx = (spacked & jnp.uint32((1 << ibits) - 1)).astype(jnp.int32)
    else:
        dead = (~alive).astype(jnp.uint32)
        _sd, _s1, _s2, _sc, sidx = jax.lax.sort(
            (dead, ch1, ch2, cost.astype(jnp.uint32), iota), num_keys=4
        )
        al = alive[sidx]
    st = state[sidx]
    fo = fok[sidx]
    fc = fcr[sidx]
    pos = jnp.arange(n)
    killed = jnp.zeros(n, bool)
    for k in range(1, window + 1):
        pst = jnp.roll(st, k)
        pfo = jnp.roll(fo, k, axis=0)
        pfc = jnp.roll(fc, k, axis=0)
        pal = jnp.roll(al, k)
        same = (pst == st) & (pfo == fo).all(-1) & pal & (pos >= k)
        killed = killed | (same & (pfc <= fc).all(-1))
    aliveD = al & ~killed
    n_w = aliveD.sum()
    # Stage 2: exact pairwise domination on a small buffer.  The windowed
    # pass thins the big candidate table; the buffer pass makes the
    # retained frontier exactly domination-free so bloat can't compound
    # across rounds.
    # The exact pass is quadratic in rows but chunked (dominate), so the
    # buffer only needs to cover the capacity with headroom.
    b2 = min(2 * capacity, n)
    sc2 = cost[sidx].astype(jnp.uint32)
    _k1, _k2, fidx = jax.lax.sort(
        ((~aliveD).astype(jnp.uint32), sc2, jnp.arange(n, dtype=jnp.int32)), num_keys=2
    )
    bsel = fidx[:b2]
    bst, bfo, bfc = st[bsel], fo[bsel], fc[bsel]
    bcost = sc2[bsel]
    balive = jnp.arange(b2) < jnp.minimum(n_w, b2)
    balive = dominate(bst, bfo, bfc, balive)
    n_x = balive.sum()
    # Final truncation to capacity.
    _j1, _j2, ksel = jax.lax.sort(
        ((~balive).astype(jnp.uint32), bcost, jnp.arange(b2, dtype=jnp.int32)),
        num_keys=2,
    )
    keep = ksel[:capacity]
    kst, kfo, kfc = bst[keep], bfo[keep], bfc[keep]
    new_alive = jnp.arange(capacity) < jnp.minimum(n_x, capacity)
    overflowed = (n_w > b2) | (n_x > capacity)
    row_cols = [kst] + [kfo[:, k] for k in range(w)] + [kfc[:, k] for k in range(g)]
    r1 = hash_rows(row_cols, FP_SEED_1)
    r2 = hash_rows(row_cols, FP_SEED_2)
    am = new_alive.astype(jnp.uint32)
    fp = jnp.stack([(r1 * am).sum(), (r2 * am).sum(), am.sum()])
    return kst, kfo, kfc, new_alive, overflowed, fp



def exact_prune(state, fok, fcr, alive, chunk_rows: int = 0):
    """Kill duplicate and dominated frontier rows, exactly.

    Row j dies when some alive row i has the same (state, fok) class with
    pointwise ≤ fired-crashed counts AND is either strictly smaller
    somewhere or earlier in the table — ties keep the first copy.  The
    survivor set is the pointwise-minimal antichain with one
    representative per duplicate group — exact pruning, never changes the
    verdict (the survivor's futures are a superset, see wgl_cpu
    domination notes).

    Chunked over the killed axis — via lax.scan, so the program size is
    constant however many chunks a wide buffer needs — to bound the
    [F, C, G] intermediates (under vmap the peak multiplies by the lane
    count, and oversized buffers here have faulted the TPU worker).
    """
    f = state.shape[0]
    g = fcr.shape[1]
    if chunk_rows <= 0:
        chunk_rows = min(f, max(16, (1 << 22) // max(1, f * g)))
    idx = jnp.arange(f, dtype=jnp.int32)

    def part(lo):
        st_c = jax.lax.dynamic_slice_in_dim(state, lo, chunk_rows)
        fo_c = jax.lax.dynamic_slice_in_dim(fok, lo, chunk_rows, axis=0)
        fc_c = jax.lax.dynamic_slice_in_dim(fcr, lo, chunk_rows, axis=0)
        al_c = jax.lax.dynamic_slice_in_dim(alive, lo, chunk_rows)
        idx_c = jax.lax.dynamic_slice_in_dim(idx, lo, chunk_rows)
        same = (state[:, None] == st_c[None, :]) & (
            (fok[:, None, :] == fo_c[None, :, :]).all(-1)
        )
        le = (fcr[:, None, :] <= fc_c[None, :, :]).all(-1)
        lt = (fcr[:, None, :] < fc_c[None, :, :]).any(-1)
        earlier = idx[:, None] < idx_c[None, :]
        dom = same & le & (lt | earlier) & alive[:, None] & al_c[None, :]
        return dom.any(axis=0)

    if f <= chunk_rows:
        return alive & ~part(jnp.int32(0))
    n_chunks = (f + chunk_rows - 1) // chunk_rows
    fpad = n_chunks * chunk_rows
    if fpad != f:
        # pad with dead rows so every dynamic_slice is in bounds (a
        # clamped slice would mis-align the reshape below)
        state = jnp.pad(state, (0, fpad - f))
        fok = jnp.pad(fok, ((0, fpad - f), (0, 0)))
        fcr = jnp.pad(fcr, ((0, fpad - f), (0, 0)))
        alive = jnp.pad(alive, (0, fpad - f))
        idx = jnp.pad(idx, (0, fpad - f))
    starts = jnp.arange(n_chunks, dtype=jnp.int32) * chunk_rows
    _, parts = jax.lax.scan(lambda c, lo: (c, part(lo)), None, starts)
    return alive[:f] & ~parts.reshape(-1)[:f]


def dominate(state, fok, fcr, alive, chunk_rows: int = 0):
    """Kill dominated frontier rows.

    Row j is dominated when some alive row i has the same (state, fok) but
    fired strictly fewer crashed ops pointwise (fcr_i ≤ fcr_j, ≠) — the
    smaller config's futures are a superset (see wgl_cpu domination notes),
    so j is redundant.  Exact pruning: removing dominated rows never
    changes the verdict.  Chunked over the dominated axis to bound the
    [F, C, G] comparison intermediates.
    """
    f = state.shape[0]
    g = fcr.shape[1]
    if chunk_rows <= 0:
        # keep [f, chunk, g] intermediates under ~4M elements (vmap
        # multiplies the peak by the lane count)
        chunk_rows = max(16, min(f, (1 << 22) // max(1, f * g)))
    parts = []
    for lo in range(0, f, chunk_rows):
        hi = min(f, lo + chunk_rows)
        eq_state = state[:, None] == state[None, lo:hi]
        eq_fok = (fok[:, None, :] == fok[None, lo:hi, :]).all(-1)
        le = (fcr[:, None, :] <= fcr[None, lo:hi, :]).all(-1)
        lt = (fcr[:, None, :] < fcr[None, lo:hi, :]).any(-1)
        dom = eq_state & eq_fok & le & lt & alive[:, None] & alive[None, lo:hi]
        parts.append(dom.any(axis=0))
    return alive & ~jnp.concatenate(parts)


def _dedup_stage(state, fok, fcr, alive, window: int, dedup_backend: str):
    """JUST the dedup stage of frontier_update_fast (row hash + partition
    + windowed kills + candidate-order keep mask) — the part the
    backends implement differently.  dedup_round_probe times it; the
    compaction/prune tail is shared (sort/bucket) or fused behind the
    same contract (pallas) and would only dilute the comparison.  The
    pallas stage hashes IN-KERNEL, so its probe window covers the same
    work as the sort/bucket ones (which include hash_rows here)."""
    if dedup_backend == "pallas":
        from jepsen_tpu.ops import wide_kernel

        if wide_kernel.keep_feasible(state.shape[0]):
            keep, _ovf = wide_kernel.keep_mask(state, fok, fcr, alive, window)
            return keep
        dedup_backend = "bucket"  # the same trace-time fallback ladder
    w = fok.shape[1]
    g = fcr.shape[1]
    row_cols = [state] + [fok[:, k] for k in range(w)] + [fcr[:, k] for k in range(g)]
    h1 = hash_rows(row_cols, HASH_SEED_1)
    h2 = hash_rows(row_cols, HASH_SEED_2)
    if dedup_backend == "bucket" and bucket_feasible(state.shape[0]):
        keep, _ovf = _keep_bucket(h1, h2, alive, window)
        return keep
    return _keep_sort(h1, h2, alive, window)


_dedup_stage_jit = jax.jit(
    _dedup_stage, static_argnames=("window", "dedup_backend")
)


def probe_candidates(capacity: int, P: int, G: int, W: int = 1, seed: int = 0):
    """A synthetic candidate table at an engine round's shape —
    ``capacity * (1 + P + G)`` rows with realistic duplicate density
    (~half the rows copy another row, ~20% dead) — for dedup timing and
    differential tests."""
    n = capacity * (1 + P + G)
    rng = np.random.default_rng(seed)
    state = rng.integers(0, 64, n).astype(np.int32)
    fok = rng.integers(0, 1 << 16, (n, W)).astype(np.uint32)
    fcr = rng.integers(0, 4, (n, G)).astype(np.int16)
    src = rng.integers(0, n, n // 2)
    state[: n // 2] = state[src]
    fok[: n // 2] = fok[src]
    fcr[: n // 2] = fcr[src]
    alive = rng.random(n) < 0.8
    return state, fok, fcr, alive


def dedup_round_probe(
    capacity: int, P: int, G: int, W: int = 1,
    backends: Sequence[str] = DEDUP_BACKENDS, rounds: int = 5,
    seed: int = 0, emit: bool = True,
) -> dict:
    """Measure per-round dedup time at a ladder rung's candidate shape,
    one ``dedup.round`` obs span per backend (attrs: backend,
    candidates, capacity, rounds, per_round_us) — how the sort-vs-bucket
    win lands in ``telemetry.json`` and ``tools/trace_summarize.py``
    (device rounds run inside a jitted scan where host spans can't
    reach, so the probe times the identical stage standalone).

    Probes every RESOLVABLE backend at the shape: "pallas" is skipped
    when the keep-mask geometry is statically infeasible there (the
    engines would have routed it away too), and its span carries an
    honest ``interpret`` attr so interpret-mode CPU probes never pass
    for chip measurements in the rolled-up comparison.

    Returns ``{backend: mean seconds per round}``.
    """
    from jepsen_tpu import obs
    from jepsen_tpu.ops import wide_kernel

    state, fok, fcr, alive = probe_candidates(capacity, P, G, W, seed)
    out: dict = {}
    for b in backends:
        extra = {}
        if b == "pallas":
            if not wide_kernel.keep_feasible(int(state.shape[0])):
                continue  # the engines statically route this shape away
            extra["interpret"] = wide_kernel.interpret_default()
        r = _dedup_stage_jit(state, fok, fcr, alive, 4, b)
        r.block_until_ready()  # compile outside the timed window
        t0 = time.perf_counter()
        for _ in range(max(1, int(rounds))):
            r = _dedup_stage_jit(state, fok, fcr, alive, 4, b)
        r.block_until_ready()
        dt = (time.perf_counter() - t0) / max(1, int(rounds))
        out[b] = dt
        if emit:
            obs.span_event(
                "dedup.round", dt, backend=b, candidates=int(state.shape[0]),
                capacity=int(capacity), rounds=int(rounds),
                per_round_us=round(dt * 1e6, 1), **extra,
            )
    return out


def compact(columns, alive, cost, capacity: int):
    """Dedup + truncate a frontier candidate table.

    ``columns``: list of [N] or [N, k] arrays describing rows; ``alive``:
    [N] bool; ``cost``: [N] int32 priority (smaller kept first under
    truncation).  Returns (select_idx [capacity], new_alive [capacity],
    n_unique, overflowed) — callers gather their columns by select_idx.
    """
    n = alive.shape[0]
    flat_cols = []
    for c in columns:
        if c.ndim == 1:
            flat_cols.append(c)
        else:
            for k in range(c.shape[1]):
                flat_cols.append(c[:, k])
    h1 = hash_rows(flat_cols, 0x1234_5678)
    h2 = hash_rows(flat_cols, 0x9ABC_DEF0)
    h3 = hash_rows(flat_cols, 0x0F1E_2D3C)
    dead = (~alive).astype(jnp.uint32)
    iota = jnp.arange(n, dtype=jnp.int32)
    sd, s1, s2, s3, sidx = jax.lax.sort((dead, h1, h2, h3, iota), num_keys=4)
    same_as_prev = (
        (s1 == jnp.roll(s1, 1)) & (s2 == jnp.roll(s2, 1)) & (s3 == jnp.roll(s3, 1))
    )
    same_as_prev = same_as_prev.at[0].set(False)
    uniq = (sd == 0) & ~same_as_prev
    n_unique = uniq.sum()
    # Compact survivors to capacity, cheapest (most-dominating) rows first.
    cost_sorted = cost[sidx]
    not_uniq = (~uniq).astype(jnp.uint32)
    _k1, _k2, fidx = jax.lax.sort(
        (not_uniq, cost_sorted.astype(jnp.uint32), sidx), num_keys=2
    )
    select = fidx[:capacity]
    new_alive = jnp.arange(capacity) < jnp.minimum(n_unique, capacity)
    return select, new_alive, n_unique, n_unique > capacity
