"""Row hashing and sort-based frontier compaction.

The WGL frontier is a struct-of-arrays table of configurations.  Dedup on
TPU is sort-based: hash each row to 96 bits (3 uint32 lanes of
murmur3-style mixing — collision probability for ~10^6 rows is ~10^-17 per
compaction, far below the kernel's other 'unknown' slack), sort by
(dead, hash) lanes, and mark rows equal to their sorted predecessor as
duplicates.  A second sort compacts survivors to the fixed capacity,
preferring configurations that fired the fewest ops (the dominating ones —
see jepsen_tpu.checker.wgl_cpu domination notes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_C1 = jnp.uint32(0x85EBCA6B)
_C2 = jnp.uint32(0xC2B2AE35)


def mix32(x):
    """murmur3 fmix32 finalizer (vectorized)."""
    x = x.astype(jnp.uint32)
    x = x ^ (x >> 16)
    x = x * _C1
    x = x ^ (x >> 13)
    x = x * _C2
    x = x ^ (x >> 16)
    return x


def hash_rows(columns, seed: int):
    """Hash a list of equal-length uint32/int32 column arrays to one uint32
    lane, column-by-column (static unroll; column count is small)."""
    h = jnp.full(columns[0].shape, jnp.uint32(seed ^ 0x9E3779B9))
    for col in columns:
        h = mix32(h ^ col.astype(jnp.uint32))
    return h


def dominate(state, fok, fcr, alive, chunk_rows: int = 0):
    """Kill dominated frontier rows.

    Row j is dominated when some alive row i has the same (state, fok) but
    fired strictly fewer crashed ops pointwise (fcr_i ≤ fcr_j, ≠) — the
    smaller config's futures are a superset (see wgl_cpu domination notes),
    so j is redundant.  Exact pruning: removing dominated rows never
    changes the verdict.  Chunked over the dominated axis to bound the
    [F, C, G] comparison intermediates.
    """
    f = state.shape[0]
    g = fcr.shape[1]
    if chunk_rows <= 0:
        chunk_rows = max(64, min(f, (1 << 22) // max(1, f * g // 64)))
    parts = []
    for lo in range(0, f, chunk_rows):
        hi = min(f, lo + chunk_rows)
        eq_state = state[:, None] == state[None, lo:hi]
        eq_fok = (fok[:, None, :] == fok[None, lo:hi, :]).all(-1)
        le = (fcr[:, None, :] <= fcr[None, lo:hi, :]).all(-1)
        lt = (fcr[:, None, :] < fcr[None, lo:hi, :]).any(-1)
        dom = eq_state & eq_fok & le & lt & alive[:, None] & alive[None, lo:hi]
        parts.append(dom.any(axis=0))
    return alive & ~jnp.concatenate(parts)


def compact(columns, alive, cost, capacity: int):
    """Dedup + truncate a frontier candidate table.

    ``columns``: list of [N] or [N, k] arrays describing rows; ``alive``:
    [N] bool; ``cost``: [N] int32 priority (smaller kept first under
    truncation).  Returns (select_idx [capacity], new_alive [capacity],
    n_unique, overflowed) — callers gather their columns by select_idx.
    """
    n = alive.shape[0]
    flat_cols = []
    for c in columns:
        if c.ndim == 1:
            flat_cols.append(c)
        else:
            for k in range(c.shape[1]):
                flat_cols.append(c[:, k])
    h1 = hash_rows(flat_cols, 0x1234_5678)
    h2 = hash_rows(flat_cols, 0x9ABC_DEF0)
    h3 = hash_rows(flat_cols, 0x0F1E_2D3C)
    dead = (~alive).astype(jnp.uint32)
    iota = jnp.arange(n, dtype=jnp.int32)
    sd, s1, s2, s3, sidx = jax.lax.sort((dead, h1, h2, h3, iota), num_keys=4)
    same_as_prev = (
        (s1 == jnp.roll(s1, 1)) & (s2 == jnp.roll(s2, 1)) & (s3 == jnp.roll(s3, 1))
    )
    same_as_prev = same_as_prev.at[0].set(False)
    uniq = (sd == 0) & ~same_as_prev
    n_unique = uniq.sum()
    # Compact survivors to capacity, cheapest (most-dominating) rows first.
    cost_sorted = cost[sidx]
    not_uniq = (~uniq).astype(jnp.uint32)
    _k1, _k2, fidx = jax.lax.sort(
        (not_uniq, cost_sorted.astype(jnp.uint32), sidx), num_keys=2
    )
    select = fidx[:capacity]
    new_alive = jnp.arange(capacity) < jnp.minimum(n_unique, capacity)
    return select, new_alive, n_unique, n_unique > capacity
