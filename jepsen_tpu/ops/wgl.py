"""Frontier-parallel Wing–Gong–Lowe linearizability search on TPU.

This is the rebuild's Knossos replacement (BASELINE.json north star): the
configuration-set sweep of jepsen_tpu.checker.wgl_cpu.sweep_analysis,
vectorized.  Where the JVM checker walks configurations one at a time with
a DFS stack, this kernel advances the *entire frontier* of configurations
through each return barrier as fixed-shape tensor ops under one jit'd
lax.scan — breadth-parallelism instead of backtracking.

Data layout (all static shapes; F = frontier capacity, P = process slots,
G = crashed-op groups, W = ⌈P/32⌉ bitset lanes, B = barriers):

  frontier:  state[F] int32 · fok[F,W] uint32 (fired-open-op bitset by
             process slot) · fcr[F,G] int16 (fired count per crashed
             group; counts gated ≤ 32767 at pack time) · alive[F] bool
  barriers:  per-barrier op (f,v1,v2,slot), per-slot open-op table
             (mov_*[B,P]), per-group open counts (grp_open[B,G])

Per barrier: a bounded closure loop (lax.while_loop, ≤R rounds) expands
every config by every legal move — firing any open ok op (process move) or
one crashed op from any group (group move) — then dedups (hash-sorted,
content-confirmed) and compacts to capacity keeping fewest-fired configs
first (sort-based, jepsen_tpu.ops.hashing).  Then configs that fired the
returning op survive; the op's slot bit is cleared and the scan advances.

Soundness contract (SURVEY.md §7 hard-part #1: "never a wrong verdict"):
any transition applied is legal, so a surviving frontier is a constructive
witness — ``True`` is always sound, truncated or not.  ``False`` requires
that no capacity/round loss occurred anywhere (``lossy`` flag); on the
single-history path (chunked_analysis) kills are content-decided
(frontier_update / exact_prune), so its refutations are exact, while the
batched fast engines dedup by 64-bit row hash and their refutations are
therefore CONFIRMED on the exact CPU sweep before being reported
(jepsen_tpu.parallel.batch_analysis overlaps the confirmation with the
remaining device stages, so it is sound and nearly free in wall clock).
Anything else answers ``"unknown"`` and the ``competition`` front-end
falls back to the CPU oracle.

The same structural optimizations as the CPU sweep apply: crashed-op
canonicalization into (f, value) groups, and fewest-fired-first compaction
(domination order) under truncation.
"""

from __future__ import annotations

import bisect
import functools
import json
import os
import time
import warnings
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from jepsen_tpu import faults
from jepsen_tpu import history as h
from jepsen_tpu import models as m
from jepsen_tpu import obs
from jepsen_tpu.checker import wgl_cpu
from jepsen_tpu.obs import provenance as _prov
from jepsen_tpu.models import tensor as tmodels
from jepsen_tpu.ops.hashing import (
    frontier_update,
    frontier_update_fast,
    resolve_dedup_backend,
)



I32 = jnp.int32
I16 = jnp.int16  #: fired-crashed counts ride int16 — halves the G-column
#: traffic that dominates pairwise prunes (counts are gated ≤ 32767 by pack)
U32 = jnp.uint32


class NotTensorizable(Exception):
    """History/model can't be packed for the kernel (exotic model, f, or
    value vocabulary); callers fall back to the CPU oracle."""


# ---------------------------------------------------------------------------
# Host-side packing
# ---------------------------------------------------------------------------


def _encode_value(value) -> tuple[int, int]:
    try:
        v1, v2 = h.encode_register_value(None, list(value) if isinstance(value, tuple) else value)
    except TypeError as e:
        raise NotTensorizable(str(e)) from None
    return v1, v2


def pack(model: m.Model, history: Sequence[dict]):
    """Pack a history into the kernel's barrier tables.

    Raises NotTensorizable when the model has no tensor step function or
    ops carry values the int32 columns can't hold.

    A stored ``ColumnHistory`` takes the COLUMN-NATIVE path (round 5,
    VERDICT item 7): the event/effective-op pass and the barrier tables
    are built straight from the SoA columns — the store→kernel chain
    materializes no per-op dicts at all (the ``.jepsen`` file's encoded
    (value1, value2) pairs ARE the kernel's value columns; knossos
    ``complete`` semantics applied by swapping in the completion's
    pair).  Falls back to the dict path when the model has a precheck
    (it consumes op dicts) or extras override client-op fields.
    """
    tm = tmodels.tensor_model_for(model)
    if tm is None:
        raise NotTensorizable(f"no tensor model for {getattr(model, 'name', model)!r}")
    if (
        isinstance(history, h.ColumnHistory)
        and tm.precheck is None
        and history.positional()
        and not any(
            {"value", "type", "process"} & e.keys()
            for i, e in history.extras.items()
            if history.cols["process"][i] != -1  # -1 = the nemesis sentinel
        )
    ):
        return _pack_columns(tm, model, history)
    history = h.materialize(history)
    events, eff_ops, crashed = wgl_cpu.prepare(model, history)
    if tm.precheck is not None:
        try:
            tm.precheck(model, eff_ops.values())
        except ValueError as e:
            raise NotTensorizable(str(e)) from None
    barriers, group_ops = wgl_cpu._barrier_snapshots(events, eff_ops, crashed)
    B = len(barriers)

    def fcode(op) -> int:
        f = op["f"]
        if f not in tm.f_codes:
            raise NotTensorizable(f"model {tm.name} has no f code for {f!r}")
        return tm.f_codes[f]

    # Process slots: one in-flight ok op per process at a time.
    slots: dict = {}
    for i in eff_ops:
        if i not in crashed:
            p = history[i]["process"]
            if p not in slots:
                slots[p] = len(slots)
    P = max(1, len(slots))
    W = (P + 31) // 32

    groups = sorted(group_ops, key=repr)
    gidx = {g: k for k, g in enumerate(groups)}
    G = max(1, len(groups))

    bar_f = np.zeros(B, np.int32)
    bar_v1 = np.zeros(B, np.int32)
    bar_v2 = np.zeros(B, np.int32)
    bar_slot = np.zeros(B, np.int32)
    bar_opid = np.zeros(B, np.int32)
    mov_f = np.zeros((B, P), np.int32)
    mov_v1 = np.zeros((B, P), np.int32)
    mov_v2 = np.zeros((B, P), np.int32)
    mov_open = np.zeros((B, P), bool)
    grp_open = np.zeros((B, G), np.int32)

    bar_quiet = np.zeros(B, bool)

    # Per-op (fcode, v1, v2) memo: an op stays open across many barriers
    # and was re-encoded at every one (measured 850k _encode_value calls
    # for a 100k-op history; one per effective op suffices).
    codes: dict[int, tuple[int, int, int]] = {}

    def op_codes(j: int) -> tuple[int, int, int]:
        t = codes.get(j)
        if t is None:
            oj = eff_ops[j]
            v1, v2 = _encode_value(oj.get("value"))
            t = codes[j] = (fcode(oj), v1, v2)
        return t

    for b, (_pos, i, open_ok, open_crashed) in enumerate(barriers):
        bar_quiet[b] = open_ok == (i,)
        bar_f[b], bar_v1[b], bar_v2[b] = op_codes(i)
        bar_slot[b] = slots[history[i]["process"]]
        bar_opid[b] = i
        for j in open_ok:
            s = slots[history[j]["process"]]
            mov_f[b, s], mov_v1[b, s], mov_v2[b, s] = op_codes(j)
            mov_open[b, s] = True
        for g, count in open_crashed:
            grp_open[b, gidx[g]] = count

    grp_f = np.zeros(G, np.int32)
    grp_v1 = np.zeros(G, np.int32)
    grp_v2 = np.zeros(G, np.int32)
    for g, k in gidx.items():
        grp_f[k] = fcode(group_ops[g])
        grp_v1[k], grp_v2[k] = _encode_value(group_ops[g].get("value"))

    return _finish_pack(
        tm, model, B, P, G, W, bar_quiet,
        (bar_f, bar_v1, bar_v2, bar_slot), bar_opid,
        (mov_f, mov_v1, mov_v2, mov_open),
        (grp_f, grp_v1, grp_v2), grp_open,
    )


def _finish_pack(tm, model, B, P, G, W, bar_quiet, bar, bar_opid, mov, grp, grp_open):
    """Shared tail of both pack paths: the int16 count gate, the slot
    one-hot layout, and the kernel-table contract (one copy — the dict
    and column paths must never drift)."""
    if B and grp_open.max(initial=0) > 32767:
        raise NotTensorizable("crashed-group open count exceeds int16 range")
    slot_lane = np.arange(P, dtype=np.int32) // 32
    slot_onehot = np.zeros((P, W), np.uint32)
    for p in range(P):
        slot_onehot[p, p // 32] = np.uint32(1) << np.uint32(p % 32)
    return {
        "B": B,
        "P": P,
        "G": G,
        "W": W,
        "init_state": np.int32(_encode_state(tm, model)),
        "step": tm.step,
        "bar_active": np.ones(B, bool),
        "bar_quiet": bar_quiet,
        "bar": bar,
        "bar_opid": bar_opid,
        "mov": mov,
        "grp": grp,
        "grp_open": grp_open,
        "slot_lane": slot_lane,
        "slot_onehot": slot_onehot,
    }


def _pack_columns(tm, model, ch):
    """Column-native pack: one pass over a ColumnHistory's SoA columns.

    Mirrors the dict path exactly (prepare → _barrier_snapshots → table
    fill, wgl_cpu.prepare semantics: fail ops dropped, crashed pure ops
    dropped, completion values become effective values) but the working
    values are the stored encoded ``(value1, value2)`` int pairs — no op
    dict is ever built.  Group keys are ``(f_code, v1, v2)`` triples;
    group ORDER is sorted on the triple (the dict path sorts on repr of
    the python values), which only permutes the grp columns — verdict-
    irrelevant, every reference to a group goes through its index."""
    cols, fs = ch.cols, ch.fs
    n = len(ch)
    # ColumnHistory._TYPE_NAMES order
    T_INVOKE, T_OK, T_FAIL, T_INFO = 0, 1, 2, 3
    typl = np.asarray(cols["type"]).tolist()
    procl = np.asarray(cols["process"]).tolist()
    fl = np.asarray(cols["f"]).tolist()
    v1l = np.asarray(cols["value1"]).tolist()
    v2l = np.asarray(cols["value2"]).tolist()
    fmap = [tm.f_codes.get(name) for name in fs]
    pure = wgl_cpu.PURE_FS.get(getattr(model, "name", None), set())
    pure_idx = {k for k, name in enumerate(fs) if name in pure}
    NILi = int(h.NIL)

    # pair matching (pair_index semantics, on plain ints)
    pair = [-1] * n
    open_by_p: dict = {}
    for i in range(n):
        if typl[i] == T_INVOKE:
            open_by_p[procl[i]] = i
        else:
            j = open_by_p.pop(procl[i], None)
            if j is not None:
                pair[j] = i
                pair[i] = j

    # effective ops + event order (wgl_cpu.prepare, columnar)
    order: list[tuple[int, int, int]] = []
    eff: dict[int, tuple[int, int, int]] = {}  # opid -> (code, v1, v2)
    crashed: set[int] = set()
    for i in range(n):
        # only -1 is the nemesis sentinel; other negative ints are
        # legitimate (if odd) client process ids the dict path includes
        if typl[i] != T_INVOKE or procl[i] == -1:
            continue
        j = pair[i]
        ctype = typl[j] if j != -1 else T_INFO
        if ctype == T_FAIL:
            continue
        fi = fl[i]
        if ctype == T_INFO and fi in pure_idx:
            continue
        code = fmap[fi]
        if code is None:
            raise NotTensorizable(f"model {tm.name} has no f code for {fs[fi]!r}")
        ev1, ev2 = v1l[i], v2l[i]
        if ctype == T_OK and not (v1l[j] == NILi and v2l[j] == NILi):
            ev1, ev2 = v1l[j], v2l[j]  # knossos complete: learn the value
        eff[i] = (code, ev1, ev2)
        order.append((i, wgl_cpu.CALL, i))
        if ctype == T_OK:
            order.append((j, wgl_cpu.RET, i))
        else:
            crashed.add(i)
    order.sort()

    # slots: one in-flight ok op per process
    slots: dict = {}
    for i in eff:
        if i not in crashed:
            p = procl[i]
            if p not in slots:
                slots[p] = len(slots)
    P = max(1, len(slots))
    W = (P + 31) // 32
    B = sum(1 for _pos, kind, _i in order if kind == wgl_cpu.RET)

    # group vocabulary over the whole history (deterministic triple sort)
    groups = sorted({eff[i] for i in crashed})
    gidx = {g: k for k, g in enumerate(groups)}
    G = max(1, len(groups))

    bar_f = np.zeros(B, np.int32)
    bar_v1 = np.zeros(B, np.int32)
    bar_v2 = np.zeros(B, np.int32)
    bar_slot = np.zeros(B, np.int32)
    bar_opid = np.zeros(B, np.int32)
    mov_f = np.zeros((B, P), np.int32)
    mov_v1 = np.zeros((B, P), np.int32)
    mov_v2 = np.zeros((B, P), np.int32)
    mov_open = np.zeros((B, P), bool)
    grp_open = np.zeros((B, G), np.int32)
    bar_quiet = np.zeros(B, bool)

    open_ok: list[int] = []
    open_crashed: dict[tuple, int] = {}
    b = 0
    for _pos, kind, i in order:
        if kind == wgl_cpu.CALL:
            if i in crashed:
                g = eff[i]
                open_crashed[g] = open_crashed.get(g, 0) + 1
            else:
                open_ok.append(i)
        else:
            bar_quiet[b] = open_ok == [i]
            bar_f[b], bar_v1[b], bar_v2[b] = eff[i]
            bar_slot[b] = slots[procl[i]]
            bar_opid[b] = i
            for jj in open_ok:
                s = slots[procl[jj]]
                mov_f[b, s], mov_v1[b, s], mov_v2[b, s] = eff[jj]
                mov_open[b, s] = True
            for g, count in open_crashed.items():
                grp_open[b, gidx[g]] = count
            b += 1
            k = bisect.bisect_left(open_ok, i)
            if k < len(open_ok) and open_ok[k] == i:
                del open_ok[k]

    grp_f = np.zeros(G, np.int32)
    grp_v1 = np.zeros(G, np.int32)
    grp_v2 = np.zeros(G, np.int32)
    for g, k in gidx.items():
        grp_f[k], grp_v1[k], grp_v2[k] = g

    return _finish_pack(
        tm, model, B, P, G, W, bar_quiet,
        (bar_f, bar_v1, bar_v2, bar_slot), bar_opid,
        (mov_f, mov_v1, mov_v2, mov_open),
        (grp_f, grp_v1, grp_v2), grp_open,
    )


def _encode_state(tm, model) -> int:
    try:
        return tm.encode_state(model)
    except ValueError as e:
        raise NotTensorizable(str(e)) from None


def _bucket(x: int, choices) -> int:
    for c in choices:
        if c >= x:
            return c
    return x


def pad_packed(packed: dict, B: int | None = None, P: int | None = None, G: int | None = None) -> dict:
    """Pad the packed tables to bucketed shapes so the jitted kernel is
    reused across histories instead of recompiling per (B, P, G) triple.
    Padding barriers are inactive (skipped); padding slots/groups are never
    open, so the kernel's behavior is unchanged.  Explicit targets override
    the buckets (used to align a batch of histories on common shapes)."""
    B0, P0, G0 = packed["B"], packed["P"], packed["G"]
    B = B if B is not None else pad_B(B0)
    P = P if P is not None else _bucket(P0, [8, 16, 32, 64, 128])
    G = G if G is not None else _bucket(G0, [4, 8, 16, 32, 64])
    assert B >= B0 and P >= P0 and G >= G0
    if (B, P, G) == (B0, P0, G0):
        return packed
    W = (P + 31) // 32
    bar_f, bar_v1, bar_v2, bar_slot = packed["bar"]
    mov_f, mov_v1, mov_v2, mov_open = packed["mov"]
    grp_f, grp_v1, grp_v2 = packed["grp"]

    def padB(a, fill=0):
        out = np.full((B,) + a.shape[1:], fill, a.dtype)
        out[:B0] = a
        return out

    def padBP(a):
        out = np.zeros((B, P), a.dtype)
        out[:B0, :P0] = a
        return out

    def padG(a):
        out = np.zeros(G, a.dtype)
        out[:G0] = a
        return out

    def padBG(a):
        out = np.zeros((B, G), a.dtype)
        out[:B0, :G0] = a
        return out

    slot_lane = np.arange(P, dtype=np.int32) // 32
    slot_onehot = np.zeros((P, W), np.uint32)
    for p in range(P):
        slot_onehot[p, p // 32] = np.uint32(1) << np.uint32(p % 32)
    out = dict(packed)
    out.update(
        B=B,
        P=P,
        G=G,
        W=W,
        bar_active=padB(packed["bar_active"], False),
        bar_quiet=padB(packed["bar_quiet"], False),
        bar=(padB(bar_f), padB(bar_v1), padB(bar_v2), padB(bar_slot)),
        mov=(padBP(mov_f), padBP(mov_v1), padBP(mov_v2), padBP(mov_open)),
        grp=(padG(grp_f), padG(grp_v1), padG(grp_v2)),
        grp_open=padBG(packed["grp_open"]),
        slot_lane=slot_lane,
        slot_onehot=slot_onehot,
    )
    return out


# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------


def expand_candidates(
    step, eye_g, slot_lane, slot_mask, slot_onehot,
    state, fok, fcr, alive,
    xmov_f, xmov_v1, xmov_v2, xmov_open,
    grp_f, grp_v1, grp_v2, xgrp_open,
):
    """One closure round's candidate table: parents + every legal single
    move (shared by the single-device and frontier-sharded kernels).

    Process moves fire any open ok op not yet fired; group moves fire one
    crashed op from any open group.  A crashed fire that leaves the state
    unchanged yields a config dominated by its own parent (same state/fok,
    one more fired) — dropped at the source.

    Returns (cat_state, cat_fok, cat_fcr, cat_alive, cost) with
    F*(1+P+G) rows."""
    F, W = fok.shape
    P = xmov_f.shape[0]
    G = grp_f.shape[0]
    pstate2, plegal = step(state[:, None], xmov_f[None, :], xmov_v1[None, :], xmov_v2[None, :])
    already = (jnp.take(fok, slot_lane, axis=1) & slot_mask[None, :]) != 0
    plegal = plegal & alive[:, None] & xmov_open[None, :] & ~already
    pfok = (fok[:, None, :] | slot_onehot[None, :, :]).reshape(F * P, W)
    pfcr = jnp.repeat(fcr, P, axis=0)
    gstate2, glegal = step(state[:, None], grp_f[None, :], grp_v1[None, :], grp_v2[None, :])
    glegal = (
        glegal & alive[:, None] & (fcr < xgrp_open[None, :]) & (gstate2 != state[:, None])
    )
    gfok = jnp.repeat(fok, G, axis=0)
    gfcr = (fcr[:, None, :] + eye_g[None, :, :]).reshape(F * G, G)

    cat_state = jnp.concatenate([state, pstate2.reshape(-1), gstate2.reshape(-1)])
    cat_alive = jnp.concatenate([alive, plegal.reshape(-1), glegal.reshape(-1)])
    cat_fok = jnp.concatenate([fok, pfok, gfok], axis=0)
    cat_fcr = jnp.concatenate([fcr, pfcr, gfcr.astype(I16)], axis=0)
    cost = (
        jax.lax.population_count(cat_fok).sum(axis=1).astype(I32)
        + cat_fcr.sum(axis=1, dtype=I32)
    )
    return cat_state, cat_fok, cat_fcr, cat_alive, cost


def _scan_chunk_core(
    step,
    F: int,
    R: int,
    P: int,
    G: int,
    W: int,
    fast: bool,
    state0,
    fok0,
    fcr0,
    alive0,
    bar_active,
    bar_f,
    bar_v1,
    bar_v2,
    bar_slot,
    mov_f,
    mov_v1,
    mov_v2,
    mov_open,
    grp_f,
    grp_v1,
    grp_v2,
    grp_open,
    slot_lane,
    slot_onehot,
    dedup: str = "sort",
):
    """Scan a frontier over a chunk of barriers, starting from an explicit
    frontier and returning the final one.

    This is the composable unit behind both the whole-history runner
    (_run_core) and the chunked escalation path (chunked_analysis): because
    the frontier is carried in and out, a long history becomes a chain of
    small scan programs — no single XLA program ever holds tens of
    thousands of scan steps (the shape that faulted the TPU worker), and
    each chunk can re-run at a wider capacity on its own.

    Returns (state, fok, fcr, alive, failed_at, lossy, peak): failed_at is
    the chunk-local barrier index where the frontier died (-1 = never);
    lossy/peak cover this chunk only.
    """
    eye_g = jnp.eye(G, dtype=I16)
    slot_mask = slot_onehot.sum(axis=1)  # [P] uint32 bit mask within its lane

    def expand_round(val):
        state, fok, fcr, alive, r, changed, lossy, fp, xs = val
        (xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open) = xs
        cat_state, cat_fok, cat_fcr, cat_alive, cost = expand_candidates(
            step, eye_g, slot_lane, slot_mask, slot_onehot,
            state, fok, fcr, alive,
            xmov_f, xmov_v1, xmov_v2, xmov_open,
            grp_f, grp_v1, grp_v2, xgrp_open,
        )
        if fast:
            # Closure terminates on the no-growth signal: no expansion
            # survived dedup ⟹ fixpoint (modulo the hash-dedup caveat
            # covered by refutation confirmation).  frontier_update_fast
            # domination-prunes its own buffer, so its output is already
            # an antichain — no outer prune (advisor r3: the double prune
            # doubled the hot loop's prune cost for zero alive change).
            state2, fok2, fcr2, alive2, ovf, fp2, child = frontier_update_fast(
                cat_state, cat_fok, cat_fcr, cat_alive, cost, F, n_parents=F,
                max_count=xmov_f.shape[-1] + 1, dedup_backend=dedup,
            )
            changed2 = (alive2 & child).any()
        else:
            state2, fok2, fcr2, alive2, ovf, fp2 = frontier_update(
                cat_state, cat_fok, cat_fcr, cat_alive, cost, F,
                dedup_backend=dedup,
            )
            changed2 = ~(fp2 == fp).all()
        return (state2, fok2, fcr2, alive2, r + 1, changed2, lossy | ovf, fp2, xs)

    def round_cond(val):
        _s, _fo, _fc, _a, r, changed, _l, _fp, _xs = val
        return (r < R) & changed

    def barrier(carry, xs):
        state, fok, fcr, alive, failed_at, lossy, peak = carry
        b_idx, active, xbar_f, xbar_v1, xbar_v2, xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open = xs
        done = (failed_at >= 0) | ~active

        def process(_):
            xs_inner = (xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open)
            fp0 = jnp.full(3, jnp.uint32(0xFFFFFFFF))
            s2, fo2, fc2, a2, _r, changed, lossy2, _fp, _ = jax.lax.while_loop(
                round_cond,
                expand_round,
                (state, fok, fcr, alive, jnp.int32(0), jnp.bool_(True), lossy, fp0, xs_inner),
            )
            lossy3 = lossy2 | changed  # ran out of rounds before fixpoint
            # Filter: only configs that fired the returning op survive;
            # then retire its slot bit.
            lane = xbar_slot // 32
            bitmask = (U32(1) << (xbar_slot % 32).astype(U32))
            lane_vals = jnp.take(fo2, lane[None], axis=1)[:, 0]
            a3 = a2 & ((lane_vals & bitmask) != 0)
            clear = jnp.where(jnp.arange(W) == lane, bitmask, U32(0))
            fo3 = fo2 & ~clear[None, :]
            # fast path: the frontier is already an antichain (pruned
            # inside frontier_update_fast), and the return filter keeps
            # only rows holding the retiring bit, so the uniform clear
            # preserves both distinctness and domination order — no
            # per-barrier reap needed.
            dead = ~a3.any()
            failed2 = jnp.where(dead, b_idx, failed_at)
            peak2 = jnp.maximum(peak, a3.sum())
            return (s2, fo3, fc2, a3, failed2, lossy3, peak2)

        def skip(_):
            return (state, fok, fcr, alive, failed_at, lossy, peak)

        return jax.lax.cond(done, skip, process, None), None

    carry0 = (state0, fok0, fcr0, alive0, jnp.int32(-1), jnp.bool_(False),
              jnp.maximum(alive0.sum(), 1))
    xs = (
        jnp.arange(bar_f.shape[0], dtype=I32),
        bar_active,
        bar_f,
        bar_v1,
        bar_v2,
        bar_slot,
        mov_f,
        mov_v1,
        mov_v2,
        mov_open,
        grp_open,
    )
    (state, fok, fcr, alive, failed_at, lossy, peak), _ = jax.lax.scan(barrier, carry0, xs)
    return state, fok, fcr, alive, failed_at, lossy, peak


def _run_core(
    step,
    F: int,
    R: int,
    P: int,
    G: int,
    W: int,
    fast: bool,
    init_state,
    bar_active,
    bar_f,
    bar_v1,
    bar_v2,
    bar_slot,
    mov_f,
    mov_v1,
    mov_v2,
    mov_open,
    grp_f,
    grp_v1,
    grp_v2,
    grp_open,
    slot_lane,
    slot_onehot,
    dedup: str = "sort",
):
    """Scan the frontier over all barriers from the initial single-config
    frontier.  Returns (any_alive, failed_at, lossy, peak_frontier)."""
    state0 = jnp.full((F,), init_state, I32)
    fok0 = jnp.zeros((F, W), U32)
    fcr0 = jnp.zeros((F, G), I16)
    alive0 = jnp.zeros((F,), bool).at[0].set(True)
    _s, _fo, _fc, alive, failed_at, lossy, peak = _scan_chunk_core(
        step, F, R, P, G, W, fast,
        state0, fok0, fcr0, alive0,
        bar_active, bar_f, bar_v1, bar_v2, bar_slot,
        mov_f, mov_v1, mov_v2, mov_open,
        grp_f, grp_v1, grp_v2, grp_open,
        slot_lane, slot_onehot, dedup=dedup,
    )
    return alive.any(), failed_at, lossy, peak


_run = functools.partial(
    jax.jit, static_argnames=("step", "F", "R", "P", "G", "W", "fast", "dedup")
)(_run_core)

_scan_chunk = functools.partial(
    jax.jit, static_argnames=("step", "F", "R", "P", "G", "W", "fast", "dedup")
)(_scan_chunk_core)

#: Bound on slices per chunk attempt: slice-width narrowing stops at
#: ceil(n_in / _MAX_SLICES), so one attempt never exceeds _MAX_SLICES
#: launches — wall clock stays bounded while headroom per entry row is
#: still capacity/width (64 slices at the host-bound frontier gives
#: 8x capacity headroom per row, ample for real closures).
_MAX_SLICES = 64

#: (step, F, R, P, G, W, fast, dedup) -> jitted vmapped runner over a
#: leading batch axis.
_BATCH_RUNNERS: dict = {}


def batched_runner(step, F: int, R: int, P: int, G: int, W: int,
                   dedup: str = "sort"):
    """A jit(vmap(_run_core)) specialised to the given static shapes: checks
    a stack of same-shape packed histories in one device program (BASELINE
    config 4: hundreds of recorded histories vmapped across a slice).
    slot tables are shape-derived and shared; everything else is batched.

    Uses the fast hash-lane frontier update: under vmap, multi-key sorts
    and full-table gathers dominate wall clock; stragglers that overflow
    its capacity escalate to the exact path or the CPU oracle
    (jepsen_tpu.parallel.batch).  ``dedup`` selects the per-round dedup
    backend (jepsen_tpu.ops.hashing, "sort"|"bucket")."""
    key = (step, F, R, P, G, W, True, dedup)
    _cache_counter(_BATCH_RUNNERS, key, "sync")
    if key not in _BATCH_RUNNERS:
        core = functools.partial(_run_core, step, F, R, P, G, W, True, dedup=dedup)
        axes = (0,) * 14 + (None, None)
        _BATCH_RUNNERS[key] = jax.jit(jax.vmap(core, in_axes=axes))
    return _BATCH_RUNNERS[key]


def exact_batched_runner(step, F: int, R: int, P: int, G: int, W: int,
                         dedup: str = "sort"):
    """jit(vmap(_run_core)) with the EXACT frontier update (sorted windowed
    (state, fok) compares + two-stage domination — kills are content
    compares, never hash-identity, under BOTH dedup backends).  One launch
    replaces the former Python
    loop of per-history exact escalations: every straggler and every
    fast-engine refutation confirms in the same vmapped program, so the
    escalation stage costs one launch instead of ~60% of bench wall clock
    (round-2 profile)."""
    key = (step, F, R, P, G, W, False, dedup)
    _cache_counter(_BATCH_RUNNERS, key, "exact")
    if key not in _BATCH_RUNNERS:
        core = functools.partial(_run_core, step, F, R, P, G, W, False, dedup=dedup)
        axes = (0,) * 14 + (None, None)
        _BATCH_RUNNERS[key] = jax.jit(jax.vmap(core, in_axes=axes))
    return _BATCH_RUNNERS[key]


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def device_buffer_bytes() -> int | None:
    """Live device-buffer bytes on the primary device — the quantity the
    ladder's per-stage memory high-water marks sample (telemetry stage
    table ``device_bytes_peak``, live gauge ``device.buffer_bytes``).

    Prefers the backend allocator's ``bytes_in_use`` (TPU/GPU); falls
    back to summing live jax array footprints (the CPU backend exposes
    no allocator stats).  Returns None when neither is available —
    callers (all telemetry-gated) just skip the sample."""
    try:
        dev = jax.devices()[0]
        stats = dev.memory_stats()
        if stats and "bytes_in_use" in stats:
            return int(stats["bytes_in_use"])
    except Exception:  # noqa: BLE001 — stats are backend-optional
        pass
    try:
        return int(sum(int(a.nbytes) for a in jax.live_arrays()))
    except Exception:  # noqa: BLE001 — never fail a launch for a gauge
        return None


def _cache_counter(cache: dict, key, kind: str) -> None:
    """One compile-cache hit/miss counter per runner lookup: a fresh key
    means jit trace+compile is about to be paid (the compile_s column's
    event-level sibling; surfaced live via /metrics as
    ``wgl_runner_cache_hit/miss``)."""
    obs.counter(
        "wgl.runner_cache.hit" if key in cache else "wgl.runner_cache.miss",
        kind=kind,
    )


def evict_runner_caches() -> int:
    """Drop every cached jitted runner (batched / async / greedy):
    releasing the references lets the backend free the executables and
    their device buffers — the process-level spill lever the OOM policy
    pulls BEFORE halving work (``jepsen_tpu.faults.try_oom_spill``; the
    default spiller in ``parallel.batch`` calls this only on non-CPU
    backends, where allocator pressure is real).  The cost is
    recompiles later, never correctness.  Returns entries evicted."""
    n = len(_BATCH_RUNNERS) + len(_ASYNC_RUNNERS) + len(_GREEDY_RUNNERS)
    _BATCH_RUNNERS.clear()
    _ASYNC_RUNNERS.clear()
    _GREEDY_RUNNERS.clear()
    return n


#: Env var naming a MEASURED multi-lane fault-grid file
#: (tools/fault_sweep.py artifact): when set and valid, measured cells
#: replace the conservative lanes x capacity product-model inference in
#: exact_scan_safe — the round-6 caveat's fix.  Queries no measured
#: cell dominates still fall back to the product model (never less
#: conservative than the data actually covers).
EXACT_GRID_ENV = "JEPSEN_TPU_EXACT_GRID"

#: path -> (mtime_ns, size, cells-or-None) parse cache; re-reads only
#: when the file changes, so the hot routing path stays file-free.
_EXACT_GRID_CACHE: dict = {}
_EXACT_GRID_WARNED: set = set()


def validate_exact_grid(obj) -> list[dict]:
    """Validate a fault-grid artifact (tools/fault_sweep.py schema) and
    return its normalized cells.  Raises ValueError naming the first
    defect — the tool's --dry-run and the loader both gate on this, so
    a malformed grid can only ever fall back to the product model,
    never silently mis-route."""
    if not isinstance(obj, dict):
        raise ValueError("grid must be a JSON object")
    if obj.get("version") != 1:
        raise ValueError(f"unsupported grid version {obj.get('version')!r}")
    if obj.get("kind") != "exact-fault-grid":
        raise ValueError(f"grid kind must be 'exact-fault-grid', "
                         f"got {obj.get('kind')!r}")
    cells = obj.get("cells")
    if not isinstance(cells, list) or not cells:
        raise ValueError("grid needs a non-empty 'cells' list")
    out = []
    for i, c in enumerate(cells):
        if not isinstance(c, dict):
            raise ValueError(f"cell {i} is not an object")
        missing = {"lanes", "capacity", "barriers", "ok"} - c.keys()
        if missing:
            raise ValueError(f"cell {i} is missing {sorted(missing)}")
        if not isinstance(c["ok"], bool):
            raise ValueError(f"cell {i}: 'ok' must be a boolean")
        try:
            lanes, cap, bars = (
                int(c["lanes"]), int(c["capacity"]), int(c["barriers"])
            )
        except (TypeError, ValueError):
            raise ValueError(
                f"cell {i}: lanes/capacity/barriers must be integers"
            ) from None
        if min(lanes, cap, bars) < 1:
            raise ValueError(f"cell {i}: lanes/capacity/barriers must be >= 1")
        out.append({"lanes": lanes, "capacity": cap, "barriers": bars,
                    "ok": bool(c["ok"])})
    return out


def _exact_grid_cells(path: str) -> list[dict] | None:
    """Cached load of the measured grid; None (with a one-shot warning)
    on an unreadable/invalid file — conservative fallback, never a
    crash on the routing path."""
    try:
        st = os.stat(path)
        key = (st.st_mtime_ns, st.st_size)
    except OSError:
        key = None
    cached = _EXACT_GRID_CACHE.get(path)
    if cached is not None and cached[0] == key:
        return cached[1]
    cells = None
    try:
        with open(path, encoding="utf-8") as f:
            cells = validate_exact_grid(json.load(f))
    except (OSError, ValueError) as e:
        if path not in _EXACT_GRID_WARNED:
            _EXACT_GRID_WARNED.add(path)
            warnings.warn(
                f"ignoring {EXACT_GRID_ENV}={path!r} ({e}); the "
                "conservative product model stays in effect",
                stacklevel=3,
            )
        obs.counter("wgl.exact_grid.invalid")
    _EXACT_GRID_CACHE[path] = (key, cells)
    return cells


def _exact_grid_decide(cells: list[dict], B: int, capacity: int,
                       lanes: int) -> bool | None:
    """Decide a (B, capacity, lanes) query against measured cells.
    Fault danger is monotone in every axis (longer scans, wider
    frontiers, more resident lanes), so: a FAULT at a componentwise-
    dominated shape proves the query faults; an OK at a componentwise-
    dominating shape proves it is safe.  Contradictory data resolves
    conservatively (fault wins); an uncovered query returns None and
    the product model decides."""
    for c in cells:
        if (not c["ok"] and c["lanes"] <= lanes
                and c["capacity"] <= capacity and c["barriers"] <= B):
            return False
    for c in cells:
        if (c["ok"] and c["lanes"] >= lanes
                and c["capacity"] >= capacity and c["barriers"] >= B):
            return True
    return None


def exact_scan_safe(B: int, capacity: int, lanes: int = 1) -> bool:
    """Measured fault boundary of the batched exact runner (the round-4
    "cap >= 1024 faults the tunneled TPU worker" cliff, isolated by
    tools/repro_exact_fault.py on the v5e chip, round 5):

    | cap \\ barriers | 2048 | 4096 | 8192 |
    |---|---|---|---|
    | 512  | ok | ok | FAULT |
    | 1024 | ok | FAULT | FAULT |
    | 2048 | ok | FAULT | FAULT |

    The crash ("TPU worker process crashed or restarted ... kernel
    fault") needs BOTH a long barrier scan and a wide frontier: every
    B <= 2048 cell is fine (including cap 2048 — 4M rows), while the
    same 4M rows at B = 4096 faults.  The grid was measured on
    SINGLE-lane launches; under vmap the live sort/domination buffers
    multiply by the lane count, so callers pass the launch's PADDED
    lane count and the effective width ``lanes * capacity`` is tested.
    NOTE the lanes x capacity product model is an INFERENCE from the
    single-lane grid, not a measurement — no multi-lane fault point has
    been observed to confirm it (round-5 advisor).  It is conservative
    by construction, and the cost of that conservatism is routing, not
    correctness: multi-lane launches that would in fact be safe are
    sent to the chunked/async paths and pay only time (see PERF.md
    "Honest limits").  Callers must route shapes where this returns False to
    the async engine (which executes them — see PERF.md) or to
    chunked_analysis (whose chunk scans keep B <= the chunk size, far
    below the cliff).

    MEASURED-GRID OVERRIDE (round 11, the round-6 caveat's fix): when
    ``JEPSEN_TPU_EXACT_GRID`` names a ``tools/fault_sweep.py``
    artifact, its measured multi-lane cells decide first — a query
    dominated by a measured fault is unsafe, a query dominated BY a
    measured pass is safe (fault wins on contradiction) — and only
    queries the grid doesn't cover fall back to the inferred product
    model below.  A measured grid thus wins back exactly the mid-size
    batched-exact launches the inference conservatively re-routes,
    with zero new inference."""
    grid_path = os.environ.get(EXACT_GRID_ENV)
    if grid_path:
        cells = _exact_grid_cells(grid_path)
        if cells:
            verdict = _exact_grid_decide(cells, B, capacity, max(1, lanes))
            if verdict is not None:
                return verdict
    rows = capacity * max(1, lanes) * B
    if B >= 8192:  # faulted at EVERY measured cap; untested below 512
        return False
    if B >= 4096 and rows >= (4 << 20):
        return False
    if rows >= (8 << 20):  # untested headroom beyond the measured grid
        return False
    return True


def pad_B(B: int) -> int:
    """The barrier-table padding the batched launch sites apply (power
    of two, floor 64).  exact_scan_safe callers must check the PADDED
    shape — the one actually launched — so this lives next to it."""
    return 1 << max(6, (B - 1).bit_length())


def _chunk_bounds(quiet, B0: int, target: int) -> list[tuple[int, int]]:
    """Split [0, B0) into chunks of ≤ target barriers, preferring to cut
    just after the LATEST quiescent barrier in the back half of each window
    (a barrier whose only open ok op is the returning one): the carried
    frontier there has every fok bitset empty, so it collapses to the
    (state, crashed-count) antichain — the smallest summary the search ever
    holds (P-compositionality: the segments compose exactly through that
    summary)."""
    bounds = []
    lo = 0
    while lo < B0:
        hi_max = min(lo + target, B0)
        hi = hi_max
        if hi_max < B0:
            for b in range(hi_max - 1, lo + target // 2 - 1, -1):
                if quiet[b]:
                    hi = b + 1
                    break
        bounds.append((lo, hi))
        lo = hi
    return bounds


def chunked_analysis(
    model: m.Model,
    history: Sequence[dict],
    packed: dict,
    capacities: Sequence[int],
    rounds: int = 8,
    chunk_barriers: int = 512,
    fast: bool = False,
    dedup_backend: str | None = None,
    deadline=None,
    spill: bool | None = None,
    frontier_budget_mb: float | None = None,
    spill_factor: float = 4.0,
    spill_launches: int | None = None,
    factor_groups: bool | None = None,
    checkpoint_dir=None,
    resume: bool = False,
) -> dict:
    """Decide linearizability as a chain of chunk scans with a carried
    frontier (history decomposition — VERDICT round-2 item #2), under a
    BOUNDED device-memory contract (round 8): an overflowing frontier
    SPILLS to host instead of dying.

    Where the whole-history ladder re-ran ALL barriers at the next
    capacity whenever the frontier overflowed ANYWHERE, here only the
    overflowing chunk re-runs (from its exact input frontier) at the wider
    capacity; chunks the frontier sails through stay at the cheap
    capacity.  The capacity position adapts: it climbs on overflow and
    steps back down when a chunk's peak leaves 4x headroom.

    BOUNDED MEMORY (``spill``; default: engaged iff a bounded-memory
    knob — ``frontier_budget_mb`` or ``spill_launches`` — is set, since
    the recovery levers multiply launches on exactly the histories that
    are already slow): the frontier-set sweep is
    linear in the frontier — scanning a chunk from A ∪ B equals the
    union of scanning from A and from B — so a carried frontier that
    exceeds the rung capacity streams through the SAME compiled chunk
    kernel in slices of ≤ capacity rows, the overflow waiting in a
    host ring (``ops.spill.HostRing``; device→host copies start
    asynchronously, overlapping the next device-bound slice), and the
    slice survivors recombine by exact LSH-bucketed union
    (``ops.spill.merge_frontiers``).  Rows are never silently dropped;
    refutation requires EVERY slice to die.  ``frontier_budget_mb``
    (argument > JEPSEN_TPU_FRONTIER_BUDGET_MB env) caps the device
    frontier working set: ladder rungs that don't fit the budget are
    skipped and slicing absorbs the difference.  When a chunk still
    overflows at the highest usable rung, the chunk BISECTS — the
    frontier is re-checked (and spilled) at the midpoint — down to
    single-barrier chunks; only a single barrier's closure overflowing
    the budget is genuine exhaustion.  The lossy/escalation ladder thus
    engages only once spill is exhausted, and a final ``unknown`` then
    carries a machine-readable undecidability report
    (``ops.spill.undecidability_report``: peak frontier growth rate,
    spill volume, budget at exhaustion) in ``"undecidability"`` and a
    json rendering in ``cause`` — never a bare unknown (the report is
    NOT gated on spill: memory-exhausted unknowns carry it in the
    legacy truncation mode too).  ``spill=False`` forces the
    pre-round-8 truncate-and-latch-lossy behavior; ``spill=True``
    forces recovery on without a budget.

    ``factor_groups`` (None = rides the spill opt-in; True forces)
    first factors the packed problem over trace-independent crashed-op
    groups (``ops.spill.factor_packed``): each independent group is a
    factor whose check is closed-form, so it is removed and G shrinks
    structurally — the verdict provably equals the monolithic one.

    Soundness: ``True`` needs only a surviving frontier (any surviving
    config is a constructive witness, truncated or not).  ``False`` is
    reported only when no loss occurred in ANY chunk up to the death —
    once loss happens, a dead frontier answers "unknown".  The
    ``verified-barriers`` stat counts barriers passed with zero loss —
    the measured "verified ops" number for histories whose tail
    exhausts (BASELINE config 5).

    ``fast`` runs chunks on the hash-dedup engine (~10x cheaper per
    lane): ``True`` stays sound, but a ``False`` is PROVISIONAL (kills
    are hash-decided, collision ~1e-13) and is marked ``provisional?``
    for the caller to confirm, the way batch_analysis confirms
    fast-engine refutations.

    ``dedup_backend`` selects the per-round dedup backend for every
    chunk scan (None → env/default via resolve_dedup_backend).

    ``deadline`` (seconds or faults.Deadline) bounds wall clock at CHUNK
    boundaries: on expiry the run degrades to an attributable
    ``"unknown"`` instead of scanning past the budget.  Every chunk
    launch runs under the transient-retry policy
    (jepsen_tpu.faults.call_with_retry); a launch that still fails (or
    OOMs — there is no sub-batch to halve on the single-history path)
    degrades this history alone with the error named in ``cause``.

    ``checkpoint_dir`` persists the scan cursor and the carried —
    possibly host-spilled, so unbounded-row — frontier after every
    accepted chunk (``store.checkpoint.save_chunked``); ``resume=True``
    reloads it (fingerprint + config must match, else the run starts
    fresh with a warning — resuming against changed inputs could only
    produce wrong verdicts) and re-enters the chain at the saved
    barrier: a kill -9 mid-spill then a resume reproduces uninterrupted
    verdicts (chaos-gated in tools/chaos_check.py --spill).
    """
    from jepsen_tpu.ops import spill as spill_mod

    dedup = resolve_dedup_backend(dedup_backend)
    deadline = faults.Deadline.coerce(deadline)
    B0 = packed["B"]
    quiet = packed["bar_quiet"]
    budget_mb = spill_mod.resolve_budget_mb(frontier_budget_mb)
    #: Spill recovery is OPT-IN through the bounded-memory knobs: with
    #: no budget configured the scan keeps its pre-round-8 cost profile
    #: (truncate-and-latch-lossy — the escalation ladder alone), because
    #: the recovery levers multiply launches on exactly the histories
    #: that are slow already, and this path rides every escalation /
    #: confirmation fallback in the tier-1 suite.  Honest exhaustion
    #: reports are NOT gated — every memory-exhausted unknown carries
    #: one either way.  Resolved ONCE here; the factorization default,
    #: the checkpoint config, and the scan loop all read this value.
    spill_on = (
        bool(spill) if spill is not None
        else (budget_mb is not None or spill_launches is not None)
    )
    #: Factorization rides the same opt-in (None = auto): the
    #: reachable-state tabulation is cheap but nonzero per call, and the
    #: structural win matters exactly where memory pressure does.
    #: ``factor_groups=True`` forces it on.
    if factor_groups is None:
        factor_groups = spill_on
    factors = 0
    if factor_groups:
        packed, factors = spill_mod.factor_packed(packed)
    packed = pad_packed(packed, B=B0)  # bucket P/G; keep B for slicing
    P, G, W = packed["P"], packed["G"], packed["W"]
    caps = [int(c) for c in capacities]
    b_rows = spill_mod.budget_rows(budget_mb, W, G, P)

    # Decision-path trajectory (obs.provenance): a bounded trail of the
    # escalations, spill levers, and fault events this scan actually
    # took, attached to every return so the caller's evidence bundle
    # records HOW the verdict was produced.
    traj: list[dict] = []
    _prov_engine = {
        "engine": "chunked-fast" if fast else "chunked-exact",
        "dedup_backend": dedup, "spill": spill_on,
    }
    _prov_cfg = {
        "capacity": caps, "rounds": int(rounds),
        "chunk_barriers": int(chunk_barriers), "fast": bool(fast),
        "frontier_budget_mb": budget_mb,
        "spill_launches": spill_launches, "factor_groups": bool(factor_groups),
    }

    def _pv(event: str, **attrs) -> None:
        if len(traj) < _prov.MAX_PATH:
            traj.append({"event": event, **attrs})

    def _finish(res: dict) -> dict:
        _prov.attach(res, traj, engine=_prov_engine, config=_prov_cfg)
        return res

    def _usable(i: int) -> bool:
        """Rung i fits the device budget (rung 0 always runs — the
        documented floor: some capacity is needed to make progress)."""
        return i == 0 or b_rows is None or caps[i] <= b_rows

    # Host-side frontier bound: the union frontier is exact, which means
    # it can grow with the TRUE configuration count — exponential on
    # adversarial histories.  ``spill_factor`` × the widest usable rung
    # bounds the host rows (and with them the per-chunk launch count);
    # crossing it is memory exhaustion like any other: honest truncation,
    # lossy latch, undecidability report with reason "host-budget".
    top_usable = max(c for i, c in enumerate(caps) if _usable(i))
    host_rows_max = max(int(spill_factor * top_usable), top_usable)

    bar_f, bar_v1, bar_v2, bar_slot = packed["bar"]
    mov_f, mov_v1, mov_v2, mov_open = packed["mov"]
    slot_lane = jnp.asarray(packed["slot_lane"])
    slot_onehot = jnp.asarray(packed["slot_onehot"])
    grp_args = tuple(jnp.asarray(a) for a in packed["grp"])

    f_state = np.array([packed["init_state"]], np.int32)
    f_fok = np.zeros((1, W), np.uint32)
    f_fcr = np.zeros((1, G), np.int16)
    idx = 0
    lossy_any = False
    peak_g = 1
    verified = 0
    launches = 0
    start_barrier = 0
    resume_spill_spent = 0
    ring = spill_mod.HostRing(W, G)
    exhaust_rep: dict | None = None
    t0 = time.perf_counter()

    # ------------------------------------------------------------------
    # Chunk checkpoint / resume (store.checkpoint chunked schema).
    # ------------------------------------------------------------------
    ck_cfg = None
    _ckpt = None
    if checkpoint_dir is not None or resume:
        from jepsen_tpu.store import checkpoint as _ckpt_mod

        _ckpt = _ckpt_mod
        ck_cfg = {
            "fingerprint": _ckpt.fingerprint([history]),
            "capacity": caps, "rounds": int(rounds),
            "chunk_barriers": int(chunk_barriers), "fast": bool(fast),
            "dedup": dedup, "budget_mb": budget_mb,
            "spill_factor": float(spill_factor),
            "spill_launches": spill_launches,
            "factor_groups": bool(factor_groups), "spill": spill_on,
        }
    if (resume and checkpoint_dir is not None and _ckpt is not None
            and _ckpt.chunked_exists(checkpoint_dir)):
        saved = None
        try:
            saved = _ckpt.load_chunked(checkpoint_dir)
        except _ckpt.CheckpointError as e:
            import logging

            logging.getLogger(__name__).warning(
                "unreadable chunk checkpoint in %s (%s); running fresh",
                checkpoint_dir, e)
            obs.counter("fault.checkpoint.mismatch", reason="unreadable")
        if saved is not None and saved["config"] != ck_cfg:
            import logging

            # Quarantine the stale pair aside (same contract as the
            # ladder checkpoint): a later resume with matching inputs
            # must not pick mismatched state back up.
            quarantined = _ckpt.quarantine_chunked(
                checkpoint_dir, reason="stale-fingerprint")
            logging.getLogger(__name__).warning(
                "chunk checkpoint in %s was written for different inputs "
                "or config; running fresh (stale files quarantined: %s)",
                checkpoint_dir, quarantined)
            obs.counter("fault.checkpoint.quarantined",
                        reason="fingerprint", files=quarantined)
            obs.counter("fault.checkpoint.mismatch", reason="fingerprint")
            saved = None
        if saved is not None:
            if saved["result"] is not None:
                # Idempotent finished-run resume: return the certified
                # result verbatim.  Its provenance already records the
                # decision path that PRODUCED the verdict; tagging the
                # no-op restore onto it would make the resumed result
                # (and its evidence digest) differ from the original.
                obs.span_event(
                    "fault.checkpoint.load", 0.0,
                    barrier=int(saved["barrier"]), chunked=True,
                    complete=True,
                )
                return saved["result"]
            st, fo, fc = saved["frontier"]
            f_state = np.asarray(st, np.int32)
            f_fok = np.asarray(fo, np.uint32)
            f_fcr = np.asarray(fc, np.int16)
            start_barrier = saved["barrier"]
            idx = min(saved["cap_idx"], len(caps) - 1)
            lossy_any = saved["lossy"]
            verified = saved["verified"]
            launches = saved["launches"]
            resume_spill_spent = saved.get("spill_spent", 0)
            obs.span_event(
                "fault.checkpoint.load", 0.0, barrier=start_barrier,
                rows=int(f_state.shape[0]), chunked=True,
            )
            _pv("checkpoint.restored", barrier=start_barrier)

    def _save_ck(barrier: int, result: dict | None = None) -> str | None:
        """Persist the chunk cursor + carried (spilled) frontier; a save
        failure is logged and never fails the analysis."""
        if checkpoint_dir is None or _ckpt is None:
            return None
        try:
            p = _ckpt.save_chunked(
                checkpoint_dir, config=ck_cfg, barrier=barrier, cap_idx=idx,
                frontier=(f_state, f_fok, f_fcr), lossy=lossy_any,
                verified=verified, launches=launches,
                spill_rows=ring.rows_total, spill_bytes=ring.bytes_total,
                spill_spent=spill_spent, result=result,
            )
            return str(p)
        except Exception:  # noqa: BLE001 — recovery aid, not verdict input
            import logging

            logging.getLogger(__name__).warning(
                "couldn't write chunk checkpoint to %s", checkpoint_dir,
                exc_info=True)
            obs.counter("fault.checkpoint.error")
            return None

    def _offset_bounds(start: int) -> list[tuple[int, int]]:
        if start >= B0:
            return []
        rel = _chunk_bounds(quiet[start:], B0 - start, int(chunk_barriers))
        return [(start + a, start + b) for a, b in rel]

    spans = _offset_bounds(start_barrier)
    n_spans0 = len(spans)
    #: the WHOLE history's span count: the default spill budget must be
    #: identical for a resumed and an uninterrupted run (spill_spent is
    #: restored from the checkpoint; a budget recomputed from only the
    #: REMAINING spans would shrink on resume and could flip verdicts)
    n_spans_full = (
        n_spans0 if start_barrier == 0 else len(_offset_bounds(0))
    )
    #: Spill WORK budget: extra launches the spill levers (multi-slice
    #: attempts, chunk bisection, slice narrowing) may spend beyond the
    #: one-launch-per-chunk baseline.  The exact union frontier can be
    #: exponential, so unbounded recovery would trade an unknown for an
    #: unbounded wall clock; when the budget is spent the scan falls
    #: back to the pre-spill truncate-and-latch-lossy behavior and the
    #: final report says so (reason "spill-budget").  The DEFAULT is
    #: deliberately small — a couple of recovery attempts per chunk —
    #: because it rides every escalation/confirmation path in the tier-1
    #: suite; callers with real memory-pressure workloads (the bench
    #: batch offenders) pass ``spill_launches`` explicitly and pair it
    #: with a deadline.  Restored from the chunk checkpoint on resume:
    #: a resumed run must not get FRESH budget, or its verdicts could
    #: diverge from the uninterrupted run's.
    spill_budget = (
        int(spill_launches) if spill_launches is not None
        else 2 * max(1, n_spans_full) + 8
    )
    spill_spent = resume_spill_spent

    def _stats(capacity: int) -> dict:
        s = {
            "frontier-peak": peak_g, "capacity": capacity,
            "lossy?": lossy_any, "chunks": n_spans0, "launches": launches,
            "spill-rows": ring.rows_total, "spill-bytes": ring.bytes_total,
        }
        if factors:
            s["factors"] = factors
        return s

    def _emit(valid, stats: dict) -> None:
        """One telemetry span per chunked run: the frontier-sweep stats the
        beam-search literature instruments (occupancy, loss, escalations)."""
        obs.span_event(
            "wgl.chunked", time.perf_counter() - t0, valid=valid,
            chunks=stats.get("chunks"), launches=stats.get("launches"),
            peak_frontier=stats.get("frontier-peak"),
            capacity=stats.get("capacity"), lossy=stats.get("lossy?"),
            verified_barriers=stats.get("verified-barriers"), dedup=dedup,
            spill_rows=stats.get("spill-rows"),
            spill_bytes=stats.get("spill-bytes"),
            factors=stats.get("factors"),
        )

    def _attach_report(res: dict) -> dict:
        """An unknown that exhausted fixed memory carries the
        machine-readable report — the OOM ladder never lies.  Only the
        GENERIC capacity cause is rewritten to the report rendering: a
        deadline or launch-failure unknown keeps its own cause (and its
        resumable-checkpoint pointer) with the report attached
        alongside under ``"undecidability"``."""
        if exhaust_rep is not None and res.get("valid?") == "unknown":
            res["undecidability"] = exhaust_rep
            if res.get("cause") in (
                    None, "frontier capacity or closure rounds exhausted"):
                res["cause"] = spill_mod.undecidable_cause(exhaust_rep)
        return res

    # Every chunked verdict records at least the scan itself — a clean
    # no-escalation pass must still be distinguishable, in the evidence
    # bundle, from a run that never reached the chunked engine.
    _pv("wgl.chunk.scan", barriers=int(B0), chunks=len(spans),
        capacity=caps[idx], start_barrier=start_barrier)
    si = 0
    while si < len(spans):
        lo, hi = spans[si]
        if deadline is not None and deadline.expired():
            obs.counter("fault.deadline.trip")
            obs.event("fault.deadline", at="wgl-chunk", barrier=lo)
            _pv("fault.deadline", at="wgl-chunk", barrier=lo)
            ck = _save_ck(lo)
            note = f"; resumable checkpoint: {ck}" if ck else ""
            stats = _stats(caps[idx])
            stats["verified-barriers"] = verified
            _emit("unknown", stats)
            return _finish(_attach_report({
                "valid?": "unknown",
                "cause": (
                    "deadline-exceeded: check budget exhausted at barrier "
                    f"{lo}/{B0}{note}"
                ),
                "kernel": stats,
            }))
        Bc = 1 << max(5, (hi - lo - 1).bit_length())

        def padc(a, fill=0):
            out = np.full((Bc,) + a.shape[1:], fill, a.dtype)
            out[: hi - lo] = a[lo:hi]
            return out

        c_args = tuple(
            jnp.asarray(padc(a, fill))
            for a, fill in [
                (packed["bar_active"], False),
                (bar_f, 0), (bar_v1, 0), (bar_v2, 0), (bar_slot, 0),
                (mov_f, 0), (mov_v1, 0), (mov_v2, 0), (mov_open, False),
            ]
        )
        c_grp_open = jnp.asarray(padc(packed["grp_open"]))
        n_in = f_state.shape[0]
        # Climb the entry rung while the carried frontier doesn't fit and
        # a LARGER, budget-usable rung exists (one launch beats many
        # slices; the budget ceiling routes the rest through spill).
        while (idx + 1 < len(caps) and caps[idx] < n_in
               and caps[idx + 1] > caps[idx] and _usable(idx + 1)):
            idx += 1
        trunc = False
        width = None  # entry rows per slice; F on entry, halves on retry
        while True:
            F = caps[idx]
            if width is None:
                width = F
            if spill_on:
                # Slices of ≤ width entry rows, each scanned at the FULL
                # kernel capacity F: width < F buys closure headroom
                # (F/width growth per entry row) — the in-chunk lever
                # between bisection and exhaustion.
                cuts = list(range(0, n_in, width)) or [0]
                if len(cuts) > 1:
                    obs.counter("wgl.chunk.slices", len(cuts))
                    spill_spent += len(cuts) - 1
            else:
                cuts = [0]
            slice_outs = []
            for a in cuts:
                b = min(a + width, n_in) if spill_on else min(a + F, n_in)
                k = max(1, b - a)  # the initial 1-row frontier case
                # k < n_in with a single cut: the carried frontier
                # overflows this capacity (spill=False compatibility
                # path) and live configs are dropped — loss, IF this
                # attempt is the one kept (discarded attempts re-slice
                # the untruncated frontier, so they lose nothing).
                st0 = np.zeros(F, np.int32)
                fo0 = np.zeros((F, W), np.uint32)
                fc0 = np.zeros((F, G), np.int16)
                al0 = np.zeros(F, bool)
                st0[:k] = f_state[a:a + k]
                fo0[:k] = f_fok[a:a + k]
                fc0[:k] = f_fcr[a:a + k]
                al0[: b - a] = True
                try:
                    out = faults.call_with_retry(
                        lambda: _scan_chunk(
                            packed["step"], F, int(rounds), P, G, W, fast,
                            jnp.asarray(st0), jnp.asarray(fo0),
                            jnp.asarray(fc0), jnp.asarray(al0), *c_args,
                            *grp_args, c_grp_open,
                            slot_lane, slot_onehot, dedup=dedup,
                        ),
                        dict(what="wgl.chunk",
                             engine="fast" if fast else "exact",
                             capacity=F, lanes=1),
                    )
                except faults.LaunchFailure as lf:
                    ring.discard()
                    cause = faults.describe(lf.cause)
                    obs.counter("fault.launch.degraded", what="wgl.chunk",
                                capacity=F, lanes=1, error=cause)
                    _pv("fault.launch-degraded", capacity=F, error=cause)
                    stats = _stats(F)
                    stats["verified-barriers"] = verified
                    _emit("unknown", stats)
                    return _finish(_attach_report({
                        "valid?": "unknown",
                        "cause": f"device launch failed: {cause}",
                        "kernel": stats,
                    }))
                launches += 1
                slice_outs.append(out)
            trunc = not spill_on and n_in > F
            # Materialize the per-slice verdict scalars (blocks until
            # that slice's scan finishes; later slices keep computing on
            # the device stream behind it).
            sliced = []
            any_lossy = trunc
            peak_total = 0
            for s, fo, fc, al, failed_at, lossy, peak in slice_outs:
                failed_at, lossy, peak = int(failed_at), bool(lossy), int(peak)
                any_lossy |= lossy
                peak_total += peak
                sliced.append((s, fo, fc, al, failed_at))
            peak_g = max(peak_g, peak_total)
            nxt = idx + 1
            if (any_lossy and nxt < len(caps) and caps[nxt] > caps[idx]
                    and _usable(nxt)):
                obs.counter("wgl.chunk.escalations")
                _pv("chunk.escalation", barrier=lo, to_capacity=caps[nxt])
                ring.discard()
                idx = nxt  # re-run THIS chunk wider, from the same frontier
                width = None
                continue
            width_floor = max(1, (n_in + _MAX_SLICES - 1) // _MAX_SLICES)
            if (any_lossy and spill_on and spill_spent < spill_budget
                    and (hi - lo) == 1 and width > width_floor):
                # Single-barrier floor, still overflowing: narrow the
                # slices (same kernel capacity, fewer entry rows each)
                # before declaring exhaustion — down to the _MAX_SLICES
                # launch bound, where only a near-single config's
                # closure overflowing the budget rung remains, which is
                # undecidable under this memory.
                obs.counter("wgl.chunk.slice_narrowing")
                _pv("chunk.slice-narrowing", barrier=lo)
                ring.discard()
                spill_spent += 1
                width = max(width_floor, width // 2)
                continue
            break
        if (any_lossy and spill_on and spill_spent < spill_budget
                and (hi - lo) > 1):
            # Spill harder before going lossy: bisect the chunk so the
            # frontier is re-checked — and its overflow host-spilled —
            # at the midpoint (preferring a quiet cut, like the original
            # chunking).  Floor: a single barrier.
            ring.discard()
            rel = _chunk_bounds(quiet[lo:hi], hi - lo,
                                max(1, (hi - lo + 1) // 2))
            spans[si:si + 1] = [(lo + a, lo + b) for a, b in rel]
            obs.counter("wgl.chunk.bisections")
            _pv("chunk.bisection", barrier=lo)
            spill_spent += 1
            continue
        if spill_on and spill_spent >= spill_budget:
            # Spill work budget exhausted: the rest of the scan runs in
            # the pre-spill truncation mode; the report names the bound
            # that bit.
            spill_on = False
            _pv("spill.budget-exhausted", barrier=lo)
            if exhaust_rep is None:
                exhaust_rep = spill_mod.undecidability_report(
                    capacity=caps[idx], frontier_rows=n_in,
                    peak_frontier=peak_total, barrier=lo, barriers_total=B0,
                    budget_mb=budget_mb, budget_rows=b_rows,
                    spill_rows=ring.rows_total, spill_bytes=ring.bytes_total,
                    factor_count=factors,
                    device_buffer_bytes=device_buffer_bytes(),
                    reason="spill-budget",
                )
        if any_lossy and exhaust_rep is None:
            # Memory exhaustion: the accepted attempt lost rows — with
            # spill engaged that means a single barrier's closure
            # overflowed the highest budget-usable rung with nothing
            # left to split; in the legacy mode it is plain capacity
            # truncation.  Record the evidence; the scan continues
            # truncated (a surviving frontier still proves True), and
            # any final unknown carries this report.
            # the kernel reports the POST-filter peak; a lossy round by
            # definition overflowed the capacity, so the true closure
            # peak is at least capacity + 1 (the growth-rate evidence)
            exhaust_rep = spill_mod.undecidability_report(
                capacity=caps[idx], frontier_rows=n_in,
                peak_frontier=max(peak_total, caps[idx] + 1),
                barrier=lo, barriers_total=B0,
                budget_mb=budget_mb, budget_rows=b_rows,
                spill_rows=ring.rows_total, spill_bytes=ring.bytes_total,
                factor_count=factors,
                device_buffer_bytes=device_buffer_bytes(),
            )
        lossy_any |= any_lossy
        if trunc:
            obs.counter("wgl.frontier.truncations")
        if obs.observing():
            # Chunk-boundary device-memory sample: the chunked path is
            # the long-history workhorse, and its carried frontier is
            # exactly where resident bytes creep (telemetry-gated — the
            # allocator/live-array walk isn't free).
            db = device_buffer_bytes()
            if db is not None:
                obs.gauge("device.buffer_bytes", db, at="wgl-chunk",
                          barrier=lo)
        # --------------------------------------------------------------
        # Recombine: union the slice survivors.  A single slice fetches
        # directly (its output is already an antichain); multiple slices
        # stream through the host ring — device→host copies started at
        # push, exact LSH-bucketed dedup/domination at the merge.
        # --------------------------------------------------------------
        all_failed = all(f >= 0 for (_s, _fo, _fc, _al, f) in sliced)
        if all_failed:
            gb = lo + max(f for (_s, _fo, _fc, _al, f) in sliced)
            op_pos = int(packed["bar_opid"][gb])
            op = history[op_pos]
            stats = _stats(caps[idx])
            stats["bar-opid"] = op_pos  # positional id for stop_at_index
            stats["verified-barriers"] = verified
            # barriers the frontier survived carry a constructive witness
            # (prefix-True), loss or not — death at gb means gb barriers
            # were witnessed
            stats["witnessed-barriers"] = gb
            if lossy_any:
                _pv("chunk.lossy-death", barrier=gb)
                _emit("unknown", stats)
                return _finish(_attach_report({
                    "valid?": "unknown",
                    "cause": "frontier capacity or closure rounds exhausted",
                    "op": op,
                    "kernel": stats,
                }))
            _pv("chunk.refuted", barrier=gb,
                provisional=bool(fast))
            res = {"valid?": False, "op": op, "kernel": stats}
            if fast:
                res["provisional?"] = True  # hash-decided kills
            _emit(False, stats)
            return _finish(res)
        if not lossy_any:
            verified = hi
        if len(sliced) == 1:
            s, fo, fc, al, _f = sliced[0]
            al_h = np.asarray(al)
            sel = np.flatnonzero(al_h)
            f_state = np.asarray(s)[sel]
            f_fok = np.asarray(fo)[sel]
            f_fcr = np.asarray(fc)[sel]
        else:
            for s, fo, fc, al, f in sliced:
                if f < 0:  # dead slices contribute no rows
                    ring.push(s, fo, fc, al)
            popped = ring.pop_all()
            f_state, f_fok, f_fcr, _mstats = spill_mod.merge_frontiers(
                [popped] if popped is not None else [])
        rows = int(f_state.shape[0])
        if rows > host_rows_max:
            # Host budget exceeded: exact union tracking would now cost
            # more memory/launches than the configured bound — truncate
            # (candidate order: the most-speculative rows drop first),
            # latch loss, and record the evidence.  True stays sound.
            if exhaust_rep is None:
                exhaust_rep = spill_mod.undecidability_report(
                    capacity=caps[idx], frontier_rows=rows,
                    peak_frontier=peak_g, barrier=hi, barriers_total=B0,
                    budget_mb=budget_mb, budget_rows=b_rows,
                    spill_rows=ring.rows_total, spill_bytes=ring.bytes_total,
                    factor_count=factors,
                    device_buffer_bytes=device_buffer_bytes(),
                    reason="host-budget",
                )
            obs.counter("wgl.frontier.truncations")
            _pv("frontier.truncated", reason="host-budget", barrier=hi)
            f_state = f_state[:host_rows_max]
            f_fok = f_fok[:host_rows_max]
            f_fcr = f_fcr[:host_rows_max]
            lossy_any = True
            rows = host_rows_max
        if (idx > 0 and peak_total * 4 <= caps[idx - 1]
                and rows <= caps[idx - 1]):
            idx -= 1
        _save_ck(hi)
        si += 1
    stats = _stats(caps[idx])
    stats["verified-barriers"] = verified
    stats["witnessed-barriers"] = B0  # the survivor IS the whole-history witness
    _emit(True, stats)
    result = _finish({"valid?": True, "kernel": stats})
    _save_ck(B0, result=result)
    return result


def scan_barrier_range(
    packed: dict,
    frontier: tuple,
    lo: int,
    hi: int,
    *,
    capacities: Sequence[int],
    rounds: int = 8,
    chunk_barriers: int = 512,
    cap_idx: int = 0,
    lossy: bool = False,
    fast: bool = False,
    dedup_backend: str | None = None,
    spill: bool = False,
    on_event=None,
) -> dict:
    """Advance a carried frontier through barriers ``[lo, hi)`` of an
    already-padded pack — chunked_analysis's scan loop factored out so
    an INCREMENTAL caller (checker.streaming's per-epoch advance) can
    extend a running scan range by range instead of owning the whole
    history up front.

    ``packed`` must be ``pad_packed`` output with ``B`` kept at the true
    barrier count (the chunked-path convention) so ``lo``/``hi`` index
    real barriers; ``frontier`` is the carried ``(state, fok, fcr)``
    host arrays in the pack's padded ``(W, G)`` shapes.  Chunk cuts, the
    ``Bc`` padding rule, the capacity-escalation ladder, the dedup
    backend resolution, and the launch retry policy are all
    chunked_analysis's own — an epoch advance compiles no kernel
    geometry the post-hoc chunked path wouldn't.

    Returns a dict::

        frontier        surviving (state, fok, fcr), alive rows compacted
        failed_barrier  GLOBAL barrier index the frontier died at
                        (None = survived to ``hi``)
        cap_idx, lossy  adapted ladder position / latched loss flag —
                        thread them back into the next call
        launches, peak  accounting deltas for the caller's stats
        error           launch-failure cause string (scan aborted; the
                        caller degrades this range to unknown) or None

    Soundness is chunked_analysis's: death with ``lossy`` False refutes
    at ``failed_barrier`` exactly (content-decided kills when ``fast``
    is False); once any loss has latched, a death only means "unknown".
    ``spill`` slices an overflowing ENTRY frontier through the same
    kernel in ≤capacity-row slices and merges the survivors exactly
    (scan linearity — refutation then requires EVERY slice to die);
    without it overflow truncates and latches ``lossy``.

    ``on_event`` (optional callable ``(event, **attrs)``) receives the
    escalation/truncation events the chunked path would log to its
    decision-path trajectory, so the caller can record them under its
    own provenance prefix.
    """
    from jepsen_tpu.ops import spill as spill_mod

    dedup = resolve_dedup_backend(dedup_backend)
    caps = [int(c) for c in capacities]
    P, G, W = packed["P"], packed["G"], packed["W"]
    quiet = packed["bar_quiet"]
    bar_f, bar_v1, bar_v2, bar_slot = packed["bar"]
    mov_f, mov_v1, mov_v2, mov_open = packed["mov"]
    slot_lane = jnp.asarray(packed["slot_lane"])
    slot_onehot = jnp.asarray(packed["slot_onehot"])
    grp_args = tuple(jnp.asarray(a) for a in packed["grp"])

    f_state = np.asarray(frontier[0], np.int32)
    f_fok = np.asarray(frontier[1], np.uint32)
    f_fcr = np.asarray(frontier[2], np.int16)
    idx = min(max(int(cap_idx), 0), len(caps) - 1)
    lossy_any = bool(lossy)
    launches = 0
    peak_g = 0

    def _ev(event: str, **attrs) -> None:
        if on_event is not None:
            on_event(event, **attrs)

    def _out(failed=None, error=None):
        return {
            "frontier": (f_state, f_fok, f_fcr),
            "failed_barrier": failed, "cap_idx": idx, "lossy": lossy_any,
            "launches": launches, "peak": peak_g, "error": error,
        }

    if hi <= lo:
        return _out()
    spans = [
        (lo + a, lo + b)
        for a, b in _chunk_bounds(quiet[lo:hi], hi - lo, int(chunk_barriers))
    ]
    for clo, chi in spans:
        Bc = 1 << max(5, (chi - clo - 1).bit_length())

        def padc(a, fill=0):
            out = np.full((Bc,) + a.shape[1:], fill, a.dtype)
            out[: chi - clo] = a[clo:chi]
            return out

        c_args = tuple(
            jnp.asarray(padc(a, fill))
            for a, fill in [
                (packed["bar_active"], False),
                (bar_f, 0), (bar_v1, 0), (bar_v2, 0), (bar_slot, 0),
                (mov_f, 0), (mov_v1, 0), (mov_v2, 0), (mov_open, False),
            ]
        )
        c_grp_open = jnp.asarray(padc(packed["grp_open"]))
        n_in = f_state.shape[0]
        while (idx + 1 < len(caps) and caps[idx] < n_in
               and caps[idx + 1] > caps[idx]):
            idx += 1
        while True:
            F = caps[idx]
            cuts = list(range(0, n_in, F)) if spill and n_in > F else [0]
            slice_outs = []
            for a in cuts:
                b = min(a + F, n_in)
                k = max(1, b - a)  # the initial 1-row frontier case
                st0 = np.zeros(F, np.int32)
                fo0 = np.zeros((F, W), np.uint32)
                fc0 = np.zeros((F, G), np.int16)
                al0 = np.zeros(F, bool)
                st0[:k] = f_state[a:a + k]
                fo0[:k] = f_fok[a:a + k]
                fc0[:k] = f_fcr[a:a + k]
                al0[: b - a] = True
                try:
                    o = faults.call_with_retry(
                        lambda: _scan_chunk(
                            packed["step"], F, int(rounds), P, G, W, fast,
                            jnp.asarray(st0), jnp.asarray(fo0),
                            jnp.asarray(fc0), jnp.asarray(al0), *c_args,
                            *grp_args, c_grp_open,
                            slot_lane, slot_onehot, dedup=dedup,
                        ),
                        dict(what="wgl.chunk",
                             engine="fast" if fast else "exact",
                             capacity=F, lanes=1),
                    )
                except faults.LaunchFailure as lf:
                    cause = faults.describe(lf.cause)
                    obs.counter("fault.launch.degraded", what="wgl.chunk",
                                capacity=F, lanes=1, error=cause)
                    _ev("launch-degraded", capacity=F, error=cause)
                    return _out(error=cause)
                launches += 1
                slice_outs.append(o)
            trunc = not spill and n_in > F
            sliced = []
            any_lossy = trunc
            peak_total = 0
            for s, fo, fc, al, failed_at, sl, peak in slice_outs:
                failed_at, sl, peak = int(failed_at), bool(sl), int(peak)
                any_lossy |= sl
                peak_total += peak
                sliced.append((s, fo, fc, al, failed_at))
            peak_g = max(peak_g, peak_total)
            nxt = idx + 1
            if any_lossy and nxt < len(caps) and caps[nxt] > caps[idx]:
                obs.counter("wgl.chunk.escalations")
                _ev("escalation", barrier=clo, to_capacity=caps[nxt])
                idx = nxt  # re-run THIS chunk wider, from the same frontier
                continue
            break
        lossy_any |= any_lossy
        if trunc:
            obs.counter("wgl.frontier.truncations")
            _ev("truncated", barrier=clo)
        all_failed = all(f >= 0 for (_s, _fo, _fc, _al, f) in sliced)
        if all_failed:
            gb = clo + max(f for (_s, _fo, _fc, _al, f) in sliced)
            return _out(failed=gb)
        if len(sliced) == 1:
            s, fo, fc, al, _f = sliced[0]
            sel = np.flatnonzero(np.asarray(al))
            f_state = np.asarray(s)[sel]
            f_fok = np.asarray(fo)[sel]
            f_fcr = np.asarray(fc)[sel]
        else:
            ring = spill_mod.HostRing(W, G)
            for s, fo, fc, al, f in sliced:
                if f < 0:  # dead slices contribute no rows
                    ring.push(s, fo, fc, al)
            popped = ring.pop_all()
            f_state, f_fok, f_fcr, _mstats = spill_mod.merge_frontiers(
                [popped] if popped is not None else [])
        rows = int(f_state.shape[0])
        if (idx > 0 and peak_total * 4 <= caps[idx - 1]
                and rows <= caps[idx - 1]):
            idx -= 1
    return _out()


def analysis(
    model: m.Model,
    history: Sequence[dict],
    capacity: int | Sequence[int] = (128, 1024, 4096),
    rounds: int = 8,
    max_groups: int = 64,
    max_procs: int = 128,
    chunk_barriers: int = 512,
    fast: bool = False,
    dedup_backend: str | None = None,
    deadline=None,
    spill: bool | None = None,
    frontier_budget_mb: float | None = None,
    spill_factor: float = 4.0,
    spill_launches: int | None = None,
    factor_groups: bool | None = None,
    checkpoint_dir=None,
    resume: bool = False,
) -> dict:
    """Decide linearizability on the accelerator.

    Knossos-shaped result: ``{"valid?": True|False|"unknown", ...}`` plus
    kernel stats under ``"kernel"``.  True is always exact; False is exact
    unless the frontier overflowed (then "unknown").

    ``capacity`` may be a sequence: the per-chunk escalation ladder.  The
    history is scanned as a chain of ≤ ``chunk_barriers``-barrier chunk
    programs with the frontier carried between them (chunked_analysis):
    easy stretches stay on the small, fast frontier; branch-heavy chunks
    re-run at the next capacity — knossos-style competition, but against
    frontier sizes instead of algorithms, and at chunk granularity
    instead of whole-history granularity.
    """
    try:
        packed = pack(model, history)
    except NotTensorizable as e:
        return {"valid?": "unknown", "cause": f"not tensorizable: {e}"}
    if packed["B"] == 0:
        return {"valid?": True, "configs": [{"model": model}]}
    if packed["G"] > max_groups:
        return {"valid?": "unknown", "cause": f"{packed['G']} crashed-op groups exceeds {max_groups}"}
    if packed["P"] > max_procs:
        return {"valid?": "unknown", "cause": f"{packed['P']} process slots exceeds {max_procs}"}
    capacities = [capacity] if isinstance(capacity, int) else list(capacity)
    return chunked_analysis(
        model, history, packed, capacities, rounds, chunk_barriers, fast=fast,
        dedup_backend=dedup_backend, deadline=deadline, spill=spill,
        frontier_budget_mb=frontier_budget_mb, spill_factor=spill_factor,
        spill_launches=spill_launches, factor_groups=factor_groups,
        checkpoint_dir=checkpoint_dir, resume=resume,
    )


# ---------------------------------------------------------------------------
# Async-tick kernel: configs carry their own barrier index
# ---------------------------------------------------------------------------


def async_ticks(B: int, capacity: int | None = None) -> int:
    """Tick budget for the lane-async kernel.  Exceeding it flags lossy
    and escalates, so the cost of a low guess is a wasted stage, never a
    wrong verdict.

    Wide stages (capacity ≥ 1024) get ~2 closure rounds per barrier plus
    slack — the deep-closure work happens there (measured: the final 7
    ladder resolutions need the full budget; 4B+128 resolves nothing
    more).  Narrow stages get 1.5 rounds per barrier: their lanes either
    converge fast or escalate anyway, and the vmapped while_loop runs
    until the SLOWEST lane finishes, so budget-burning lossy lanes
    dictate the stage wall clock (measured ~8% off the full ladder at
    equal verdicts)."""
    if capacity is not None and capacity < 1024:
        return (3 * B) // 2 + 32
    return 2 * B + 64


def _run_core_async(
    step,
    F: int,
    T: int,
    B: int,
    P: int,
    G: int,
    W: int,
    bptr0,
    state0,
    fok0,
    fcr0,
    alive0,
    n_active,
    bar_f,
    bar_v1,
    bar_v2,
    bar_slot,
    mov_f,
    mov_v1,
    mov_v2,
    mov_open,
    grp_f,
    grp_v1,
    grp_v2,
    grp_open,
    slot_lane,
    slot_onehot,
    dedup: str = "sort",
):
    """Lane-asynchronous barrier stepping.

    The barrier-scan kernel (_run_core) closes each barrier to fixpoint
    inside a while_loop — under vmap every lane pays the MAX closure
    depth of any lane at every barrier (Σ_b max_lanes r_b).  Here the
    whole search is ONE scan of ``T`` uniform ticks: each tick runs one
    closure round at the lane's own current barrier; when the round
    reaches the closure fixpoint (no expansion survives content-
    confirmed dedup — an exact no-growth signal), the barrier's return
    filter applies and the lane's barrier pointer advances.  A barrier
    whose frontier is already closed advances in ONE tick.  Lanes drift
    apart freely, so the cost is max_lanes(Σ_b r_b) — each lane's own
    total closure depth.

    Semantics (and the soundness contract) are exactly _run_core's:
    same move algebra, same per-barrier filter, True only via a
    surviving frontier, False only when no loss occurred, tick-budget
    exhaustion or overflow → lossy → "unknown".

    CARRIED-FRONTIER RESUME (round 5): the search starts from an explicit
    (bptr0, frontier) instead of (0, single-config) — the escalation
    ladder resumes each straggler at its failure point instead of
    re-running the whole history wider.  The kernel returns, besides the
    verdict, a RESUME SNAPSHOT: the frontier as it stood at tick entry of
    the FIRST overflowing tick (exact — no loss has occurred yet — and a
    superset of that barrier's entry frontier, so re-closing from it at a
    wider capacity reaches the identical closure), or the final carry
    when no overflow happened (budget exhaustion; also exact).  A lane
    resumed from an exact snapshot with a fresh ``lossy`` latch keeps
    full refutation power: False still means "no loss anywhere on the
    accepted path".
    """
    eye_g = jnp.eye(G, dtype=I16)
    slot_mask = slot_onehot.sum(axis=1)

    def tick(carry):
        (t, bptr, state, fok, fcr, alive, failed_at, lossy, peak,
         snapped, bsnap, sst, sfo, sfc, sal) = carry
        bc = jnp.clip(bptr, 0, B - 1)
        done = (bptr >= n_active) | (failed_at >= 0)
        # One closure round at barrier bptr.
        cat_state, cat_fok, cat_fcr, cat_alive, cost = expand_candidates(
            step, eye_g, slot_lane, slot_mask, slot_onehot,
            state, fok, fcr, alive,
            mov_f[bc], mov_v1[bc], mov_v2[bc], mov_open[bc],
            grp_f, grp_v1, grp_v2, grp_open[bc],
        )
        s2, fo2, fc2, a2, ovf, _fp, child = frontier_update_fast(
            cat_state, cat_fok, cat_fcr, cat_alive, cost, F, n_parents=F,
            max_count=mov_f.shape[-1] + 1, dedup_backend=dedup,
        )
        # First overflow: snapshot the PRE-update frontier (exact: lossy
        # is still False) for the next ladder rung to resume from.
        take = ovf & ~snapped & ~lossy & ~done
        snapped2 = snapped | take
        bsnap2 = jnp.where(take, bc, bsnap)
        sst2 = jnp.where(take, state, sst)
        sfo2 = jnp.where(take, fok, sfo)
        sfc2 = jnp.where(take, fcr, sfc)
        sal2 = jnp.where(take, alive, sal)
        # frontier_update_fast domination-prunes its own 2C buffer, so a2
        # already marks a duplicate-free antichain (the "+5 resolved
        # histories at cap 128" benefit lives there) — no outer prune
        # (advisor r3: the doubled prune bought zero alive change).
        stable = ~(a2 & child).any()
        # At the fixpoint: only configs that fired the returning op
        # survive; its slot bit retires; the barrier pointer advances.
        lane = bar_slot[bc] // 32
        bitmask = U32(1) << (bar_slot[bc] % 32).astype(U32)
        lane_vals = jnp.take_along_axis(fo2, jnp.full((F, 1), lane), axis=1)[:, 0]
        a3 = a2 & ((lane_vals & bitmask) != 0)
        clear = jnp.where(jnp.arange(W) == lane, bitmask, U32(0))
        fo3 = fo2 & ~clear[None, :]
        # The return filter subsets an antichain and the uniform bit clear
        # preserves it (all survivors held the bit), so a3/fo3 need no
        # reaping; they are used only on the ticks that advance.
        adv = stable & ~done
        state2 = jnp.where(done, state, s2)
        fok2 = jnp.where(done[None], fok, jnp.where(adv, fo3, fo2))
        fcr2 = jnp.where(done, fcr, fc2)
        alive2 = jnp.where(done, alive, jnp.where(adv, a3, a2))
        failed2 = jnp.where(adv & ~a3.any() & ~lossy, bc, failed_at)
        # a lossy lane can't refute: record no failure, report unknown
        failed2 = jnp.where(adv & ~a3.any() & lossy, jnp.int32(B + 1), failed2)
        bptr2 = jnp.where(adv, bptr + 1, bptr)
        lossy2 = lossy | (ovf & ~done)
        peak2 = jnp.maximum(peak, alive2.sum())
        return (t + 1, bptr2, state2, fok2, fcr2, alive2, failed2, lossy2,
                peak2, snapped2, bsnap2, sst2, sfo2, sfc2, sal2)

    def cont(carry):
        t, bptr, _s, _fo, _fc, _a, failed_at = carry[:7]
        running = (bptr < n_active) & (failed_at < 0)
        return (t < T) & running

    carry0 = (jnp.int32(0), jnp.asarray(bptr0, I32), state0, fok0, fcr0,
              alive0, jnp.int32(-1), jnp.bool_(False),
              jnp.maximum(alive0.sum(), 1).astype(I32),
              jnp.bool_(False), jnp.asarray(bptr0, I32),
              state0, fok0, fcr0, alive0)
    (_t, bptr, state, fok, fcr, alive, failed_at, lossy, peak,
     snapped, bsnap, sst, sfo, sfc, sal) = jax.lax.while_loop(
        cont, tick, carry0
    )
    finished = bptr >= n_active
    valid = finished & alive.any()
    # Budget exhaustion (neither finished nor failed) is loss.
    lossy_out = lossy | (~finished & (failed_at < 0)) | (failed_at > B)
    failed_out = jnp.where(failed_at > B, jnp.int32(-1), failed_at)
    # No overflow snapshot -> resume from the final carry (exact: the
    # lane simply ran out of ticks mid-search).
    bsnap = jnp.where(snapped, bsnap, bptr)
    sst = jnp.where(snapped, sst, state)
    sfo = jnp.where(snapped, sfo, fok)
    sfc = jnp.where(snapped, sfc, fcr)
    sal = jnp.where(snapped, sal, alive)
    return valid, failed_out, lossy_out, peak, bsnap, sst, sfo, sfc, sal


_run_async = functools.partial(
    jax.jit, static_argnames=("step", "F", "T", "B", "P", "G", "W", "dedup")
)(_run_core_async)

#: (step, F, T, B, P, G, W, dedup) -> jitted vmapped async runner.
_ASYNC_RUNNERS: dict = {}


def async_runner(step, F: int, T: int, B: int, P: int, G: int, W: int,
                 dedup: str = "sort"):
    """jit(vmap(_run_core_async)) — the batched async-tick checker.

    Batched inputs (leading lane axis): bptr0, state0, fok0, fcr0,
    alive0 (the resume frontier — see fresh_frontier for stage one),
    n_active, then the 12 barrier/mover/group tables; slot tables
    broadcast.  ``dedup`` selects the per-round dedup backend."""
    key = (step, F, T, B, P, G, W, dedup)
    _cache_counter(_ASYNC_RUNNERS, key, "async")
    if key not in _ASYNC_RUNNERS:
        core = functools.partial(
            _run_core_async, step, F, T, B, P, G, W, dedup=dedup
        )
        axes = (0,) * 18 + (None, None)
        _ASYNC_RUNNERS[key] = jax.jit(jax.vmap(core, in_axes=axes))
    return _ASYNC_RUNNERS[key]


def fresh_frontier(n: int, F: int, W: int, G: int, init_states):
    """Stage-one resume inputs for ``n`` lanes: barrier 0, one alive
    config per lane holding the lane's initial state."""
    bptr0 = np.zeros(n, np.int32)
    state0 = np.zeros((n, F), np.int32)
    state0[:] = np.asarray(init_states, np.int32)[:, None]
    fok0 = np.zeros((n, F, W), np.uint32)
    fcr0 = np.zeros((n, F, G), np.int16)
    alive0 = np.zeros((n, F), bool)
    alive0[:, 0] = True
    return bptr0, state0, fok0, fcr0, alive0


def pad_resume(resume, F: int, W: int, G: int):
    """Re-bucket one lane's saved (bsnap, state, fok, fcr, alive) resume
    frontier to the next stage's (F, W, G).  Growing pads with dead rows
    / zero columns; shrinking is safe because a history's own slots and
    groups always fit its OWN (P, G) — bucket padding beyond them is
    never set (see pad_packed)."""
    bsnap, st, fo, fc, al = resume
    F0, W0 = fo.shape
    G0 = fc.shape[1]
    n_alive = int(al.sum())
    assert n_alive <= F, f"resume frontier {n_alive} exceeds capacity {F}"
    out_st = np.zeros(F, np.int32)
    out_fo = np.zeros((F, W), np.uint32)
    out_fc = np.zeros((F, G), np.int16)
    out_al = np.zeros(F, bool)
    k = min(F0, F)
    out_st[:k] = st[:k]
    out_fo[:k, : min(W0, W)] = fo[:k, : min(W0, W)]
    out_fc[:k, : min(G0, G)] = fc[:k, : min(G0, G)]
    out_al[:k] = al[:k]
    if F < F0 and al[F:].any():
        # compact alive rows first instead of truncating live configs
        sel = np.flatnonzero(al)[:F]
        out_st[: len(sel)] = st[sel]
        out_fo[: len(sel), : min(W0, W)] = fo[sel][:, : min(W0, W)]
        out_fc[: len(sel), : min(G0, G)] = fc[sel][:, : min(G0, G)]
        out_al[:] = False
        out_al[: len(sel)] = True
    return int(bsnap), out_st, out_fo, out_fc, out_al


# ---------------------------------------------------------------------------
# Greedy witness walk: one config, fire-returning-op-first, one-enabler
# lookahead — the device-side equivalent of the CPU DFS's greedy path
# ---------------------------------------------------------------------------


def _greedy_core(
    step,
    B: int,
    P: int,
    G: int,
    W: int,
    init_state,
    n_active,
    bar_f,
    bar_v1,
    bar_v2,
    bar_slot,
    mov_f,
    mov_v1,
    mov_v2,
    mov_open,
    grp_f,
    grp_v1,
    grp_v2,
    grp_open,
    slot_lane,
    slot_onehot,
):
    """Walk ONE configuration through all barriers, greedily.

    The CPU DFS resolves valid histories by its greedy path — fire the
    returning op first, backtracking only when stuck
    (wgl_cpu.dfs_analysis; knossos's observation that valid histories
    "usually walk straight through").  This kernel is that path as a
    fixed-shape ``lax.scan``: per barrier, fire the returning op
    directly if legal, else fire ONE enabling move (an open ok op or a
    crashed-group op) whose step makes the returning op legal — a
    two-step lookahead over all P+G movers, evaluated as one vectorized
    step batch — else the walk is STUCK and escalates.

    Every applied transition is legal, so completion is a constructive
    witness: ``True`` is exact.  The walk never refutes — stuck means
    "unknown", it proves nothing (a frontier/DFS engine decides).  Cost
    is O(B·(P+G)) scalar step evaluations with no frontier buffers at
    all — the cheapest possible first rung, and the shape that resolves
    BASELINE config 2 (10k-op valid register) on-device.

    Returns (finished, stuck_at, fired_crashed_total):
    ``stuck_at`` = barrier index where the walk stuck (-1 = never).
    """
    slot_mask = slot_onehot.sum(axis=1)  # [P] uint32 in-lane bit
    p_iota = jnp.arange(P, dtype=I32)

    def barrier(carry, xs):
        state, fok, fcr, stuck_at = carry
        b_idx, bf, bv1, bv2, bslot, mf, mv1, mv2, mopen, gopen = xs
        done = (stuck_at >= 0) | (b_idx >= n_active)
        lane = bslot // 32
        bit = (U32(1) << (bslot % 32).astype(U32))
        has_bit = (fok[lane] & bit) != 0
        # Case A: already fired as an earlier barrier's enabler — retire.
        # Case B: direct fire.
        s1, legal1 = step(state, bf, bv1, bv2)
        # Case C: one enabling open ok op, then the returning op.
        already = (jnp.take(fok, slot_lane) & slot_mask) != 0  # [P]
        ps2, plegal = step(state, mf, mv1, mv2)
        ps3, plegal3 = step(ps2, bf, bv1, bv2)
        pcand = plegal & plegal3 & mopen & ~already & (p_iota != bslot)
        # Case D: one enabling crashed-group op, then the returning op.
        gs2, glegal = step(state, grp_f, grp_v1, grp_v2)
        gs3, glegal3 = step(gs2, bf, bv1, bv2)
        gcand = glegal & glegal3 & (fcr < gopen) & (gs2 != state)
        p_any = pcand.any()
        g_any = gcand.any()
        p_idx = jnp.argmax(pcand)
        g_idx = jnp.argmax(gcand)
        clear = jnp.where(jnp.arange(W) == lane, bit, U32(0))
        # Priority: A (no step) > B (direct) > C (ok enabler) > D (group).
        new_state = jnp.where(
            has_bit, state,
            jnp.where(legal1, s1,
                      jnp.where(p_any, ps3[p_idx], gs3[g_idx])))
        new_fok = jnp.where(
            has_bit, fok & ~clear,
            jnp.where(legal1, fok,
                      jnp.where(p_any, fok | slot_onehot[p_idx], fok)))
        new_fcr = jnp.where(
            ~has_bit & ~legal1 & ~p_any & g_any,
            fcr + (jnp.arange(G) == g_idx).astype(I16), fcr)
        ok = has_bit | legal1 | p_any | g_any
        stuck2 = jnp.where(~done & ~ok, b_idx, stuck_at)
        keep = done | ~ok
        state2 = jnp.where(keep, state, new_state)
        fok2 = jnp.where(keep, fok, new_fok)
        fcr2 = jnp.where(keep, fcr, new_fcr)
        return (state2, fok2, fcr2, stuck2), None

    carry0 = (
        jnp.asarray(init_state, I32),
        jnp.zeros(W, U32),
        jnp.zeros(G, I16),
        jnp.int32(-1),
    )
    xs = (
        jnp.arange(B, dtype=I32), bar_f, bar_v1, bar_v2, bar_slot,
        mov_f, mov_v1, mov_v2, mov_open, grp_open,
    )
    (state, fok, fcr, stuck_at), _ = jax.lax.scan(barrier, carry0, xs)
    finished = stuck_at < 0
    return finished, stuck_at, fcr.sum().astype(I32)


_greedy = functools.partial(
    jax.jit, static_argnames=("step", "B", "P", "G", "W")
)(_greedy_core)

#: (step, B, P, G, W) -> jitted vmapped greedy runner.
_GREEDY_RUNNERS: dict = {}


def greedy_runner(step, B: int, P: int, G: int, W: int):
    """jit(vmap(_greedy_core)) — the batched greedy witness walk."""
    key = (step, B, P, G, W)
    _cache_counter(_GREEDY_RUNNERS, key, "greedy")
    if key not in _GREEDY_RUNNERS:
        core = functools.partial(_greedy_core, step, B, P, G, W)
        axes = (0,) * 14 + (None, None)
        _GREEDY_RUNNERS[key] = jax.jit(jax.vmap(core, in_axes=axes))
    return _GREEDY_RUNNERS[key]


def greedy_analysis(
    model: m.Model,
    history: Sequence[dict],
    max_groups: int = 64,
    max_procs: int = 128,
) -> dict:
    """Single-history greedy witness walk.  ``True`` (with a witness) or
    ``"unknown"`` — never ``False`` (see _greedy_core)."""
    try:
        packed = pack(model, history)
    except NotTensorizable as e:
        return {"valid?": "unknown", "cause": f"not tensorizable: {e}"}
    if packed["B"] == 0:
        return {"valid?": True}
    if packed["G"] > max_groups:
        return {"valid?": "unknown", "cause": f"{packed['G']} crashed-op groups exceeds {max_groups}"}
    if packed["P"] > max_procs:
        return {"valid?": "unknown", "cause": f"{packed['P']} process slots exceeds {max_procs}"}
    n_active = int(packed["bar_active"].sum())
    packed = pad_packed(packed)
    finished, stuck_at, fired = _greedy(
        packed["step"],
        packed["B"],
        packed["P"],
        packed["G"],
        packed["W"],
        packed["init_state"],
        np.int32(n_active),
        *packed["bar"],
        *packed["mov"],
        *packed["grp"],
        packed["grp_open"],
        jnp.asarray(packed["slot_lane"]),
        jnp.asarray(packed["slot_onehot"]),
    )
    stats = {"engine": "greedy", "fired-crashed": int(fired)}
    if bool(finished):
        return {"valid?": True, "kernel": stats}
    return {
        "valid?": "unknown",
        "cause": "greedy walk stuck (no single-enabler move)",
        "op": history[int(packed["bar_opid"][int(stuck_at)])],
        "kernel": {**stats, "stuck-at": int(stuck_at)},
    }


def analysis_async(
    model: m.Model,
    history: Sequence[dict],
    capacity: int = 128,
    ticks: int | None = None,
    max_groups: int = 64,
    max_procs: int = 128,
    dedup_backend: str | None = None,
) -> dict:
    """Single-history front-end for the async-tick kernel (testing and
    shape exploration; the batched path drives async_runner directly)."""
    dedup = resolve_dedup_backend(dedup_backend)
    try:
        packed = pack(model, history)
    except NotTensorizable as e:
        return {"valid?": "unknown", "cause": f"not tensorizable: {e}"}
    if packed["B"] == 0:
        return {"valid?": True}
    if packed["G"] > max_groups:
        return {"valid?": "unknown", "cause": f"{packed['G']} crashed-op groups exceeds {max_groups}"}
    if packed["P"] > max_procs:
        return {"valid?": "unknown", "cause": f"{packed['P']} process slots exceeds {max_procs}"}
    n_active = int(packed["bar_active"].sum())
    packed = pad_packed(packed)
    B = packed["B"]
    T = int(ticks) if ticks is not None else async_ticks(B)
    F, W, G = int(capacity), packed["W"], packed["G"]
    t0 = time.perf_counter()
    bptr0, st0, fo0, fc0, al0 = fresh_frontier(
        1, F, W, G, [packed["init_state"]]
    )
    valid, failed_at, lossy, peak, _bs, _st, _fo, _fc, _al = _run_async(
        packed["step"],
        F,
        T,
        B,
        packed["P"],
        G,
        W,
        bptr0[0],
        st0[0],
        fo0[0],
        fc0[0],
        al0[0],
        np.int32(n_active),
        *packed["bar"],
        *packed["mov"],
        *packed["grp"],
        packed["grp_open"],
        jnp.asarray(packed["slot_lane"]),
        jnp.asarray(packed["slot_onehot"]),
        dedup=dedup,
    )
    valid = bool(valid)
    failed_at = int(failed_at)
    lossy = bool(lossy)
    stats = {"frontier-peak": int(peak), "capacity": int(capacity), "ticks": T, "lossy?": lossy}
    obs.span_event(
        "wgl.async", time.perf_counter() - t0, valid=valid, lossy=lossy,
        peak_frontier=int(peak), capacity=int(capacity), ticks=T, dedup=dedup,
    )
    if valid:
        return {"valid?": True, "kernel": stats}
    if not lossy:
        op = None
        if 0 <= failed_at < len(packed["bar_opid"]):
            op_pos = int(packed["bar_opid"][failed_at])
            op = history[op_pos]
            # POSITIONAL id (invoke position in the history, the identity
            # sweep_analysis's stop_at_index matches) — op.get("index") is
            # a user-facing field that may differ on unindexed or
            # re-indexed histories (advisor r4).
            stats["bar-opid"] = op_pos
        return {"valid?": False, "op": op, "kernel": stats}
    return {
        "valid?": "unknown",
        "cause": "frontier capacity or tick budget exhausted",
        "kernel": stats,
    }
