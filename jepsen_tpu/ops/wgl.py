"""Frontier-parallel Wing–Gong–Lowe linearizability search on TPU.

This is the rebuild's Knossos replacement (BASELINE.json north star): the
configuration-set sweep of jepsen_tpu.checker.wgl_cpu.sweep_analysis,
vectorized.  Where the JVM checker walks configurations one at a time with
a DFS stack, this kernel advances the *entire frontier* of configurations
through each return barrier as fixed-shape tensor ops under one jit'd
lax.scan — breadth-parallelism instead of backtracking.

Data layout (all static shapes; F = frontier capacity, P = process slots,
G = crashed-op groups, W = ⌈P/32⌉ bitset lanes, B = barriers):

  frontier:  state[F] int32 · fok[F,W] uint32 (fired-open-op bitset by
             process slot) · fcr[F,G] int32 (fired count per crashed
             group) · alive[F] bool
  barriers:  per-barrier op (f,v1,v2,slot), per-slot open-op table
             (mov_*[B,P]), per-group open counts (grp_open[B,G])

Per barrier: a bounded closure loop (lax.while_loop, ≤R rounds) expands
every config by every legal move — firing any open ok op (process move) or
one crashed op from any group (group move) — then dedups by 96-bit row
hash and compacts to capacity keeping fewest-fired configs first
(sort-based, jepsen_tpu.ops.hashing).  Then configs that fired the
returning op survive; the op's slot bit is cleared and the scan advances.

Soundness contract (SURVEY.md §7 hard-part #1: "never a wrong verdict"):
any transition applied is legal, so a surviving frontier is a constructive
witness — ``True`` is always sound, truncated or not.  ``False`` is only
reported when no capacity/round/collision loss occurred anywhere
(``lossy`` flag); otherwise the kernel answers ``"unknown"`` and the
``competition`` front-end falls back to the CPU oracle.

The same structural optimizations as the CPU sweep apply: crashed-op
canonicalization into (f, value) groups, and fewest-fired-first compaction
(domination order) under truncation.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np

import jax
import jax.numpy as jnp

from jepsen_tpu import history as h
from jepsen_tpu import models as m
from jepsen_tpu.checker import wgl_cpu
from jepsen_tpu.models import tensor as tmodels
from jepsen_tpu.ops.hashing import compact, dominate, hash_rows

I32 = jnp.int32
U32 = jnp.uint32


class NotTensorizable(Exception):
    """History/model can't be packed for the kernel (exotic model, f, or
    value vocabulary); callers fall back to the CPU oracle."""


# ---------------------------------------------------------------------------
# Host-side packing
# ---------------------------------------------------------------------------


def _encode_value(value) -> tuple[int, int]:
    try:
        v1, v2 = h.encode_register_value(None, list(value) if isinstance(value, tuple) else value)
    except TypeError as e:
        raise NotTensorizable(str(e)) from None
    return v1, v2


def pack(model: m.Model, history: Sequence[dict]):
    """Pack a history into the kernel's barrier tables.

    Raises NotTensorizable when the model has no tensor step function or
    ops carry values the int32 columns can't hold.
    """
    tm = tmodels.tensor_model_for(model)
    if tm is None:
        raise NotTensorizable(f"no tensor model for {getattr(model, 'name', model)!r}")
    events, eff_ops, crashed = wgl_cpu.prepare(model, history)
    barriers, group_ops = wgl_cpu._barrier_snapshots(events, eff_ops, crashed)
    B = len(barriers)

    def fcode(op) -> int:
        f = op["f"]
        if f not in tm.f_codes:
            raise NotTensorizable(f"model {tm.name} has no f code for {f!r}")
        return tm.f_codes[f]

    # Process slots: one in-flight ok op per process at a time.
    slots: dict = {}
    for i in eff_ops:
        if i not in crashed:
            p = history[i]["process"]
            if p not in slots:
                slots[p] = len(slots)
    P = max(1, len(slots))
    W = (P + 31) // 32

    groups = sorted(group_ops, key=repr)
    gidx = {g: k for k, g in enumerate(groups)}
    G = max(1, len(groups))

    bar_f = np.zeros(B, np.int32)
    bar_v1 = np.zeros(B, np.int32)
    bar_v2 = np.zeros(B, np.int32)
    bar_slot = np.zeros(B, np.int32)
    bar_opid = np.zeros(B, np.int32)
    mov_f = np.zeros((B, P), np.int32)
    mov_v1 = np.zeros((B, P), np.int32)
    mov_v2 = np.zeros((B, P), np.int32)
    mov_open = np.zeros((B, P), bool)
    grp_open = np.zeros((B, G), np.int32)

    for b, (_pos, i, open_ok, open_crashed) in enumerate(barriers):
        op = eff_ops[i]
        bar_f[b] = fcode(op)
        bar_v1[b], bar_v2[b] = _encode_value(op.get("value"))
        bar_slot[b] = slots[history[i]["process"]]
        bar_opid[b] = i
        for j in open_ok:
            s = slots[history[j]["process"]]
            oj = eff_ops[j]
            mov_f[b, s] = fcode(oj)
            mov_v1[b, s], mov_v2[b, s] = _encode_value(oj.get("value"))
            mov_open[b, s] = True
        for g, count in open_crashed:
            grp_open[b, gidx[g]] = count

    grp_f = np.zeros(G, np.int32)
    grp_v1 = np.zeros(G, np.int32)
    grp_v2 = np.zeros(G, np.int32)
    for g, k in gidx.items():
        grp_f[k] = fcode(group_ops[g])
        grp_v1[k], grp_v2[k] = _encode_value(group_ops[g].get("value"))

    slot_lane = np.arange(P, dtype=np.int32) // 32
    slot_onehot = np.zeros((P, W), np.uint32)
    for p in range(P):
        slot_onehot[p, p // 32] = np.uint32(1) << np.uint32(p % 32)

    return {
        "B": B,
        "P": P,
        "G": G,
        "W": W,
        "init_state": np.int32(tm.encode_state(model)),
        "step": tm.step,
        "bar": (bar_f, bar_v1, bar_v2, bar_slot),
        "bar_opid": bar_opid,
        "mov": (mov_f, mov_v1, mov_v2, mov_open),
        "grp": (grp_f, grp_v1, grp_v2),
        "grp_open": grp_open,
        "slot_lane": slot_lane,
        "slot_onehot": slot_onehot,
    }


# ---------------------------------------------------------------------------
# Device kernel
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("step", "F", "R", "P", "G", "W")
)
def _run(
    step,
    F: int,
    R: int,
    P: int,
    G: int,
    W: int,
    init_state,
    bar_f,
    bar_v1,
    bar_v2,
    bar_slot,
    mov_f,
    mov_v1,
    mov_v2,
    mov_open,
    grp_f,
    grp_v1,
    grp_v2,
    grp_open,
    slot_lane,
    slot_onehot,
):
    """Scan the frontier over all barriers.  Returns (any_alive, failed_at,
    lossy, peak_frontier)."""
    eye_g = jnp.eye(G, dtype=I32)
    slot_mask = slot_onehot.sum(axis=1)  # [P] uint32 bit mask within its lane

    def expand_round(val):
        state, fok, fcr, alive, r, changed, lossy, fp, xs = val
        (xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open) = xs
        # Process moves: fire any open ok op not yet fired.     [F, P]
        pstate2, plegal = step(state[:, None], xmov_f[None, :], xmov_v1[None, :], xmov_v2[None, :])
        already = (jnp.take(fok, slot_lane, axis=1) & slot_mask[None, :]) != 0
        plegal = plegal & alive[:, None] & xmov_open[None, :] & ~already
        pfok = (fok[:, None, :] | slot_onehot[None, :, :]).reshape(F * P, W)
        pfcr = jnp.repeat(fcr, P, axis=0)
        # Group moves: fire one crashed op from any open group. [F, G]
        gstate2, glegal = step(state[:, None], grp_f[None, :], grp_v1[None, :], grp_v2[None, :])
        # A crashed fire that leaves the state unchanged yields a config
        # dominated by its own parent (same state/fok, one more fired) —
        # drop it at the source.
        glegal = (
            glegal & alive[:, None] & (fcr < xgrp_open[None, :]) & (gstate2 != state[:, None])
        )
        gfok = jnp.repeat(fok, G, axis=0)
        gfcr = (fcr[:, None, :] + eye_g[None, :, :]).reshape(F * G, G)

        cat_state = jnp.concatenate([state, pstate2.reshape(-1), gstate2.reshape(-1)])
        cat_alive = jnp.concatenate([alive, plegal.reshape(-1), glegal.reshape(-1)])
        cat_fok = jnp.concatenate([fok, pfok, gfok], axis=0)
        cat_fcr = jnp.concatenate([fcr, pfcr, gfcr.astype(I32)], axis=0)
        cost = (
            jax.lax.population_count(cat_fok).sum(axis=1).astype(I32)
            + cat_fcr.sum(axis=1)
        )
        # Compact into a 4F buffer first: domination (below) can only kill
        # rows in favour of strictly-cheaper rows, which sort first, so a
        # buffer of a few times the capacity lets dominated overflow be
        # discarded without counting as loss.
        F2 = min(4 * F, F * (1 + P + G))
        sel, buf_alive, n_uniq, _ovf = compact(
            [cat_state, cat_fok, cat_fcr], cat_alive, cost, F2
        )
        bstate = cat_state[sel]
        bfok = cat_fok[sel]
        bfcr = cat_fcr[sel]
        # Exact domination pruning keeps the closure finite: without it,
        # gratuitous crashed-op fires grow the reachable set for
        # sum(open-counts) rounds instead of the length of the longest
        # *minimal* enabling chain.
        balive = dominate(bstate, bfok, bfcr, buf_alive)
        n_undom = balive.sum()
        bcost = (
            jax.lax.population_count(bfok).sum(axis=1).astype(I32) + bfcr.sum(axis=1)
        )
        _d, _c, tsel = jax.lax.sort(
            ((~balive).astype(U32), bcost.astype(U32), jnp.arange(F2, dtype=I32)),
            num_keys=2,
        )
        keep = tsel[:F]
        state2 = bstate[keep]
        fok2 = bfok[keep]
        fcr2 = bfcr[keep]
        alive2 = jnp.arange(F) < jnp.minimum(n_undom, F)
        ovf = (n_uniq > F2) | (n_undom > F)
        # Fixpoint detection by frontier fingerprint (hash-sum of alive
        # rows): stable fingerprint => closure converged.
        f1 = hash_rows([state2] + [fok2[:, k] for k in range(W)] + [fcr2[:, k] for k in range(G)], 0xA5A5_0001)
        f2 = hash_rows([state2] + [fok2[:, k] for k in range(W)] + [fcr2[:, k] for k in range(G)], 0x5A5A_0002)
        am = alive2.astype(U32)
        fp2_ = jnp.stack([(f1 * am).sum(), (f2 * am).sum(), am.sum().astype(U32)])
        changed2 = ~(fp2_ == fp).all()
        return (state2, fok2, fcr2, alive2, r + 1, changed2, lossy | ovf, fp2_, xs)

    def round_cond(val):
        _s, _fo, _fc, _a, r, changed, _l, _fp, _xs = val
        return (r < R) & changed

    def barrier(carry, xs):
        state, fok, fcr, alive, failed_at, lossy, peak = carry
        b_idx, xbar_f, xbar_v1, xbar_v2, xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open = xs
        done = failed_at >= 0

        def process(_):
            xs_inner = (xbar_slot, xmov_f, xmov_v1, xmov_v2, xmov_open, xgrp_open)
            fp0 = jnp.zeros(3, U32)
            s2, fo2, fc2, a2, _r, changed, lossy2, _fp, _ = jax.lax.while_loop(
                round_cond,
                expand_round,
                (state, fok, fcr, alive, jnp.int32(0), jnp.bool_(True), lossy, fp0, xs_inner),
            )
            lossy3 = lossy2 | changed  # ran out of rounds before fixpoint
            # Filter: only configs that fired the returning op survive;
            # then retire its slot bit.
            lane = xbar_slot // 32
            bitmask = (U32(1) << (xbar_slot % 32).astype(U32))
            lane_vals = jnp.take(fo2, lane[None], axis=1)[:, 0]
            a3 = a2 & ((lane_vals & bitmask) != 0)
            clear = jnp.where(jnp.arange(W) == lane, bitmask, U32(0))
            fo3 = fo2 & ~clear[None, :]
            dead = ~a3.any()
            failed2 = jnp.where(dead, b_idx, jnp.int32(-1))
            peak2 = jnp.maximum(peak, a3.sum())
            return (s2, fo3, fc2, a3, failed2, lossy3, peak2)

        def skip(_):
            return (state, fok, fcr, alive, failed_at, lossy, peak)

        return jax.lax.cond(done, skip, process, None), None

    F_ = F
    state0 = jnp.full((F_,), init_state, I32)
    fok0 = jnp.zeros((F_, W), U32)
    fcr0 = jnp.zeros((F_, G), I32)
    alive0 = jnp.zeros((F_,), bool).at[0].set(True)
    carry0 = (state0, fok0, fcr0, alive0, jnp.int32(-1), jnp.bool_(False), jnp.int32(1))
    xs = (
        jnp.arange(bar_f.shape[0], dtype=I32),
        bar_f,
        bar_v1,
        bar_v2,
        bar_slot,
        mov_f,
        mov_v1,
        mov_v2,
        mov_open,
        grp_open,
    )
    (state, fok, fcr, alive, failed_at, lossy, peak), _ = jax.lax.scan(barrier, carry0, xs)
    return alive.any(), failed_at, lossy, peak


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------


def analysis(
    model: m.Model,
    history: Sequence[dict],
    capacity: int = 1024,
    rounds: int = 8,
    max_groups: int = 64,
    max_procs: int = 128,
) -> dict:
    """Decide linearizability on the accelerator.

    Knossos-shaped result: ``{"valid?": True|False|"unknown", ...}`` plus
    kernel stats under ``"kernel"``.  True is always exact; False is exact
    unless the frontier overflowed (then "unknown").
    """
    try:
        packed = pack(model, history)
    except NotTensorizable as e:
        return {"valid?": "unknown", "cause": f"not tensorizable: {e}"}
    if packed["B"] == 0:
        return {"valid?": True, "configs": [{"model": model}]}
    if packed["G"] > max_groups:
        return {"valid?": "unknown", "cause": f"{packed['G']} crashed-op groups exceeds {max_groups}"}
    if packed["P"] > max_procs:
        return {"valid?": "unknown", "cause": f"{packed['P']} process slots exceeds {max_procs}"}

    valid, failed_at, lossy, peak = _run(
        packed["step"],
        int(capacity),
        int(rounds),
        packed["P"],
        packed["G"],
        packed["W"],
        packed["init_state"],
        *packed["bar"],
        *packed["mov"],
        *packed["grp"],
        packed["grp_open"],
        jnp.asarray(packed["slot_lane"]),
        jnp.asarray(packed["slot_onehot"]),
    )
    valid = bool(valid)
    failed_at = int(failed_at)
    lossy = bool(lossy)
    stats = {"frontier-peak": int(peak), "capacity": capacity, "lossy?": lossy}
    if failed_at < 0 and valid:
        return {"valid?": True, "kernel": stats}
    op = history[int(packed["bar_opid"][failed_at])] if failed_at >= 0 else None
    if lossy:
        return {
            "valid?": "unknown",
            "cause": "frontier capacity or closure rounds exhausted",
            "op": op,
            "kernel": stats,
        }
    return {"valid?": False, "op": op, "kernel": stats}
