"""Fused Pallas TPU wide-stage frontier update (``dedup_backend="pallas"``).

The wide (cap-2048) rung is the ladder's dominant cost — 56% of wall
clock for the 6 straggler lanes (PERF.md "Honest limits") — and its
cost is THREE separate XLA passes (hash sort, MXU prune, cumsum-rank
gather) that round-trip the full candidate table through HBM between
each.  This module fuses the whole stage into a single
``pl.pallas_call``: every table (hashes, keep masks, the 2C domination
buffer, the compacted output) stays VMEM-resident for the full sweep —
the candidate table at the headline wide shape (26,624 rows, W=1, G=4)
is ~1.5 MB against 16 MB of VMEM, which is the entire point: the LSH-
bucketed beam kernels win by keeping their buckets on-chip (PAPERS:
1806.00588), and the wide rung's working set fits.

One grid sweep over 128-row tiles (T=128 — one full 128-lane stride,
so the ≥128-lane Mosaic stride constraint is satisfied by
construction; the <128 limitation simply doesn't bind at cap 2048):

  * **dedup** — the bucket backend's packed-radix semantics WITHOUT the
    sort.  ``_keep_bucket`` sorts ``[dead|bucket|index]`` and kills a
    row when an alive predecessor within ``window`` sorted slots has
    both 64-bit hash lanes equal.  Because the packed sort is stable by
    candidate index and same-bucket rows land contiguously, that is
    EXACTLY: kill row i iff some alive j < i (candidate order) has both
    hash lanes equal and ``pre[i] - pre[j] <= window``, where
    ``pre[i]`` counts alive same-bucket predecessors of i.  Both
    ``pre`` and the kills are windowed all-pairs tile sweeps
    ([128 x 128] VPU compares, the tiles resident), so the sort — the
    measured per-round floor — disappears from the stage entirely.
    The keep mask is BIT-IDENTICAL to ``_keep_bucket``'s (differential-
    gated in tests/test_wide_kernel.py), so the fused stage inherits
    the bucket backend's kill contract unchanged: a kill needs both
    hash lanes equal on an alive earlier copy, survivors are the first
    copy in candidate order, overflow never drops a row.
  * **domination** — ``exact_prune_mxu``'s one-hot contract on the 2C
    buffer: cumulative one-hot u-planes against saturating exact
    v-planes, one bf16 matmul per [128 x 128] tile pair on the MXU
    (``preferred_element_type=f32``; counts <= G so bf16 is exact),
    ``cnt > G - 0.5`` ⟹ pointwise ≤, saturating last plane so the
    test stays sound at any true count — the round-5 contract, tiled.
  * **compaction** — cumsum-rank, as matmuls: per-tile ranks from a
    lower-triangular f32 matmul, then a rank-one-hot matmul gathers
    survivors to the tile front (row contents ride as BYTE planes so
    f32 accumulation is exact for full u32 lanes), and overlapping
    ragged dynamic stores advance a running SMEM cursor — each tile's
    garbage tail is overwritten by the next tile's write, the classic
    ragged-output pattern.  No scatter, no gather, no sort.

On CPU the kernel runs under Pallas INTERPRET mode (``interpret=True``
— resolved at trace time from the backend, overridable via
``JEPSEN_TPU_PALLAS_INTERPRET``), so the tier-1 differential suite
executes the real kernel body, jitted/vmapped inside the production
runners like any other backend.  Compiled Mosaic execution is a
chip-day validation (PERF.md round 11 records the honest status); the
routing below is static, so an infeasible geometry — stride < 128
rows, bucket bits < ``BUCKET_MIN_BITS``, a non-wide rung below
``wide_min_capacity()``, or a missing ``max_count`` — falls back to
the bucket/sort paths at trace time and never a runtime branch.

**Mesh-spanning wide stage (round 12).**  VMEM residency is the fused
stage's entire advantage, and it is also its ceiling: the per-stage
working-set model (``fused_vmem_bytes`` against
``vmem_budget_bytes()``) caps a single device near cap 2048 at the
headline shape, so cap-4096+ rungs used to fall back to bucket/sort
and, past the frontier budget, to host spill (PR 8) — the source of
the standing frontier-blowup unknowns.  The mesh section at the bottom
of this module shards ONE wide stage across every device of a
``Placement`` mesh: each device owns a contiguous range of the class-
hash space (the SAME ``(state, fok)`` key ``parallel.sharded._route``
partitions on), candidate rows hash-route to their owner via
``pltpu.make_async_remote_copy`` ring exchanges (DMA semaphores in
scratch, start-all-then-wait-all so the D-1 transfers overlap), and
dedup + MXU domination + compaction then run purely locally per shard.
Bucket independence makes the local stage EXACT, not approximate:
hash-equal duplicates and domination pairs share the class key, so
every kill decision is local, and the psum'd order-insensitive
fingerprint of the union equals the single-device one whenever neither
path overflows (position within a shard is deterministic; global
positions differ, which is why the cross-path differential compares
content sets and fingerprints).  Per-device VMEM now has to hold only
``~HEADROOM/D`` of the candidate table, so the feasible capacity
scales linearly with mesh size (``mesh_feasible``): cap-8192 rungs run
on a 4-device mesh instead of spilling.  Development and tier-1 run
the mesh path in interpret mode on a virtual mesh
(``XLA_FLAGS=--xla_force_host_platform_device_count=4``); every
telemetry row from this path carries ``mesh_devices``/``interpret``
attrs so chip records stay separable.
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jepsen_tpu.ops import hashing

I32 = jnp.int32
U32 = jnp.uint32
F32 = jnp.float32

#: Row-tile: one full 128-lane stride — the pair sweeps are [T, T]
#: VPU/MXU tiles and every ragged store moves T rows.
TILE = 128

#: Env override for the wide-rung routing floor (see wide_min_capacity).
PALLAS_MIN_CAPACITY_ENV = "JEPSEN_TPU_PALLAS_MIN_CAPACITY"

#: Env override for interpret mode (default: interpret unless the
#: default jax backend is a real TPU).
PALLAS_INTERPRET_ENV = "JEPSEN_TPU_PALLAS_INTERPRET"

#: Default routing floor: the kernel exists for the WIDE rungs (the
#: cap-2048 straggler stage); narrow rungs keep the measured bucket/sort
#: routing.  Matches the ops.wgl.async_ticks wide/narrow boundary.
PALLAS_MIN_CAPACITY = 1024

#: Env override (MiB) for the per-stage VMEM working-set budget (see
#: vmem_budget_bytes).
PALLAS_VMEM_BUDGET_ENV = "JEPSEN_TPU_PALLAS_VMEM_MB"

#: Default per-stage VMEM budget, MiB.  Deliberately a conservative
#: slice of the 16 MiB physical VMEM: the budget covers ONE stage's
#: inputs + scratch + outputs and must leave room for double-buffered
#: DMA windows, the compiler's own spill slack, and the co-resident
#: exchange buffers on the mesh path.  3 MiB places the single-device
#: ceiling at cap 2048 for the headline shape (P=8, G=4, W=1) — the
#: measured wide rung — and gives the mesh path its clean scaling law:
#: feasible capacity = devices x 2048 (mesh_feasible).
PALLAS_VMEM_BUDGET_MB = 3.0


def wide_min_capacity() -> int:
    """The smallest rung capacity routed to the fused kernel (env
    override > module default).  Resolved at TRACE time, like
    ``resolve_dedup_backend`` — engines thread it through their runner
    caches, so tests that lower it must build fresh runner shapes (or
    evict the runner caches)."""
    v = os.environ.get(PALLAS_MIN_CAPACITY_ENV)
    return int(v) if v else PALLAS_MIN_CAPACITY


def interpret_default() -> bool:
    """Whether the kernel should run under the Pallas interpreter:
    anything that is not a real TPU backend (CPU CI, tests) interprets;
    ``JEPSEN_TPU_PALLAS_INTERPRET=0/1`` forces.  Resolved at trace
    time; recorded honestly in telemetry/ledger rows so chip records
    stay separable from interpret ones."""
    v = os.environ.get(PALLAS_INTERPRET_ENV)
    if v is not None and v != "":
        return v not in ("0", "false", "no")
    try:
        return jax.default_backend() != "tpu"
    except Exception:  # noqa: BLE001 — no backend at all: interpret
        return True


def keep_feasible(n: int) -> bool:
    """Static geometry gate for the dedup (keep-mask) stage: at least
    one full 128-lane stride of candidates, and a usable packed-radix
    bucket geometry (the kernel's ``pre`` ranks are bucket ranks)."""
    return n >= TILE and hashing.bucket_feasible(n)


def fused_feasible(n: int, capacity: int, max_count: int | None,
                   w: int | None = None, g: int | None = None) -> bool:
    """Static geometry gate for the FUSED update (dedup + domination +
    compaction).  Beyond ``keep_feasible``: the MXU prune needs the
    static ``max_count`` plane bound; the 2C domination buffer must
    tile evenly (capacity % 64 == 0 so 2C % TILE == 0) and actually be
    2C (n >= 2C — engine candidate tables are F*(1+P+G) >= 3F, so this
    only excludes exotic direct calls); and the rung must be wide
    (``wide_min_capacity()`` — the routing floor, not a correctness
    bound).  A False routes the round to bucket/sort at trace time.

    When ``w``/``g`` (fok lanes / factor groups) are given, the VMEM
    working-set model gates too: the fused stage's whole advantage is
    VMEM residency, so a shape whose one-launch working set
    (``fused_vmem_bytes``) exceeds ``vmem_budget_bytes()`` is routed
    away — down to bucket/sort on a single device, or spread across a
    mesh by the ``mesh_feasible`` variant, whose per-device model
    scales the feasible capacity linearly with mesh size.  The bare
    3-arg form stays a pure geometry gate (probes and telemetry use it
    to describe shapes independent of the budget knob)."""
    ok = (
        keep_feasible(n)
        and max_count is not None
        and capacity % (TILE // 2) == 0
        and n >= 2 * capacity
        and capacity >= wide_min_capacity()
    )
    if ok and w is not None and g is not None:
        ok = fused_vmem_bytes(n, capacity, w, g) <= vmem_budget_bytes()
    return ok


def vmem_budget_bytes() -> int:
    """The per-stage VMEM working-set budget in bytes (env override in
    MiB > module default).  Resolved at trace time like the routing
    floor; engines thread it through their runner caches."""
    v = os.environ.get(PALLAS_VMEM_BUDGET_ENV)
    mb = float(v) if v else PALLAS_VMEM_BUDGET_MB
    return int(mb * 1024 * 1024)


def fused_vmem_bytes(n: int, capacity: int, w: int, g: int) -> int:
    """One fused-stage launch's VMEM working set (inputs + scratch +
    outputs, bytes) at ``n`` candidate rows / ``capacity`` output rows
    with ``w`` fok lanes and ``g`` factor groups.  Pure arithmetic —
    the model ``stage_occupancy`` reports and ``fused_feasible`` /
    ``mesh_feasible`` gate on."""
    n_pad = _pad_rows(n)
    C = int(capacity)
    Cb = 2 * C
    CC = _plane_cols(w, g)
    inputs = n_pad * (4 + 4 * w + 4 * g + 4 + 4)  # state/fok/fcr/alive/child
    scratch = (
        n_pad * (4 + 4 + 4 + 4)            # h1, h2, pre, keep
        + (Cb + TILE) * CC * 4             # domination buffer
        + Cb * 4                           # prune kills
        + (C + TILE) * CC * 4              # compacted output
    )
    outputs = C * (4 + 4 * w + 4 * g + 4 + 4) + 4 * 2 + 4 * 3
    return int(inputs + scratch + outputs)


def _pad_rows(n: int) -> int:
    return ((n + TILE - 1) // TILE) * TILE


# ---------------------------------------------------------------------------
# In-kernel helpers (traced inside the pallas kernel body)
# ---------------------------------------------------------------------------


#: numpy scalars, NOT jnp: a pallas kernel may not close over concrete
#: jax arrays (even scalar ones) — numpy scalars embed as literals.
_MIX_C1 = np.uint32(0x85EBCA6B)
_MIX_C2 = np.uint32(0xC2B2AE35)


def _mix32(x):
    """hashing.mix32's murmur3 fmix32 fold, with literal-safe constants
    (bit-identical — differential-gated against the host fold)."""
    x = x ^ (x >> 16)
    x = x * _MIX_C1
    x = x ^ (x >> 13)
    x = x * _MIX_C2
    x = x ^ (x >> 16)
    return x


def _iota1(n: int):
    """[n] int32 iota — built 2D then collapsed (TPU requires >=2D iota)."""
    return jax.lax.broadcasted_iota(I32, (n, 1), 0)[:, 0]


def _tri_f32():
    """[T, T] lower-triangular ones (inclusive) — the cumsum matmul."""
    ii = jax.lax.broadcasted_iota(I32, (TILE, TILE), 0)
    jj = jax.lax.broadcasted_iota(I32, (TILE, TILE), 1)
    return (jj <= ii).astype(F32)


def _row_hashes(state_ref, fok_ref, fcr_ref, W: int, G: int):
    """64-bit row hashes (two u32 lanes) over the full candidate table —
    hashing.hash_rows' fold, computed in-kernel so the hash arrays never
    exist in HBM."""
    cols = (
        [state_ref[:]]
        + [fok_ref[:, w] for w in range(W)]  # graftlint: disable=trace-host-control
        + [fcr_ref[:, g] for g in range(G)]  # graftlint: disable=trace-host-control
    )
    n_pad = cols[0].shape[0]
    h1 = jnp.full((n_pad,), np.uint32(hashing.HASH_SEED_1 ^ 0x9E3779B9))
    h2 = jnp.full((n_pad,), np.uint32(hashing.HASH_SEED_2 ^ 0x9E3779B9))
    for col in cols:  # graftlint: disable=trace-host-control
        c = col.astype(U32)
        h1 = _mix32(h1 ^ c)
        h2 = _mix32(h2 ^ c)
    return h1, h2


def _dedup_tile(i, h1_s, h2_s, alive_ref, pre_s, keep_s, window: int,
                bbits: int):
    """Phases A+B for row tile ``i``: bucket prefix-ranks, then windowed
    64-bit-hash kills — ``_keep_bucket``'s exact semantics, sort-free.
    Returns (keep_i bool [T], pre_i [T])."""
    shift = np.uint32(32 - bbits)
    row0 = i * TILE
    sl = pl.ds(row0, TILE)
    h1_i = h1_s[sl]
    h2_i = h2_s[sl]
    b_i = h1_i >> shift
    al_i = alive_ref[sl] != 0
    ii = jax.lax.broadcasted_iota(I32, (TILE, TILE), 0)
    jj = jax.lax.broadcasted_iota(I32, (TILE, TILE), 1)

    def pre_body(J, pre_i):
        sj = pl.ds(J * TILE, TILE)
        b_j = h1_s[sj] >> shift
        al_j = alive_ref[sj] != 0
        lt = (J * TILE + jj) < (row0 + ii)  # global j strictly before i
        m = (b_i[:, None] == b_j[None, :]) & al_j[None, :] & lt
        return pre_i + m.astype(I32).sum(axis=1)

    pre_i = jax.lax.fori_loop(0, i + 1, pre_body, jnp.zeros((TILE,), I32))
    pre_s[sl] = pre_i

    def kill_body(J, kill):
        sj = pl.ds(J * TILE, TILE)
        al_j = alive_ref[sj] != 0
        lt = (J * TILE + jj) < (row0 + ii)
        eq = (h1_i[:, None] == h1_s[sj][None, :]) & (
            h2_i[:, None] == h2_s[sj][None, :]
        )
        near = (pre_i[:, None] - pre_s[sj][None, :]) <= window
        return kill | (eq & al_j[None, :] & lt & near).any(axis=1)

    kill = jax.lax.fori_loop(0, i + 1, kill_body,
                             jnp.zeros((TILE,), jnp.bool_))
    keep_i = al_i & ~kill
    keep_s[sl] = keep_i.astype(I32)
    return keep_i, pre_i


# Byte-plane layout for the compaction matmuls: row contents ride as
# bytes so the f32 one-hot gather is exact for full u32 lanes (a one-hot
# row selects exactly one value <= 255 — trivially exact in f32).
# [ state:4 | fok: 4 per lane | fcr: 2 per group (counts gated <= 32767
#   at pack time) | child-bit:1 ]


def _plane_cols(W: int, G: int) -> int:
    return 4 + 4 * W + 2 * G + 1


def _u32_bytes(x):
    u = x if x.dtype == jnp.uint32 else jax.lax.bitcast_convert_type(x, U32)
    return [((u >> np.uint32(8 * k)) & np.uint32(0xFF)).astype(I32)
            for k in range(4)]


def _tile_planes(state_t, fok_t, fcr_t, child_t, W: int, G: int):
    """[T, CC] int32 byte-plane matrix for one row tile."""
    cols = _u32_bytes(state_t)
    for w in range(W):  # graftlint: disable=trace-host-control
        cols += _u32_bytes(fok_t[:, w])
    for g in range(G):  # graftlint: disable=trace-host-control
        v = fcr_t[:, g]
        cols += [v & np.int32(0xFF), (v >> np.int32(8)) & np.int32(0xFF)]
    cols.append(child_t.astype(I32))
    return jnp.stack(cols, axis=1)


def _planes_rows(buf_t, W: int, G: int):
    """Inverse of _tile_planes: (state [T] i32, fok [T, W] u32,
    fcr [T, G] i32, child [T] i32) from a byte-plane tile."""

    def u32_of(c0):
        b = [buf_t[:, c0 + k].astype(U32) for k in range(4)]
        return (b[0] | (b[1] << np.uint32(8)) | (b[2] << np.uint32(16))
                | (b[3] << np.uint32(24)))

    state = jax.lax.bitcast_convert_type(u32_of(0), I32)
    fok = jnp.stack(
        [u32_of(4 + 4 * w) for w in range(W)],  # graftlint: disable=trace-host-control
        axis=1,
    )
    f0 = 4 + 4 * W
    fcr = jnp.stack(
        [buf_t[:, f0 + 2 * g] | (buf_t[:, f0 + 2 * g + 1] << np.int32(8))
         for g in range(G)],  # graftlint: disable=trace-host-control
        axis=1,
    )
    child = buf_t[:, f0 + 2 * G]
    return state, fok, fcr, child


def _compact_tile(keep_t, planes_t):
    """Rank the kept rows of one tile (triangular f32 matmul) and gather
    them to the tile front with a rank-one-hot matmul.  Returns
    ([T, CC] compacted planes — zeros past the kept count, [] count)."""
    kf = keep_t.astype(F32).reshape(TILE, 1)
    lr = (
        jax.lax.dot_general(_tri_f32(), kf, (((1,), (0,)), ((), ())),
                            preferred_element_type=F32)
        .reshape(TILE).astype(I32) - 1
    )
    ii = jax.lax.broadcasted_iota(I32, (TILE, TILE), 0)
    onehot = ((ii == lr[None, :]) & keep_t[None, :]).astype(F32)
    out = jax.lax.dot_general(
        onehot, planes_t.astype(F32), (((1,), (0,)), ((), ())),
        preferred_element_type=F32,
    ).astype(I32)
    return out, keep_t.astype(I32).sum()


def _prune_uv(fcr_t, G: int, m: int):
    """exact_prune_mxu's one-hot planes for one tile: cumulative u and
    SATURATING exact v, [T, G*m] (counts at or past the last plane
    compare saturating — sound at any count, exact below m-1)."""
    c = _iota1(m)
    u = (fcr_t[:, :, None] <= c[None, None, :]).reshape(TILE, G * m)
    sat = jnp.minimum(fcr_t, m - 1)
    v = (sat[:, :, None] == c[None, None, :]).reshape(TILE, G * m)
    return u.astype(jnp.bfloat16), v.astype(jnp.bfloat16)


# ---------------------------------------------------------------------------
# Kernels
# ---------------------------------------------------------------------------


def _keep_kernel(window: int, bbits: int, W: int, G: int,
                 state_ref, fok_ref, fcr_ref, alive_ref,
                 keep_ref, ovf_ref, h1_s, h2_s, pre_s):
    """Dedup stage only: the keep mask in candidate order + the bucket
    overflow flag (a survivor whose whole window was same-bucket alive
    rows — possible bloat, never loss)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        h1, h2 = _row_hashes(state_ref, fok_ref, fcr_ref, W, G)
        h1_s[:] = h1
        h2_s[:] = h2
        ovf_ref[0] = I32(0)

    keep_i, pre_i = _dedup_tile(i, h1_s, h2_s, alive_ref, pre_s, keep_ref,
                                window, bbits)
    full_any = (keep_i & (pre_i >= window)).any()
    ovf_ref[0] = ovf_ref[0] | full_any.astype(I32)


def _fused_kernel(n: int, C: int, Cb: int, window: int, bbits: int,
                  W: int, G: int, m: int, n_parents: int, use_child: bool,
                  state_ref, fok_ref, fcr_ref, alive_ref, childin_ref,
                  kst_ref, kfo_ref, kfc_ref, alv_ref, chd_ref,
                  flg_ref, fp_ref,
                  h1_s, h2_s, pre_s, keep_s, buf_s, dead_s, out_s, sm_s):
    """The fused wide-stage update: dedup + 2C-buffer MXU domination +
    cumsum-rank compaction to capacity, one grid sweep, VMEM-resident.
    Output contract is frontier_update_fast's (see the wrapper)."""
    i = pl.program_id(0)
    nt = pl.num_programs(0)
    CC = _plane_cols(W, G)

    @pl.when(i == 0)
    def _():
        h1, h2 = _row_hashes(state_ref, fok_ref, fcr_ref, W, G)
        h1_s[:] = h1
        h2_s[:] = h2
        sm_s[0] = I32(0)  # stage-1 compaction cursor (dedup survivors)
        sm_s[1] = I32(0)  # stage-2 compaction cursor (prune survivors)
        buf_s[...] = jnp.zeros_like(buf_s)
        out_s[...] = jnp.zeros_like(out_s)
        dead_s[:] = jnp.zeros_like(dead_s)

    _dedup_tile(i, h1_s, h2_s, alive_ref, pre_s, keep_s, window, bbits)

    @pl.when(i == nt - 1)
    def _final():
        tidx = _iota1(TILE)

        # ---- stage 1: compact dedup survivors into the 2C buffer ----
        # (candidate order preserved; overlapping ragged stores — each
        # tile's zero tail is overwritten by the next tile's rows)
        def s1(J, _):
            sj = pl.ds(J * TILE, TILE)
            keep_j = keep_s[sj] != 0
            gidx = J * TILE + tidx
            # Child provenance: positional (rows past n_parents are this
            # round's expansions) on the single-device path; an explicit
            # input column on the mesh path, where hash routing has
            # scrambled positions before the kernel sees the rows.
            if use_child:
                child_j = childin_ref[sj] != 0
            elif n_parents >= 0:
                child_j = gidx >= n_parents
            else:
                child_j = jnp.zeros((TILE,), jnp.bool_)
            planes = _tile_planes(
                state_ref[sj], fok_ref[sj, :], fcr_ref[sj, :], child_j, W, G
            )
            compacted, cnt = _compact_tile(keep_j, planes)
            base = sm_s[0]
            buf_s[pl.ds(jnp.minimum(base, Cb), TILE), :] = compacted
            sm_s[0] = base + cnt
            return 0

        jax.lax.fori_loop(0, nt, s1, 0)
        nk0 = sm_s[0]
        nk0c = jnp.minimum(nk0, Cb)
        spill = nk0 > Cb

        # ---- stage 2: content-exact domination antichain on the buffer
        # (exact_prune_mxu's one-hot contract, [T, T] bf16 MXU tiles,
        # saturating last plane; ties keep the earlier row) ----
        nb = Cb // TILE
        gm_half = np.float32(G) - np.float32(0.5)

        def pr_i(I2, _):
            si = pl.ds(I2 * TILE, TILE)
            st_i, fok_i, fcr_i, _c = _planes_rows(buf_s[si, :], W, G)
            al_i = (I2 * TILE + tidx) < nk0c
            u_i, v_i = _prune_uv(fcr_i, G, m)
            ii = jax.lax.broadcasted_iota(I32, (TILE, TILE), 0)
            jj = jax.lax.broadcasted_iota(I32, (TILE, TILE), 1)

            def pr_j(J2, _):
                sj = pl.ds(J2 * TILE, TILE)
                st_j, fok_j, fcr_j, _c2 = _planes_rows(buf_s[sj, :], W, G)
                al_j = (J2 * TILE + tidx) < nk0c
                u_j, v_j = _prune_uv(fcr_j, G, m)
                # cnt[i, j] counts groups with fcr_i <= sat(fcr_j): == G
                # => pointwise <=.  The second product is le_ji
                # TRANSPOSED for free (contract the plane axis of v_i
                # against u_j) — no in-kernel transpose.
                cnt = jax.lax.dot_general(
                    u_i, v_j, (((1,), (1,)), ((), ())),
                    preferred_element_type=F32,
                )
                cnt_t = jax.lax.dot_general(
                    v_i, u_j, (((1,), (1,)), ((), ())),
                    preferred_element_type=F32,
                )
                le_ij = cnt > gm_half
                le_ji_t = cnt_t > gm_half
                same = st_i[:, None] == st_j[None, :]
                for w in range(W):  # graftlint: disable=trace-host-control
                    same &= fok_i[:, w][:, None] == fok_j[:, w][None, :]
                earlier = (I2 * TILE + ii) < (J2 * TILE + jj)
                killer = (
                    same & le_ij & (~le_ji_t | earlier)
                    & al_i[:, None] & al_j[None, :]
                )
                dead_s[sj] = dead_s[sj] | killer.any(axis=0).astype(I32)
                return 0

            jax.lax.fori_loop(0, nb, pr_j, 0)
            return 0

        jax.lax.fori_loop(0, nb, pr_i, 0)

        # ---- stage 3: compact the antichain to capacity ----
        def s3(J2, _):
            sj = pl.ds(J2 * TILE, TILE)
            keep2 = ((J2 * TILE + tidx) < nk0c) & (dead_s[sj] == 0)
            compacted, cnt = _compact_tile(keep2, buf_s[sj, :])
            base = sm_s[1]
            out_s[pl.ds(jnp.minimum(base, C), TILE), :] = compacted
            sm_s[1] = base + cnt
            return 0

        jax.lax.fori_loop(0, nb, s3, 0)
        nk = sm_s[1]
        overflowed = spill | (nk > C)

        # ---- outputs: reassemble planes, alive/child masks, flags,
        # order-insensitive content fingerprint ----
        kst, kfo, kfc, child = _planes_rows(out_s[0:C, :], W, G)
        new_alive = _iota1(C) < jnp.minimum(nk, C)
        kst_ref[:] = kst
        kfo_ref[:, :] = kfo
        kfc_ref[:, :] = kfc
        alv_ref[:] = new_alive.astype(I32)
        chd_ref[:] = ((child != 0) & new_alive).astype(I32)
        flg_ref[0] = overflowed.astype(I32)
        flg_ref[1] = nk
        r1 = jnp.full((C,), np.uint32(hashing.FP_SEED_1 ^ 0x9E3779B9))
        r2 = jnp.full((C,), np.uint32(hashing.FP_SEED_2 ^ 0x9E3779B9))
        out_cols = (
            [kst]
            + [kfo[:, w] for w in range(W)]  # graftlint: disable=trace-host-control
            + [kfc[:, g] for g in range(G)]  # graftlint: disable=trace-host-control
        )
        for col in out_cols:  # graftlint: disable=trace-host-control
            r1 = _mix32(r1 ^ col.astype(U32))
            r2 = _mix32(r2 ^ col.astype(U32))
        am = new_alive.astype(U32)
        fp_ref[0] = (r1 * am).sum()
        fp_ref[1] = (r2 * am).sum()
        fp_ref[2] = am.sum()


# ---------------------------------------------------------------------------
# Wrappers (traced; call from inside jitted engines or eagerly)
# ---------------------------------------------------------------------------


def _pad_table(state, fok, fcr, alive):
    n = state.shape[0]
    n_pad = _pad_rows(n)
    if n_pad != n:
        d = n_pad - n
        state = jnp.pad(state, (0, d))
        fok = jnp.pad(fok, ((0, d), (0, 0)))
        fcr = jnp.pad(fcr, ((0, d), (0, 0)))
        alive = jnp.pad(alive, (0, d))
    return state, fok, fcr.astype(I32), alive.astype(I32), n_pad


def keep_mask(state, fok, fcr, alive, window: int = 4,
              interpret: bool | None = None):
    """The dedup stage alone (row hash + bucket partition + windowed
    kills), as the standalone kernel — what ``dedup_round_probe`` times
    and the differential suite compares bit-for-bit against
    ``_keep_bucket``.  Returns (keep [n] bool in candidate order,
    overflow [] bool)."""
    n = state.shape[0]
    assert keep_feasible(n), f"pallas keep-mask infeasible at {n} rows"
    W, G = fok.shape[1], fcr.shape[1]
    _ibits, bbits = hashing._bucket_bits(n)
    st, fo, fc, al, n_pad = _pad_table(state, fok, fcr, alive)
    if interpret is None:
        interpret = interpret_default()
    keep, ovf = pl.pallas_call(
        functools.partial(_keep_kernel, int(window), bbits, W, G),
        grid=(n_pad // TILE,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 4,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((n_pad,), I32),
            jax.ShapeDtypeStruct((1,), I32),
        ),
        scratch_shapes=[
            pltpu.VMEM((n_pad,), U32),
            pltpu.VMEM((n_pad,), U32),
            pltpu.VMEM((n_pad,), I32),
        ],
        interpret=bool(interpret),
    )(st, fo, fc, al)
    return keep[:n] != 0, ovf[0] != 0


def fused_frontier_update(
    state, fok, fcr, alive, cost, capacity: int, window: int = 4,
    n_parents: int | None = None, max_count: int | None = None,
    interpret: bool | None = None, child=None,
):
    """Drop-in fused replacement for ``hashing.frontier_update_fast``
    on feasible wide geometry (``fused_feasible``) — same signature
    (``cost`` accepted and unused, same candidate-order truncation
    argument), same returns (state', fok', fcr', alive', overflowed,
    fp, child).

    Output parity with the bucket backend (differential-gated): alive
    rows are bit-identical in content AND position, and so are
    ``overflowed`` and the fingerprint ``fp``.  Dead output rows are
    ZEROS here (the reference gathers arbitrary row-0 copies into dead
    slots); ``child`` is masked by alive' (the reference leaves garbage
    on dead rows) — engines only consume ``alive' & child``.

    ``child``: an explicit per-row child bit ([n] bool/int), for
    callers whose candidate order no longer encodes provenance — the
    mesh path routes rows by class hash before this stage, so
    ``n_parents`` positional provenance is meaningless there.
    Mutually exclusive with ``n_parents``.
    """
    n = state.shape[0]
    assert fused_feasible(n, capacity, max_count), (
        f"pallas fused update infeasible at n={n}, capacity={capacity}"
    )
    assert child is None or n_parents is None, (
        "pass either an explicit child column or positional n_parents"
    )
    W, G = fok.shape[1], fcr.shape[1]
    fcr_dtype = fcr.dtype
    _ibits, bbits = hashing._bucket_bits(n)
    C = int(capacity)
    Cb = 2 * C
    m = min(int(max_count), hashing.MXU_PRUNE_MAX_COUNT)
    CC = _plane_cols(W, G)
    st, fo, fc, al, n_pad = _pad_table(state, fok, fcr, alive)
    if child is None:
        ch = jnp.zeros((n_pad,), I32)
    else:
        ch = jnp.pad(child.astype(I32), (0, n_pad - n))
    if interpret is None:
        interpret = interpret_default()
    kst, kfo, kfc, alv, chd, flg, fp = pl.pallas_call(
        functools.partial(
            _fused_kernel, n, C, Cb, int(window), bbits, W, G, m,
            -1 if n_parents is None else int(n_parents), child is not None,
        ),
        grid=(n_pad // TILE,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * 5,
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.ANY),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((C,), I32),
            jax.ShapeDtypeStruct((C, W), U32),
            jax.ShapeDtypeStruct((C, G), I32),
            jax.ShapeDtypeStruct((C,), I32),
            jax.ShapeDtypeStruct((C,), I32),
            jax.ShapeDtypeStruct((2,), I32),
            jax.ShapeDtypeStruct((3,), U32),
        ),
        scratch_shapes=[
            pltpu.VMEM((n_pad,), U32),          # h1
            pltpu.VMEM((n_pad,), U32),          # h2
            pltpu.VMEM((n_pad,), I32),          # pre (bucket ranks)
            pltpu.VMEM((n_pad,), I32),          # keep mask
            pltpu.VMEM((Cb + TILE, CC), I32),   # 2C domination buffer
            pltpu.VMEM((Cb,), I32),             # prune kills
            pltpu.VMEM((C + TILE, CC), I32),    # compacted output
            pltpu.SMEM((2,), I32),              # ragged-store cursors
        ],
        interpret=bool(interpret),
    )(st, fo, fc, al, ch)
    return (
        kst, kfo, kfc.astype(fcr_dtype), alv != 0, flg[0] != 0, fp, chd != 0
    )


@functools.partial(
    jax.jit,
    static_argnames=("capacity", "window", "n_parents", "max_count",
                     "interpret"),
)
def fused_update_jit(state, fok, fcr, alive, cost, capacity, window=4,
                     n_parents=None, max_count=None, interpret=None,
                     child=None):
    """Jitted entry for eager callers (tests, probes): the engines trace
    ``fused_frontier_update`` into their own runner programs instead."""
    return fused_frontier_update(
        state, fok, fcr, alive, cost, capacity, window=window,
        n_parents=n_parents, max_count=max_count, interpret=interpret,
        child=child,
    )


def stage_occupancy(capacity: int, P: int, G: int, W: int | None = None,
                    max_count: int | None = None) -> dict:
    """Host-side tile/VMEM occupancy estimate for one fused-stage launch
    at a rung's shape — the attrs ladder telemetry rows carry
    (``pallas_tile``, ``pallas_vmem_bytes``, ...) and the chip-day flip
    procedure reads next to the compete verdict.  Pure arithmetic, no
    device work."""
    W = (P + 31) // 32 if W is None else W
    n = capacity * (1 + P + G)
    return {
        "tile": TILE,
        "candidates": int(n),
        "candidates_padded": int(_pad_rows(n)),
        "vmem_bytes": fused_vmem_bytes(n, capacity, W, G),
        "vmem_budget_bytes": vmem_budget_bytes(),
        "prune_planes": (
            min(int(max_count), hashing.MXU_PRUNE_MAX_COUNT)
            if max_count is not None else None
        ),
        "interpret": interpret_default(),
    }


# ---------------------------------------------------------------------------
# Mesh-spanning wide stage: hash-routed shards + remote-DMA ring exchange
# ---------------------------------------------------------------------------


#: Skew headroom on the per-peer receive slots: each of the D peers gets
#: a FIXED slot of ``ceil(HEADROOM * n_loc / D)`` rows (TILE-padded), so
#: the static exchange tolerates 1.5x the uniform routing load before
#: the honest overflow flag escalates the round — the same
#: fixed-bucket-plus-spill-flag contract as ``sharded._route``, with the
#: factor chosen so the received table (``D * rcap ~ 1.5 * n_loc``)
#: keeps the local stage inside the VMEM budget at cap 2048 per device.
MESH_RCAP_HEADROOM = 1.5

#: Routing seed for the class-hash device owner — the SAME seed and the
#: SAME class key (state, fok) ``parallel.sharded._route`` partitions
#: on.  Routing by CLASS (not full row content) is what makes the local
#: stage exact: hash-equal duplicates share all columns, and domination
#: pairs share (state, fok) by definition, so both kinds of kill
#: decision see all their rows on one device.
MESH_ROUTE_SEED = 0x5EED_0D15


def exchange_cols(w: int, g: int) -> int:
    """i32 columns per exchanged row:
    [ state | fok lanes (bitcast) | fcr groups | alive | child ]."""
    return 1 + w + g + 2


def mesh_rcap(n_loc: int, devices: int) -> int:
    """Fixed per-peer receive-slot rows for a shard with ``n_loc`` local
    candidate rows on a ``devices``-wide mesh, TILE-padded so the
    received ``[D * rcap]`` table tiles evenly."""
    per = int(np.ceil(MESH_RCAP_HEADROOM * n_loc / devices))
    return _pad_rows(max(per, 1))


def exchange_vmem_bytes(n_loc: int, devices: int, w: int, g: int) -> int:
    """VMEM held by one exchange launch: the send and receive slot
    matrices, ``[D, rcap, NC]`` i32 each (the DMA semaphores are
    negligible)."""
    return 2 * devices * mesh_rcap(n_loc, devices) * exchange_cols(w, g) * 4


def mesh_feasible(n: int, capacity: int, max_count: int | None,
                  devices: int, w: int | None = None,
                  g: int | None = None) -> bool:
    """Static gate for the mesh-spanning fused stage at GLOBAL shape
    ``n`` candidate rows / ``capacity`` output rows on a
    ``devices``-wide mesh.  Both totals must split evenly; the
    per-device slice (received table ``D * rcap`` rows against capacity
    ``capacity / D``) must pass ``fused_feasible`` — including, when
    ``w``/``g`` are given, the VMEM model, now applied to a working set
    ``~HEADROOM / D`` the size of the global table.  That is the whole
    capacity-scaling story: the budget that caps one device at 2048
    admits ``devices x 2048`` here.  The exchange buffers live in a
    separate launch and are budgeted separately.  A False routes the
    stage to the single-device kernel (and down its own ladder)."""
    if devices < 2:
        return False
    if capacity % devices or n % devices:
        return False
    cap_d = capacity // devices
    n_loc = n // devices
    rcap = mesh_rcap(n_loc, devices)
    if not fused_feasible(devices * rcap, cap_d, max_count, w=w, g=g):
        return False
    if w is not None and g is not None:
        if exchange_vmem_bytes(n_loc, devices, w, g) > vmem_budget_bytes():
            return False
    return True


def mesh_occupancy(capacity: int, P: int, G: int, W: int | None = None,
                   max_count: int | None = None, devices: int = 2) -> dict:
    """Host-side per-device occupancy estimate for one mesh-spanning
    stage at a rung's shape — the mesh counterpart of
    ``stage_occupancy``, feeding the ``mesh_devices``-tagged telemetry
    attrs and the capacity-vs-devices scaling curve.  Pure arithmetic."""
    W = (P + 31) // 32 if W is None else W
    D = int(devices)
    n = int(capacity) * (1 + P + G)
    cap_d = int(capacity) // D
    n_loc = n // D
    rcap = mesh_rcap(n_loc, D)
    return {
        "tile": TILE,
        "devices": D,
        "per_device_capacity": cap_d,
        "candidates": int(n),
        "rcap": int(rcap),
        "recv_rows": int(D * rcap),
        "local_vmem_bytes": fused_vmem_bytes(D * rcap, max(cap_d, 1), W, G),
        "exchange_vmem_bytes": exchange_vmem_bytes(n_loc, D, W, G),
        "vmem_budget_bytes": vmem_budget_bytes(),
        "feasible": mesh_feasible(n, int(capacity), max_count, D, w=W, g=G),
        "interpret": interpret_default(),
    }


def _exchange_kernel(axis: str, nd: int, send_ref, recv_ref, *sems):
    """All-to-all of the pre-rotated slot matrix ``[D, rcap, NC]``.

    Slot 0 is the shard's own bucket — a local async copy.  Slot s > 0
    remote-DMA-copies to logical device ``(me + s) % D``; by the same
    arithmetic on every shard, the RECEIVER's slot-s window holds rows
    from ``(me - s) % D``, so one send semaphore and one receive
    semaphore per step pair up symmetrically across the ring (SNIPPETS
    [1]/[2] skeleton).  All D-1 transfers start before any wait so they
    overlap; scratch semaphores are scalar (one per DMA edge) and
    indexed statically — D is a trace-time constant."""
    me = jax.lax.axis_index(axis)
    local = pltpu.make_async_copy(send_ref.at[0], recv_ref.at[0], sems[0])
    local.start()
    ops = []
    for s in range(1, nd):  # graftlint: disable=trace-host-control
        dst = jax.lax.rem(me + np.int32(s), np.int32(nd))
        op = pltpu.make_async_remote_copy(
            src_ref=send_ref.at[s], dst_ref=recv_ref.at[s],
            send_sem=sems[2 * s - 1], recv_sem=sems[2 * s],
            device_id=dst, device_id_type=pltpu.DeviceIdType.LOGICAL,
        )
        op.start()
        ops.append(op)
    local.wait()
    for op in ops:  # graftlint: disable=trace-host-control
        op.wait()


def mesh_exchange(axis: str, devices: int, send,
                  interpret: bool | None = None):
    """Exchange the pre-rotated ``[D, rcap, NC]`` i32 slot matrix across
    the mesh ``axis`` (call INSIDE shard_map).  Returns the received
    matrix: slot s holds the rows sent to this shard by source
    ``(me - s) % D``."""
    D = int(devices)
    if interpret is None:
        interpret = interpret_default()
    return pl.pallas_call(
        functools.partial(_exchange_kernel, axis, D),
        in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct(send.shape, send.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * (2 * D - 1),
        interpret=bool(interpret),
    )(send)


def mesh_frontier_update(
    axis: str, devices: int, state, fok, fcr, alive, cost, capacity: int,
    window: int = 4, n_parents: int | None = None,
    max_count: int | None = None, interpret: bool | None = None,
    child=None,
):
    """The mesh-spanning fused wide stage — the per-shard body, to be
    called INSIDE shard_map over ``axis``.  ``capacity`` is PER-DEVICE;
    inputs are this shard's slice of the candidate table; returns
    (state', fok', fcr', alive', overflowed, fp, child) where the row
    outputs are this shard's slice of the global frontier and
    ``overflowed``/``fp`` are psum'd global (identical on every shard —
    safe for while_loop predicates).

    Three phases: (1) class-hash routing — every alive row is assigned
    to device ``hash(state, fok) % D`` and packed into that target's
    fixed ``rcap`` slot (rank-ordered, with the over-``rcap`` residue
    flagged as overflow, never silently dropped into a wrong verdict);
    (2) the remote-DMA ring exchange (``mesh_exchange``); (3) the
    single-device fused kernel on the received table, with the child
    bit carried as an explicit column because routing scrambled
    positions.  Un-rotating the received slots by source id puts rows
    in source-major order, so the local candidate order — and therefore
    which copy of a duplicate survives — is deterministic.

    Exactness: duplicates and domination pairs share the routing class,
    so every kill is decided with all of its rows local; the surviving
    CONTENT set equals the single-device kernel's whenever neither path
    overflows, and the psum of the per-shard order-insensitive
    fingerprints is bit-identical to the single-device fingerprint.
    Positions differ (rows live on their hash owner), which is honest:
    overflow/escalation, not verdicts, depend on layout."""
    D = int(devices)
    n_loc = state.shape[0]
    W, G = fok.shape[1], fcr.shape[1]
    fcr_dtype = fcr.dtype
    NC = exchange_cols(W, G)
    rcap = mesh_rcap(n_loc, D)
    if interpret is None:
        interpret = interpret_default()
    me = jax.lax.axis_index(axis)

    if child is None:
        if n_parents is not None:
            child = jnp.arange(n_loc, dtype=I32) >= np.int32(int(n_parents))
        else:
            child = jnp.zeros((n_loc,), jnp.bool_)

    # ---- phase 1: class-hash routing into fixed per-target slots ----
    alive_b = alive != 0
    class_cols = [state] + [fok[:, k] for k in range(W)]  # graftlint: disable=trace-host-control
    target = (hashing.hash_rows(class_cols, MESH_ROUTE_SEED)
              % U32(D)).astype(I32)
    onehot = (
        (target[:, None] == jnp.arange(D, dtype=I32)[None, :])
        & alive_b[:, None]
    )
    oh = onehot.astype(I32)
    rank = ((jnp.cumsum(oh, axis=0) - oh) * oh).sum(axis=1)
    counts = oh.sum(axis=0)
    spill = (counts > rcap).any()
    ok = alive_b & (rank < rcap)
    slot = jnp.where(ok, target * rcap + rank, D * rcap)  # D*rcap = drop
    cols = (
        [state.astype(I32)]
        + [jax.lax.bitcast_convert_type(fok[:, k].astype(U32), I32)
           for k in range(W)]  # graftlint: disable=trace-host-control
        + [fcr[:, k].astype(I32) for k in range(G)]  # graftlint: disable=trace-host-control
        + [ok.astype(I32), child.astype(I32)]
    )
    packed = jnp.stack(cols, axis=1)
    buckets = (
        jnp.zeros((D * rcap + 1, NC), I32)
        .at[slot].set(packed)
        [: D * rcap].reshape(D, rcap, NC)
    )

    # ---- phase 2: remote-DMA ring exchange ----
    # Pre-rotate so slot s holds the bucket for target (me + s) % D —
    # the static slot arithmetic the exchange kernel's semaphore pairing
    # relies on; un-rotate the received slots into source-major order.
    fwd = jnp.remainder(me + jnp.arange(D, dtype=I32), np.int32(D))
    recv = mesh_exchange(axis, D, jnp.take(buckets, fwd, axis=0),
                         interpret=interpret)
    bwd = jnp.remainder(me - jnp.arange(D, dtype=I32), np.int32(D))
    table = jnp.take(recv, bwd, axis=0).reshape(D * rcap, NC)

    # ---- phase 3: the local fused stage on the received table ----
    # Parents-first stable partition: dedup keeps the FIRST copy of a
    # duplicate and domination ties keep the EARLIER row, so a parent
    # must precede any identical child — otherwise a re-routed
    # duplicate would resurrect the child bit every round and the
    # engines' (alive' & child) no-growth fixpoint would never settle.
    # The single-device path has this invariant by construction
    # (candidate tables are [parents; expansions]); source-major
    # receive order interleaves sources, so restore it with a
    # cumsum-rank permutation (cheaper than the sort this kernel
    # exists to avoid; empty slots ride along as dead parents).
    ic = (table[:, 2 + W + G] != 0).astype(I32)
    pc = 1 - ic
    dest = jnp.where(
        ic != 0,
        pc.sum() + jnp.cumsum(ic) - ic,
        jnp.cumsum(pc) - pc,
    )
    table = jnp.zeros_like(table).at[dest].set(table)
    st_r = table[:, 0]
    fok_r = jnp.stack(
        [jax.lax.bitcast_convert_type(table[:, 1 + k], U32)
         for k in range(W)],  # graftlint: disable=trace-host-control
        axis=1,
    )
    fcr_r = table[:, 1 + W: 1 + W + G]
    alive_r = table[:, 1 + W + G] != 0
    child_r = table[:, 2 + W + G] != 0
    kst, kfo, kfc, al2, ovf, fp, ch2 = fused_frontier_update(
        st_r, fok_r, fcr_r, alive_r, jnp.zeros((D * rcap,), I32),
        capacity, window=window, max_count=max_count,
        interpret=interpret, child=child_r,
    )
    ovf_g = jax.lax.psum((ovf | spill).astype(I32), axis) > 0
    fp_g = jax.lax.psum(fp, axis)
    return kst, kfo, kfc.astype(fcr_dtype), al2, ovf_g, fp_g, ch2
