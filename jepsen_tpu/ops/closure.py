"""TPU cycle-detection kernels for transactional dependency graphs.

Where Elle uses Tarjan's SCC + BFS cycle search on the JVM (elle 0.1.3, the
reference's dep at jepsen/project.clj:13), this rebuild detects cycles with
dense boolean matrix powering on the MXU: the transitive closure of an
[n, n] adjacency matrix is ``ceil(log2 n)`` squarings ``R ← R ∨ R·R``, each
a single bf16 matmul — exactly the shape the systolic array wants.  Graphs
are padded to multiples of 128 (MXU tile) and batch via ``vmap`` so
thousands of per-key subhistory graphs check in one launch.

Anomaly classification follows Adya's vocabulary (surfaced by the reference
at tests/cycle/wr.clj:30-46):

  G0        cycle of ww edges only
  G1c       cycle of ww+wr edges with ≥1 wr
  G-single  cycle with exactly one rw edge (rest ww/wr)
  G2        cycle with ≥1 rw edge (≥2 when G-single is absent)

Cycle *existence* is decided on-device; witness cycles for human-readable
explanations are recovered host-side (jepsen_tpu.checker.elle) by BFS over
the closure, which is cheap once the flagged edge is known.

``extra`` edges (realtime/process session graphs) are dependency-neutral:
they may participate in any cycle but never count as the ww/wr/rw evidence.
Both the realtime and process graphs are acyclic by construction, so a
cycle in ``ww ∨ extra`` still implies a ww edge is involved.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jepsen_tpu import _platform
from jepsen_tpu._platform import honor_env_platform

# This module is a backend-initializing entry point in its own right
# (checker.elle -> ops.closure, never touching ops.hashing), so the
# JEPSEN_TPU_PLATFORM override must be applied here too (advisor r4).
honor_env_platform()

MXU_TILE = 128


def _pad_to(n: int, tile: int = MXU_TILE) -> int:
    return max(tile, ((n + tile - 1) // tile) * tile)


def _n_steps(n: int) -> int:
    # After k squarings R covers paths of length up to 2^k; need 2^k >= n.
    return max(1, int(np.ceil(np.log2(max(2, n)))))


@functools.partial(jax.jit, static_argnames=("steps",))
def transitive_closure(adj: jax.Array, steps: int) -> jax.Array:
    """Closure of a 0/1 float adjacency matrix by repeated squaring.

    ``adj`` is [n, n] float32 (1.0 = edge).  Matmuls run in bf16 on the MXU;
    only sign information is needed, so bf16 accumulation inaccuracy is
    harmless (sums of non-negative terms never round to zero).
    """

    def body(_, r):
        sq = jnp.dot(
            r.astype(jnp.bfloat16),
            r.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return jnp.maximum(r, (sq > 0).astype(jnp.float32))

    return lax.fori_loop(0, steps, body, adj)


class CycleFlags(NamedTuple):
    """Device-side anomaly verdicts + the closures needed for witnesses."""

    g0: jax.Array  # bool scalar
    g1c: jax.Array
    g_single: jax.Array
    g2: jax.Array  # ≥1 rw in some cycle (g_single implies a weak g2)
    closure_ww: jax.Array  # closure(ww|extra)
    closure_wwr: jax.Array  # closure(ww|wr|extra)
    closure_all: jax.Array  # closure(ww|wr|rw|extra)


@functools.partial(jax.jit, static_argnames=("steps",))
def classify_cycles(
    ww: jax.Array, wr: jax.Array, rw: jax.Array, extra: jax.Array, steps: int
) -> CycleFlags:
    """Compute Adya cycle-anomaly flags for one dependency graph.

    Inputs are [n, n] float32 0/1 matrices.  The edge-presence tests use the
    pattern "∃ edge (a, b) of type T with a return path b→a in graph G" —
    computed as ``(T ∧ closureᵀ(G)).any()`` without leaving the device.
    """
    c_ww = transitive_closure(jnp.maximum(ww, extra), steps)
    c_wwr = transitive_closure(jnp.maximum(c_ww, wr), steps)  # warm-start
    c_all = transitive_closure(jnp.maximum(c_wwr, rw), steps)

    g0 = jnp.trace(c_ww) > 0
    g1c = jnp.any((wr > 0) & (c_wwr.T > 0))
    g_single = jnp.any((rw > 0) & (c_wwr.T > 0))
    g2 = jnp.any((rw > 0) & (c_all.T > 0))
    return CycleFlags(g0, g1c, g_single, g2, c_ww, c_wwr, c_all)


class CycleHints(NamedTuple):
    """Anomaly flags + one witness *hint* per anomaly: the (a, b) indices
    of an offending edge (diag node for G0), or (-1, -1).  Hints replace
    shipping [n, n] closures to the host — witness cycles are recovered by
    host BFS over the (sparse, already host-resident) adjacency, so a
    10k-node graph returns 4 flags + 8 ints instead of 3 × 400 MB."""

    g0: jax.Array
    g1c: jax.Array
    g_single: jax.Array
    g2: jax.Array
    h_g0: jax.Array  # [2] int32
    h_g1c: jax.Array
    h_g_single: jax.Array
    h_g2: jax.Array


def _first_edge(mask: jax.Array) -> jax.Array:
    """(i, j) of some true cell of a [n, n] bool mask, else (-1, -1)."""
    n = mask.shape[1]
    flat = mask.reshape(-1)
    idx = jnp.argmax(flat)
    found = flat[idx]
    ij = jnp.stack([idx // n, idx % n]).astype(jnp.int32)
    return jnp.where(found, ij, jnp.full(2, -1, jnp.int32))


@functools.partial(jax.jit, static_argnames=("steps",))
def classify_cycles_hints(
    ww: jax.Array, wr: jax.Array, rw: jax.Array, extra: jax.Array, steps: int
) -> CycleHints:
    """classify_cycles, but returning witness hints instead of closures —
    the scalable form (host transfer is O(1) regardless of n)."""
    c_ww = transitive_closure(jnp.maximum(ww, extra), steps)
    c_wwr = transitive_closure(jnp.maximum(c_ww, wr), steps)
    c_all = transitive_closure(jnp.maximum(c_wwr, rw), steps)

    diag = jnp.diagonal(c_ww) > 0
    v = jnp.argmax(diag).astype(jnp.int32)
    h_g0 = jnp.where(diag[v], jnp.stack([v, v]), jnp.full(2, -1, jnp.int32))
    m_g1c = (wr > 0) & (c_wwr.T > 0)
    m_gs = (rw > 0) & (c_wwr.T > 0)
    m_g2 = (rw > 0) & (c_all.T > 0)
    return CycleHints(
        jnp.any(diag),
        m_g1c.any(),
        m_gs.any(),
        m_g2.any(),
        h_g0,
        _first_edge(m_g1c),
        _first_edge(m_gs),
        _first_edge(m_g2),
    )


# vmapped batch form: [b, n, n] inputs, shared step count — the per-key /
# independent scale-out path (BASELINE config 4 for Elle: many small graphs
# in one launch).
classify_cycles_batch = jax.jit(
    jax.vmap(classify_cycles_hints, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("steps",),
)


def pad_adj(m: np.ndarray, size: int) -> np.ndarray:
    """Zero-pad a bool adjacency to [size, size] float32."""
    out = np.zeros((size, size), dtype=np.float32)
    n = m.shape[0]
    out[:n, :n] = m.astype(np.float32)
    return out


_EMPTY_FLAGS = {"G0": False, "G1c": False, "G-single": False, "G2": False}
_EMPTY_HINTS = {"G0": None, "G1c": None, "G-single": None, "G2": None}


def _hints_out(res, i=None) -> tuple[dict, dict]:
    def get(x):
        return np.asarray(x) if i is None else np.asarray(x)[i]

    flags = {
        "G0": bool(get(res.g0)),
        "G1c": bool(get(res.g1c)),
        "G-single": bool(get(res.g_single)),
        "G2": bool(get(res.g2)),
    }
    hints = {}
    for name, h in (
        ("G0", res.h_g0),
        ("G1c", res.h_g1c),
        ("G-single", res.h_g_single),
        ("G2", res.h_g2),
    ):
        pair = get(h)
        hints[name] = (int(pair[0]), int(pair[1])) if pair[0] >= 0 else None
    return flags, hints


def classify_graph(ww: np.ndarray, wr: np.ndarray, rw: np.ndarray, extra: np.ndarray):
    """Host convenience wrapper: pad → device classify → (flags, hints).

    ``hints[anomaly]`` is an (a, b) witness-edge index pair (diag node for
    G0) or None; witness cycles are recovered host-side by BFS over the
    adjacency (jepsen_tpu.checker.elle), so nothing O(n²) leaves the
    device.
    """
    n = ww.shape[0]
    if n == 0:
        return dict(_EMPTY_FLAGS), dict(_EMPTY_HINTS)
    size = _pad_to(n)
    steps = _n_steps(n)
    res = classify_cycles_hints(
        jnp.asarray(pad_adj(ww, size)),
        jnp.asarray(pad_adj(wr, size)),
        jnp.asarray(pad_adj(rw, size)),
        jnp.asarray(pad_adj(extra, size)),
        steps,
    )
    return _hints_out(res)


def classify_graphs(graphs) -> list[tuple[dict, dict]]:
    """Classify MANY dependency graphs in batched device launches.

    ``graphs``: sequence of (ww, wr, rw, extra) numpy bool matrix tuples
    (ragged sizes fine).  Graphs are bucketed by padded size (MXU tiles)
    and each bucket runs as ONE vmapped kernel — the per-key scale-out
    shape (reference: independent.clj:285-307 bounded-pmap becomes a
    batch axis).  Returns (flags, hints) per graph, in input order.
    """
    out: list = [None] * len(graphs)
    buckets: dict[int, list[int]] = {}
    for i, (ww, _wr, _rw, _extra) in enumerate(graphs):
        n = ww.shape[0]
        if n == 0:
            out[i] = (dict(_EMPTY_FLAGS), dict(_EMPTY_HINTS))
        else:
            buckets.setdefault(_pad_to(n), []).append(i)
    for size, idxs in sorted(buckets.items()):
        steps = _n_steps(size)
        stacks = [
            np.stack([pad_adj(graphs[i][k], size) for i in idxs]) for k in range(4)
        ]
        res = classify_cycles_batch(*(jnp.asarray(s) for s in stacks), steps)
        res = CycleHints(*(np.asarray(x) for x in res))  # one transfer per field
        for j, i in enumerate(idxs):
            out[i] = _hints_out(res, j)
    return out


# ---------------------------------------------------------------------------
# Mesh-sharded closure: one big graph across many chips
# ---------------------------------------------------------------------------


def transitive_closure_sharded(adj: np.ndarray, mesh, steps: int | None = None):
    """Closure of one large adjacency row-block-sharded over ``mesh``.

    Each device owns an [n/D, n] row block; per squaring step it
    ``all_gather``s the full matrix over the mesh axis (ICI) and multiplies
    its block against it on the MXU — the classic 1-D sharded matmul.  Use
    when a single dependency graph outgrows one chip's HBM (the Elle
    context-parallel axis; SURVEY.md §2.5 item 5).
    """
    from jax.sharding import NamedSharding, PartitionSpec

    axis = mesh.axis_names[0]
    D = mesh.devices.size
    n0 = adj.shape[0]
    n = max(MXU_TILE, ((n0 + D * MXU_TILE - 1) // (D * MXU_TILE)) * D * MXU_TILE // D * D)
    steps = steps if steps is not None else _n_steps(n0)
    padded = pad_adj(np.asarray(adj, dtype=bool), n)

    def body(r_blk):
        # bool carry: the all_gather ships 1-byte cells over ICI, not f32.
        def step_fn(_, r):
            full = jax.lax.all_gather(r, axis, axis=0, tiled=True)
            sq = jnp.dot(
                r.astype(jnp.bfloat16),
                full.astype(jnp.bfloat16),
                preferred_element_type=jnp.float32,
            )
            return r | (sq > 0)

        return lax.fori_loop(0, steps, step_fn, r_blk.astype(bool))

    fn = jax.jit(
        _platform.shard_map(
            body,
            mesh=mesh,
            in_specs=PartitionSpec(axis, None),
            out_specs=PartitionSpec(axis, None),
        )
    )
    arr = jax.device_put(
        padded.astype(bool), NamedSharding(mesh, PartitionSpec(axis, None))
    )
    return np.asarray(fn(arr))[:n0, :n0]


# ---------------------------------------------------------------------------
# CPU oracle (differential-test reference, mirrors SURVEY.md §4 pattern 1)
# ---------------------------------------------------------------------------


def transitive_closure_np(adj: np.ndarray) -> np.ndarray:
    """Pure-numpy Warshall closure — the slow-but-obvious oracle."""
    r = adj.copy().astype(bool)
    n = r.shape[0]
    for k in range(n):
        r |= np.outer(r[:, k], r[k, :])
    return r
