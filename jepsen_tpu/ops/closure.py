"""TPU cycle-detection kernels for transactional dependency graphs.

Where Elle uses Tarjan's SCC + BFS cycle search on the JVM (elle 0.1.3, the
reference's dep at jepsen/project.clj:13), this rebuild detects cycles with
dense boolean matrix powering on the MXU: the transitive closure of an
[n, n] adjacency matrix is ``ceil(log2 n)`` squarings ``R ← R ∨ R·R``, each
a single bf16 matmul — exactly the shape the systolic array wants.  Graphs
are padded to multiples of 128 (MXU tile) and batch via ``vmap`` so
thousands of per-key subhistory graphs check in one launch.

Anomaly classification follows Adya's vocabulary (surfaced by the reference
at tests/cycle/wr.clj:30-46):

  G0        cycle of ww edges only
  G1c       cycle of ww+wr edges with ≥1 wr
  G-single  cycle with exactly one rw edge (rest ww/wr)
  G2        cycle with ≥1 rw edge (≥2 when G-single is absent)

Cycle *existence* is decided on-device; witness cycles for human-readable
explanations are recovered host-side (jepsen_tpu.checker.elle) by BFS over
the closure, which is cheap once the flagged edge is known.

``extra`` edges (realtime/process session graphs) are dependency-neutral:
they may participate in any cycle but never count as the ww/wr/rw evidence.
Both the realtime and process graphs are acyclic by construction, so a
cycle in ``ww ∨ extra`` still implies a ww edge is involved.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

MXU_TILE = 128


def _pad_to(n: int, tile: int = MXU_TILE) -> int:
    return max(tile, ((n + tile - 1) // tile) * tile)


def _n_steps(n: int) -> int:
    # After k squarings R covers paths of length up to 2^k; need 2^k >= n.
    return max(1, int(np.ceil(np.log2(max(2, n)))))


@functools.partial(jax.jit, static_argnames=("steps",))
def transitive_closure(adj: jax.Array, steps: int) -> jax.Array:
    """Closure of a 0/1 float adjacency matrix by repeated squaring.

    ``adj`` is [n, n] float32 (1.0 = edge).  Matmuls run in bf16 on the MXU;
    only sign information is needed, so bf16 accumulation inaccuracy is
    harmless (sums of non-negative terms never round to zero).
    """

    def body(_, r):
        sq = jnp.dot(
            r.astype(jnp.bfloat16),
            r.astype(jnp.bfloat16),
            preferred_element_type=jnp.float32,
        )
        return jnp.maximum(r, (sq > 0).astype(jnp.float32))

    return lax.fori_loop(0, steps, body, adj)


class CycleFlags(NamedTuple):
    """Device-side anomaly verdicts + the closures needed for witnesses."""

    g0: jax.Array  # bool scalar
    g1c: jax.Array
    g_single: jax.Array
    g2: jax.Array  # ≥1 rw in some cycle (g_single implies a weak g2)
    closure_ww: jax.Array  # closure(ww|extra)
    closure_wwr: jax.Array  # closure(ww|wr|extra)
    closure_all: jax.Array  # closure(ww|wr|rw|extra)


@functools.partial(jax.jit, static_argnames=("steps",))
def classify_cycles(
    ww: jax.Array, wr: jax.Array, rw: jax.Array, extra: jax.Array, steps: int
) -> CycleFlags:
    """Compute Adya cycle-anomaly flags for one dependency graph.

    Inputs are [n, n] float32 0/1 matrices.  The edge-presence tests use the
    pattern "∃ edge (a, b) of type T with a return path b→a in graph G" —
    computed as ``(T ∧ closureᵀ(G)).any()`` without leaving the device.
    """
    c_ww = transitive_closure(jnp.maximum(ww, extra), steps)
    c_wwr = transitive_closure(jnp.maximum(c_ww, wr), steps)  # warm-start
    c_all = transitive_closure(jnp.maximum(c_wwr, rw), steps)

    g0 = jnp.trace(c_ww) > 0
    g1c = jnp.any((wr > 0) & (c_wwr.T > 0))
    g_single = jnp.any((rw > 0) & (c_wwr.T > 0))
    g2 = jnp.any((rw > 0) & (c_all.T > 0))
    return CycleFlags(g0, g1c, g_single, g2, c_ww, c_wwr, c_all)


# vmapped batch form: [b, n, n] inputs, shared step count.
classify_cycles_batch = jax.jit(
    jax.vmap(classify_cycles, in_axes=(0, 0, 0, 0, None)),
    static_argnames=("steps",),
)


def pad_adj(m: np.ndarray, size: int) -> np.ndarray:
    """Zero-pad a bool adjacency to [size, size] float32."""
    out = np.zeros((size, size), dtype=np.float32)
    n = m.shape[0]
    out[:n, :n] = m.astype(np.float32)
    return out


def classify_graph(ww: np.ndarray, wr: np.ndarray, rw: np.ndarray, extra: np.ndarray):
    """Host convenience wrapper: pad → device classify → numpy results.

    Returns (flags dict, closures dict) with numpy arrays trimmed back to n.
    """
    n = ww.shape[0]
    if n == 0:
        z = np.zeros((0, 0), dtype=bool)
        return (
            {"G0": False, "G1c": False, "G-single": False, "G2": False},
            {"ww": z, "wwr": z, "all": z},
        )
    size = _pad_to(n)
    steps = _n_steps(n)
    res = classify_cycles(
        jnp.asarray(pad_adj(ww, size)),
        jnp.asarray(pad_adj(wr, size)),
        jnp.asarray(pad_adj(rw, size)),
        jnp.asarray(pad_adj(extra, size)),
        steps,
    )
    flags = {
        "G0": bool(res.g0),
        "G1c": bool(res.g1c),
        "G-single": bool(res.g_single),
        "G2": bool(res.g2),
    }
    closures = {
        "ww": np.asarray(res.closure_ww)[:n, :n] > 0,
        "wwr": np.asarray(res.closure_wwr)[:n, :n] > 0,
        "all": np.asarray(res.closure_all)[:n, :n] > 0,
    }
    return flags, closures


# ---------------------------------------------------------------------------
# CPU oracle (differential-test reference, mirrors SURVEY.md §4 pattern 1)
# ---------------------------------------------------------------------------


def transitive_closure_np(adj: np.ndarray) -> np.ndarray:
    """Pure-numpy Warshall closure — the slow-but-obvious oracle."""
    r = adj.copy().astype(bool)
    n = r.shape[0]
    for k in range(n):
        r |= np.outer(r[:, k], r[k, :])
    return r
