"""TPU compute kernels (jit/vmap/pjit) for the checker phase.

This package is L7's device half: the host-side checker framework
(jepsen_tpu.checker) packs histories into tensors and calls these kernels.

  wgl         — frontier-parallel Wing–Gong–Lowe linearizability search
  hashing     — row hashing + frontier dedup/compaction (sort/bucket
                backends + the dedup-backend resolver)
  wide_kernel — the fused Pallas wide-stage frontier update (the
                "pallas" dedup backend; interpret mode off-chip)
  scc         — dense reachability / SCC kernels for the Elle-style txn checker
"""
