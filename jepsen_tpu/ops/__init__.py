"""TPU compute kernels (jit/vmap/pjit) for the checker phase.

This package is L7's device half: the host-side checker framework
(jepsen_tpu.checker) packs histories into tensors and calls these kernels.

  wgl      — frontier-parallel Wing–Gong–Lowe linearizability search
  hashing  — row hashing + sort-based frontier dedup/compaction
  scc      — dense reachability / SCC kernels for the Elle-style txn checker
"""
