/* Jump the system wall clock by a signed number of milliseconds.
 *
 * Usage: bump-time DELTA_MS
 *
 * TPU-rebuild equivalent of the reference's on-node clock-jump tool
 * (jepsen/resources/bump-time.c, driven by jepsen/src/jepsen/nemesis/
 * time.clj:86-96); written fresh for this repo against clock_gettime/
 * clock_settime.  Exit 0 on success, 1 on clock errors, 2 on usage.
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#define NS_PER_S 1000000000LL
#define NS_PER_MS 1000000LL

int main(int argc, char **argv) {
  long long delta_ms, total_ns;
  struct timespec ts;
  char *end;

  if (argc != 2) {
    fprintf(stderr, "usage: %s DELTA_MS\n", argv[0]);
    return 2;
  }
  delta_ms = strtoll(argv[1], &end, 10);
  if (*end != '\0') {
    fprintf(stderr, "%s: not an integer: %s\n", argv[0], argv[1]);
    return 2;
  }
  if (clock_gettime(CLOCK_REALTIME, &ts)) {
    perror("clock_gettime");
    return 1;
  }
  total_ns = ts.tv_sec * NS_PER_S + ts.tv_nsec + delta_ms * NS_PER_MS;
  if (total_ns < 0) {
    fprintf(stderr, "%s: refusing to set clock before the epoch\n", argv[0]);
    return 1;
  }
  ts.tv_sec = total_ns / NS_PER_S;
  ts.tv_nsec = total_ns % NS_PER_S;
  if (clock_settime(CLOCK_REALTIME, &ts)) {
    perror("clock_settime");
    return 1;
  }
  return 0;
}
