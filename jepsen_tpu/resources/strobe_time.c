/* Oscillate the system wall clock: alternately add and subtract DELTA_MS
 * every PERIOD_MS, for DURATION_S seconds.
 *
 * Usage: strobe-time DELTA_MS PERIOD_MS DURATION_S
 *
 * TPU-rebuild equivalent of the reference's clock-strobe tool
 * (jepsen/resources/strobe-time.c, driven by jepsen/src/jepsen/nemesis/
 * time.clj:92-96).  The loop is paced by CLOCK_MONOTONIC so the strobing
 * itself cannot be derailed by the wall-clock jumps it causes.
 */
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#define NS_PER_S 1000000000LL
#define NS_PER_MS 1000000LL

static long long mono_ns(void) {
  struct timespec ts;
  if (clock_gettime(CLOCK_MONOTONIC, &ts)) {
    perror("clock_gettime(CLOCK_MONOTONIC)");
    exit(1);
  }
  return ts.tv_sec * NS_PER_S + ts.tv_nsec;
}

static void bump_wall(long long delta_ms) {
  struct timespec ts;
  long long total_ns;
  if (clock_gettime(CLOCK_REALTIME, &ts)) {
    perror("clock_gettime");
    exit(1);
  }
  total_ns = ts.tv_sec * NS_PER_S + ts.tv_nsec + delta_ms * NS_PER_MS;
  if (total_ns < 0)
    return; /* never strobe across the epoch */
  ts.tv_sec = total_ns / NS_PER_S;
  ts.tv_nsec = total_ns % NS_PER_S;
  if (clock_settime(CLOCK_REALTIME, &ts)) {
    perror("clock_settime");
    exit(1);
  }
}

int main(int argc, char **argv) {
  long long delta_ms, period_ms, duration_s, deadline;
  struct timespec nap;
  int sign = 1;

  if (argc != 4) {
    fprintf(stderr, "usage: %s DELTA_MS PERIOD_MS DURATION_S\n", argv[0]);
    return 2;
  }
  delta_ms = atoll(argv[1]);
  period_ms = atoll(argv[2]);
  duration_s = atoll(argv[3]);
  if (period_ms <= 0 || duration_s < 0) {
    fprintf(stderr, "%s: PERIOD_MS must be > 0, DURATION_S >= 0\n", argv[0]);
    return 2;
  }
  nap.tv_sec = period_ms / 1000;
  nap.tv_nsec = (period_ms % 1000) * NS_PER_MS;
  deadline = mono_ns() + duration_s * NS_PER_S;
  while (mono_ns() < deadline) {
    bump_wall(sign * delta_ms);
    sign = -sign;
    nanosleep(&nap, NULL);
  }
  /* Leave the clock where a whole number of strobe pairs would: if we
   * ended mid-pair (last bump unbalanced), undo it. */
  if (sign < 0)
    bump_wall(-delta_ms);
  return 0;
}
