"""Entry points for confirmation-sweep worker processes.

Refutations from the fast device engines are hash-deduped, so
``parallel.batch_analysis`` confirms each one with the exact CPU
config-set sweep in a worker process, concurrent with the remaining
device stages (the reference seam: checkers must run anywhere,
jepsen/src/jepsen/independent.clj:285-307).

This module is deliberately import-light.  A spawned worker unpickles
its initializer and task functions by importing the module that defines
them — if that pulls in jax-heavy modules (``ops.hashing`` builds
``jnp`` constants at import time), the worker initializes an accelerator
backend and, under the axon TPU plugin, dies fighting the parent for the
chip (the round-3 BrokenProcessPool regression).  So:

  * the import chain here is jax-free: ``checker.wgl_cpu`` ->
    ``history`` + ``models`` are numpy/stdlib only, and the sweep itself
    never touches jax;
  * ``init`` pins any *later* jax import to CPU via the config flag —
    the axon plugin overrides the JAX_PLATFORMS env var, so the env var
    alone is not enough (same dance as tests/conftest.py).
"""

from __future__ import annotations

import os


def init() -> None:
    """Pool initializer: force any jax backend in this process to CPU.

    Runs before any task, i.e. before any task's import chain could
    initialize a backend.  Importing jax here does NOT initialize a
    backend (that happens on first device use); it just lets us set the
    config flag the axon plugin respects.
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


def confirm_refutation(
    model, history, max_configs: int, stop_at_index: int | None = None
) -> dict:
    """Exact CPU config-set sweep over one refuted history.

    The sweep's kills are content-decided, so its verdict is exact; it
    confirms (or, in the ~1e-13 hash-collision case, overturns) a fast
    device engine's provisional refutation.  ``stop_at_index`` bounds the
    sweep to the prefix ending at the device's failure barrier — a
    genuine refutation dies by there, so the suffix is never swept.
    """
    from jepsen_tpu.checker import wgl_cpu

    return wgl_cpu.sweep_analysis(
        model, history, max_configs=max_configs, stop_at_index=stop_at_index
    )


def probe_backend() -> dict:
    """Diagnostic task for tests/warm-up: report this worker's jax
    platform and which jepsen_tpu modules its tasks so far dragged in.

    Initializes the backend (first device use), so the platform must
    come back "cpu" even when the parent's environment was pointed at a
    TPU.  The module list (snapshotted before this probe imports jax)
    guards the import-light invariant: a confirmation must never have
    imported the kernel modules.
    """
    import sys

    modules = sorted(k for k in sys.modules if k.startswith("jepsen_tpu"))
    jax_loaded = "jax" in sys.modules
    import jax

    return {
        "platform": jax.default_backend(),
        "pid": os.getpid(),
        "jepsen_tpu_modules": modules,
        "jax_loaded_before_probe": jax_loaded,
    }
