"""The CheckService scheduler: admission, packing, placement — separated.

PR 4's service was a monolithic window-then-launch loop: one queue, one
batch at a time, launch on whatever device jax defaulted to.  Its own
telemetry showed the cost — ``serve.batch`` occupancy 0.50–0.57 (the
device idles between batches and pads dead lanes inside them) and p50
~0.2 s for a ~3 ms request riding a worst-lane batch (PERF.md round 7).
This package is the scheduler refactor ROADMAP item 2 calls for, split
along the three decisions a serving scheduler actually makes:

  * **admission** (``sched.admission``) — WHO gets in, and into which
    latency class: an ``interactive`` tier (small likely-valid
    histories; served by a speculative greedy single-rung fast path)
    and a ``batch`` tier (everything else), each with its own bounded
    queue and its own retry-after EWMA, so a queue-full interactive
    request is told to come back in fast-path units, not batch-ladder
    units.  Graph-shaped work (elle ``CycleChecker`` & co.) is tagged
    non-geometry-batchable here and runs on a host side lane — it never
    occupies a geometry bucket or stalls packable ladder work.
  * **packing** (``sched.packing``) — WHAT shares a launch, over TIME:
    continuous batching.  A ``RungFeeder`` is handed to
    ``parallel.batch.batch_analysis(admission=...)`` and consulted at
    every rung boundary: geometry-compatible queued requests JOIN the
    running ladder as members resolve and free lane slots (streaming
    batched beam search, arXiv:2010.02164), verdicts demux the moment
    they are decided, and true per-rung occupancy is recorded.
  * **placement** (``sched.placement``) — WHERE a packed batch runs:
    lane-parallel across an N-device mesh (the ``_platform.shard_map``
    shim ``parallel/sharded.py`` builds on), with a verdict-parity
    assertion against single-device execution.

``serve.service.CheckService`` composes the three; nothing here decides
a verdict — soundness stays in the ladder.
"""

from jepsen_tpu.serve.sched.admission import (
    CLASSES,
    AdmissionQueues,
    classify,
    geometry_batchable,
    graph_batch_key,
)
from jepsen_tpu.serve.sched.packing import RungFeeder
from jepsen_tpu.serve.sched.placement import Placement, PlacementMismatch, assert_parity

__all__ = [
    "CLASSES",
    "AdmissionQueues",
    "Placement",
    "PlacementMismatch",
    "RungFeeder",
    "assert_parity",
    "classify",
    "geometry_batchable",
    "graph_batch_key",
]
