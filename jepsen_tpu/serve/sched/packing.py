"""Packing: continuous batching via the ladder's rung-admission hook.

``RungFeeder`` is the bridge object ``CheckService`` hands to
``parallel.batch.batch_analysis(admission=...)``.  The ladder consults
it at every rung boundary:

  * ``poll`` — geometry-compatible queued requests JOIN the running
    ladder (entering at rung 0, the greedy walk, so their verdict path
    is identical to a one-shot call); lane slots freed by resolved
    members are what the joiners recycle.  The poll also gives the
    service a bounded mid-ladder service opportunity (expiring overdue
    queued requests and running an interactive fast-path wave), which
    is what bounds interactive latency by ONE RUNG instead of one
    batch.
  * ``on_result`` — a member's verdict demuxes the moment the ladder
    decides it: the caller's future resolves mid-ladder instead of at
    batch completion.
  * ``on_rung`` — true per-rung lane occupancy (live lanes over the
    padded batch axis the kernel actually launched), the continuous
    counterpart of PR 4's per-batch occupancy spans.  The aggregate is
    DEVICE-TIME-WEIGHTED: each rung's live/padded ratio counts in
    proportion to its launch seconds (compile + execute, not host-side
    packing/demux), so a 2 ms underfull greedy tail launch cannot
    swamp the 300 ms full-width beam rungs that carried the device's
    actual work.  This is the number the ≥ 0.80 acceptance gate reads.

The feeder also advertises ``pad_lanes`` — the fixed batch axis every
rung of this ladder launches at (the padded width of the group's
members plus its queued backlog, clamped to the service width).
Joiners and resolved lanes then recycle slots inside ONE compiled
kernel shape; without it, membership churn walks the ladder through a
fresh XLA compile per batch size (measured ~2.5 s for one mid-service
async rung on CPU — worse than the batch it served).

The feeder never decides verdicts and never blocks the ladder: every
hook call is bounded work on the scheduler thread, and a hook failure
degrades to "no joiners" inside ``batch_analysis`` by contract.
"""

from __future__ import annotations

import time

from jepsen_tpu import obs
from jepsen_tpu.obs import metrics


class RungFeeder:
    """One running ladder's admission hook + demux table.

    ``members`` stays index-aligned with the ladder's result list: the
    ladder assigns each joiner index ``len(histories)`` at poll time,
    which is exactly ``len(self.members)`` here — appending on return
    keeps the two counters mirrored (the demux contract in
    ``batch_analysis``'s docstring)."""

    def __init__(self, service, group, members):
        self.service = service
        self.group = group
        self.members: list = list(members)
        #: the fixed batch axis every rung of this ladder launches at
        #: (batch_analysis reads this): the padded width of the work
        #: this GROUP can actually fill — initial members plus the
        #: same-group queue at feeder construction, clamped to the
        #: service width.  Pinning per-ladder keeps membership churn
        #: from walking the ladder through a fresh XLA compile per
        #: batch size (a narrow straggler's mid-serve async compile
        #: measured 4.7 s — it stalled serving a full run), while a
        #: 2-member odd-geometry group pads to 8 lanes, not the
        #: service's 16 — its kernels are separate compiles anyway
        #: (different geometry bucket), so full-width padding there
        #: bought no shape reuse, only dead lane-slot-seconds.
        from jepsen_tpu.parallel import batch as _batch

        with service._lock:
            backlog = sum(
                1 for r in service._adm.queues["batch"] if r.group == group
            )
        self.pad_lanes = _batch.padded_batch(
            min(max(1, service.max_batch),
                max(1, len(self.members) + backlog)),
            service._placement.mesh,
        )
        #: rung-occupancy accumulators (read into service stats):
        #: live lane-seconds over launched lane-slot-seconds — the
        #: device-TIME-utilization aggregate, so a 2 ms underfull tail
        #: launch can't swamp the full-width rungs that carried the
        #: work.
        self.rungs = 0
        self.lane_sum = 0.0
        self.slot_sum = 0.0
        self.joined = 0
        self.t_start = time.monotonic()
        #: a closed feeder admits no more joiners — flipped by the
        #: service when the ladder must drain (hung-launch abandonment:
        #: the zombie thread's polls must not pull queued requests into
        #: a ladder nobody will settle; device-loss re-placement: the
        #: mesh is changing under it).
        self.closed = False
        #: the placement generation this ladder launched under; a
        #: mid-ladder device-loss shrink bumps the service's counter
        #: and the mismatch closes the feeder at the next poll.
        self.placement_gen = service._placement.generation

    def close(self) -> None:
        self.closed = True

    # -- the batch_analysis hook protocol ---------------------------------

    def poll(self, *, stage: int, lanes: int):
        """New member histories for the running ladder (may be empty).
        Budget: the service's ``max_batch`` minus the lanes still live —
        resolved members' slots are recycled, the batch never grows past
        the configured width."""
        svc = self.service
        joiners = svc._admit_joiners(self, stage=stage, lanes=lanes)
        for r in joiners:
            self.members.append(r)
            self.joined += 1
            with obs.attach(r.ctx):
                obs.counter(
                    "serve.rung_joined", stage=stage, client=r.client
                )
        return [r.history for r in joiners]

    def on_result(self, i: int, result: dict) -> None:
        """Mid-ladder demux: member ``i``'s verdict is final — settle
        its future now."""
        self.service._settle_member(self.members[i], result)

    def on_rung(self, *, stage: int, engine: str, capacity: int,
                lanes: int, padded: int, seconds: float = 0.0) -> None:
        occ = lanes / max(1, padded)
        w = max(float(seconds), 1e-6)  # device-time weight per rung
        self.rungs += 1
        self.lane_sum += lanes * w
        self.slot_sum += padded * w
        metrics.set_gauge("serve.continuous_occupancy", round(occ, 4))
        obs.gauge(
            "serve.rung_occupancy", round(occ, 4),
            stage=stage, engine=engine, capacity=capacity,
            lanes=lanes, padded=padded, seconds=round(w, 6),
        )

    # -- introspection -----------------------------------------------------

    @property
    def mean_occupancy(self) -> float | None:
        return (
            round(self.lane_sum / self.slot_sum, 4) if self.slot_sum else None
        )

    def unresolved(self) -> list:
        """Members whose futures the ladder's early demux did NOT settle
        (unknowns and confirmation leftovers) — the service resolves
        them from the returned result list."""
        return [r for r in self.members if not r.future.done()]
