"""Placement: which devices a packed batch launches on.

The ladder is lane-parallel by construction — one vmapped kernel over
the padded batch axis — so placing a packed batch on an N-device mesh
is sharding that axis: ``batch_analysis(mesh=...)`` device_puts every
stacked operand with a lane-axis ``NamedSharding``, and the greedy
fast-path wave goes through ``parallel.sharded.lane_shard`` (the
``_platform.shard_map`` shim ``parallel/sharded.py`` builds every mesh
kernel on).  Each device sweeps its lane shard in lockstep; padded
batch sizes round up to a mesh multiple so shards stay equal.

Placement is pure arbitration — WHERE, never WHAT: a mesh-sharded
launch must produce verdicts identical to single-device execution.
``assert_parity`` is that check (the same invariant
``__graft_entry__.dryrun_multichip`` asserts for the production
ladder), runnable at service start (``verify_placement=True``) and in
the test suite.

Mesh-kernel routing (round 12): with ``dedup_backend="pallas"`` and a
>1-device placement, a ladder that exhausts its capacity rungs rescues
the unresolved lanes on the mesh-SPANNING fused wide stage
(``parallel.sharded.mesh_kernel_analysis``) — candidate rows hash-route
to their class-owner device over remote-DMA ring exchanges and the
fused dedup/domination/compaction runs per shard, so the feasible
frontier capacity scales linearly with mesh size.  The routing is
static and honest: a 1-device placement (including one produced by
``shrink_to`` after device loss) or an infeasible per-device VMEM shape
falls back to the single-device pallas ladder (then bucket/sort) with
verdicts unchanged — ``shrink_to`` evicts the dead mesh's compiled
mesh-kernel runners along with the lane-shard kernels, so a mid-run
loss drains at the rung boundary and re-routes instead of relaunching
on a dead device.
"""

from __future__ import annotations

import logging
from typing import Sequence

from jepsen_tpu import obs

logger = logging.getLogger(__name__)


class PlacementMismatch(AssertionError):
    """Mesh-sharded verdicts disagreed with single-device verdicts —
    a placement (sharding) bug, never an acceptable degradation."""


class Placement:
    """The service's launch-placement policy.

    ``devices=N`` lane-shards every packed batch across the first N
    jax devices (a 1-D ``histories`` mesh via
    ``parallel.batch.make_mesh``); ``mesh=`` pins an explicit mesh;
    neither means single-device (jax's default placement).  The mesh is
    built lazily — constructing a Placement must not initialize a
    backend (the CLI builds one before deciding whether to serve)."""

    def __init__(self, *, devices: int | None = None, mesh=None):
        if devices is not None and mesh is not None:
            raise TypeError("pass devices= or mesh=, not both")
        self.devices = int(devices) if devices is not None else None
        self._mesh = mesh
        #: bumped by every shrink_to — running ladders compare it to
        #: the generation they launched under and drain on mismatch.
        self.generation = 0
        #: devices removed by shrink_to (operator-visible in describe).
        self.lost: list = []

    @property
    def mesh(self):
        if self._mesh is None and self.devices is not None:
            from jepsen_tpu.parallel import batch

            self._mesh = batch.make_mesh(self.devices)
        return self._mesh

    @property
    def n_devices(self) -> int:
        m = self.mesh
        return int(m.devices.size) if m is not None else 1

    def span(self, *, requests: int, tier: str):
        """The per-launch ``serve.placement`` telemetry span: where this
        batch ran and how wide."""
        return obs.span(
            "serve.placement", devices=self.n_devices, requests=requests,
            tier=tier, sharded=self.mesh is not None,
        )

    def probe(self) -> tuple[list, list]:
        """Health-probe every mesh device with a tiny round-trip op;
        returns ``(healthy, failed)`` device lists.  Device loss on a
        real chip surfaces as the put/readback raising — and the
        ``faults.INJECT`` seam runs first with
        ``{"what": "placement.probe", "device": i}`` so chaos harnesses
        can fail a virtual device deterministically."""
        import numpy as np

        import jax

        from jepsen_tpu import faults

        m = self.mesh
        if m is None:
            return [], []
        healthy, failed = [], []
        for i, dev in enumerate(m.devices.ravel().tolist()):
            try:
                hook = faults.INJECT
                if hook is not None:
                    hook({"what": "placement.probe", "device": i}, 0)
                x = jax.device_put(np.int32(1), dev)
                if int(jax.device_get(x)) != 1:
                    raise RuntimeError("device readback mismatch")
                healthy.append(dev)
            except Exception:  # noqa: BLE001 — a failing device is the
                # condition being probed for, whatever the exception
                logger.warning("device %s failed its health probe",
                               dev, exc_info=True)
                failed.append(dev)
        return healthy, failed

    def shrink_to(self, devices: Sequence) -> None:
        """Re-place onto the surviving devices (device-loss recovery):
        rebuild the 1-D mesh over ``devices``, bump the generation so
        running ladders drain at their next rung boundary, and evict the
        dead mesh's compiled kernels — the lane-shard runners AND the
        mesh-spanning fused-stage runners (``sharded.forget_mesh``
        clears both; they hold references to lost devices).  A carried
        frontier resumes on the shrunk placement: if only one device
        survives, the mesh-kernel path statically routes to the
        single-device pallas ladder (then bucket/sort) with verdicts
        unchanged."""
        import numpy as np

        from jepsen_tpu.parallel import sharded
        from jax.sharding import Mesh

        old = self._mesh
        axis = old.axis_names[0] if old is not None else "histories"
        self.lost.extend(
            d for d in (old.devices.ravel().tolist() if old is not None
                        else [])
            if d not in devices
        )
        self._mesh = Mesh(np.array(list(devices)), (axis,))
        self.devices = len(devices)
        self.generation += 1
        if old is not None:
            sharded.forget_mesh(old)

    def describe(self) -> dict:
        return {
            "devices": self.n_devices,
            "sharded": self.mesh is not None,
            # the mesh-spanning fused stage engages only beyond one
            # device — operators read this to know which dedup path a
            # pallas ladder's rescue rung will take
            "mesh_kernel": self.n_devices > 1,
            **({"lost_devices": len(self.lost),
                "generation": self.generation} if self.generation else {}),
        }


def assert_parity(model, histories, *, mesh, capacity=(64, 256), **opts) -> list[dict]:
    """Run the same batch mesh-sharded AND single-device; raise
    ``PlacementMismatch`` on any verdict disagreement.  Returns the
    mesh results (so a verifying caller pays the single-device run as
    the only overhead)."""
    from jepsen_tpu.parallel import batch

    sharded = batch.batch_analysis(
        model, histories, capacity=capacity, mesh=mesh, **opts
    )
    single = batch.batch_analysis(
        model, histories, capacity=capacity, mesh=None, **opts
    )
    got = [r["valid?"] for r in sharded]
    want = [r["valid?"] for r in single]
    if got != want:
        raise PlacementMismatch(
            f"mesh-sharded verdicts {got} != single-device {want} "
            f"(devices={mesh.devices.size if mesh is not None else 1})"
        )
    obs.counter("serve.placement_parity_ok", histories=len(histories))
    return sharded
