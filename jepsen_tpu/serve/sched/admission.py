"""Admission: latency-class queues, per-class backpressure, tagging.

The class decides the SERVING PATH, never the verdict:

  * ``interactive`` — small histories a caller is blocked on.  Served
    by the speculative greedy single-rung fast path (per-request host
    witness walks; ``wgl_cpu.greedy_walk`` — the device-batched
    variant ``parallel.batch.greedy_fastpath`` exists for hosts where
    the walk is kernel-bound): a likely-valid history resolves in one
    cheap scan, everything the walk can't finish escalates into the
    batch tier's full ladder.
    The speculation is free soundness-wise — the greedy walk never
    refutes, so a wrong guess costs latency, not correctness.
  * ``batch`` — everything else: throughput-bound work that rides the
    (continuous) ladder.

Each class keeps its own queue depth and its own batch-wall EWMA, so
``retry_after`` estimates are computed per class — a queue-full
interactive request used to get an estimate dominated by batch-tier
residence times (PR 4's single EWMA), which told a 3 ms caller to come
back in ladder units.

Geometry batchability is tagged HERE, at admission: requests checked by
a graph checker (elle's ``CycleChecker`` family sets
``geometry_batchable = False``) have no padded-kernel geometry to share,
so they must never occupy a geometry bucket in the packable queue —
they are routed to a host side lane instead (ROADMAP item 4 records
that elle got no cross-request batching *by accident*; this makes it
explicit and keeps graph work from stalling ladder work).
"""

from __future__ import annotations

import time

from jepsen_tpu.obs import metrics

#: the latency classes, in fast-path-first service order.
CLASSES = ("interactive", "batch")

#: EWMA seeds: an interactive wave is one greedy launch (~ms warm); a
#: batch is a full ladder.  Both converge to measured values quickly —
#: the seeds only shape the first retry-after hints.
_EWMA_SEED = {"interactive": 0.02, "batch": 1.0}
_EWMA_ALPHA = 0.3


def geometry_batchable(checker) -> bool:
    """Whether a checker's work shares padded-kernel geometry (and so
    may pack into shared ladder launches).  Graph checkers opt out via
    a ``geometry_batchable = False`` class attribute."""
    return bool(getattr(checker, "geometry_batchable", True))


def graph_batch_key(checker) -> tuple:
    """The graph lane's batch-compatibility key — the graph analogue of
    ``parallel.batch.bucket_geometry``: queued graph requests sharing
    this key are served by ONE ``check_batch`` call (one vectorized
    inference pass + one host-SCC sweep) instead of per-request checks.

    Checkers advertise compatibility via a ``batch_key()`` method
    (elle's checkers key on their config: workload, anomalies,
    additional graphs, key-order assumptions, engine); anything without
    one gets a per-instance key and is served unbatched — correctness
    first, batching by explicit contract."""
    key = getattr(checker, "batch_key", None)
    if callable(key):
        try:
            return ("graph",) + tuple(key())
        except Exception:  # noqa: BLE001 — a broken key means no sharing
            pass
    return ("graph", type(checker).__name__, id(checker))


def classify(requested: str | None, *, B: int, interactive_max_b: int = 0) -> str:
    """The request's latency class.  An explicit ``requested`` class
    wins (validated); otherwise a history with at most
    ``interactive_max_b`` barriers auto-routes interactive (0 disables
    auto-routing — the library default, so embedding callers see PR 4
    semantics unless they opt in)."""
    if requested is not None:
        if requested not in CLASSES:
            raise ValueError(
                f"unknown latency class {requested!r}; expected one of {CLASSES}"
            )
        return requested
    if interactive_max_b > 0 and 0 < B <= interactive_max_b:
        return "interactive"
    return "batch"


class AdmissionQueues:
    """Per-class bounded queues + per-class batch-wall EWMAs.

    NOT thread-safe by itself: the owning ``CheckService`` serializes
    every call under its own lock (the queues are one shared structure
    with the service's admission/scheduler state, and a second lock
    here would only add ordering hazards)."""

    def __init__(self, max_queue: int, *, max_interactive: int | None = None):
        self.max_queue = int(max_queue)
        #: optional dedicated bound for the interactive tier (None:
        #: only the shared max_queue bounds it).  A full batch tier
        #: must not starve interactive admission when a dedicated
        #: allowance is configured.
        self.max_interactive = (
            int(max_interactive) if max_interactive is not None else None
        )
        self.queues: dict[str, list] = {c: [] for c in CLASSES}  # guarded-by: caller
        self.ewma_s: dict[str, float] = dict(_EWMA_SEED)  # guarded-by: caller

    # -- depth / admission ------------------------------------------------

    def depth(self, tier: str | None = None) -> int:
        if tier is not None:
            return len(self.queues[tier])
        return sum(len(q) for q in self.queues.values())

    def over_limit(self, tier: str, reserved: int) -> bool:
        """Would admitting one more ``tier`` request breach its bound?
        ``reserved`` counts slots held by in-flight submits (packing
        off-lock)."""
        if self.depth() + reserved >= self.max_queue:
            # A dedicated interactive allowance keeps the fast lane
            # admitting while the shared queue is full of batch work.
            if not (
                tier == "interactive"
                and self.max_interactive is not None
                and self.depth("interactive") < self.max_interactive
            ):
                return True
        if (
            tier == "interactive"
            and self.max_interactive is not None
            and self.depth("interactive") >= self.max_interactive
        ):
            return True
        return False

    def push(self, req) -> None:
        self.queues[req.tier].append(req)
        self._sync_depth_gauges()

    def remove(self, reqs) -> None:
        taken = {id(r) for r in reqs}
        for q in self.queues.values():
            q[:] = [r for r in q if id(r) not in taken]
        self._sync_depth_gauges()

    def requeue(self, req, tier: str) -> None:
        """Re-enter a request into ``tier``'s queue (fast-path
        escalation: ``req.tier`` stays what admission decided, so
        latency accounting still attributes the request to its class)."""
        self.queues[tier].append(req)
        self._sync_depth_gauges()

    def take_expired(self) -> list:
        """Pull queued requests whose deadline has passed, all classes
        (the caller resolves them outside the service lock)."""
        expired = []
        for tier, q in self.queues.items():
            live = []
            for r in q:
                if r.deadline is not None and r.deadline.expired():
                    expired.append(r)
                else:
                    live.append(r)
            self.queues[tier] = live
        if expired:
            self._sync_depth_gauges()
        return expired

    def drain_all(self) -> list:
        """Remove and return every queued request (shutdown)."""
        out = []
        for tier in CLASSES:
            out.extend(self.queues[tier])
            self.queues[tier] = []
        self._sync_depth_gauges()
        return out

    def _sync_depth_gauges(self) -> None:
        # Refreshed on every mutation so the live per-class gauge can't
        # stick at a stale depth between scrapes (the aggregate
        # serve.queue_depth obs gauge is the service's job).
        for tier in CLASSES:
            metrics.set_gauge(
                "serve.class_queue_depth", len(self.queues[tier]), tier=tier
            )

    # -- retry-after ------------------------------------------------------

    def record_wall(self, tier: str, seconds: float) -> None:
        """Fold one service cycle's wall clock into ``tier``'s EWMA (an
        interactive fast-path wave, or a batch-tier slot-recycle cycle:
        one ladder RUNG under continuous admission — joiners enter and
        lanes free at rung boundaries, so that is the cadence a
        retry-after should quote — the whole ladder otherwise)."""
        self.ewma_s[tier] = (
            (1 - _EWMA_ALPHA) * self.ewma_s[tier] + _EWMA_ALPHA * float(seconds)
        )

    def retry_after(self, tier: str, max_batch: int) -> float:
        """Backpressure hint for ``tier``: ITS queue depth over batch
        width, in units of ITS recent cycle EWMA — an interactive
        rejection quotes fast-path waves, a batch rejection quotes
        ladder batches."""
        waves = max(1.0, self.depth(tier) / max(1, max_batch))
        return round(max(0.02, waves * self.ewma_s[tier]), 3)

    # -- introspection ----------------------------------------------------

    def describe(self, max_batch: int) -> dict:
        """The per-class block in the queue-status document."""
        return {
            tier: {
                "queued": self.depth(tier),
                "ewma_s": round(self.ewma_s[tier], 4),
                "retry_after_hint_s": self.retry_after(tier, max_batch),
            }
            for tier in CLASSES
        }


class WaveTimer:
    """A tiny context manager folding one cycle's wall into a class
    EWMA (kept here so the service's scheduler reads as policy, not
    bookkeeping)."""

    def __init__(self, queues: AdmissionQueues, tier: str):
        self.queues = queues
        self.tier = tier

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self.queues.record_wall(self.tier, time.monotonic() - self._t0)
        return False
