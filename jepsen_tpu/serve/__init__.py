"""Check-serving subsystem: a persistent, multi-tenant checking service.

Everything before this package was one-shot: each ``core.analyze`` / CLI
invocation paid its own compile + launch, small histories wasted ladder
lanes, and nothing arbitrated concurrent callers for the device.  This
package is the serving layer on top of the checker pipeline — the shape
that made batched decoding practical (continuously pack independent
small requests into one padded launch; cf. arXiv:2010.02164's streaming
batched beam search) applied to the WGL ladder:

  * ``CheckService`` — admission queue (per-request priority, deadline,
    client id), a batching scheduler that packs compatible queued
    histories into shared ``parallel.batch.batch_analysis`` launches
    keyed by padded geometry (kernel compilations are reused across
    requests, not per caller), per-request result demux via futures,
    and explicit backpressure (bounded queue depth, ``QueueFull`` with
    a retry-after estimate — HTTP 429 in ``jepsen_tpu.web``).
  * Graceful drain: shutdown checkpoints still-queued work through
    ``store.checkpoint`` so a restarted operator can finish it with
    ``resume_drained``.
  * ``serve.*`` telemetry (queue depth, admission latency, batch
    occupancy, padding waste, per-request end-to-end latency) into the
    existing obs tables (``telemetry.json``'s "serve" section).

Scheduling is delegated to ``jepsen_tpu.serve.sched`` (PR 6): admission
into latency-class queues (``interactive`` fast path vs ``batch`` tier,
per-class backpressure/retry-after), CONTINUOUS packing (rung-boundary
admission into running ladders via ``batch_analysis(admission=...)``),
and mesh-sharded launch placement (``devices=N`` /
``verify_placement``).

Self-healing is delegated to ``jepsen_tpu.serve.health`` (PR 7):
poison-request quarantine (bisect a non-transiently failing shared
launch, quarantine the poison member by history fingerprint), a
circuit breaker (K consecutive batch failures → 503 + retry-after,
half-open probe), a hung-launch watchdog (EWMA-derived wall-clock
caps, cancel-and-retry on reduced placement), device-loss re-placement
(mesh health probes, shrink to survivors + parity re-probe), and a
crash-safe fsync'd admission journal replayed by ``start()``.

Fleet federation is delegated to ``jepsen_tpu.serve.fleet`` (PR 18):
a front-door ``FleetRouter`` over N replicas (in-process services or
subprocess HTTP workers) with geometry-affinity routing +
power-of-two-choices spill, health-probe fencing with exactly-once
resubmission through the shared ``IdempotencyMap``, fleet-wide
``SharedQuarantine``, and zero-downtime ``rollout()`` via
drain/replay/``resume_drained``.

Exposure: this Python API (``submit(history, ...) -> Future[verdict]``),
the HTTP API mounted into ``jepsen_tpu.web`` (``POST /check``,
``GET /check/<id>``, ``GET /queue``, ``GET /healthz``, ``GET
/readyz``), and ``jepsen-tpu serve --check`` (``--replicas N`` mounts
the fleet router).

Streaming (``checker.streaming``, PR 19): beside the request queues the
service runs a bounded lane of OPEN op-streams — ``stream_open`` /
``stream_feed`` / ``stream_close`` (HTTP ``POST /stream`` and friends)
feed an incremental checker epoch by epoch and surface
verdict-on-violation while the test still runs; per-stream durable
checkpoints under ``stream_dir`` make a SIGKILL'd stream resumable with
identical verdicts.
"""

from jepsen_tpu.serve import fleet, health, sched
from jepsen_tpu.serve.service import (
    MODELS,
    CheckFuture,
    CheckRequest,
    CheckService,
    QueueFull,
    ServiceClosed,
    ServiceUnavailable,
    StreamSession,
    model_by_name,
    resume_drained,
)

__all__ = [
    "MODELS",
    "CheckFuture",
    "CheckRequest",
    "CheckService",
    "QueueFull",
    "ServiceClosed",
    "ServiceUnavailable",
    "StreamSession",
    "fleet",
    "health",
    "model_by_name",
    "resume_drained",
    "sched",
]
