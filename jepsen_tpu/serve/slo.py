"""Live SLO burn-rate engine: declarative objectives evaluated
continuously from the process metrics registry.

An SLO here is a JSON-able spec dict; the engine samples the live
registry (``jepsen_tpu.obs.metrics``), keeps a bounded ring of
``(timestamp, per-slo cumulative good/bad counts)`` samples, and
computes **burn rates** over two windows — fast (default 5 min) and
slow (default 1 h): ``burn = bad_fraction / error_budget`` where the
budget is ``1 − target``.  Burn 1.0 means eating the budget exactly as
fast as allowed; an alert FIRES when BOTH windows exceed the spec's
``burn_threshold`` (the classic multi-window rule: the fast window
catches the breach quickly, the slow window keeps one spike from
paging).  With less history than a window, the window degrades to
"since the oldest sample" — a young process alerts on sustained
breaches without waiting an hour.

Spec kinds:

  * ``latency`` — a latency histogram (``metric`` + ``labels``) with
    ``threshold_s`` and ``target`` (fraction of requests that must be
    at or under the threshold; 0.5 = a p50 objective, 0.95 = p95).
    Bad events are histogram observations above the FIRST bucket bound
    at/above ``threshold_s`` — a threshold between bounds snaps UP
    (conservative toward silence; align thresholds with
    ``metrics.LATENCY_BUCKETS`` for exact semantics).
  * ``ratio`` — two counters: ``bad`` over ``total`` events must stay
    under ``1 − target`` (e.g. queue-deadline expiries over
    submissions: the batch deadline-hit rate).
  * ``gauge_floor`` — a gauge sampled per evaluation must stay at or
    above ``floor``; each evaluation contributes one good/bad event
    (``target`` bounds the below-floor sample fraction).

Surfaces: ``GET /alerts`` (web.py), the home-page SLO panel, the
``serve_slo_burn_rate{slo=,window=}`` gauges + ``serve_slo_alerts``
count, and ``tools/loadgen.py``'s ``--assert-alert`` /
``--assert-no-alerts`` acceptance gates.  ``CheckService`` evaluates
the engine from its scheduler loop (and from every ``step()``, so
step-driven tests are deterministic).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path
from typing import Mapping, Sequence

from jepsen_tpu.obs import metrics

__all__ = ["DEFAULT_SLOS", "SloEngine", "load_specs"]

#: the built-in objectives (conservative: a healthy CPU-backend service
#: must not page).  Override any of them — or add your own — with a
#: ``--slo-file`` JSON list; a spec with the same name replaces the
#: default.
DEFAULT_SLOS: tuple[dict, ...] = (
    {"name": "interactive-p50", "kind": "latency",
     "metric": "serve.class_request_latency_seconds",
     "labels": {"tier": "interactive"},
     "threshold_s": 0.025, "target": 0.50},
    {"name": "interactive-p95", "kind": "latency",
     "metric": "serve.class_request_latency_seconds",
     "labels": {"tier": "interactive"},
     "threshold_s": 0.25, "target": 0.95},
    {"name": "batch-deadline", "kind": "ratio",
     "bad": "serve.expired", "total": "serve.submitted",
     "target": 0.99},
    # Collapse detector, deliberately forgiving: per-rung occupancy
    # legitimately dips on underfull tail rungs, so the floor is low
    # and the target allows 75% of (changed) samples below it — only a
    # sustained occupancy collapse burns budget.
    {"name": "occupancy-floor", "kind": "gauge_floor",
     "metric": "serve.continuous_occupancy",
     "floor": 0.1, "target": 0.25},
)

#: default burn-rate windows (seconds): the multi-window pair.
FAST_WINDOW_S = 300.0
SLOW_WINDOW_S = 3600.0

#: default alert threshold: burning budget faster than allowed.
BURN_THRESHOLD = 1.0

_KINDS = ("latency", "ratio", "gauge_floor")


def load_specs(path: str | Path) -> list[dict]:
    """An ``--slo-file``: a JSON list of spec dicts.  Specs are merged
    OVER the defaults by name (same name replaces; new names append) —
    a file tuning one threshold doesn't silently drop the rest."""
    specs = json.loads(Path(path).read_text(encoding="utf-8"))
    if isinstance(specs, Mapping):
        specs = specs.get("slos", [])
    if not isinstance(specs, list):
        raise ValueError(f"{path}: expected a JSON list of SLO specs")
    merged = {s["name"]: dict(s) for s in DEFAULT_SLOS}
    for s in specs:
        if not isinstance(s, Mapping) or not s.get("name"):
            raise ValueError(f"{path}: every SLO spec needs a 'name'")
        merged[str(s["name"])] = dict(s)
    return list(merged.values())


#: fields a spec must carry per kind — checked at CONSTRUCTION so a
#: typo'd --slo-file fails the service start loudly instead of
#: KeyError-ing inside every evaluation while the pager reads "ok".
_REQUIRED = {
    "latency": ("metric", "threshold_s"),
    "ratio": ("bad", "total"),
    "gauge_floor": ("metric", "floor"),
}


def _validate(spec: Mapping) -> dict:
    s = dict(spec)
    kind = s.get("kind")
    if kind not in _KINDS:
        raise ValueError(
            f"SLO {s.get('name')!r}: unknown kind {kind!r}; expected one "
            f"of {_KINDS}")
    missing = [k for k in _REQUIRED[kind] if s.get(k) is None]
    if missing:
        raise ValueError(
            f"SLO {s.get('name')!r}: kind {kind!r} requires "
            f"{', '.join(missing)}")
    target = float(s.get("target", 0.99))
    if not 0.0 < target < 1.0:
        raise ValueError(
            f"SLO {s.get('name')!r}: target must be in (0, 1), got {target}")
    if kind == "latency":
        thr = float(s["threshold_s"])
        if thr <= 0:
            raise ValueError(
                f"SLO {s.get('name')!r}: threshold_s must be > 0")
        s["threshold_s"] = thr
    if kind == "gauge_floor":
        s["floor"] = float(s["floor"])
    s["target"] = target
    s.setdefault("burn_threshold", BURN_THRESHOLD)
    return s


class _Ring:
    """Bounded sample ring for one engine: (ts, {slo: (bad, total)}).

    Pushes closer than ``coalesce_s`` to the previous sample REPLACE
    it (cumulative counts: the newest supersedes) — a busy scheduler
    evaluating per cycle must not grow the ring past
    ``keep_s / coalesce_s`` entries or make the window scans pay for
    its cycle rate."""

    def __init__(self, keep_s: float, coalesce_s: float = 1.0):
        self.keep_s = keep_s
        self.coalesce_s = coalesce_s
        self.samples: deque[tuple[float, dict]] = deque()

    def push(self, ts: float, counts: dict) -> None:
        if (len(self.samples) > 1
                and ts - self.samples[-1][0] < self.coalesce_s):
            self.samples[-1] = (ts, counts)
        else:
            self.samples.append((ts, counts))
        horizon = ts - self.keep_s
        while len(self.samples) > 2 and self.samples[1][0] < horizon:
            # keep one sample older than the horizon so the slow window
            # always has a baseline to delta against
            self.samples.popleft()

    def window_delta(self, name: str, now: float,
                     window_s: float) -> tuple[float, float]:
        """(bad, total) accumulated inside the window (delta vs the
        newest sample at/older than the window start; degrades to
        since-oldest when history is shorter than the window).  Scans
        from the NEWEST sample backward so the cost is the window's
        sample count, not the ring's."""
        if not self.samples:
            return 0.0, 0.0
        newest = self.samples[-1][1].get(name, (0.0, 0.0))
        base = None
        start = now - window_s
        for ts, counts in reversed(self.samples):
            if ts <= start:
                base = counts.get(name, (0.0, 0.0))
                break
        if base is None:
            base = self.samples[0][1].get(name, (0.0, 0.0))
        return max(0.0, newest[0] - base[0]), max(0.0, newest[1] - base[1])


class SloEngine:
    """Evaluate a set of SLO specs against the live registry.

    Thread-safe: ``evaluate()`` serializes on an internal lock (the
    scheduler loop, ``step()``-driven tests, and a load harness's
    final settle evaluation may all call it); ``alerts()`` reads the
    newest snapshot via a single attribute load of an immutable dict,
    safe from HTTP handler threads without the lock."""

    def __init__(self, specs: Sequence[Mapping] | str | Path | None = None,
                 *, registry: metrics.Registry | None = None,
                 fast_window_s: float = FAST_WINDOW_S,
                 slow_window_s: float = SLOW_WINDOW_S):
        if specs is None:
            specs = DEFAULT_SLOS
        elif isinstance(specs, (str, Path)):
            specs = load_specs(specs)
        self.specs = [_validate(s) for s in specs]
        self.registry = registry if registry is not None else metrics.REGISTRY
        self.fast_window_s = float(fast_window_s)
        self.slow_window_s = float(slow_window_s)
        self._ring = _Ring(keep_s=self.slow_window_s * 1.25)
        #: serializes evaluate(): ring pushes, gauge-change tracking,
        #: and firing-state transitions are read-modify-write.
        self._eval_lock = threading.Lock()
        #: last raw value seen per gauge_floor spec (sample-on-change:
        #: a gauge HOLDS its last write between batches, and an idle
        #: service re-sampling a stale tail-rung value must not
        #: accumulate it into the burn windows as fresh evidence).
        self._gauge_last: dict[str, float] = {}
        self._firing_since: dict[str, float] = {}
        #: the newest evaluation snapshot (immutable; read by alerts()).
        self._last: dict = {"evaluated_at": None, "slos": []}
        # Baseline sample at construction: cumulative counts that
        # predate the engine (a registry shared with earlier traffic)
        # must not read as in-window burn — only what happens AFTER
        # the engine attaches counts against the windows.
        baseline: dict[str, tuple[float, float]] = {}
        for spec in self.specs:
            c = (self._counts(spec)
                 if spec["kind"] != "gauge_floor" else None)
            baseline[spec["name"]] = c if c is not None else (0.0, 0.0)
        # -inf timestamp: the baseline sorts before any evaluation
        # clock (tests drive evaluate() with their own ``now``) and is
        # only ever the fallback delta base, never evicted.
        self._ring.push(float("-inf"), baseline)

    # -- cumulative counts per spec ------------------------------------

    def _counts(self, spec: Mapping) -> tuple[float, float] | None:
        """Cumulative (bad, total) events for a spec, or None when the
        underlying series doesn't exist yet (no traffic)."""
        kind = spec["kind"]
        if kind == "latency":
            h = self.registry.histogram_buckets(
                spec["metric"], **(spec.get("labels") or {}))
            if h is None:
                return None
            # The histogram can't resolve between bucket bounds, so the
            # effective threshold SNAPS UP to the first bound at/above
            # threshold_s: requests in the bucket containing the
            # threshold count GOOD.  Conservative toward silence —
            # a misaligned spec must never page on a healthy service.
            thr = float(spec["threshold_s"])
            good = 0
            for bound, n in zip(h["bounds"], h["buckets"]):
                good += n
                if bound >= thr - 1e-12:
                    break
            return float(h["count"] - good), float(h["count"])
        if kind == "ratio":
            bad = self.registry.get(spec["bad"]) or 0.0
            total = self.registry.get(spec["total"])
            if total is None:
                return None
            return float(bad), float(total)
        # gauge_floor: one good/bad event per CHANGED sample — a gauge
        # holds its last write, so an unchanged value is no new evidence
        v = self.registry.get(spec["metric"], **(spec.get("labels") or {}))
        if v is None:
            return None
        prev = (self._ring.samples[-1][1].get(spec["name"], (0.0, 0.0))
                if self._ring.samples else (0.0, 0.0))
        last = self._gauge_last.get(spec["name"])
        self._gauge_last[spec["name"]] = float(v)
        if last is not None and float(v) == last:
            return prev
        below = 1.0 if float(v) < float(spec["floor"]) else 0.0
        return prev[0] + below, prev[1] + 1.0

    # -- evaluation -----------------------------------------------------

    def evaluate(self, now: float | None = None) -> list[dict]:
        """Take one sample, recompute every SLO's fast/slow burn rates,
        update the ``serve.slo_burn_rate`` gauges and the alert states,
        and return the per-SLO rows."""
        with self._eval_lock:
            return self._evaluate_locked(now)

    def _evaluate_locked(self, now: float | None) -> list[dict]:
        now = time.monotonic() if now is None else float(now)
        counts: dict[str, tuple[float, float]] = {}
        missing: set[str] = set()
        for spec in self.specs:
            try:
                c = self._counts(spec)
            except Exception:  # noqa: BLE001 — one broken spec must not
                # stop the other objectives from being monitored
                c = None
            if c is None:
                missing.add(spec["name"])
                # carry the previous cumulative forward so a series
                # that appears later deltas from zero, not from junk
                c = (self._ring.samples[-1][1].get(
                    spec["name"], (0.0, 0.0)) if self._ring.samples
                    else (0.0, 0.0))
            counts[spec["name"]] = c
        self._ring.push(now, counts)
        rows: list[dict] = []
        firing = 0
        for spec in self.specs:
            name = spec["name"]
            budget = 1.0 - spec["target"]
            burns = {}
            for window, w_s in (("fast", self.fast_window_s),
                                ("slow", self.slow_window_s)):
                bad, total = self._ring.window_delta(name, now, w_s)
                frac = (bad / total) if total > 0 else 0.0
                burns[window] = round(frac / budget, 4) if budget else 0.0
            alerting = (
                name not in missing
                and burns["fast"] >= spec["burn_threshold"]
                and burns["slow"] >= spec["burn_threshold"]
            )
            if alerting:
                firing += 1
                self._firing_since.setdefault(name, now)
            else:
                self._firing_since.pop(name, None)
            row = {
                "slo": name,
                "kind": spec["kind"],
                "target": spec["target"],
                "budget": round(budget, 6),
                "burn_fast": burns["fast"],
                "burn_slow": burns["slow"],
                "burn_threshold": spec["burn_threshold"],
                "state": "firing" if alerting else (
                    "no-data" if name in missing else "ok"),
            }
            if alerting:
                row["firing_for_s"] = round(
                    now - self._firing_since[name], 3)
            rows.append(row)
            metrics.set_gauge("serve.slo_burn_rate", burns["fast"],
                              slo=name, window="fast")
            metrics.set_gauge("serve.slo_burn_rate", burns["slow"],
                              slo=name, window="slow")
        metrics.set_gauge("serve.slo_alerts", firing)
        self._last = {
            "evaluated_at": now,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "slos": rows,
        }
        return rows

    def alerts(self) -> dict:
        """The ``GET /alerts`` document: currently-firing alerts plus
        the full per-SLO burn table from the newest evaluation."""
        last = self._last
        return {
            "alerts": [r for r in last["slos"] if r["state"] == "firing"],
            "slos": last["slos"],
            "evaluated_at": last["evaluated_at"],
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
        }
