"""Self-healing for the check service: blast-radius isolation primitives.

PRs 4–6 made checking a long-lived multi-tenant service; sharing a
launch also shares its failures.  This module is the policy layer that
keeps one bad input, one lost device, or one wedged launch from
degrading everyone else — four pillars, composed by
``serve.service.CheckService``:

  * **Poison quarantine** (``bisect_poison`` + ``Quarantine``) — when a
    shared ``batch_analysis`` launch fails NON-transiently (transient
    and OOM faults are already retried/halved inside the ladder by
    ``jepsen_tpu.faults``), the member set is bisected with bounded
    relaunches: innocent members get their real verdicts from the
    succeeding halves, and the member(s) whose presence makes launches
    fail are quarantined — unknown verdict with the cause, plus a
    TTL'd registry entry keyed by history fingerprint so a repeat
    offender skips straight to rejection instead of poisoning another
    shared launch.  Isolating a single poison member costs O(log n)
    relaunches.
  * **Circuit breaker** (``CircuitBreaker``) — K consecutive batch
    failures open the breaker: admission returns 503 + retry-after
    instead of queueing work the device can't serve; after a cooldown
    the breaker half-opens and one probe batch decides whether to
    close it again.
  * **Hung-launch watchdog** (``LaunchWatchdog``) — per-launch
    wall-clock caps derived from the EWMA of recorded launch times
    (``faults.launch_seconds_ewma``, fed by ``parallel.batch._launch``);
    a launch that exceeds its cap raises ``HungLaunch`` so the service
    can cancel (abandon — first-write-wins result demux discards the
    zombie's late verdicts) and retry on reduced placement.
  * **Crash-safe restart** (``AdmissionJournal``) — an fsync'd,
    checksummed journal of admitted-but-unfinished requests
    (``store.durable`` envelopes over ``store._atomic_write``, one file
    per request in the drain-dir format) replayed by
    ``CheckService.start()``: a service crash loses no admitted
    request, and replayed requests keep their ids so ``GET
    /check/<id>`` keeps working across the restart.  Corrupt entries
    quarantine aside with a machine-readable report instead of
    blocking (or silently shrinking) the replay.
  * **Idempotent resubmission** (``IdempotencyMap``) — a journaled
    TTL'd ``idempotency_key`` registry: the retry behavior the
    backpressure 429s / breaker 503s / wait timeouts instruct can
    never double-run a check — duplicates attach to the in-flight
    future or get the settled result, original request id preserved,
    across a SIGKILL restart.

Nothing here decides verdicts: quarantine and watchdog degradation
resolve only to attributable ``unknown``s, never to a flipped verdict.
"""

from __future__ import annotations

import contextlib
import logging
import math
import threading
import time
from pathlib import Path
from typing import Callable, Mapping, Sequence

from jepsen_tpu import obs, store
from jepsen_tpu.store import checkpoint as _ckpt
from jepsen_tpu.store import durable as _durable

logger = logging.getLogger(__name__)

#: durable-record kinds this layer persists (see store.durable): the
#: admission journal's per-request entries, the idempotency map's
#: per-key entries, and the shared quarantine registry's per-
#: fingerprint entries.  All are envelope v1; journal/idem carry a
#: legacy (pre-envelope, version 0) migration so a pre-durable journal
#: replays unchanged.
KIND_JOURNAL = "admission-journal"
KIND_IDEM = "idempotency-entry"
KIND_QUAR = "quarantine-entry"

_durable.register_kind(KIND_JOURNAL, 1)
_durable.register_kind(KIND_IDEM, 1)
_durable.register_kind(KIND_QUAR, 1)


@_durable.register_migration(KIND_JOURNAL, 0)
def _journal_v0_to_v1(payload):
    # v0 was the bare entry dict — same fields, no checksum.
    return dict(payload), 1


@_durable.register_migration(KIND_IDEM, 0)
def _idem_v0_to_v1(payload):
    # pre-envelope idem entries (e.g. hand-restored by an operator)
    # read as payload-only version 0 — same fields
    return dict(payload), 1


def history_fingerprint(history) -> str:
    """The quarantine/journal identity of one history (the same sha256
    the checkpoint layer uses, over a single-history list)."""
    return _ckpt.fingerprint([history])


# ---------------------------------------------------------------------------
# Poison quarantine
# ---------------------------------------------------------------------------

class Quarantine:
    """A TTL'd registry of poison-history fingerprints.

    ``add`` records a fingerprint with its cause; ``check`` returns the
    live entry (or None) so admission can reject a repeat offender
    before it reaches a shared launch.  Entries expire after ``ttl_s``
    — a poison verdict is evidence, not a life sentence (the failure
    may have been environmental) — and expired entries are purged
    lazily on access.  Thread-safe."""

    def __init__(self, ttl_s: float = 900.0):
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        #: fp -> {"cause", "expires", "hits", "added"}
        self._entries: dict[str, dict] = {}      # guarded-by: _lock [rw]

    def __len__(self) -> int:
        with self._lock:
            self._purge_locked()
            return len(self._entries)

    # holds: _lock
    def _purge_locked(self) -> None:
        now = time.monotonic()
        dead = [fp for fp, e in self._entries.items() if e["expires"] <= now]
        for fp in dead:
            del self._entries[fp]

    def add(self, fp: str, cause: str) -> None:
        with self._lock:
            self._purge_locked()
            self._entries[fp] = {
                "cause": str(cause)[:300],
                "expires": time.monotonic() + self.ttl_s,
                "hits": 0,
                "added": time.time(),
            }

    def check(self, fp: str) -> dict | None:
        """The live entry for ``fp`` (hit-counted), or None.  A hit
        refreshes the TTL — a fingerprint still being submitted is
        still worth remembering."""
        with self._lock:
            self._purge_locked()
            e = self._entries.get(fp)
            if e is not None:
                e["hits"] += 1
                e["expires"] = time.monotonic() + self.ttl_s
            return e

    def describe(self) -> dict:
        with self._lock:
            self._purge_locked()
            return {
                "entries": len(self._entries),
                "ttl_s": self.ttl_s,
                "hits": sum(e["hits"] for e in self._entries.values()),
            }


class SharedQuarantine(Quarantine):
    """``Quarantine`` semantics over a shared fsync'd directory: the
    fleet-wide poison registry.

    One ``store.durable`` enveloped file per fingerprint.  ``add``
    writes the entry (under the fingerprint's advisory file lock, see
    ``store.durable.file_lock``) so EVERY replica pointed at the same
    ``quarantine_dir`` refuses the history at admission — on its FIRST
    local offense, not after poisoning its own shared launch too.
    ``check`` consults the in-memory registry first; a miss costs one
    ``stat`` on the fingerprint's path (O(1), no directory scan), and a
    disk hit is adopted into memory, counted as the
    ``fleet.quarantine_hits`` counter.

    Expiry on disk is WALL clock (``expires`` epoch seconds — replicas
    don't share a monotonic clock); the in-memory mirror keeps the
    superclass's monotonic TTL.  Corrupt entries count on ``errors``
    and read as absent — a broken registry file must degrade to
    "launch and decide", never to refusing service."""

    def __init__(self, ttl_s: float = 900.0, dir: str | Path | None = None):  # noqa: A002
        super().__init__(ttl_s)
        if dir is None:
            raise ValueError("SharedQuarantine requires a directory; "
                             "use Quarantine for in-memory-only")
        self.dir = Path(dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.errors = 0
        self.disk_hits = 0

    def _fp_path(self, fp: str) -> Path:
        # fingerprints are sha256 hex; 40 chars of it is filename-safe
        # and collision-negligible (the payload keeps the full fp and
        # check() verifies it before trusting the entry)
        return self.dir / f"quar-{str(fp)[:40]}.json"

    def add(self, fp: str, cause: str) -> None:
        super().add(fp, cause)
        now = time.time()
        payload = {
            "fp": str(fp), "cause": str(cause)[:300],
            "added": now, "expires": now + self.ttl_s,
        }
        p = self._fp_path(fp)
        try:
            with _durable.file_lock(Path(str(p) + ".lock"), timeout_s=10.0):
                _durable.write_record(p, KIND_QUAR, payload)
        except Exception:  # noqa: BLE001 — registry persistence is a
            # fleet-wide aid; THIS replica still quarantines in memory
            self.errors += 1
            logger.warning("shared quarantine write failed for %s",
                           fp, exc_info=True)

    def check(self, fp: str) -> dict | None:
        e = super().check(fp)
        if e is not None:
            return e
        p = self._fp_path(fp)
        if not p.exists():
            return None
        try:
            with _durable.file_lock(Path(str(p) + ".lock"), timeout_s=10.0):
                rr = _durable.read_verified(p, KIND_QUAR)
        except _durable.DurableError:
            self.errors += 1
            return None
        except Exception:  # noqa: BLE001 — lock timeout / IO error
            self.errors += 1
            logger.warning("shared quarantine read failed for %s",
                           fp, exc_info=True)
            return None
        d = rr.payload
        if not isinstance(d, dict) or d.get("fp") != str(fp):
            self.errors += 1
            return None
        if float(d.get("expires") or 0) <= time.time():
            with contextlib.suppress(OSError):
                p.unlink()
            return None
        entry = {
            "cause": str(d.get("cause") or "")[:300],
            # adopted with a FULL local TTL — same refresh-on-hit
            # semantics a locally-added entry gets
            "expires": time.monotonic() + self.ttl_s,
            "hits": 1,
            "added": float(d.get("added") or time.time()),
        }
        with self._lock:
            self._entries[str(fp)] = entry
        self.disk_hits += 1
        obs.counter("fleet.quarantine_hits")
        return dict(entry)

    def describe(self) -> dict:
        out = super().describe()
        out.update(shared=True, dir=str(self.dir),
                   disk_hits=self.disk_hits, errors=self.errors)
        return out


def bisect_launch_budget(n: int) -> int:
    """The relaunch budget ``bisect_poison`` defaults to: enough to
    isolate one poison member among ``n`` — both bisection paths at
    every level, ~2·(log2(n)+1) — with one extra level of slack for a
    second offender before the remainder is quarantined as a group."""
    levels = max(1, math.ceil(math.log2(max(2, n)))) + 1
    return 3 * levels


def bisect_poison(
    launch: Callable[[list], list],
    members: Sequence,
    *,
    max_launches: int | None = None,
) -> tuple[list, dict, int]:
    """Isolate the poison member(s) of a failed shared launch.

    ``launch(subset)`` re-runs the shared work over ``subset`` and
    returns one result per member (or raises — the failure signature
    being bisected).  Returns ``(poison, results, launches)``: the
    members whose presence makes launches fail, a ``{member: result}``
    map for every innocent member (their REAL verdicts, recovered from
    the succeeding halves), and the relaunch count.

    Classic group testing: a failing group of one is poison; a failing
    group of many splits in half and recurses.  A single poison member
    among n costs O(log n) relaunches.  ``max_launches`` (default
    ``bisect_launch_budget(n)``) bounds the degradation: when the
    budget runs out, the still-unresolved group is quarantined TOGETHER
    (conservative — innocents in it degrade to unknown, never to a
    wrong verdict)."""
    members = list(members)
    budget = (
        bisect_launch_budget(len(members))
        if max_launches is None else int(max_launches)
    )
    poison: list = []
    results: dict = {}
    launches = 0
    stack: list[list] = [members]
    while stack:
        group = stack.pop()
        if not group:
            continue
        if launches >= budget:
            # Budget exhausted: quarantine the rest as a group rather
            # than launch forever against a pathological failure mix.
            poison.extend(group)
            continue
        launches += 1
        try:
            out = launch(list(group))
        except Exception:  # noqa: BLE001 — the signature being bisected
            if len(group) == 1:
                poison.append(group[0])
            else:
                mid = (len(group) + 1) // 2
                # push the back half first so the front half (older
                # members) is served next — deterministic order
                stack.append(group[mid:])
                stack.append(group[:mid])
            continue
        for mem, res in zip(group, out):
            results[mem] = res
    return poison, results, launches


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Closed → (K consecutive failures) → open → (cooldown) →
    half-open → one probe success closes / failure re-opens.

    ``allow()`` is the admission gate: False means reject now (the HTTP
    layer returns 503 + Retry-After ``retry_after()``).  While OPEN the
    gate stays shut until ``cooldown_s`` elapses; the first ``allow()``
    after that transitions to HALF-OPEN and admits exactly ONE probe —
    further ``allow()`` calls stay rejected until a batch outcome is
    recorded, so a retry stampede at cooldown expiry can't refill the
    queue with doomed work against a still-broken device.  Thread-safe;
    the owning service calls ``record_failure``/``record_success`` per
    batch outcome."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 30.0):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self.state = "closed"                    # guarded-by: _lock [rw]
        self.consecutive_failures = 0            # guarded-by: _lock [rw]
        self.opened_at: float | None = None      # guarded-by: _lock [rw]
        self.opens = 0                           # guarded-by: _lock [rw]
        # half-open admissions left before outcome
        self._probe_budget = 0                   # guarded-by: _lock [rw]

    def allow(self) -> bool:
        with self._lock:
            if self.state == "open":
                if (time.monotonic() - self.opened_at) >= self.cooldown_s:
                    self.state = "half-open"
                    self._probe_budget = 1
            if self.state == "half-open":
                if self._probe_budget > 0:
                    self._probe_budget -= 1
                    return True
                return False
            return self.state == "closed"

    def retry_after(self) -> float:
        with self._lock:
            if self.state == "half-open":
                # a probe is in flight; its outcome decides shortly
                return 0.5
            if self.state != "open" or self.opened_at is None:
                return 0.0
            return max(
                0.0, self.cooldown_s - (time.monotonic() - self.opened_at)
            )

    def record_failure(self) -> bool:
        """One batch failed; returns True when THIS failure opened (or
        re-opened) the breaker."""
        with self._lock:
            self.consecutive_failures += 1
            if self.state == "half-open" or (
                self.state == "closed"
                and self.consecutive_failures >= self.threshold
            ):
                self.state = "open"
                self.opened_at = time.monotonic()
                self.opens += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            if self.state in ("half-open", "open"):
                # an open breaker can see a success when a probe batch
                # admitted just before the trip completes late — either
                # way the device demonstrably serves again
                self.state = "closed"
                self.opened_at = None

    def describe(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "opens": self.opens,
                "retry_after_s": round(
                    max(0.0, self.cooldown_s
                        - (time.monotonic() - self.opened_at))
                    if self.state == "open" and self.opened_at is not None
                    else 0.0, 3),
            }


# ---------------------------------------------------------------------------
# Hung-launch watchdog
# ---------------------------------------------------------------------------

class HungLaunch(Exception):
    """A watched launch exceeded its wall-clock cap.  The worker thread
    may STILL be running (jax launches aren't interruptible from
    Python) — the caller abandons it and retries on reduced placement;
    first-write-wins result demux discards the zombie's late output."""

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s
        super().__init__(f"launch exceeded its {timeout_s:.1f}s watchdog cap")


class LaunchWatchdog:
    """Per-launch wall-clock caps derived from the launch-time EWMA.

    ``timeout_s()`` is ``factor ×`` the process launch EWMA
    (``faults.launch_seconds_ewma``), clamped to ``[floor_s, cap_s]`` —
    a healthy ladder's launches are milliseconds-to-seconds, so a
    multi-minute one is wedged, not slow.  ``run(fn)`` executes ``fn``
    on a daemon worker thread and raises ``HungLaunch`` when the cap
    passes first."""

    def __init__(self, factor: float = 16.0, floor_s: float = 30.0,
                 cap_s: float = 600.0):
        self.factor = float(factor)
        self.floor_s = float(floor_s)
        self.cap_s = float(cap_s)
        self.trips = 0

    def timeout_s(self) -> float:
        from jepsen_tpu import faults

        ewma = faults.launch_seconds_ewma()
        t = self.factor * ewma if ewma is not None else self.floor_s
        return min(self.cap_s, max(self.floor_s, t))

    def run(self, fn: Callable[[], object], timeout_s: float | None = None):
        """``fn()``'s result, or ``HungLaunch`` after the cap.  ``fn``'s
        own exception re-raises on this thread."""
        timeout_s = self.timeout_s() if timeout_s is None else float(timeout_s)
        box: dict = {}
        done = threading.Event()

        def _work():
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(
            target=_work, name="launch-watchdog-worker", daemon=True
        )
        t.start()
        if not done.wait(timeout_s):
            self.trips += 1
            raise HungLaunch(timeout_s)
        if "error" in box:
            raise box["error"]
        return box["result"]


# ---------------------------------------------------------------------------
# Crash-safe admission journal
# ---------------------------------------------------------------------------

class AdmissionJournal:
    """An fsync'd, CHECKSUMMED record of admitted-but-unfinished
    requests.

    One JSON file per request in the ``store.durable`` envelope
    (``store._atomic_write`` underneath: tmp + fsync + rename + dir
    fsync), in the drain-dir format (model name + history + request
    identity + idempotency key) so ``replay()`` can rebuild the exact
    submission.  ``record`` on admission, ``resolve`` when the request
    settles (any terminal status — done, expired, quarantined,
    drained); whatever files remain after a crash ARE the lost queue,
    replayed by ``CheckService.start()``.  A corrupt entry — atomic
    renames rule out torn writes, but bit rot, partial copies, and
    operators hand-editing the dir do not go away — is QUARANTINED
    aside (``<name>.corrupt-<n>``), counted, and its corruption report
    kept on ``corrupt_reports`` for the stats surface; the rest of the
    queue still replays.  Write failures are counted and logged, never
    raised into admission — journaling is a recovery aid, not an
    admission gate.

    ``depth()`` is a CACHED counter (maintained at record/resolve,
    reconciled against the directory at ``replay()``) — it used to
    re-glob the journal dir on every stats call, which made ``GET
    /queue`` an O(queue-depth) directory walk.

    ``shared=True`` serializes record/resolve/replay across PROCESSES
    under a directory-level advisory lock (``journal.lock``,
    ``store.durable.file_lock``): a journal dir handed between fleet
    replicas (rollout successor replaying while the predecessor's last
    resolves land) can't interleave a replay with a half-applied
    mutation.  Off by default — a journal dir owned by exactly one
    process pays no extra syscalls."""

    def __init__(self, journal_dir: str | Path, *, shared: bool = False):
        self.dir = Path(journal_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.shared = bool(shared)
        self.errors = 0
        self.corrupt_reports: list[dict] = []    # guarded-by: _lock [rw]
        self._lock = threading.Lock()
        self._depth = self._glob_depth()         # guarded-by: _lock [rw]

    @contextlib.contextmanager
    def _dir_lock(self):
        if not self.shared:
            yield
            return
        with _durable.file_lock(self.dir / "journal.lock", timeout_s=30.0):
            yield

    def _glob_depth(self) -> int:
        try:
            return sum(1 for _ in self.dir.glob("req-*.json"))
        except OSError:
            return 0

    def _path(self, req_id: str) -> Path:
        return self.dir / f"req-{req_id}.json"

    def record(self, *, req_id: str, seq: int, model_name: str, history,
               priority: int, client: str, tier: str,
               trace_id: str, deadline_s: float | None,
               idempotency_key: str | None = None) -> bool:
        entry = {
            "id": req_id, "seq": int(seq), "model": model_name,
            "history": store._jsonable(list(history)),
            "priority": int(priority), "client": str(client),
            "class": str(tier), "trace_id": str(trace_id),
            "deadline_s": deadline_s,
        }
        if idempotency_key is not None:
            entry["idempotency_key"] = str(idempotency_key)
        try:
            with self._dir_lock():
                existed = self._path(req_id).exists()
                _durable.write_record(self._path(req_id), KIND_JOURNAL, entry)
            if not existed:
                with self._lock:
                    self._depth += 1
            return True
        except Exception:  # noqa: BLE001 — see docstring
            self.errors += 1
            logger.warning("admission journal write failed for %s",
                           req_id, exc_info=True)
            return False

    def resolve(self, req_id: str) -> None:
        try:
            with self._dir_lock():
                self._path(req_id).unlink()
        except FileNotFoundError:
            return  # already resolved (or never journaled): depth unchanged
        except OSError:
            self.errors += 1
            logger.warning("admission journal unlink failed for %s",
                           req_id, exc_info=True)
            return
        with self._lock:
            self._depth = max(0, self._depth - 1)

    def depth(self) -> int:
        with self._lock:
            return self._depth

    def replay(self) -> list[dict]:
        """Every surviving VERIFIED entry, in admission (seq) order.
        Corrupt entries are quarantined aside with their reports
        collected; the cached depth is reconciled against what is
        actually on disk afterwards (quarantined files leave the
        glob)."""
        out = []
        with self._dir_lock():
            entries = sorted(self.dir.glob("req-*.json"))
        for p in entries:
            try:
                rr = _durable.read_verified(p, KIND_JOURNAL)
                out.append(rr.payload)
            except _durable.DurableError as e:
                self.errors += 1
                with self._lock:
                    self.corrupt_reports.append(e.report)
                logger.warning("corrupt journal entry %s quarantined: %s",
                               p, e)
        out.sort(key=lambda e: e.get("seq", 0))
        with self._lock:
            self._depth = self._glob_depth()
        return out


# ---------------------------------------------------------------------------
# Idempotent resubmission
# ---------------------------------------------------------------------------

class IdempotencyMap:
    """A TTL'd ``idempotency_key -> (request id, settled result)`` map,
    optionally journaled to disk so it survives a SIGKILL restart.

    The retry story PR 7 built actively INSTRUCTS clients to resubmit:
    backpressure 429s, breaker 503s and wait timeouts all carry
    Retry-After hints — and a naive resubmit after a timeout whose
    first attempt was actually admitted double-runs the check.  This
    map closes that hole: ``claim`` atomically either binds a fresh
    key to the new request id or hands back the live entry (the caller
    then attaches the duplicate to the in-flight future, or returns
    the settled result — under the ORIGINAL request id).  ``settle``
    records the verdict against the key; entries expire ``ttl_s`` after
    their last write (wall clock, so expiry works across restarts).

    With a ``dir``, every bind/settle is persisted as one
    ``store.durable`` enveloped file per key and ``replay()`` reloads
    the map at service start — a duplicate submitted AFTER a crash
    still attaches to the journal-replayed in-flight request (same id)
    or gets the previously settled result.  Corrupt entries are
    quarantined aside and counted (``errors``); persistence failures
    never fail a submit.

    ``shared=True`` makes the dir a FLEET-wide map: claim / rebind /
    settle / release become read-modify-writes of the key's entry file
    under a per-key advisory ``fcntl`` lock (a ``.lock`` sidecar,
    ``store.durable.file_lock``), with the on-disk entry as the source
    of truth.  Without it, two PROCESSES pointed at the same dir can
    both claim a key — the in-process ``_lock`` only arbitrates
    threads — and a router resubmitting a fenced replica's in-flight
    work through its idempotency keys could double-run a check.  With
    it, a cross-process duplicate claim loses atomically: the loser
    reads the winner's live entry and attaches (or, if the winner died
    unsettled, rebinds under the same lock).  ``settle`` takes an
    optional ``req_id`` CAS so a fenced-but-still-running zombie
    replica whose request was rebound elsewhere can never overwrite
    the binding's verdict of record."""

    def __init__(self, dir: str | Path | None = None,  # noqa: A002
                 ttl_s: float = 3600.0, *, shared: bool = False):
        self.dir = Path(dir) if dir is not None else None
        self.shared = bool(shared) and self.dir is not None
        self.ttl_s = float(ttl_s)
        self.errors = 0
        self._lock = threading.Lock()
        #: key -> {"key", "req_id", "ts", "result"}
        self._entries: dict[str, dict] = {}      # guarded-by: _lock [rw]
        #: monotonic state-transition stamp (every mutation bumps it)
        self._seq = 0                            # guarded-by: _lock [rw]
        #: the IO side: disk writes happen OUTSIDE ``_lock`` (an fsync
        #: under the map lock would stall every stats()/lookup() behind
        #: disk latency — the hazard class the journal depth cache just
        #: removed) but serialized under ``_io_lock`` with a per-key
        #: last-written seq, so an older snapshot can never overwrite a
        #: newer state on disk.
        self._io_lock = threading.Lock()
        self._written: dict[str, int] = {}       # guarded-by: _io_lock [rw]
        if self.dir is not None:
            self.dir.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        import hashlib as _hashlib

        digest = _hashlib.sha256(key.encode()).hexdigest()[:24]
        return self.dir / f"idem-{digest}.json"

    # -- shared (cross-process) mode helpers ---------------------------

    def _key_lock(self, key: str):
        # sidecar lock file, never unlinked (see durable.file_lock) —
        # and never matched by replay()'s "idem-*.json" glob
        return _durable.file_lock(
            Path(str(self._path(key)) + ".lock"), timeout_s=30.0
        )

    # holds: the key's file lock
    def _read_disk_locked(self, key: str) -> dict | None:
        """The live on-disk entry for ``key``, or None.  An expired
        file is deleted here (safe: we hold its lock); a corrupt one
        reads as absent and counts on ``errors`` (read_verified has
        already quarantined it aside)."""
        p = self._path(key)
        if not p.exists():
            return None
        try:
            rr = _durable.read_verified(p, KIND_IDEM)
        except _durable.DurableError:
            self.errors += 1
            return None
        e = rr.payload
        if not isinstance(e, dict) or "key" not in e:
            self.errors += 1
            return None
        if time.time() - float(e.get("ts") or 0) > self.ttl_s:
            with contextlib.suppress(OSError):
                p.unlink()
            return None
        return {
            "key": str(e["key"]), "req_id": str(e.get("req_id") or ""),
            "ts": float(e.get("ts") or time.time()),
            "result": e.get("result"), "fp": e.get("fp"),
        }

    # holds: the key's file lock
    def _write_disk_locked(self, key: str, snapshot: dict) -> None:
        try:
            _durable.write_record(self._path(key), KIND_IDEM, snapshot)
        except Exception:  # noqa: BLE001 — same contract as _persist
            self.errors += 1
            logger.warning("idempotency entry write failed for key %r",
                           key, exc_info=True)

    # holds: the key's file lock
    def _sync_memory_locked(self, key: str, disk: dict | None) -> None:
        """Make the in-memory mirror agree with the disk truth just
        read under the lock (another process may have moved the key)."""
        with self._lock:
            if disk is None:
                self._entries.pop(key, None)
            else:
                self._entries[key] = dict(disk)
            self._seq += 1

    # holds: _lock
    def _purge_locked(self) -> list[str]:
        """Drop expired entries from memory; returns the expired keys
        so the caller can reclaim their DISK files outside the lock
        (a long-lived service must not grow one idem file per key it
        ever saw until the next restart)."""
        now = time.time()
        dead = [k for k, e in self._entries.items()
                if now - e["ts"] > self.ttl_s]
        for k in dead:
            del self._entries[k]
            self._seq += 1
        return dead

    def _unlink_keys(self, keys) -> None:
        """Reclaim dead keys' disk files.  A racing in-flight persist
        (snapshot taken before the key died) may recreate a file after
        this unlink; that residue is harmless — replay() either sees
        an expired ts and deletes it, or an unsettled binding to a
        request that never ran, which the rebind-after-grace path runs
        fresh.  What must NOT leak is ``_written``: popping the key
        here is what keeps the seq map bounded by live entries.

        Shared mode re-checks the DISK ts under the key's file lock
        before unlinking: this replica's memory expiring a key says
        nothing about a sibling replica having refreshed it since."""
        if self.dir is None or not keys:
            return
        if self.shared:
            for k in keys:
                try:
                    with self._key_lock(k):
                        # reads the disk entry; an expired one is
                        # unlinked inside, a live (refreshed-elsewhere)
                        # one is left alone
                        self._read_disk_locked(k)
                except Exception:  # noqa: BLE001 — lock timeout/IO
                    self.errors += 1
            with self._io_lock:
                for k in keys:
                    self._written.pop(k, None)
            return
        with self._io_lock:
            for k in keys:
                try:
                    self._path(k).unlink(missing_ok=True)
                except OSError:
                    self.errors += 1
                self._written.pop(k, None)

    def _persist(self, key: str, seq: int, snapshot: dict) -> None:
        """Write one entry snapshot taken at state-transition ``seq``.
        Runs outside the map lock; ``_io_lock`` + the per-key
        last-written seq enforce that disk state never goes BACKWARD
        even when two transitions race to the writer — in-memory order
        and on-disk order agree, which is what replay() trusts."""
        if self.dir is None:
            return
        with self._io_lock:
            if self._written.get(key, 0) >= seq:
                return  # a newer state for this key already landed
            self._written[key] = seq
            try:
                _durable.write_record(self._path(key), KIND_IDEM, snapshot)
            except Exception:  # noqa: BLE001 — persistence is a recovery
                # aid; the in-memory map still dedups within this process
                self.errors += 1
                logger.warning("idempotency entry write failed for key %r",
                               key, exc_info=True)

    def claim(self, key: str, req_id: str,
              fp: str | None = None) -> dict | None:
        """Atomically bind ``key`` to ``req_id`` — unless a live entry
        already holds it, in which case THAT entry (a copy) is returned
        and nothing is written.  None means the claim is ours.  ``fp``
        (the history fingerprint) is stored on the entry so the caller
        can detect KEY REUSE across different histories — without it a
        key collision would hand one caller another history's
        verdict."""
        key = str(key)
        if self.shared:
            # Cross-process atomicity: the entry FILE is the claim
            # token.  Under the key's advisory lock, read disk truth —
            # a live entry (ours from an earlier claim, or a sibling
            # replica's) loses the claim; absence binds us, and the
            # write lands BEFORE the lock releases, so no second
            # process can observe the gap two in-process claims never
            # had.
            with self._lock:
                dead = self._purge_locked()
            with self._key_lock(key):
                disk = self._read_disk_locked(key)
                if disk is not None:
                    self._sync_memory_locked(key, disk)
                    claimed = None
                else:
                    e = {"key": key, "req_id": str(req_id),
                         "ts": time.time(), "result": None, "fp": fp}
                    self._sync_memory_locked(key, e)
                    self._write_disk_locked(key, dict(e))
                    claimed = dict(e)
            self._unlink_keys(dead)
            return None if claimed is not None else dict(disk)
        with self._lock:
            dead = self._purge_locked()
            e = self._entries.get(key)
            if e is not None:
                snapshot, seq = dict(e), None
            else:
                self._seq += 1
                seq = self._seq
                e = {"key": key, "req_id": str(req_id), "ts": time.time(),
                     "result": None, "fp": fp}
                self._entries[key] = e
                snapshot = dict(e)
        self._unlink_keys(dead)
        if seq is None:
            return snapshot
        self._persist(key, seq, snapshot)
        return None

    def rebind(self, key: str, old_req_id: str, new_req_id: str) -> bool:
        """CAS a STALE entry (its request evaporated — e.g. evicted
        before settling, or bound by a replica that died) onto a new
        request id.  False when the entry changed underneath (someone
        else rebound or settled it)."""
        key = str(key)
        if self.shared:
            with self._key_lock(key):
                disk = self._read_disk_locked(key)
                if disk is None or disk["req_id"] != str(old_req_id) \
                        or disk["result"] is not None:
                    self._sync_memory_locked(key, disk)
                    return False
                disk["req_id"] = str(new_req_id)
                disk["ts"] = time.time()
                self._sync_memory_locked(key, disk)
                self._write_disk_locked(key, dict(disk))
            return True
        with self._lock:
            e = self._entries.get(key)
            if e is None or e["req_id"] != str(old_req_id) \
                    or e["result"] is not None:
                return False
            e["req_id"] = str(new_req_id)
            e["ts"] = time.time()
            self._seq += 1
            seq, snapshot = self._seq, dict(e)
        self._persist(key, seq, snapshot)
        return True

    def settle(self, key: str, result: Mapping | None,
               req_id: str | None = None) -> None:
        """Record the settled verdict against ``key`` (refreshes the
        TTL: a settled entry answers duplicates for a full window after
        the verdict, not after the submit).  With ``req_id``, settle
        only if the key is still bound to THAT request — the fence/
        rebind race guard: a zombie replica finishing a request whose
        key the router already rebound elsewhere must discard its
        verdict, not publish it over the binding of record."""
        key = str(key)
        if self.shared:
            with self._key_lock(key):
                disk = self._read_disk_locked(key)
                if disk is None or (req_id is not None
                                    and disk["req_id"] != str(req_id)):
                    self._sync_memory_locked(key, disk)
                    return
                disk["result"] = store._jsonable(dict(result)) \
                    if result is not None else None
                disk["ts"] = time.time()
                self._sync_memory_locked(key, disk)
                self._write_disk_locked(key, dict(disk))
            return
        with self._lock:
            e = self._entries.get(key)
            if e is None or (req_id is not None
                             and e["req_id"] != str(req_id)):
                return
            e["result"] = store._jsonable(dict(result)) \
                if result is not None else None
            e["ts"] = time.time()
            self._seq += 1
            seq, snapshot = self._seq, dict(e)
        self._persist(key, seq, snapshot)

    def release(self, key: str, req_id: str) -> None:
        """Drop OUR unsettled claim (the submit it covered failed
        admission) so the client's retry isn't answered with a request
        that never existed."""
        key = str(key)
        if self.shared:
            with self._key_lock(key):
                disk = self._read_disk_locked(key)
                if disk is None or disk["req_id"] != str(req_id) \
                        or disk["result"] is not None:
                    self._sync_memory_locked(key, disk)
                    return
                self._sync_memory_locked(key, None)
                try:
                    self._path(key).unlink(missing_ok=True)
                except OSError:
                    self.errors += 1
            with self._io_lock:
                self._written.pop(key, None)
            return
        with self._lock:
            e = self._entries.get(key)
            if e is None or e["req_id"] != str(req_id) \
                    or e["result"] is not None:
                return
            del self._entries[key]
            self._seq += 1
        self._unlink_keys([key])

    def lookup(self, key: str) -> dict | None:
        with self._lock:
            dead = self._purge_locked()
            e = self._entries.get(str(key))
            out = dict(e) if e is not None else None
        self._unlink_keys(dead)
        return out

    def depth(self) -> int:
        with self._lock:
            dead = self._purge_locked()
            n = len(self._entries)
        self._unlink_keys(dead)
        return n

    def replay(self) -> int:
        """Reload the journaled map (service start).  Expired files are
        deleted, corrupt ones quarantined + counted; returns live
        entries loaded."""
        if self.dir is None:
            return 0
        n = 0
        now = time.time()
        for p in sorted(self.dir.glob("idem-*.json")):
            try:
                rr = _durable.read_verified(p, KIND_IDEM)
            except _durable.DurableError as e:
                self.errors += 1
                logger.warning("corrupt idempotency entry %s quarantined: "
                               "%s", p, e)
                continue
            e = rr.payload
            if not isinstance(e, dict) or "key" not in e:
                self.errors += 1
                continue
            if now - float(e.get("ts") or 0) > self.ttl_s:
                with contextlib.suppress(OSError):
                    p.unlink()
                continue
            with self._lock:
                self._entries[str(e["key"])] = {
                    "key": str(e["key"]),
                    "req_id": str(e.get("req_id") or ""),
                    "ts": float(e.get("ts") or now),
                    "result": e.get("result"),
                    # fp must survive the restart or key-reuse-across-
                    # histories rejection silently turns off after it
                    "fp": e.get("fp"),
                }
            n += 1
        return n

    def describe(self) -> dict:
        with self._lock:
            dead = self._purge_locked()
            out = {
                "entries": len(self._entries),
                "settled": sum(1 for e in self._entries.values()
                               if e["result"] is not None),
                "ttl_s": self.ttl_s,
                "errors": self.errors,
                "journaled": self.dir is not None,
                "shared": self.shared,
            }
        self._unlink_keys(dead)
        return out
