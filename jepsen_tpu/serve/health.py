"""Self-healing for the check service: blast-radius isolation primitives.

PRs 4–6 made checking a long-lived multi-tenant service; sharing a
launch also shares its failures.  This module is the policy layer that
keeps one bad input, one lost device, or one wedged launch from
degrading everyone else — four pillars, composed by
``serve.service.CheckService``:

  * **Poison quarantine** (``bisect_poison`` + ``Quarantine``) — when a
    shared ``batch_analysis`` launch fails NON-transiently (transient
    and OOM faults are already retried/halved inside the ladder by
    ``jepsen_tpu.faults``), the member set is bisected with bounded
    relaunches: innocent members get their real verdicts from the
    succeeding halves, and the member(s) whose presence makes launches
    fail are quarantined — unknown verdict with the cause, plus a
    TTL'd registry entry keyed by history fingerprint so a repeat
    offender skips straight to rejection instead of poisoning another
    shared launch.  Isolating a single poison member costs O(log n)
    relaunches.
  * **Circuit breaker** (``CircuitBreaker``) — K consecutive batch
    failures open the breaker: admission returns 503 + retry-after
    instead of queueing work the device can't serve; after a cooldown
    the breaker half-opens and one probe batch decides whether to
    close it again.
  * **Hung-launch watchdog** (``LaunchWatchdog``) — per-launch
    wall-clock caps derived from the EWMA of recorded launch times
    (``faults.launch_seconds_ewma``, fed by ``parallel.batch._launch``);
    a launch that exceeds its cap raises ``HungLaunch`` so the service
    can cancel (abandon — first-write-wins result demux discards the
    zombie's late verdicts) and retry on reduced placement.
  * **Crash-safe restart** (``AdmissionJournal``) — an fsync'd journal
    of admitted-but-unfinished requests (``store._atomic_write``, one
    file per request in the drain-dir format) replayed by
    ``CheckService.start()``: a service crash loses no admitted
    request, and replayed requests keep their ids so ``GET
    /check/<id>`` keeps working across the restart.

Nothing here decides verdicts: quarantine and watchdog degradation
resolve only to attributable ``unknown``s, never to a flipped verdict.
"""

from __future__ import annotations

import json
import logging
import math
import threading
import time
from pathlib import Path
from typing import Callable, Sequence

from jepsen_tpu import store
from jepsen_tpu.store import checkpoint as _ckpt

logger = logging.getLogger(__name__)


def history_fingerprint(history) -> str:
    """The quarantine/journal identity of one history (the same sha256
    the checkpoint layer uses, over a single-history list)."""
    return _ckpt.fingerprint([history])


# ---------------------------------------------------------------------------
# Poison quarantine
# ---------------------------------------------------------------------------

class Quarantine:
    """A TTL'd registry of poison-history fingerprints.

    ``add`` records a fingerprint with its cause; ``check`` returns the
    live entry (or None) so admission can reject a repeat offender
    before it reaches a shared launch.  Entries expire after ``ttl_s``
    — a poison verdict is evidence, not a life sentence (the failure
    may have been environmental) — and expired entries are purged
    lazily on access.  Thread-safe."""

    def __init__(self, ttl_s: float = 900.0):
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        #: fp -> {"cause", "expires", "hits", "added"}
        self._entries: dict[str, dict] = {}      # guarded-by: _lock [rw]

    def __len__(self) -> int:
        with self._lock:
            self._purge_locked()
            return len(self._entries)

    # holds: _lock
    def _purge_locked(self) -> None:
        now = time.monotonic()
        dead = [fp for fp, e in self._entries.items() if e["expires"] <= now]
        for fp in dead:
            del self._entries[fp]

    def add(self, fp: str, cause: str) -> None:
        with self._lock:
            self._purge_locked()
            self._entries[fp] = {
                "cause": str(cause)[:300],
                "expires": time.monotonic() + self.ttl_s,
                "hits": 0,
                "added": time.time(),
            }

    def check(self, fp: str) -> dict | None:
        """The live entry for ``fp`` (hit-counted), or None.  A hit
        refreshes the TTL — a fingerprint still being submitted is
        still worth remembering."""
        with self._lock:
            self._purge_locked()
            e = self._entries.get(fp)
            if e is not None:
                e["hits"] += 1
                e["expires"] = time.monotonic() + self.ttl_s
            return e

    def describe(self) -> dict:
        with self._lock:
            self._purge_locked()
            return {
                "entries": len(self._entries),
                "ttl_s": self.ttl_s,
                "hits": sum(e["hits"] for e in self._entries.values()),
            }


def bisect_launch_budget(n: int) -> int:
    """The relaunch budget ``bisect_poison`` defaults to: enough to
    isolate one poison member among ``n`` — both bisection paths at
    every level, ~2·(log2(n)+1) — with one extra level of slack for a
    second offender before the remainder is quarantined as a group."""
    levels = max(1, math.ceil(math.log2(max(2, n)))) + 1
    return 3 * levels


def bisect_poison(
    launch: Callable[[list], list],
    members: Sequence,
    *,
    max_launches: int | None = None,
) -> tuple[list, dict, int]:
    """Isolate the poison member(s) of a failed shared launch.

    ``launch(subset)`` re-runs the shared work over ``subset`` and
    returns one result per member (or raises — the failure signature
    being bisected).  Returns ``(poison, results, launches)``: the
    members whose presence makes launches fail, a ``{member: result}``
    map for every innocent member (their REAL verdicts, recovered from
    the succeeding halves), and the relaunch count.

    Classic group testing: a failing group of one is poison; a failing
    group of many splits in half and recurses.  A single poison member
    among n costs O(log n) relaunches.  ``max_launches`` (default
    ``bisect_launch_budget(n)``) bounds the degradation: when the
    budget runs out, the still-unresolved group is quarantined TOGETHER
    (conservative — innocents in it degrade to unknown, never to a
    wrong verdict)."""
    members = list(members)
    budget = (
        bisect_launch_budget(len(members))
        if max_launches is None else int(max_launches)
    )
    poison: list = []
    results: dict = {}
    launches = 0
    stack: list[list] = [members]
    while stack:
        group = stack.pop()
        if not group:
            continue
        if launches >= budget:
            # Budget exhausted: quarantine the rest as a group rather
            # than launch forever against a pathological failure mix.
            poison.extend(group)
            continue
        launches += 1
        try:
            out = launch(list(group))
        except Exception:  # noqa: BLE001 — the signature being bisected
            if len(group) == 1:
                poison.append(group[0])
            else:
                mid = (len(group) + 1) // 2
                # push the back half first so the front half (older
                # members) is served next — deterministic order
                stack.append(group[mid:])
                stack.append(group[:mid])
            continue
        for mem, res in zip(group, out):
            results[mem] = res
    return poison, results, launches


# ---------------------------------------------------------------------------
# Circuit breaker
# ---------------------------------------------------------------------------

class CircuitBreaker:
    """Closed → (K consecutive failures) → open → (cooldown) →
    half-open → one probe success closes / failure re-opens.

    ``allow()`` is the admission gate: False means reject now (the HTTP
    layer returns 503 + Retry-After ``retry_after()``).  While OPEN the
    gate stays shut until ``cooldown_s`` elapses; the first ``allow()``
    after that transitions to HALF-OPEN and admits exactly ONE probe —
    further ``allow()`` calls stay rejected until a batch outcome is
    recorded, so a retry stampede at cooldown expiry can't refill the
    queue with doomed work against a still-broken device.  Thread-safe;
    the owning service calls ``record_failure``/``record_success`` per
    batch outcome."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 30.0):
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._lock = threading.Lock()
        self.state = "closed"                    # guarded-by: _lock [rw]
        self.consecutive_failures = 0            # guarded-by: _lock [rw]
        self.opened_at: float | None = None      # guarded-by: _lock [rw]
        self.opens = 0                           # guarded-by: _lock [rw]
        # half-open admissions left before outcome
        self._probe_budget = 0                   # guarded-by: _lock [rw]

    def allow(self) -> bool:
        with self._lock:
            if self.state == "open":
                if (time.monotonic() - self.opened_at) >= self.cooldown_s:
                    self.state = "half-open"
                    self._probe_budget = 1
            if self.state == "half-open":
                if self._probe_budget > 0:
                    self._probe_budget -= 1
                    return True
                return False
            return self.state == "closed"

    def retry_after(self) -> float:
        with self._lock:
            if self.state == "half-open":
                # a probe is in flight; its outcome decides shortly
                return 0.5
            if self.state != "open" or self.opened_at is None:
                return 0.0
            return max(
                0.0, self.cooldown_s - (time.monotonic() - self.opened_at)
            )

    def record_failure(self) -> bool:
        """One batch failed; returns True when THIS failure opened (or
        re-opened) the breaker."""
        with self._lock:
            self.consecutive_failures += 1
            if self.state == "half-open" or (
                self.state == "closed"
                and self.consecutive_failures >= self.threshold
            ):
                self.state = "open"
                self.opened_at = time.monotonic()
                self.opens += 1
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self.consecutive_failures = 0
            if self.state in ("half-open", "open"):
                # an open breaker can see a success when a probe batch
                # admitted just before the trip completes late — either
                # way the device demonstrably serves again
                self.state = "closed"
                self.opened_at = None

    def describe(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "consecutive_failures": self.consecutive_failures,
                "threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "opens": self.opens,
                "retry_after_s": round(
                    max(0.0, self.cooldown_s
                        - (time.monotonic() - self.opened_at))
                    if self.state == "open" and self.opened_at is not None
                    else 0.0, 3),
            }


# ---------------------------------------------------------------------------
# Hung-launch watchdog
# ---------------------------------------------------------------------------

class HungLaunch(Exception):
    """A watched launch exceeded its wall-clock cap.  The worker thread
    may STILL be running (jax launches aren't interruptible from
    Python) — the caller abandons it and retries on reduced placement;
    first-write-wins result demux discards the zombie's late output."""

    def __init__(self, timeout_s: float):
        self.timeout_s = timeout_s
        super().__init__(f"launch exceeded its {timeout_s:.1f}s watchdog cap")


class LaunchWatchdog:
    """Per-launch wall-clock caps derived from the launch-time EWMA.

    ``timeout_s()`` is ``factor ×`` the process launch EWMA
    (``faults.launch_seconds_ewma``), clamped to ``[floor_s, cap_s]`` —
    a healthy ladder's launches are milliseconds-to-seconds, so a
    multi-minute one is wedged, not slow.  ``run(fn)`` executes ``fn``
    on a daemon worker thread and raises ``HungLaunch`` when the cap
    passes first."""

    def __init__(self, factor: float = 16.0, floor_s: float = 30.0,
                 cap_s: float = 600.0):
        self.factor = float(factor)
        self.floor_s = float(floor_s)
        self.cap_s = float(cap_s)
        self.trips = 0

    def timeout_s(self) -> float:
        from jepsen_tpu import faults

        ewma = faults.launch_seconds_ewma()
        t = self.factor * ewma if ewma is not None else self.floor_s
        return min(self.cap_s, max(self.floor_s, t))

    def run(self, fn: Callable[[], object], timeout_s: float | None = None):
        """``fn()``'s result, or ``HungLaunch`` after the cap.  ``fn``'s
        own exception re-raises on this thread."""
        timeout_s = self.timeout_s() if timeout_s is None else float(timeout_s)
        box: dict = {}
        done = threading.Event()

        def _work():
            try:
                box["result"] = fn()
            except BaseException as e:  # noqa: BLE001 — re-raised below
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(
            target=_work, name="launch-watchdog-worker", daemon=True
        )
        t.start()
        if not done.wait(timeout_s):
            self.trips += 1
            raise HungLaunch(timeout_s)
        if "error" in box:
            raise box["error"]
        return box["result"]


# ---------------------------------------------------------------------------
# Crash-safe admission journal
# ---------------------------------------------------------------------------

class AdmissionJournal:
    """An fsync'd record of admitted-but-unfinished requests.

    One JSON file per request (``store._atomic_write``: tmp + fsync +
    rename + dir fsync — the same durability contract checkpoints
    ride), in the drain-dir format (model name + history + request
    identity) so ``replay()`` can rebuild the exact submission.
    ``record`` on admission, ``resolve`` when the request settles (any
    terminal status — done, expired, quarantined, drained); whatever
    files remain after a crash ARE the lost queue, replayed by
    ``CheckService.start()``.  Write failures are counted and logged,
    never raised into admission — journaling is a recovery aid, not an
    admission gate."""

    def __init__(self, journal_dir: str | Path):
        self.dir = Path(journal_dir)
        self.dir.mkdir(parents=True, exist_ok=True)
        self.errors = 0

    def _path(self, req_id: str) -> Path:
        return self.dir / f"req-{req_id}.json"

    def record(self, *, req_id: str, seq: int, model_name: str, history,
               priority: int, client: str, tier: str,
               trace_id: str, deadline_s: float | None) -> bool:
        entry = {
            "id": req_id, "seq": int(seq), "model": model_name,
            "history": store._jsonable(list(history)),
            "priority": int(priority), "client": str(client),
            "class": str(tier), "trace_id": str(trace_id),
            "deadline_s": deadline_s,
        }
        try:
            store._atomic_write(
                self._path(req_id), json.dumps(entry, default=str)
            )
            return True
        except Exception:  # noqa: BLE001 — see docstring
            self.errors += 1
            logger.warning("admission journal write failed for %s",
                           req_id, exc_info=True)
            return False

    def resolve(self, req_id: str) -> None:
        try:
            self._path(req_id).unlink(missing_ok=True)
        except OSError:
            self.errors += 1
            logger.warning("admission journal unlink failed for %s",
                           req_id, exc_info=True)

    def depth(self) -> int:
        try:
            return sum(1 for _ in self.dir.glob("req-*.json"))
        except OSError:
            return 0

    def replay(self) -> list[dict]:
        """Every surviving entry, in admission (seq) order.  Unreadable
        files are counted and skipped — a torn write can't exist
        (atomic rename), but an operator hand-editing the dir can."""
        out = []
        for p in sorted(self.dir.glob("req-*.json")):
            try:
                out.append(json.loads(p.read_text()))
            except (OSError, ValueError):
                self.errors += 1
                logger.warning("unreadable journal entry %s; skipping", p)
        out.sort(key=lambda e: e.get("seq", 0))
        return out
