"""The CheckService: queue, batching scheduler, demux, backpressure.

Request lifecycle::

    submit() ──admission──▶ queued ──scheduler──▶ running ──demux──▶ done
        │ (queue full)         │ (deadline up)                        ▲
        ▼                      ▼                                      │
    QueueFull(retry_after)   expired (unknown)        drained (checkpoint)

The scheduler thread owns the device: it pops the highest-priority
queued request, gathers up to ``max_batch`` queued requests from the
SAME compatibility group — ``(model, padded B, bucketed P, bucketed G)``
via ``parallel.batch.bucket_geometry``, so every batch re-launches an
already-compiled kernel shape — and runs ONE ``batch_analysis`` over
them.  Requests from other groups stay queued for the next cycle;
submissions arriving mid-batch queue up behind it (continuous
cross-request batching: the device never waits for a "full" batch, and
a batch never waits on a straggler caller).

Per-request deadlines bound the QUEUE wait: a request whose
``faults.Deadline`` expires while queued resolves ``unknown``
(``deadline-exceeded``) without consuming batch lanes — expiry degrades
only that request, never the shared batch.  A request already riding a
launch when its budget runs out still gets its verdict (it costs the
batch nothing extra); the result carries ``"deadline-overrun": True``.

Soundness is inherited unchanged from ``batch_analysis``: the service
only arbitrates WHICH histories share a launch, never how they are
decided.
"""

from __future__ import annotations

import itertools
import json
import logging
import threading
import time
import uuid
from concurrent.futures import Future
from pathlib import Path
from typing import Mapping, Sequence

from jepsen_tpu import faults, obs, store
from jepsen_tpu import models as m
from jepsen_tpu.obs import metrics

logger = logging.getLogger(__name__)

#: models a request may name over HTTP / in a drain file (model classes
#: with argument-free constructors, keyed by their ClassVar name).
MODELS = {
    cls.name: cls
    for cls in (
        m.Register, m.CASRegister, m.Mutex, m.UnorderedQueue,
        m.FIFOQueue, m.MonotonicCounter,
    )
}

#: completed request records kept for GET /check/<id> (oldest evicted).
_KEEP_DONE = 1024

#: drain metadata file (model name + histories + request ids), written
#: next to the store.checkpoint files so resume_drained can rebuild the
#: exact batch_analysis call the scheduler would have made.
DRAIN_META = "drained.json"


def model_by_name(name: str) -> m.Model:
    """A fresh default-constructed model instance for a registry name."""
    try:
        return MODELS[name]()
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(MODELS)}"
        ) from None


class QueueFull(Exception):
    """Admission rejected: the queue is at ``max_queue`` depth.

    ``retry_after`` estimates (seconds) when a slot should free up —
    queue depth over batch width times the recent batch wall-clock EWMA.
    The HTTP layer maps this to 429 + a Retry-After header."""

    def __init__(self, depth: int, limit: int, retry_after: float):
        self.depth = depth
        self.limit = limit
        self.retry_after = retry_after
        super().__init__(
            f"check queue full ({depth}/{limit}); retry after "
            f"~{retry_after:.1f}s"
        )


class ServiceClosed(Exception):
    """Submit after shutdown began: the service no longer admits work."""


class CheckFuture(Future):
    """The verdict future ``submit`` returns; resolves to the same
    knossos-shaped result dict ``batch_analysis`` produces.  ``id`` keys
    ``GET /check/<id>``."""

    id: str


class CheckRequest:
    """One admitted request's record (the HTTP status object)."""

    __slots__ = (
        "id", "seq", "model", "history", "priority", "deadline", "client",
        "group", "future", "status", "result", "t_submit", "t_done",
        "trace_id", "ctx",
    )

    def __init__(self, *, seq, model, history, priority, deadline, client,
                 group, trace_id=None):
        self.id = uuid.uuid4().hex[:12]
        self.seq = seq
        self.model = model
        self.history = history
        self.priority = priority
        self.deadline = deadline
        self.client = client
        self.group = group
        self.future = CheckFuture()
        self.future.id = self.id
        self.status = "queued"
        self.result: dict | None = None
        self.t_submit = time.monotonic()
        self.t_done: float | None = None
        # The request's trace identity + the admission thread's span
        # context, captured HERE so the scheduler thread's demux events
        # re-attach to it (obs.attach) — parent links and the trace id
        # survive the admission -> scheduler -> demux thread hops.
        self.trace_id = trace_id or obs.new_trace_id()
        self.ctx = obs.capture(trace=self.trace_id)

    def describe(self) -> dict:
        """The JSONable status document (GET /check/<id>)."""
        out = {
            "id": self.id,
            "status": self.status,
            "client": self.client,
            "priority": self.priority,
            "model": self.model.name,
            "trace_id": self.trace_id,
        }
        if self.result is not None:
            out["result"] = self.result
        if self.t_done is not None:
            out["latency_s"] = round(self.t_done - self.t_submit, 6)
        return out

    def resolve(self, result: dict, status: str = "done") -> bool:
        """Resolve the future once; later attempts are no-ops (a zombie
        batch finishing after shutdown already drained its requests must
        not raise InvalidStateError in the scheduler; a client may also
        have cancel()ed the future).  Returns whether THIS call resolved
        it."""
        if self.future.done():
            return False
        self.result = result
        self.status = status
        self.t_done = time.monotonic()
        try:
            self.future.set_result(result)
        except Exception:  # noqa: BLE001 — lost the race; first write won
            return False
        return True


class CheckService:
    """A persistent multi-tenant check service over ``batch_analysis``.

    ``capacity``/``mesh``/``**check_opts`` configure the ONE ladder every
    batch runs (requests carry no per-request ladder knobs — a shared
    launch needs a shared config; per-request opts are priority,
    deadline, and client id).  ``max_queue`` bounds admission
    (``QueueFull`` beyond it), ``max_batch`` bounds lanes per launch,
    ``batch_window_s`` is the brief pile-in pause before each batch so
    concurrent submitters coalesce.  ``drain_dir`` is where shutdown
    checkpoints still-queued work (None: drained requests resolve
    unknown without a checkpoint).

    ``start()`` spawns the scheduler thread (and pre-forks the
    confirmation worker pool, so the first confirmed-unknown request
    doesn't eat pool fork latency); tests drive ``step()`` directly for
    deterministic single-batch control."""

    def __init__(
        self,
        *,
        capacity: int | Sequence[int] = (64, 512, 4096),
        mesh=None,
        max_queue: int = 256,
        max_batch: int = 64,
        batch_window_s: float = 0.002,
        warm_pool: bool = True,
        drain_dir: str | Path | None = None,
        **check_opts,
    ):
        for k in ("capacity", "mesh", "deadline", "checkpoint_dir", "resume"):
            if k in check_opts:
                raise TypeError(
                    f"{k!r} is service-level configuration, not a check opt"
                )
        self.capacity = capacity
        self.mesh = mesh
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch)
        self.batch_window_s = float(batch_window_s)
        self.warm_pool = warm_pool
        self.drain_dir = Path(drain_dir) if drain_dir is not None else None
        self._check_opts = dict(check_opts)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: list[CheckRequest] = []
        self._reserved = 0  # admission slots held while packing off-lock
        self._requests: dict[str, CheckRequest] = {}
        self._seq = itertools.count()
        self._closed = False
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._running = 0
        self._inflight: list[CheckRequest] = []  # the batch on the device
        self._t_start = time.monotonic()
        self._batch_ewma_s = 1.0
        self._totals = {
            "submitted": 0, "completed": 0, "rejected": 0, "expired": 0,
            "drained": 0, "batches": 0, "batch_errors": 0,
        }
        self._occ_sum = 0.0  # occupancy accumulator for stats()

    # ------------------------------------------------------------------
    # Admission
    # ------------------------------------------------------------------

    def submit(
        self,
        history: Sequence[Mapping],
        *,
        model: m.Model | None = None,
        priority: int = 0,
        deadline=None,
        client: str = "anon",
        trace_id: str | None = None,
    ) -> CheckFuture:
        """Admit one history; returns a future resolving to its verdict.

        ``model`` defaults to ``CASRegister()``.  ``priority``: higher
        runs first (FIFO within a priority).  ``deadline``: seconds (or
        a ``faults.Deadline``) bounding the queue wait.  ``trace_id``
        joins this request to a caller's existing trace (HTTP clients
        pass it in the POST body); None mints a fresh id — read it back
        from the returned future's request record or the status
        document.  Raises ``QueueFull`` (backpressure) or
        ``ServiceClosed``."""
        # Coerce every argument BEFORE reserving a slot: a reservation
        # leaked past a bad-argument raise would shrink admission
        # capacity forever.
        model = model if model is not None else m.CASRegister()
        deadline = faults.Deadline.coerce(deadline)
        history = list(history)
        priority = int(priority)
        client = str(client)
        trace_id = str(trace_id) if trace_id is not None else None
        with self._lock:
            if self._closed:
                raise ServiceClosed("check service is shutting down")
            depth = len(self._queue) + self._reserved
            if depth >= self.max_queue:
                self._totals["rejected"] += 1
                obs.counter("serve.rejected", client=client)
                raise QueueFull(depth, self.max_queue, self._retry_after())
            # Hold the slot while packing off-lock: two racing submitters
            # must not both pass the depth check into a full queue.
            self._reserved += 1
        try:
            group = self._group_of(model, history)
            req = CheckRequest(
                seq=next(self._seq), model=model, history=history,
                priority=priority, deadline=deadline, client=client,
                group=group, trace_id=trace_id,
            )
        except BaseException:
            with self._lock:
                self._reserved -= 1
            raise
        with self._cond:
            self._reserved -= 1
            if self._closed and group is not None:
                # shutdown() began while we were packing off-lock: its
                # drain already snapshotted the queue, so appending now
                # would strand this request unresolved forever.
                self._totals["rejected"] += 1
                obs.counter("serve.rejected", client=client)
                raise ServiceClosed("check service is shutting down")
            self._totals["submitted"] += 1
            self._remember(req)
            if group is None:
                self._totals["completed"] += 1
            else:
                self._queue.append(req)
                self._cond.notify_all()
            with obs.attach(req.ctx):
                obs.counter("serve.submitted", client=client)
                obs.gauge("serve.queue_depth", len(self._queue))
        if group is None:
            # Trivial fast path: no barriers -> valid, no lanes spent.
            # Resolved OUTSIDE the lock: set_result runs done-callbacks
            # synchronously, and a callback re-entering the service
            # (submit/stats) must not deadlock on a held lock.
            req.resolve({"valid?": True})
            with obs.attach(req.ctx):
                obs.counter("serve.completed")
            metrics.inc("serve.verdicts", verdict="true")
            metrics.observe("serve.request_latency_seconds",
                            time.monotonic() - req.t_submit)
        return req.future

    def _group_of(self, model: m.Model, history) -> tuple | None:
        """The batch-compatibility key: (model, padded geometry).  None
        means trivially valid (no device work); untensorizable histories
        get their own group so ``batch_analysis`` decides them the same
        way it would for a direct caller (CPU fallback or unknown).

        Known cost: the admission pack is thrown away and
        ``batch_analysis`` re-packs at launch — removing the double pack
        needs batch_analysis to accept pre-packed inputs (its
        checkpoint fingerprint and confirmation paths key on the raw
        histories today)."""
        from jepsen_tpu.ops import wgl
        from jepsen_tpu.parallel import batch

        try:
            p = wgl.pack(model, list(history))
        except wgl.NotTensorizable:
            return (model, "untensorizable")
        if p["B"] == 0:
            return None
        return (model, *batch.bucket_geometry(p["B"], p["P"], p["G"]))

    def _retry_after(self) -> float:
        """Backpressure hint: queue depth over batch width, in units of
        the recent batch wall-clock EWMA."""
        waves = max(1.0, len(self._queue) / max(1, self.max_batch))
        return round(max(0.05, waves * self._batch_ewma_s), 3)

    def _remember(self, req: CheckRequest) -> None:
        self._requests[req.id] = req
        if len(self._requests) > self.max_queue + _KEEP_DONE:
            done = [
                i for i, r in self._requests.items()
                if r.status not in ("queued", "running")
            ]
            for i in done[: len(done) - _KEEP_DONE]:
                del self._requests[i]

    # ------------------------------------------------------------------
    # Scheduling
    # ------------------------------------------------------------------

    def start(self) -> "CheckService":
        """Spawn the scheduler thread; idempotent.  Also turns on the
        live metrics registry (obs.metrics) — a started service is a
        serving process, and /metrics should reflect it."""
        if self._thread is not None:
            return self
        metrics.enable_mirror()
        if self.warm_pool and self._check_opts.get(
                "confirm_refutations", True) is True:
            # Satellite contract: pre-fork the confirmation workers at
            # service start so the first confirmed-unknown request
            # doesn't eat the pool's spawn+init latency (~seconds).
            from jepsen_tpu.parallel import batch

            batch.warm_confirm_pool(self._check_opts.get("confirm_workers"))
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="check-service", daemon=True
        )
        self._thread.start()
        return self

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop.is_set():
                    self._cond.wait(timeout=0.2)
                if self._stop.is_set():
                    return
            if self.batch_window_s > 0:
                # The pile-in window: let concurrent submitters coalesce
                # into this batch instead of each paying its own launch.
                time.sleep(self.batch_window_s)
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the scheduler must survive
                logger.exception("check-service batch step failed")

    def step(self) -> int:
        """Process one batch synchronously: expire overdue queued
        requests, select the highest-priority compatibility group, run
        one shared launch, demux.  Returns requests resolved (expired +
        batched).  The scheduler loop calls this; tests call it directly
        for deterministic control."""
        batch_reqs: list[CheckRequest] = []
        with self._cond:
            expired = self._take_expired_locked()
            if self._queue:
                self._queue.sort(key=lambda r: (-r.priority, r.seq))
                lead = self._queue[0]
                batch_reqs = [r for r in self._queue if r.group == lead.group]
                batch_reqs = batch_reqs[: self.max_batch]
                taken = set(id(r) for r in batch_reqs)
                self._queue = [r for r in self._queue if id(r) not in taken]
                for r in batch_reqs:
                    r.status = "running"
                self._running = len(batch_reqs)
                self._inflight = list(batch_reqs)
                obs.gauge("serve.queue_depth", len(self._queue))
        # Expired futures resolve outside the lock (done-callbacks may
        # re-enter the service); the shared batch is untouched.
        for r in expired:
            with obs.attach(r.ctx):
                obs.counter("serve.expired", client=r.client)
            metrics.inc("serve.verdicts", verdict="unknown")
            r.resolve(
                {
                    "valid?": "unknown",
                    "cause": (
                        "deadline-exceeded: request budget expired while "
                        "queued (the shared batch is unaffected)"
                    ),
                },
                status="expired",
            )
        handled = len(expired)
        if not batch_reqs:
            return handled
        t_start = time.monotonic()
        for r in batch_reqs:
            # Re-attach each request's admission-thread context: the
            # scheduler thread's per-request events carry the request's
            # trace id, not the scheduler's.
            with obs.attach(r.ctx):
                obs.span_event(
                    "serve.admission", t_start - r.t_submit, client=r.client
                )
            metrics.observe("serve.admission_latency_seconds",
                            t_start - r.t_submit)
        try:
            self._run_batch(batch_reqs)
        finally:
            with self._lock:
                self._running = 0
                self._inflight = []
        return handled + len(batch_reqs)

    def _take_expired_locked(self) -> list[CheckRequest]:
        """Pull queued requests whose deadline has passed off the queue
        (caller resolves them OUTSIDE the lock)."""
        live, expired = [], []
        for r in self._queue:
            if r.deadline is not None and r.deadline.expired():
                expired.append(r)
            else:
                live.append(r)
        self._queue = live
        self._totals["expired"] += len(expired)
        return expired

    def _run_batch(self, batch_reqs: list[CheckRequest]) -> None:
        from jepsen_tpu.parallel import batch

        model = batch_reqs[0].model
        n = len(batch_reqs)
        n_pad = batch.padded_batch(n, self.mesh)
        geom = batch_reqs[0].group[1:]
        trace_ids = [r.trace_id for r in batch_reqs]
        metrics.set_gauge("serve.batch_occupancy", round(n / n_pad, 4))
        metrics.set_gauge("serve.batch_padding_waste",
                          round(1.0 - n / n_pad, 4))
        metrics.set_gauge("serve.batch_requests", n)
        with obs.span(
            "serve.batch", requests=n, padded=n_pad,
            occupancy=round(n / n_pad, 4),
            padding_waste=round(1.0 - n / n_pad, 4),
            model=model.name, geometry=str(geom),
            trace_ids=trace_ids,
        ):
            t0 = time.monotonic()
            try:
                # The shared-batch trace scope: everything the launch
                # emits below here (ladder stages, confirmations,
                # fault retries) carries the member trace ids, so one
                # request's journey is findable inside the shared work.
                with obs.attach(trace=trace_ids, parent="serve.batch"):
                    results = batch.batch_analysis(
                        model, [r.history for r in batch_reqs],
                        capacity=self.capacity, mesh=self.mesh,
                        **self._check_opts,
                    )
                err = None
            except Exception as e:  # noqa: BLE001 — degrade the batch's
                # requests, never the service (the scheduler lives on)
                logger.exception("check-service batch failed")
                results, err = None, e
            dt = time.monotonic() - t0
        metrics.observe("serve.batch_seconds", dt)
        with self._lock:
            self._batch_ewma_s = 0.7 * self._batch_ewma_s + 0.3 * dt
            self._totals["batches"] += 1
            self._occ_sum += n / n_pad
            if err is not None:
                self._totals["batch_errors"] += 1
        metrics.inc("serve.batches")
        if err is not None:
            metrics.inc("serve.batch_errors")
            obs.counter("serve.batch_error", error=faults.describe(err))
            for r in batch_reqs:
                metrics.inc("serve.verdicts", verdict="unknown")
                r.resolve(
                    {
                        "valid?": "unknown",
                        "cause": f"service batch failed: {faults.describe(err)}",
                    },
                    status="error",
                )
            return
        t_done = time.monotonic()
        for r, res in zip(batch_reqs, results):
            if r.deadline is not None and r.deadline.expired():
                # Launched before the budget ran out: the verdict is
                # already paid for, so hand it over — annotated, so an
                # SLA-bound caller can still discount it.
                res = {**res, "deadline-overrun": True}
            r.resolve(res)
            with obs.attach(r.ctx):
                obs.span_event(
                    "serve.request", t_done - r.t_submit, client=r.client,
                    verdict=str(res.get("valid?")),
                )
            metrics.observe("serve.request_latency_seconds",
                            t_done - r.t_submit)
            metrics.inc("serve.verdicts",
                        verdict=str(res.get("valid?")).lower())
        with self._lock:
            self._totals["completed"] += len(batch_reqs)
        obs.counter("serve.completed", len(batch_reqs))

    # ------------------------------------------------------------------
    # Introspection (GET /queue, GET /check/<id>)
    # ------------------------------------------------------------------

    def get(self, request_id: str) -> CheckRequest | None:
        with self._lock:
            return self._requests.get(request_id)

    def stats(self) -> dict:
        """The queue-status document (GET /queue, web panel)."""
        with self._lock:
            by_client: dict[str, int] = {}
            for r in self._queue:
                by_client[r.client] = by_client.get(r.client, 0) + 1
            groups = len({r.group for r in self._queue})
            t = dict(self._totals)
            return {
                "queue_depth": len(self._queue),
                "queue_groups": groups,
                "running": self._running,
                "max_queue": self.max_queue,
                "max_batch": self.max_batch,
                "closed": self._closed,
                "by_client": by_client,
                "batch_ewma_s": round(self._batch_ewma_s, 4),
                "avg_occupancy": round(
                    self._occ_sum / t["batches"], 4) if t["batches"] else None,
                "retry_after_hint_s": self._retry_after(),
                "uptime_s": round(time.monotonic() - self._t_start, 3),
                **t,
            }

    # ------------------------------------------------------------------
    # Shutdown / drain
    # ------------------------------------------------------------------

    def shutdown(self, *, drain: bool = True, wait: bool = False,
                 join_timeout: float = 600.0) -> dict:
        """Stop admitting, stop the scheduler, settle EVERY admitted
        request.

        ``wait=True`` finishes ALL queued work first (every future gets
        its real verdict).  Otherwise the in-flight batch is given
        ``join_timeout`` seconds to complete and the still-queued
        remainder is DRAINED: with a ``drain_dir``, each compatibility
        group's histories + a resumable ``store.checkpoint`` land on
        disk (finish later with ``resume_drained``); the futures
        resolve unknown with the checkpoint path in ``cause``.  A batch
        still on the device after ``join_timeout`` has its requests
        drained too (resolve() is first-write-wins, so the zombie
        batch's late verdicts are discarded harmlessly).  Returns a
        summary dict."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if wait:
            # Settle the backlog before stopping the scheduler.  If the
            # scheduler thread isn't running, step() here.
            while True:
                with self._lock:
                    empty = not self._queue and self._running == 0
                if empty:
                    break
                if self._thread is None:
                    self.step()
                else:
                    time.sleep(0.01)
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            if self._thread.is_alive():
                logger.warning(
                    "scheduler still mid-batch after %.0fs; draining its "
                    "requests (late verdicts will be discarded)",
                    join_timeout,
                )
            self._thread = None
        with self._lock:
            # _inflight is non-empty only when the join timed out: those
            # requests were admitted and must still settle (drain below).
            remaining = list(self._inflight) + list(self._queue)
            self._queue = []
        summary = {"drained": 0, "checkpoints": []}
        if remaining:
            if drain:
                summary = self._drain(remaining)
            else:
                for r in remaining:
                    r.resolve(
                        {"valid?": "unknown",
                         "cause": "service shut down before this request "
                                  "was checked"},
                        status="drained",
                    )
                summary["drained"] = len(remaining)
        with self._lock:
            self._totals["drained"] += summary["drained"]
        return summary

    def _drain(self, remaining: list[CheckRequest]) -> dict:
        """Checkpoint still-queued work, one group per subdir: the
        histories + request ids (DRAIN_META) and a resumable
        ``store.checkpoint`` written by the real ladder machinery (a
        zero-budget ``batch_analysis`` trips its deadline at stage 0 and
        persists config + fingerprint + pending set — exactly the state
        ``resume=True`` re-enters)."""
        from jepsen_tpu.parallel import batch

        groups: dict[tuple | None, list[CheckRequest]] = {}
        for r in remaining:
            groups.setdefault(r.group, []).append(r)
        out = {"drained": len(remaining), "checkpoints": []}
        # Timestamped group dirs: a second drain into the same drain_dir
        # (service restarted with the same --drain-dir, drained again)
        # must never overwrite an earlier drain's checkpoint.
        stamp = store.time_str()
        for gi, (group, rs) in enumerate(sorted(
                groups.items(), key=lambda kv: kv[1][0].seq)):
            sub = None
            if self.drain_dir is not None:
                sub = self.drain_dir / f"{stamp}-g{gi:02d}"
                try:
                    sub.mkdir(parents=True, exist_ok=True)
                    meta = {
                        "model": rs[0].model.name,
                        "ids": [r.id for r in rs],
                        "clients": [r.client for r in rs],
                        "histories": [
                            store._jsonable(list(r.history)) for r in rs
                        ],
                    }
                    store._atomic_write(
                        sub / DRAIN_META,
                        json.dumps(meta, indent=1, default=str),
                    )
                    batch.batch_analysis(
                        rs[0].model, [r.history for r in rs],
                        capacity=self.capacity, mesh=self.mesh,
                        checkpoint_dir=sub, deadline=faults.Deadline(0.0),
                        **self._check_opts,
                    )
                    out["checkpoints"].append(str(sub))
                except Exception:  # noqa: BLE001 — drain is best-effort;
                    # the futures below still resolve either way
                    logger.exception("drain checkpoint failed for %s", sub)
                    sub = None
            cause = "service shut down before this request was checked"
            if sub is not None:
                cause += f"; resumable drain checkpoint: {sub}"
            for r in rs:
                with obs.attach(r.ctx):
                    obs.counter("serve.drained", client=r.client)
                metrics.inc("serve.verdicts", verdict="unknown")
                r.resolve({"valid?": "unknown", "cause": cause},
                          status="drained")
        return out


def resume_drained(drain_dir: str | Path, **kw) -> list[dict]:
    """Finish work a shutdown drained: for each group subdir, reload the
    histories from DRAIN_META and re-enter the saved checkpoint
    (``batch_analysis(resume=True)`` — the saved ladder config wins).
    Returns [{"dir", "model", "ids", "results"}] per group."""
    from jepsen_tpu.parallel import batch

    out = []
    root = Path(drain_dir)
    for sub in sorted(p for p in root.iterdir() if p.is_dir()):
        meta_p = sub / DRAIN_META
        if not meta_p.is_file():
            continue
        meta = json.loads(meta_p.read_text())
        model = model_by_name(meta["model"])
        results = batch.batch_analysis(
            model, meta["histories"], checkpoint_dir=sub, resume=True, **kw
        )
        out.append({
            "dir": str(sub), "model": meta["model"],
            "ids": meta.get("ids", []), "results": results,
        })
    return out
