"""The CheckService: scheduled checking over batch_analysis.

Request lifecycle::

    submit() ──admission──▶ queued(class) ──scheduler──▶ running ──demux──▶ done
        │ (queue full)         │ (deadline up)             ▲ (rung joiners)
        ▼                      ▼                           │
    QueueFull(retry_after   expired (unknown)   drained (checkpoint)
      per class)

The scheduler is split along the three decisions it makes
(``jepsen_tpu.serve.sched``):

  * **admission** — requests land in a latency-class queue
    (``interactive`` or ``batch``; ``sched.admission``), each with its
    own backpressure and retry-after EWMA.  Graph-shaped work (elle
    checkers: ``geometry_batchable = False``) is tagged
    non-geometry-batchable and runs on a host side lane, never
    occupying a geometry bucket.
  * **packing** — the interactive tier is served by a speculative
    greedy single-rung fast path (one batched witness-walk launch;
    walk-complete histories resolve there, the rest escalate to the
    batch tier).  The batch tier runs CONTINUOUS batching: one
    ``batch_analysis`` ladder per compatibility group —
    ``(model, padded B, bucketed P, bucketed G)`` via
    ``parallel.batch.bucket_geometry`` — with a ``sched.RungFeeder``
    admitting geometry-compatible queued requests into the RUNNING
    ladder at rung boundaries as resolved members free lane slots
    (streaming batched beam search, arXiv:2010.02164).  Verdicts demux
    the moment the ladder decides them.
  * **placement** — packed batches launch lane-parallel across an
    N-device mesh when configured (``devices=`` / ``mesh=``;
    ``sched.Placement``), with a verdict-parity check against
    single-device execution available at ``verify_placement=True``.

Per-request deadlines bound the QUEUE wait: a request whose
``faults.Deadline`` expires while queued resolves ``unknown``
(``deadline-exceeded``) without consuming batch lanes — expiry degrades
only that request, never the shared batch.  A request already riding a
launch when its budget runs out still gets its verdict (it costs the
batch nothing extra); the result carries ``"deadline-overrun": True``.

Soundness is inherited unchanged from ``batch_analysis``: the service
only arbitrates WHICH histories share a launch (and where it runs),
never how they are decided.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
import uuid
from concurrent.futures import Future, ThreadPoolExecutor
from pathlib import Path
from typing import Mapping, Sequence

from jepsen_tpu import faults, obs, store
from jepsen_tpu import models as m
from jepsen_tpu.obs import metrics
from jepsen_tpu.obs import provenance as _prov
from jepsen_tpu.serve import health as _health
from jepsen_tpu.store import durable as _durable
from jepsen_tpu.serve import slo as _slo
from jepsen_tpu.serve.sched import admission as _sched_adm
from jepsen_tpu.serve.sched import packing as _sched_pack
from jepsen_tpu.serve.sched import placement as _sched_place

logger = logging.getLogger(__name__)

#: models a request may name over HTTP / in a drain file (model classes
#: with argument-free constructors, keyed by their ClassVar name).
MODELS = {
    cls.name: cls
    for cls in (
        m.Register, m.CASRegister, m.Mutex, m.UnorderedQueue,
        m.FIFOQueue, m.MonotonicCounter,
    )
}

#: completed request records kept for GET /check/<id> (oldest evicted).
_KEEP_DONE = 1024

#: drain metadata file (model name + histories + request ids), written
#: next to the store.checkpoint files so resume_drained can rebuild the
#: exact batch_analysis call the scheduler would have made.  Written as
#: a store.durable envelope (checksummed + versioned); pre-envelope
#: drain dirs resume through the registered legacy migration.
DRAIN_META = "drained.json"
KIND_DRAIN = "drain-meta"

_durable.register_kind(KIND_DRAIN, 1)


@_durable.register_migration(KIND_DRAIN, 0)
def _drain_v0_to_v1(payload):
    # v0 was the bare meta dict — same fields, no checksum.
    return dict(payload), 1


def model_by_name(name: str) -> m.Model:
    """A fresh default-constructed model instance for a registry name."""
    try:
        return MODELS[name]()
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; known: {sorted(MODELS)}"
        ) from None


#: Continuous ladders: once ANOTHER geometry group's queued batch-tier
#: request has waited this long, the running ladder stops admitting
#: joiners and drains — the cross-group face of the bounded-wait
#: contract ``parallel.batch._STARVE_SECONDS`` gives members inside a
#: ladder.  Same magnitude on purpose: both bound "how long a steady
#: stream may defer someone else's launch".
_GROUP_STARVE_S = 5.0


class QueueFull(Exception):
    """Admission rejected: ``tier``'s queue is at its depth bound.

    ``retry_after`` estimates (seconds) when a slot should free up —
    THAT CLASS's queue depth over batch width times ITS recent cycle
    wall-clock EWMA (an interactive rejection is quoted in fast-path
    waves, a batch rejection in ladder batches — never each other's).
    The HTTP layer maps this to 429 + a Retry-After header."""

    def __init__(self, depth: int, limit: int, retry_after: float,
                 tier: str = "batch"):
        self.depth = depth
        self.limit = limit
        self.retry_after = retry_after
        self.tier = tier
        super().__init__(
            f"check queue full ({depth}/{limit}, {tier} tier); retry "
            f"after ~{retry_after:.2f}s"
        )


class ServiceClosed(Exception):
    """Submit after shutdown began: the service no longer admits work."""


class ServiceUnavailable(Exception):
    """Admission rejected: the circuit breaker is open (K consecutive
    batch failures).  ``retry_after`` is the breaker cooldown remainder
    — the HTTP layer maps this to 503 + Retry-After, distinct from the
    backpressure 429 (the queue has room; the DEVICE is the problem)."""

    def __init__(self, retry_after: float):
        self.retry_after = retry_after
        super().__init__(
            "check service circuit breaker is open; retry after "
            f"~{retry_after:.1f}s"
        )


class CheckFuture(Future):
    """The verdict future ``submit`` returns; resolves to the same
    knossos-shaped result dict ``batch_analysis`` produces.  ``id`` keys
    ``GET /check/<id>``."""

    id: str


class CheckRequest:
    """One admitted request's record (the HTTP status object)."""

    __slots__ = (
        "id", "seq", "model", "history", "priority", "deadline", "client",
        "group", "future", "status", "result", "t_submit", "t_done",
        "t_start", "t_launch", "t_launch_end",
        "trace_id", "ctx", "tier", "kind", "checker", "escalated", "fp",
        "idem_key",
    )

    def __init__(self, *, seq, model, history, priority, deadline, client,
                 group, trace_id=None, tier="batch", kind="ladder",
                 checker=None, request_id=None, fp=None):
        # ``request_id`` preserves identity across a crash-safe restart
        # (journal replay): GET /check/<id> keeps working after the
        # process that minted the id died.
        self.id = request_id or uuid.uuid4().hex[:12]
        self.fp = fp  # history fingerprint (quarantine/journal identity)
        self.seq = seq
        self.model = model
        self.history = history
        self.priority = priority
        self.deadline = deadline
        self.client = client
        self.group = group
        self.tier = tier          # latency class (fixed once queued)
        self.kind = kind          # "ladder" | "graph"
        self.checker = checker    # graph requests: the Checker instance
        self.escalated = False    # fast path couldn't finish; rode the ladder
        self.idem_key = None      # idempotency key (service sets + settles)
        self.future = CheckFuture()
        self.future.id = self.id
        self.status = "queued"
        self.result: dict | None = None
        self.t_submit = time.monotonic()
        self.t_done: float | None = None
        # Lifecycle stamps for the per-request latency decomposition
        # (the "latency" block on results and GET /check/<id>):
        # picked out of the class queue / joined at a rung boundary,
        # the shared launch began, the shared launch returned.
        self.t_start: float | None = None
        self.t_launch: float | None = None
        self.t_launch_end: float | None = None
        # The request's trace identity + the admission thread's span
        # context, captured HERE so the scheduler thread's demux events
        # re-attach to it (obs.attach) — parent links and the trace id
        # survive the admission -> scheduler -> demux thread hops.
        self.trace_id = trace_id or obs.new_trace_id()
        self.ctx = obs.capture(trace=self.trace_id)

    def describe(self) -> dict:
        """The JSONable status document (GET /check/<id>)."""
        out = {
            "id": self.id,
            "status": self.status,
            "client": self.client,
            "priority": self.priority,
            "class": self.tier,
            "model": self.model.name if self.model is not None else None,
            "trace_id": self.trace_id,
        }
        if self.kind == "graph":
            out["checker"] = type(self.checker).__name__
            out["geometry_batchable"] = False
        if self.escalated:
            out["escalated"] = True
        if self.result is not None:
            out["result"] = self.result
        if self.t_done is not None:
            out["latency_s"] = round(self.t_done - self.t_submit, 6)
            out["latency"] = self.latency()
        return out

    def latency(self) -> dict:
        """The per-request latency decomposition block: class-queue
        wait, packing/placement overhead, shared-launch residence, the
        confirm/demux tail after the launch returned, and the residual
        (``other_s``).  A request that never reached a launch (queue
        expiry, quarantine hit, trivial fast path) attributes its whole
        lifetime to ``queue_s`` — it spent it queued.  The stages sum
        to ``total_s`` exactly — the live counterpart of
        ``obs.critpath.decompose_requests`` over the recorded spans
        (expiry emits a ``serve.admission`` span so the two agree)."""
        done = self.t_done if self.t_done is not None else time.monotonic()
        total = max(0.0, done - self.t_submit)
        pack = launch = confirm = 0.0
        # never picked out of the queue (expired / drained / quarantine):
        # the whole lifetime was queue wait
        queue = total
        if self.t_start is not None:
            queue = min(total, max(0.0, self.t_start - self.t_submit))
            t_launch = self.t_launch if self.t_launch is not None \
                else self.t_start
            pack = max(0.0, min(t_launch, done) - self.t_start)
            if self.t_launch is not None:
                l_end = min(done, self.t_launch_end
                            if self.t_launch_end is not None else done)
                launch = max(0.0, l_end - self.t_launch)
                confirm = max(0.0, done - max(self.t_launch, l_end))
        other = total - (queue + pack + launch + confirm)
        if other < -1e-9:
            launch = max(0.0, launch + other)
            other = 0.0
        return {
            "queue_s": round(queue, 6),
            "pack_s": round(pack, 6),
            "launch_s": round(launch, 6),
            "confirm_s": round(confirm, 6),
            "other_s": round(max(0.0, other), 6),
            "total_s": round(total, 6),
        }

    def resolve(self, result: dict, status: str = "done") -> bool:
        """Resolve the future once; later attempts are no-ops (a zombie
        batch finishing after shutdown already drained its requests must
        not raise InvalidStateError in the scheduler; a client may also
        have cancel()ed the future).  Returns whether THIS call resolved
        it."""
        if self.future.done():
            return False
        self.status = status
        self.t_done = time.monotonic()
        # Every settled result carries the per-request latency
        # decomposition (satellite contract: CheckFuture.result() and
        # GET /check/<id> expose the same block).
        result = {**result, "latency": self.latency()}
        self.result = result
        try:
            self.future.set_result(result)
        except Exception:  # noqa: BLE001 — lost the race; first write won
            return False
        return True


class StreamSession:
    """One open op-stream's serving record (the ``/stream/<id>``
    surface).  The wrapped ``checker.streaming.StreamingChecker`` is NOT
    thread-safe, so every feed/finalize runs under the session's own
    lock — never the service lock, which would stall admission behind a
    device launch."""

    __slots__ = ("id", "client", "checker", "trace_id", "lock",
                 "t_open", "t_last", "t_close", "closed", "evidence_done")

    def __init__(self, *, checker, client: str, trace_id: str | None):
        self.id = checker.stream_id
        self.client = client
        self.checker = checker
        self.trace_id = trace_id or obs.new_trace_id()
        self.lock = threading.Lock()
        self.t_open = time.monotonic()
        self.t_last = self.t_open
        self.t_close: float | None = None
        self.closed = False
        self.evidence_done = False

    def describe(self) -> dict:
        """The status document behind GET /stream/<id>."""
        out = self.checker.status()
        out["client"] = self.client
        out["trace_id"] = self.trace_id
        out["closed?"] = self.closed
        out["age_s"] = round(time.monotonic() - self.t_open, 3)
        return out


class CheckService:
    """A persistent multi-tenant check service over ``batch_analysis``.

    ``capacity``/``devices``/``mesh``/``**check_opts`` configure the ONE
    ladder every batch runs (requests carry no per-request ladder knobs
    — a shared launch needs a shared config; per-request opts are
    priority, deadline, latency class, and client id).  ``max_queue``
    bounds admission (``QueueFull`` beyond it) with an optional
    dedicated ``max_interactive_queue`` allowance so batch backlog
    can't starve the fast lane.  ``max_batch`` bounds lanes per launch.
    ``interactive_max_b`` auto-routes histories with at most that many
    barriers to the interactive tier (0, the library default, keeps
    auto-routing off — callers opt in per request with
    ``class_="interactive"``).  ``continuous`` enables rung-boundary
    admission into running ladders (the default; False restores PR 4's
    window-then-launch batching for A/B).  ``devices=N`` lane-shards
    every launch across the first N jax devices; ``verify_placement``
    re-runs the first sharded batch single-device and reports any
    verdict disagreement.  ``drain_dir`` is where shutdown checkpoints
    still-queued work (None: drained requests resolve unknown without a
    checkpoint).

    The STREAMING lane (``checker.streaming``; HTTP ``POST /stream``)
    runs beside the request queues: up to ``max_streams`` open
    op-streams, each an incremental checker with carried frontier state,
    fed in epochs via ``stream_feed`` and emitting verdict-on-violation
    before the stream ends.  ``stream_dir`` roots per-stream durable
    checkpoints so a SIGKILL'd stream resumes mid-history with identical
    verdicts.  A rejected open raises ``QueueFull(tier="stream")``
    quoted from the stream lane's own session-duration EWMA.

    ``start()`` spawns the scheduler thread (and pre-forks the
    confirmation worker pool, so the first confirmed-unknown request
    doesn't eat pool fork latency); tests drive ``step()`` directly for
    deterministic single-batch control.

    Self-healing (``serve.health``): a non-transiently failing shared
    launch is BISECTED (``poison_bisect``, default on) so only the
    poison member(s) degrade — they land in a TTL'd quarantine registry
    (``quarantine_ttl_s``) keyed by history fingerprint and repeat
    offenders resolve unknown at admission without touching a launch;
    ``breaker_threshold`` consecutive batch failures open a circuit
    breaker (submit raises ``ServiceUnavailable`` → HTTP 503 +
    Retry-After; after ``breaker_cooldown_s`` one probe batch half-opens
    it); ``watchdog_factor`` (None: off) caps each batch's wall clock at
    ``factor ×`` the launch-time EWMA (clamped to
    ``[watchdog_floor_s, watchdog_cap_s]``) and retries a hung launch
    once on reduced placement; ``journal_dir`` (None: off) keeps an
    fsync'd admission journal replayed by ``start()`` after a crash;
    ``health_probe_every_s`` (None: off) probes the mesh's devices and
    shrinks placement to the survivors when one fails."""

    def __init__(
        self,
        *,
        capacity: int | Sequence[int] = (64, 512, 4096),
        mesh=None,
        devices: int | None = None,
        max_queue: int = 256,
        max_interactive_queue: int | None = None,
        max_batch: int = 64,
        batch_window_s: float = 0.002,
        interactive_max_b: int = 0,
        continuous: bool = True,
        verify_placement: bool = False,
        warm_pool: bool = True,
        max_streams: int = 8,
        stream_dir: str | Path | None = None,
        drain_dir: str | Path | None = None,
        evidence_dir: str | Path | None = None,
        journal_dir: str | Path | None = None,
        journal_shared: bool = False,
        idempotency_dir: str | Path | None = None,
        idempotency_shared: bool = False,
        idempotency_ttl_s: float = 3600.0,
        quarantine_dir: str | Path | None = None,
        quarantine_ttl_s: float = 900.0,
        poison_bisect: bool = True,
        breaker_threshold: int = 5,
        breaker_cooldown_s: float = 30.0,
        watchdog_factor: float | None = None,
        watchdog_floor_s: float = 30.0,
        watchdog_cap_s: float = 600.0,
        health_probe_every_s: float | None = None,
        slo_specs=None,
        slo_fast_window_s: float = _slo.FAST_WINDOW_S,
        slo_slow_window_s: float = _slo.SLOW_WINDOW_S,
        **check_opts,
    ):
        for k in ("capacity", "mesh", "deadline", "checkpoint_dir", "resume",
                  "admission"):
            if k in check_opts:
                raise TypeError(
                    f"{k!r} is service-level configuration, not a check opt"
                )
        self.capacity = capacity
        self._placement = _sched_place.Placement(devices=devices, mesh=mesh)
        self.max_queue = int(max_queue)
        self.max_batch = int(max_batch)
        self.batch_window_s = float(batch_window_s)
        self.interactive_max_b = int(interactive_max_b)
        self.continuous = bool(continuous)
        self.verify_placement = bool(verify_placement)
        self.warm_pool = warm_pool
        # -- the streaming lane (checker.streaming) ----------------------
        #: concurrent open op-streams admitted before POST /stream gets a
        #: 429.  Streams hold carried device state for their whole
        #: lifetime, so the lane is bounded separately from the request
        #: queues — and its Retry-After is quoted from STREAM-session
        #: wall clocks, never the batch ladder's cycle EWMA (the PR 6
        #: per-class rule, applied to the new lane).
        self.max_streams = int(max_streams)
        #: per-stream checkpoint root (None: streams are memory-only and
        #: a SIGKILL loses them; with a dir, POST /stream resume=true
        #: reconstructs a killed stream mid-history).
        self.stream_dir = Path(stream_dir) if stream_dir is not None else None
        self._streams: dict[str, StreamSession] = {}  # guarded-by: _lock [rw]
        #: stream-session duration EWMA (seconds), folded on every close
        #: — the stream lane's own retry-after basis.  Seeded at a
        #: plausible short-session wall so the first rejection quotes
        #: something sane rather than a batch-tier number.
        self._stream_ewma_s = 5.0                # guarded-by: _lock
        self.drain_dir = Path(drain_dir) if drain_dir is not None else None
        #: durable evidence-bundle directory (None: in-memory ring only).
        #: Every settled request's bundle is retrievable via
        #: ``get_evidence(id)`` / GET /evidence/<id> either way.
        self.evidence_dir = (Path(evidence_dir)
                             if evidence_dir is not None else None)
        self._evidence: dict[str, dict] = {}     # guarded-by: _lock [rw]
        # Warm the host-fingerprint cache off the request path: the
        # first evidence bundle would otherwise eat a cold ~10ms
        # import inside a request's measured lifetime.
        _prov.machine_fingerprint()
        self._check_opts = dict(check_opts)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # AdmissionQueues is caller-serialized: every self._adm call in
        # this class runs under self._lock / self._cond (its fields
        # carry the caller-guarded annotation in admission.py).
        self._adm = _sched_adm.AdmissionQueues(
            self.max_queue, max_interactive=max_interactive_queue
        )
        # admission slots held while packing off-lock
        self._reserved = 0                       # guarded-by: _lock
        self._requests: dict[str, CheckRequest] = {}  # guarded-by: _lock [rw]
        self._seq = itertools.count()  # thread-safe under the GIL (next())
        self._closed = False                     # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._fp_thread: threading.Thread | None = None
        self._graph_pool: ThreadPoolExecutor | None = None  # guarded-by: _lock [rw]
        # requests on the device
        self._inflight: list[CheckRequest] = []  # guarded-by: _lock [rw]
        self._t_start = time.monotonic()
        self._parity_checked = False             # guarded-by: _lock [rw]
        self._totals = {                         # guarded-by: _lock [rw]
            "submitted": 0, "completed": 0, "rejected": 0, "expired": 0,
            "drained": 0, "batches": 0, "batch_errors": 0,
            "fastpath_resolved": 0, "escalated": 0, "graphs": 0,
            "graph_batches": 0,
            "quarantined": 0, "poison_isolated": 0, "bisect_launches": 0,
            "watchdog_trips": 0, "journal_replayed": 0,
            "devices_replaced": 0, "breaker_rejected": 0, "drain_errors": 0,
            "idempotent_hits": 0,
            "streams_opened": 0, "streams_closed": 0,
            "streams_rejected": 0, "streams_resumed": 0,
        }
        # -- the self-healing layer (serve.health) ----------------------
        #: with ``quarantine_dir``, the registry is the FLEET-wide
        #: durable store (serve.health.SharedQuarantine): a history
        #: poisoned by any replica sharing the dir is refused at
        #: admission here on its first local offense.
        self.quarantine = (
            _health.SharedQuarantine(ttl_s=quarantine_ttl_s,
                                     dir=quarantine_dir)
            if quarantine_dir is not None
            else _health.Quarantine(ttl_s=quarantine_ttl_s)
        )
        self.poison_bisect = bool(poison_bisect)
        self.breaker = _health.CircuitBreaker(
            threshold=breaker_threshold, cooldown_s=breaker_cooldown_s
        )
        self._watchdog = (
            _health.LaunchWatchdog(
                factor=watchdog_factor, floor_s=watchdog_floor_s,
                cap_s=watchdog_cap_s,
            )
            if watchdog_factor else None
        )
        self.journal = (
            _health.AdmissionJournal(journal_dir, shared=journal_shared)
            if journal_dir is not None else None
        )
        #: the idempotent-resubmission registry: in-memory always (a
        #: duplicate within one process dedups regardless), journaled
        #: when ``idempotency_dir`` is set so it survives SIGKILL, and
        #: cross-process atomic when ``idempotency_shared`` marks the
        #: dir as fleet-shared (per-key advisory file locks).
        self.idempotency = _health.IdempotencyMap(
            idempotency_dir, ttl_s=idempotency_ttl_s,
            shared=idempotency_shared,
        )
        #: keys with a submit currently mid-_admit (claim taken, request
        #: not yet in _requests): count per key — the live signal that
        #: stops a concurrent duplicate from treating the claim as
        #: stale, however long the admission's journal fsync stalls.
        self._idem_admitting: dict[str, int] = {}  # guarded-by: _lock [rw]
        self.health_probe_every_s = health_probe_every_s
        self._t_probe = 0.0                      # guarded-by: _lock [rw]
        # -- the live SLO burn-rate engine (serve.slo) -------------------
        #: ``slo_specs``: a spec list, an --slo-file path, or None (the
        #: built-in defaults).  Evaluated from the scheduler loop (at
        #: most once per _SLO_EVAL_S) and from every step(); GET /alerts
        #: and the serve_slo_burn_rate{slo=,window=} gauges read it.
        self.slo = _slo.SloEngine(
            slo_specs, fast_window_s=slo_fast_window_s,
            slow_window_s=slo_slow_window_s,
        )
        self._t_slo = 0.0                        # guarded-by: _lock [rw]
        self._recovered = False  # start()-serialized (pre-thread)
        # per-batch occupancy accumulator
        self._occ_sum = 0.0                      # guarded-by: _lock [rw]
        #: continuous-occupancy accumulators: live lane-seconds over
        #: launched lane-slot-seconds across every rung — the
        #: device-TIME-utilization aggregate the ≥ 0.80 gate reads
        #: (each rung weighted by its wall clock; see RungFeeder).
        self._rung_lane_sum = 0.0                # guarded-by: _lock [rw]
        self._rung_slot_sum = 0.0                # guarded-by: _lock [rw]
        self._rungs = 0                          # guarded-by: _lock [rw]

    @property
    def mesh(self):
        """The placement mesh (None: single-device)."""
        return self._placement.mesh

    @property
    def _batch_ewma_s(self) -> float:
        # Back-compat alias (stats key batch_ewma_s): the batch tier's
        # cycle EWMA now lives in the admission queues, per class.
        with self._lock:
            return self._adm.ewma_s["batch"]

    # ------------------------------------------------------------------
    # Admission
    # ------------------------------------------------------------------

    def submit(
        self,
        history: Sequence[Mapping],
        *,
        model: m.Model | None = None,
        priority: int = 0,
        deadline=None,
        client: str = "anon",
        trace_id: str | None = None,
        class_: str | None = None,
        checker=None,
        idempotency_key: str | None = None,
    ) -> CheckFuture:
        """Admit one history; returns a future resolving to its verdict.

        ``model`` defaults to ``CASRegister()``.  ``priority``: higher
        runs first (FIFO within a priority).  ``deadline``: seconds (or
        a ``faults.Deadline``) bounding the queue wait.  ``class_``:
        the latency class — ``"interactive"`` (greedy fast path, p50 in
        single-launch units) or ``"batch"``; None auto-routes small
        histories when ``interactive_max_b`` is configured, else batch.
        ``checker``: a graph checker instance (elle ``CycleChecker`` &
        co.) instead of a ladder model — tagged non-geometry-batchable
        at admission and run on the host side lane, never occupying a
        geometry bucket.  ``trace_id`` joins this request to a caller's
        existing trace (HTTP clients pass it in the POST body); None
        mints a fresh id.  ``idempotency_key``: a caller-chosen token
        making resubmission safe — a duplicate submit (a retry after a
        timeout / 429 / breaker 503, even across a SIGKILL restart when
        ``idempotency_dir`` + ``journal_dir`` are set) attaches to the
        in-flight request's future or returns the already-settled
        result, under the ORIGINAL request id, instead of running the
        check again.  Raises ``QueueFull`` (backpressure, with a
        per-class retry-after) or ``ServiceClosed``."""
        # Coerce every argument BEFORE reserving a slot: a reservation
        # leaked past a bad-argument raise would shrink admission
        # capacity forever.
        if checker is None:
            model = model if model is not None else m.CASRegister()
        deadline = faults.Deadline.coerce(deadline)
        history = list(history)
        priority = int(priority)
        client = str(client)
        trace_id = str(trace_id) if trace_id is not None else None
        if class_ is not None and class_ not in _sched_adm.CLASSES:
            raise ValueError(
                f"unknown latency class {class_!r}; expected one of "
                f"{_sched_adm.CLASSES}"
            )
        idem_key = (str(idempotency_key)
                    if idempotency_key is not None else None)
        idem_req_id = None
        idem_fp = None
        if idem_key is None:
            return self._admit(
                model=model, history=history, priority=priority,
                deadline=deadline, client=client, trace_id=trace_id,
                class_=class_, checker=checker,
            )
        # Claim-before-admit: the claim is atomic in the map, so two
        # racing duplicates can't both reach a launch.  The claim holds
        # the request id we WOULD mint; if this submit fails admission
        # (queue full, breaker, bad input), the claim is released so
        # the client's retry runs fresh.  The history fingerprint rides
        # the entry so key REUSE across different histories is rejected
        # instead of handing this caller someone else's verdict.
        idem_req_id = uuid.uuid4().hex[:12]
        if checker is None:
            idem_fp = _health.history_fingerprint(history)
        with self._lock:
            self._idem_admitting[idem_key] = \
                self._idem_admitting.get(idem_key, 0) + 1
        try:
            hit = self._idem_claim(idem_key, idem_req_id, client, idem_fp)
            if hit is not None:
                return hit
            try:
                return self._admit(
                    model=model, history=history, priority=priority,
                    deadline=deadline, client=client, trace_id=trace_id,
                    class_=class_, checker=checker, idem_key=idem_key,
                    request_id=idem_req_id, fp_hint=idem_fp,
                )
            except BaseException as e:
                # A simulated crash (faults.CrashPoint) must leave the
                # SIGKILL disk state — the key stays bound, exactly as
                # a real kill would leave it; every OTHER failure
                # releases the claim so the client's retry runs fresh.
                if not isinstance(e, faults.CrashPoint):
                    self.idempotency.release(idem_key, idem_req_id)
                raise
        finally:
            with self._lock:
                n = self._idem_admitting.get(idem_key, 0) - 1
                if n <= 0:
                    self._idem_admitting.pop(idem_key, None)
                else:
                    self._idem_admitting[idem_key] = n

    #: safety cap on how long a duplicate waits for a same-key submit
    #: that is mid-admission.  The live "is someone admitting this key"
    #: signal is the ``_idem_admitting`` counter (exact, no clock); the
    #: cap only bounds the wait against a pathologically wedged
    #: admission so the duplicate eventually treats the entry as stale.
    _IDEM_ADMIT_WAIT_CAP_S = 60.0

    def _idem_claim(self, key: str, new_req_id: str, client: str,
                    fp: str | None) -> CheckFuture | None:
        """The duplicate-submit check: None means the claim is OURS (a
        fresh request proceeds under ``new_req_id``); a future means
        this key is already live — the in-flight original's future, or
        a fresh future pre-resolved with the settled result (original
        request id either way).  Raises ValueError when the key is
        bound to a DIFFERENT history's fingerprint — key reuse must
        never hand this caller someone else's verdict."""
        t0 = time.monotonic()
        while True:
            entry = self.idempotency.claim(key, new_req_id, fp=fp)
            if entry is None:
                return None
            if (fp is not None and entry.get("fp")
                    and entry["fp"] != fp):
                raise ValueError(
                    f"idempotency_key {key!r} is already bound to a "
                    "submission with a DIFFERENT history; reusing a key "
                    "across histories would return the wrong verdict — "
                    "pick a fresh key per logical request"
                )
            if entry.get("result") is not None:
                fut = CheckFuture()
                fut.id = str(entry["req_id"])
                fut.set_result(entry["result"])
                self._count_idem_hit(client)
                return fut
            with self._lock:
                req = self._requests.get(str(entry["req_id"]))
                admitting = self._idem_admitting.get(key, 0)
            if req is not None:
                self._count_idem_hit(client)
                return req.future
            if (admitting > 1
                    and time.monotonic() - t0 < self._IDEM_ADMIT_WAIT_CAP_S):
                # Claimed but not yet registered, and another submit of
                # THIS key (the original) is verifiably mid-_admit on a
                # live thread (the counter includes us, so > 1 means
                # someone else): wait for it to land in _requests or
                # release — rebinding now would run the check twice.
                # The counter, not a clock: a stalled journal fsync in
                # the original's _admit cannot fake staleness.
                time.sleep(0.005)
                continue
            # Genuinely stale: the bound request evaporated unsettled
            # (evicted, or a crash without the journal).  CAS the key
            # onto our fresh request; a lost race means someone else
            # just did — loop and read their entry.
            if self.idempotency.rebind(key, entry["req_id"], new_req_id):
                return None

    def _count_idem_hit(self, client: str) -> None:
        with self._lock:
            self._totals["idempotent_hits"] += 1
        # mirrors to /metrics as jepsen_tpu_serve_idempotent_hits_total
        obs.counter("serve.idempotent_hits", client=client)

    def _idem_watch(self, req: CheckRequest, key: str | None) -> None:
        """Wire a request to settle its idempotency entry: a DONE
        verdict is recorded against the key (duplicates for the next
        TTL window get it without a run); any other terminal status —
        expired, drained, quarantined, batch error — RELEASES the key
        instead: the check never (usefully) ran, so a retry should run
        it, and none of those paths can double-run anything."""
        if key is None:
            return
        req.idem_key = key

        def _done(f):
            try:
                if not f.cancelled() and req.status == "done":
                    # req_id-CAS'd: if a fleet router rebound this key
                    # to another replica's request after fencing us,
                    # our late verdict is discarded, not published
                    self.idempotency.settle(key, req.result,
                                            req_id=req.id)
                else:
                    self.idempotency.release(key, req.id)
            except Exception:  # noqa: BLE001 — bookkeeping must not
                # break the resolve path
                logger.exception("idempotency settle failed for key %r",
                                 key)

        req.future.add_done_callback(_done)

    def _admit(
        self,
        *,
        model,
        history,
        priority,
        deadline,
        client,
        trace_id,
        class_,
        checker,
        idem_key=None,
        request_id=None,
        fp_hint=None,
    ) -> CheckFuture:
        """The admission body behind ``submit`` (arguments already
        coerced, idempotency claim already held by the caller;
        ``fp_hint`` is the history fingerprint the claim path already
        computed, so it isn't hashed twice)."""
        if not self.breaker.allow():
            # The breaker gates ADMISSION, not the queue: K consecutive
            # batch failures mean the device isn't serving — queueing
            # more work would only grow the blast radius.  503-shaped,
            # with the cooldown remainder as the retry hint.
            with self._lock:
                self._totals["breaker_rejected"] += 1
            obs.counter("serve.breaker_rejected", client=client)
            raise ServiceUnavailable(self.breaker.retry_after())
        fp = None
        if checker is None:
            fp = fp_hint or _health.history_fingerprint(history)
            q = self.quarantine.check(fp)
            if q is not None:
                # Repeat offender: skip straight to rejection — this
                # fingerprint already poisoned a shared launch, and the
                # registry entry is still live.  Resolved as an
                # attributable unknown (never queued, never packed), so
                # the caller learns WHY without costing anyone else a
                # bisection.
                req = CheckRequest(
                    seq=next(self._seq), model=model, history=history,
                    priority=priority, deadline=deadline, client=client,
                    group=None, trace_id=trace_id,
                    tier=class_ or "batch", fp=fp, request_id=request_id,
                )
                self._idem_watch(req, idem_key)
                with self._lock:
                    if self._closed:
                        raise ServiceClosed(
                            "check service is shutting down")
                    self._totals["submitted"] += 1
                    self._totals["completed"] += 1
                    self._totals["quarantined"] += 1
                    self._remember(req)
                with obs.attach(req.ctx):
                    obs.counter("serve.submitted", client=client,
                                tier=req.tier)
                    obs.counter("serve.quarantine_hit", client=client)
                    obs.counter("serve.completed")
                metrics.inc("serve.verdicts", verdict="unknown")
                qres = {
                    "valid?": "unknown",
                    "quarantined": True,
                    "cause": (
                        "quarantined history (repeat poison "
                        f"offender): {q['cause']}"
                    ),
                }
                self._bundle(req, qres, [{
                    "event": "fault.quarantine-hit",
                    "error": str(q["cause"]),
                }])
                req.resolve(qres, status="quarantined")
                dt = time.monotonic() - req.t_submit
                metrics.observe("serve.request_latency_seconds", dt)
                return req.future
        #: the tier used for the pre-pack depth check; auto-routing can
        #: only move a request INTO the interactive tier after packing,
        #: and only when that tier has room (checked again below).
        pre_tier = class_ or "batch"
        with self._lock:
            if self._closed:
                raise ServiceClosed("check service is shutting down")
            if self._adm.over_limit(pre_tier, self._reserved):
                self._totals["rejected"] += 1
                obs.counter("serve.rejected", client=client, tier=pre_tier)
                metrics.inc("serve.rejections", tier=pre_tier)
                if (pre_tier == "interactive"
                        and self._adm.max_interactive is not None
                        and (self._adm.depth("interactive")
                             >= self._adm.max_interactive)):
                    # The dedicated interactive bound is what tripped:
                    # quote ITS depth/limit, not the shared queue's
                    # (a "full at 10/256" rejection reads as a bug).
                    depth, limit = (self._adm.depth("interactive"),
                                    self._adm.max_interactive)
                else:
                    depth, limit = (self._adm.depth() + self._reserved,
                                    self.max_queue)
                raise QueueFull(
                    depth, limit,
                    self._adm.retry_after(pre_tier, self.max_batch),
                    tier=pre_tier,
                )
            # Hold the slot while packing off-lock: two racing submitters
            # must not both pass the depth check into a full queue.
            self._reserved += 1
        try:
            if checker is not None:
                if _sched_adm.geometry_batchable(checker):
                    # The admission tag is the routing contract: the
                    # side lane exists for work that CANNOT share
                    # padded-kernel geometry (elle's CycleChecker
                    # family sets geometry_batchable = False).  A
                    # checker that doesn't opt out is asking for
                    # geometry batching the service can only do from
                    # model= + history — reject loudly instead of
                    # silently serving it unbatched.
                    raise ValueError(
                        f"{type(checker).__name__} does not set "
                        "geometry_batchable = False; checker-based "
                        "submissions ride the host side lane, so "
                        "geometry-batchable work must be submitted as "
                        "model= + history for the service to pack it"
                    )
                # Graph work: no kernel geometry — grouped by the
                # checker's COLUMN-SHAPE key instead, so compatible
                # queued requests share one batched inference pass
                # (sched.graph_batch_key; the graph bucket_geometry).
                group: tuple | None = _sched_adm.graph_batch_key(checker)
                pack = None
                kind = "graph"
                tier = class_ or "batch"
            else:
                group, pack = self._group_of(model, history)
                kind = "ladder"
                if pack is None:
                    # Untensorizable: no geometry, no fast path — the
                    # ladder's CPU fallback decides it on the batch tier
                    # regardless of the requested class.
                    tier = "batch"
                else:
                    tier = _sched_adm.classify(
                        class_, B=int(pack["B"]),
                        interactive_max_b=self.interactive_max_b,
                    )
            req = CheckRequest(
                seq=next(self._seq), model=model, history=history,
                priority=priority, deadline=deadline, client=client,
                group=group, trace_id=trace_id, tier=tier, kind=kind,
                checker=checker, fp=fp, request_id=request_id,
            )
            self._idem_watch(req, idem_key)
            if (self.journal is not None and kind == "ladder"
                    and group is not None):
                # Journal BEFORE the queue push: a crash between the
                # two replays a request nobody queued (harmless — it
                # just runs) instead of losing one somebody admitted.
                # The idempotency key rides along so a post-crash
                # duplicate still binds to the replayed request.
                self.journal.record(
                    req_id=req.id, seq=req.seq, model_name=model.name,
                    history=req.history, priority=req.priority,
                    client=req.client, tier=req.tier,
                    trace_id=req.trace_id,
                    deadline_s=(deadline.remaining()
                                if deadline is not None else None),
                    idempotency_key=idem_key,
                )
        except BaseException:
            with self._lock:
                self._reserved -= 1
            raise
        with self._cond:
            self._reserved -= 1
            if self._closed and group is not None:
                # shutdown() began while we were packing off-lock: its
                # drain already snapshotted the queue, so appending now
                # would strand this request unresolved forever.  The
                # just-written journal entry goes too — a restart must
                # not replay a request this client was told was
                # rejected.
                self._totals["rejected"] += 1
                obs.counter("serve.rejected", client=client, tier=tier)
                self._journal_done(req)
                raise ServiceClosed("check service is shutting down")
            self._totals["submitted"] += 1
            self._remember(req)
            if group is None:
                self._totals["completed"] += 1
            else:
                if (class_ is None and req.tier == "interactive"
                        and self._adm.max_interactive is not None
                        and (self._adm.depth("interactive")
                             >= self._adm.max_interactive)):
                    # Auto-routing must not bypass the dedicated
                    # interactive bound: a full fast lane demotes
                    # opportunistic traffic to the batch tier instead of
                    # overfilling it (explicit class_="interactive" was
                    # depth-checked at admission and rejected there).
                    req.tier = "batch"
                self._adm.push(req)
                if kind == "graph":
                    self._sync_graph_depth()
                self._cond.notify_all()
            with obs.attach(req.ctx):
                obs.counter("serve.submitted", client=client, tier=tier)
                self._gauge_queue_depth()
        if group is None:
            # Trivial fast path: no barriers -> valid, no lanes spent.
            # Resolved OUTSIDE the lock: set_result runs done-callbacks
            # synchronously, and a callback re-entering the service
            # (submit/stats) must not deadlock on a held lock.
            tres = {"valid?": True}
            self._bundle(req, tres,
                         [{"event": "serve.trivial", "barriers": 0}])
            req.resolve(tres)
            with obs.attach(req.ctx):
                obs.counter("serve.completed")
            metrics.inc("serve.verdicts", verdict="true")
            dt = time.monotonic() - req.t_submit
            metrics.observe("serve.request_latency_seconds", dt)
            metrics.observe("serve.class_request_latency_seconds", dt,
                            tier=tier)
        return req.future

    def _group_of(self, model: m.Model, history) -> tuple[tuple | None, dict | None]:
        """The batch-compatibility key ``(model, padded geometry)`` plus
        the pack it was computed from.  A None group means trivially
        valid (no device work); untensorizable histories get their own
        group so ``batch_analysis`` decides them the same way it would
        for a direct caller (CPU fallback or unknown).

        The pack is returned so admission can classify by barrier
        count; it is then dropped — the interactive greedy walk runs on
        the raw history, and ``batch_analysis`` re-packs at launch (its
        checkpoint fingerprint and confirmation paths key on the raw
        histories)."""
        from jepsen_tpu.ops import wgl
        from jepsen_tpu.parallel import batch

        try:
            p = wgl.pack(model, list(history))
        except wgl.NotTensorizable:
            return (model, "untensorizable"), None
        if p["B"] == 0:
            return None, p
        return (model, *batch.bucket_geometry(p["B"], p["P"], p["G"])), p

    def _retry_after(self) -> float:
        """Back-compat backpressure hint (batch tier)."""
        with self._lock:
            return self._adm.retry_after("batch", self.max_batch)

    # holds: _lock
    def _gauge_queue_depth(self) -> None:
        """Queue-depth gauges: the shared total plus one series per
        latency class (``serve.queue_depth.<tier>``) — the per-class
        Perfetto counter lanes and the live registry read these."""
        obs.gauge("serve.queue_depth", self._adm.depth())
        for tier in _sched_adm.CLASSES:
            obs.gauge("serve.queue_depth." + tier, self._adm.depth(tier))

    # holds: _lock
    def _remember(self, req: CheckRequest) -> None:
        self._requests[req.id] = req
        if len(self._requests) > self.max_queue + _KEEP_DONE:
            done = [
                i for i, r in self._requests.items()
                if r.status not in ("queued", "running")
            ]
            for i in done[: len(done) - _KEEP_DONE]:
                del self._requests[i]

    # ------------------------------------------------------------------
    # Scheduling
    # ------------------------------------------------------------------

    def start(self) -> "CheckService":
        """Spawn the scheduler thread; idempotent.  Also turns on the
        live metrics registry (obs.metrics) — a started service is a
        serving process, and /metrics should reflect it."""
        if self._thread is not None:
            return self
        metrics.enable_mirror()
        # Reclaim *.tmp orphans crashed writers left in the durable
        # dirs this service owns (store.durable.sweep_tmp counts them
        # as durable.tmp_swept).  The journal/idempotency dirs are
        # exclusively ours — a starting service means their previous
        # writer is dead, so no age gate; the drain dir may be shared
        # with a concurrently-draining sibling, so its sweep keeps the
        # age gate.
        if self.journal is not None:
            _durable.sweep_tmp(
                self.journal.dir,
                min_age_s=60.0 if self.journal.shared else 0.0,
                what="serve.journal")
        if self.idempotency.dir is not None:
            # a SHARED dir has live sibling writers — keep the age gate
            # so their in-flight tmp files survive this start
            _durable.sweep_tmp(
                self.idempotency.dir,
                min_age_s=60.0 if self.idempotency.shared else 0.0,
                what="serve.idempotency")
        qdir = getattr(self.quarantine, "dir", None)
        if qdir is not None:
            _durable.sweep_tmp(qdir, what="serve.quarantine")
        if self.drain_dir is not None and self.drain_dir.is_dir():
            _durable.sweep_tmp(self.drain_dir, what="serve.drain")
            for sub in self.drain_dir.iterdir():
                if sub.is_dir():
                    _durable.sweep_tmp(sub, what="serve.drain")
        self.recover()
        if self.warm_pool and self._check_opts.get(
                "confirm_refutations", True) is True:
            # Satellite contract: pre-fork the confirmation workers at
            # service start so the first confirmed-unknown request
            # doesn't eat the pool's spawn+init latency (~seconds).
            from jepsen_tpu.parallel import batch

            batch.warm_confirm_pool(self._check_opts.get("confirm_workers"))
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="check-service", daemon=True
        )
        self._thread.start()
        # The interactive tier gets its OWN service thread: a greedy
        # fast-path wave is a ~ms launch, and riding the scheduler loop
        # would bound its latency by the batch tier's rung wall clock.
        # jax dispatch is thread-safe; the wave's tiny launch interleaves
        # with the ladder's on the device (one device serves both tiers).
        self._fp_thread = threading.Thread(
            target=self._fastpath_loop, name="check-service-fastpath",
            daemon=True,
        )
        self._fp_thread.start()
        return self

    def recover(self) -> int:
        """Replay the admission journal (crash-safe restart): every
        admitted-but-unfinished request a previous process journaled is
        re-admitted here, KEEPING its request id — a client polling
        ``GET /check/<id>`` across the crash still finds its request.
        Called by ``start()``; step()-driven tests call it directly.
        Idempotent per service instance.  Returns requests replayed."""
        if self._recovered:
            return 0
        self._recovered = True
        # The idempotency map replays FIRST — and regardless of whether
        # an admission journal exists: a service configured with only
        # idempotency_dir still owes duplicates their settled results
        # across a restart.  With a journal, the map's entries point at
        # the request ids about to be resurrected, so a duplicate
        # arriving mid-recovery binds to the replayed request, not a
        # fresh run.
        self.idempotency.replay()
        if self.journal is None:
            return 0
        n = 0
        for e in self.journal.replay():
            try:
                model = model_by_name(str(e["model"]))
                history = list(e["history"])
                group, _pack = self._group_of(model, history)
            except Exception:  # noqa: BLE001 — one bad entry must not
                # block the rest of the queue from recovering
                logger.exception("journal replay failed for entry %s",
                                 e.get("id"))
                continue
            tier = e.get("class") or "batch"
            if tier not in _sched_adm.CLASSES:
                tier = "batch"
            req = CheckRequest(
                seq=next(self._seq), model=model, history=history,
                priority=int(e.get("priority") or 0),
                deadline=faults.Deadline.coerce(e.get("deadline_s")),
                client=str(e.get("client") or "anon"), group=group,
                trace_id=e.get("trace_id"), tier=tier,
                request_id=str(e.get("id") or "") or None,
                fp=_health.history_fingerprint(history),
            )
            idem_key = e.get("idempotency_key")
            if idem_key:
                # Re-bind the key to the resurrected request: the idem
                # journal normally already points at this id, but if
                # ITS entry was lost/corrupt the admission journal is
                # the backup copy of the binding.
                existing = self.idempotency.claim(idem_key, req.id,
                                                  fp=req.fp)
                if (existing is not None and existing.get("result") is None
                        and existing["req_id"] != req.id):
                    self.idempotency.rebind(idem_key, existing["req_id"],
                                            req.id)
                self._idem_watch(req, str(idem_key))
            with self._cond:
                self._totals["submitted"] += 1
                self._totals["journal_replayed"] += 1
                self._remember(req)
                if group is None:
                    self._totals["completed"] += 1
                else:
                    self._adm.push(req)
                    self._cond.notify_all()
            with obs.attach(req.ctx):
                obs.counter("serve.journal_replayed", client=req.client)
            if group is None:
                tres = {"valid?": True}
                self._bundle(req, tres,
                             [{"event": "serve.trivial", "barriers": 0}])
                req.resolve(tres)
                self.journal.resolve(req.id)
            n += 1
        if n:
            logger.info("admission journal replayed %d request(s)", n)
        return n

    def _journal_done(self, r: CheckRequest) -> None:
        """Drop a settled request's journal entry (terminal statuses
        only reach here via resolve() call sites)."""
        if self.journal is not None and r.kind == "ladder":
            self.journal.resolve(r.id)

    #: minimum seconds between SLO evaluations (loop ticks AND step():
    #: a busy scheduler cycling at ms scale must not pay a full
    #: evaluation per cycle; within one step, members settle BEFORE the
    #: evaluation, so the first evaluation after a batch already sees
    #: its latencies — step-driven tests stay deterministic).
    _SLO_EVAL_S = 1.0

    def _maybe_eval_slo(self) -> None:
        """Throttled SLO evaluation for the scheduler loop: the burn
        windows are minutes wide, so sub-second sampling buys nothing —
        but an IDLE service must keep evaluating (a breach's burn rate
        decays back under threshold only if samples keep arriving)."""
        now = time.monotonic()
        with self._lock:
            if now - self._t_slo < self._SLO_EVAL_S:
                return
            self._t_slo = now
        try:
            self.slo.evaluate()
        except Exception:  # noqa: BLE001 — a broken spec must not take
            logger.exception("SLO evaluation failed")  # down the scheduler

    def _loop(self) -> None:
        while True:
            with self._cond:
                if self._adm.depth() == 0 and not self._stop.is_set():
                    # bounded wait, then fall out to the SLO tick below
                    # (an idle service still samples its objectives)
                    self._cond.wait(timeout=0.2)
                stopping = self._stop.is_set()
                idle = self._adm.depth() == 0
            if stopping:
                return
            self._maybe_eval_slo()
            if idle:
                continue
            if self.batch_window_s > 0:
                # The pile-in window: let concurrent submitters coalesce
                # into this batch instead of each paying its own launch.
                # Rung-boundary admission makes this window nearly moot
                # (latecomers join the running ladder), so it stays tiny.
                time.sleep(self.batch_window_s)
            try:
                self.step()
            except Exception:  # noqa: BLE001 — the scheduler must survive
                logger.exception("check-service batch step failed")

    def _fastpath_loop(self) -> None:
        while True:
            with self._cond:
                # Wait for LADDER-kind interactive work specifically: a
                # graph request parked in the interactive queue belongs
                # to the side lane (step()/rung boundaries), and a bare
                # depth check would busy-spin on it — the wave below
                # takes only ladder requests and would never drain it.
                while (not self._stop.is_set() and not any(
                        r.kind == "ladder"
                        for r in self._adm.queues["interactive"])):
                    self._cond.wait(timeout=0.2)
                if self._stop.is_set():
                    return
            # No coalesce window and no yield-to-the-ladder: the host
            # greedy walk batches nothing (per-request host work) and
            # contends with nothing (no kernel launch), so the lowest-
            # latency move is always to serve the queue immediately.
            try:
                self._interactive_wave()
            except Exception:  # noqa: BLE001 — the fast lane must survive
                logger.exception("check-service fast-path wave failed")

    def step(self) -> int:
        """Process one scheduler cycle synchronously: expire overdue
        queued requests, dispatch graph side-lane work, serve one
        interactive fast-path wave, then run one (continuous) batch-tier
        ladder.  Returns requests handled.  The scheduler loop calls
        this; tests call it directly for deterministic control."""
        self._probe_placement()
        with self._cond:
            expired = self._adm.take_expired()
            self._totals["expired"] += len(expired)
        self._resolve_expired(expired)
        handled = len(expired)
        handled += self._step_graphs()
        handled += self._interactive_wave()
        handled += self._step_batch()
        self._maybe_eval_slo()
        return handled

    def _resolve_expired(self, expired: list[CheckRequest]) -> None:
        # Expired futures resolve outside the lock (done-callbacks may
        # re-enter the service); the shared batch is untouched.
        for r in expired:
            with obs.attach(r.ctx):
                obs.counter("serve.expired", client=r.client, tier=r.tier)
            metrics.inc("serve.verdicts", verdict="unknown")
            xres = {
                "valid?": "unknown",
                "cause": (
                    "deadline-exceeded: request budget expired while "
                    "queued (the shared batch is unaffected)"
                ),
            }
            self._bundle(r, xres,
                         [{"event": "fault.deadline", "at": "queue"}])
            r.resolve(xres, status="expired")
            with obs.attach(r.ctx):
                # the whole lifetime WAS queue wait — record the
                # admission span over the SAME interval the live
                # latency block uses (t_done - t_submit, which includes
                # the evidence-bundle build) so the offline
                # decomposition (critpath.decompose_requests) and the
                # live block agree that queue_s == total_s
                obs.span_event(
                    "serve.admission", r.t_done - r.t_submit,
                    client=r.client, tier=r.tier, expired=True,
                )
                # the end-to-end span every settled request gets — an
                # expired lifecycle must decompose offline too
                obs.span_event(
                    "serve.request", r.t_done - r.t_submit,
                    client=r.client, verdict="unknown", tier=r.tier,
                    expired=True,
                )
            self._journal_done(r)

    # -- graph side lane ---------------------------------------------------

    def _step_graphs(self) -> int:
        """Dispatch queued non-geometry-batchable (graph) requests to
        the host side lane, BATCHED by column-shape key: requests whose
        checkers share a ``graph_batch_key`` are served by one
        ``check_batch`` call (one vectorized inference pass + one
        host-SCC sweep), demuxed per request afterwards.  Each group is
        one task on a small thread pool when the scheduler thread runs
        (graph checks must not stall ladder work), inline when tests
        drive ``step()`` directly (determinism)."""
        with self._cond:
            gq = [
                r for q in self._adm.queues.values() for r in q
                if r.kind == "graph"
            ]
            self._adm.remove(gq)
            t_pick = time.monotonic()
            for r in gq:
                r.status = "running"
                r.t_start = t_pick
            self._sync_graph_depth()
        groups: dict[tuple, list[CheckRequest]] = {}
        for r in gq:
            groups.setdefault(r.group, []).append(r)
        for rs in groups.values():
            pool = None
            if self._thread is not None:
                # Lazy pool creation is racy without the lock: the
                # scheduler thread and a continuous ladder's rung poll
                # (running on the watchdog worker thread) both dispatch
                # graphs, and two creators would leak a pool.  A CLOSED
                # service must not mint a fresh pool either — shutdown
                # already swapped the old one out, and a pool created
                # after that swap would never be joined.
                with self._lock:
                    if not self._closed:
                        if self._graph_pool is None:
                            self._graph_pool = ThreadPoolExecutor(
                                max_workers=2,
                                thread_name_prefix="check-graph",
                            )
                        pool = self._graph_pool
            if pool is not None:
                try:
                    pool.submit(self._run_graph_batch, rs)
                    continue
                except RuntimeError:
                    # the pool we grabbed shut down between the locked
                    # read and the submit (close() joins outside the
                    # lock) — serve the group inline instead
                    pass
            self._run_graph_batch(rs)
        return len(gq)

    def _sync_graph_depth(self) -> None:
        """Refresh the graph-lane queue-depth gauge (caller holds the
        lock)."""
        depth = sum(
            1 for q in self._adm.queues.values() for r in q
            if r.kind == "graph"
        )
        metrics.set_gauge("serve.graph_queue_depth", depth)

    def _run_graph_batch(self, rs: list[CheckRequest]) -> None:
        """One shared graph-lane dispatch: a single ``check_batch`` for
        the whole compatibility group when the checker supports it,
        per-request ``check_safe`` otherwise (and as the fallback when
        the shared pass fails — one poison graph must degrade alone,
        never its batchmates)."""
        chk = rs[0].checker
        results = None
        if len(rs) > 1 and hasattr(chk, "check_batch"):
            trace_ids = [r.trace_id for r in rs]
            t0 = time.monotonic()
            for r in rs:
                r.t_launch = t0
            try:
                with obs.attach(trace=trace_ids, parent="serve.graph_batch"):
                    with obs.span(
                        "serve.graph_batch", requests=len(rs),
                        checker=type(chk).__name__, trace_ids=trace_ids,
                    ):
                        results = chk.check_batch(
                            {"name": "serve"},
                            [list(r.history) for r in rs], {},
                        )
                if results is None or len(results) != len(rs):
                    results = None
            except Exception:  # noqa: BLE001 — fall back per request
                logger.exception(
                    "graph-lane batch failed; retrying per request"
                )
                results = None
            if results is not None:
                metrics.observe("serve.graph_batch_seconds",
                                time.monotonic() - t0)
                metrics.inc("serve.graph_batch_requests", len(rs))
                obs.counter("serve.graph_batches")
                with self._lock:
                    self._totals["graph_batches"] += 1
        if results is None:
            for r in rs:
                self._run_graph(r)
            return
        with self._lock:
            self._totals["graphs"] += len(rs)
        obs.counter("serve.graphs", len(rs))
        t_end = time.monotonic()
        for r, res in zip(rs, results):
            r.t_launch_end = t_end
            self._settle_member(
                r, res,
                extra_path=[{"event": "serve.graph-lane", "batched": True}],
            )

    def _run_graph(self, r: CheckRequest) -> None:
        from jepsen_tpu import checker as _checker

        r.t_launch = time.monotonic()
        with obs.attach(r.ctx):
            with obs.span(
                "serve.graph", checker=type(r.checker).__name__,
                client=r.client,
            ):
                # check_safe owns the Checker.check contract: a None
                # result means valid, exceptions become an attributable
                # unknown — one bad graph request degrades alone, never
                # the side lane.
                res = _checker.check_safe(
                    r.checker, {"name": "serve"}, list(r.history)
                )
        with self._lock:
            self._totals["graphs"] += 1
        obs.counter("serve.graphs")
        r.t_launch_end = time.monotonic()
        self._settle_member(
            r, res,
            extra_path=[{"event": "serve.graph-lane", "batched": False}],
        )

    # -- interactive fast path ---------------------------------------------

    def _interactive_wave(self) -> int:
        """One speculative greedy wave over the interactive queue: a
        host-side witness walk per request (``wgl_cpu.greedy_walk`` —
        one beam lane, returning-op first, no backtracking; the host
        counterpart of the ladder's rung-0 greedy kernel).  Walks that
        complete resolve True (a full linearization IS a constructive
        witness — the same verdict rung 0 of a one-shot ladder would
        return); walks that stick escalate into the batch tier, where
        the full ladder decides them.  The walk never touches the
        device, so an interactive request's latency is bounded by
        microseconds of host work — not by a beam rung mid-flight on
        the device (the device wave this replaced measured 10–30 ms
        when racing a rung for host cores, on top of a bounded yield).
        Returns requests RESOLVED here (escalations are in flight)."""
        from jepsen_tpu.checker import wgl_cpu

        with self._cond:
            wave = [
                r for r in self._adm.queues["interactive"]
                if r.kind == "ladder"
            ]
            if not wave:
                return 0
            wave.sort(key=lambda r: (-r.priority, r.seq))
            wave = wave[: self.max_batch]
            self._adm.remove(wave)
            for r in wave:
                r.status = "running"
            self._inflight.extend(wave)
            self._gauge_queue_depth()
        t0 = time.monotonic()
        for r in wave:
            r.t_start = t0
            with obs.attach(r.ctx):
                obs.span_event(
                    "serve.admission", t0 - r.t_submit, client=r.client,
                    tier="interactive",
                )
            metrics.observe("serve.admission_latency_seconds",
                            t0 - r.t_submit)
            metrics.observe("serve.class_admission_latency_seconds",
                            t0 - r.t_submit, tier="interactive")
        with _sched_adm.WaveTimer(self._adm, "interactive"):
            with obs.span(
                "serve.fastpath", requests=len(wave), engine="host-greedy",
                trace_ids=[r.trace_id for r in wave],
            ) as sp:
                t_walk = time.monotonic()
                for r in wave:
                    r.t_launch = t_walk
                flags = []
                for r in wave:
                    try:
                        flags.append(
                            wgl_cpu.greedy_walk(r.model, r.history) is True
                        )
                    except Exception:  # noqa: BLE001 — a failed walk
                        # escalates its member; the ladder decides it
                        logger.exception("interactive greedy walk failed")
                        flags.append(False)
                sp.set(resolved=sum(flags),
                       escalated=len(wave) - sum(flags))
        t_wave_end = time.monotonic()
        for r in wave:
            r.t_launch_end = t_wave_end
        resolved = 0
        for r, ok in zip(wave, flags):
            if ok:
                resolved += 1
                with self._cond:
                    if r in self._inflight:
                        self._inflight.remove(r)
                self._settle_member(
                    r, {"valid?": True, "fastpath": "greedy"},
                    extra_path=[{"event": "serve.fastpath",
                                 "engine": "host-greedy"}],
                )
            else:
                r.escalated = True
                # the fast-path stamps are void — the batch tier will
                # re-stamp the ladder lifecycle it actually rides
                r.t_start = r.t_launch = r.t_launch_end = None
                with self._cond:
                    self._inflight.remove(r)
                    r.status = "queued"
                    self._adm.requeue(r, "batch")
                    self._cond.notify_all()
        with self._lock:
            self._totals["fastpath_resolved"] += resolved
            self._totals["escalated"] += len(wave) - resolved
        if resolved:
            obs.counter("serve.fastpath_resolved", resolved)
        if len(wave) - resolved:
            obs.counter("serve.fastpath_escalated", len(wave) - resolved)
        return resolved

    # -- batch tier (continuous ladder) -------------------------------------

    def _step_batch(self) -> int:
        """Run one batch-tier ladder over the lead compatibility group
        (continuous: a RungFeeder admits compatible latecomers at rung
        boundaries).  Returns requests settled."""
        with self._cond:
            q = [
                r for r in self._adm.queues["batch"] if r.kind == "ladder"
            ]
            if not q:
                return 0
            q.sort(key=lambda r: (-r.priority, r.seq))
            lead = q[0]
            batch_reqs = [r for r in q if r.group == lead.group]
            batch_reqs = batch_reqs[: self.max_batch]
            self._adm.remove(batch_reqs)
            for r in batch_reqs:
                r.status = "running"
            self._inflight.extend(batch_reqs)
            self._gauge_queue_depth()
        t_start = time.monotonic()
        for r in batch_reqs:
            r.t_start = t_start
            # Re-attach each request's admission-thread context: the
            # scheduler thread's per-request events carry the request's
            # trace id, not the scheduler's.
            with obs.attach(r.ctx):
                obs.span_event(
                    "serve.admission", t_start - r.t_submit,
                    client=r.client, tier=r.tier,
                )
            metrics.observe("serve.admission_latency_seconds",
                            t_start - r.t_submit)
            metrics.observe("serve.class_admission_latency_seconds",
                            t_start - r.t_submit, tier=r.tier)
        feeder = (
            _sched_pack.RungFeeder(self, lead.group, batch_reqs)
            if self.continuous else None
        )
        try:
            self._run_batch(batch_reqs, feeder)
        finally:
            members = feeder.members if feeder is not None else batch_reqs
            with self._lock:
                for r in members:
                    if r in self._inflight:
                        self._inflight.remove(r)
        return len(members)

    def _admit_joiners(self, feeder, *, stage: int, lanes: int) -> list:
        """The RungFeeder's poll body: a bounded mid-ladder service
        opportunity.  Expire overdue queued requests, serve one
        interactive wave (this is what bounds interactive latency by a
        RUNG, not a batch), then hand geometry-compatible batch-tier
        requests to the running ladder — at most ``max_batch - lanes``,
        so recycled lane slots are what joiners consume."""
        # The rung boundary is where device-loss re-placement lands: a
        # probe failure shrinks placement for the NEXT batch and closes
        # this feeder so the running ladder drains instead of growing
        # on a degraded mesh.
        self._probe_placement()
        if self._placement.generation != feeder.placement_gen:
            feeder.close()
        with self._cond:
            expired = self._adm.take_expired()
            self._totals["expired"] += len(expired)
        self._resolve_expired(expired)
        with self._lock:
            interactive_waiting = self._adm.depth("interactive") > 0
        if interactive_waiting:
            # The rung boundary is an interactive service opportunity
            # whether or not the dedicated fast-path thread runs: the
            # ladder pausing here means the wave launches uncontended,
            # and an interactive request is never stuck behind more than
            # ONE rung even if the fast-path thread is mid-wave.  (The
            # two pickers take disjoint requests under the lock.)
            self._interactive_wave()
        if self._thread is not None:
            # Graph work dispatches to its thread pool, so the rung
            # boundary is its service opportunity too — a continuous
            # ladder with a steady joiner stream would otherwise pin
            # queued graph requests behind the whole ladder lifetime
            # (inline/step() callers keep their deterministic ordering:
            # graphs there run in step() itself).
            self._step_graphs()
        if not self.continuous or self._closed or feeder.closed:
            return []
        with self._cond:
            now = time.monotonic()
            other_wait = max(
                (now - r.t_submit
                 for r in self._adm.queues["batch"]
                 if r.kind == "ladder" and r.group != feeder.group),
                default=0.0,
            )
            if other_wait > _GROUP_STARVE_S:
                # Another geometry group has waited a full starvation
                # bound: stop feeding this ladder so it drains and the
                # next scheduler cycle serves that group — the
                # cross-GROUP face of the bounded-wait contract
                # parallel.batch._STARVE_SECONDS gives members inside a
                # ladder (a steady same-group stream must not hold the
                # device forever).
                return []
            # Joiners may grow the ladder past the feeder's initial
            # pad_lanes: pad widths are power-of-2 bucketed, so growth
            # changes the compiled shape at most log2(max_batch /
            # pad_lanes) times per ladder and every width re-warms for
            # the process lifetime — clamping the budget to the initial
            # width instead was measured at 0.70-0.73 occupancy against
            # ~0.90 (overflow seeded extra narrow ladders all day to
            # dodge a once-per-shape compile).
            budget = self.max_batch - int(lanes)
            if budget <= 0:
                return []
            q = [
                r for r in self._adm.queues["batch"]
                if r.kind == "ladder" and r.group == feeder.group
            ]
            q.sort(key=lambda r: (-r.priority, r.seq))
            joiners = q[:budget]
            self._adm.remove(joiners)
            for r in joiners:
                r.status = "running"
            self._inflight.extend(joiners)
            if joiners:
                self._gauge_queue_depth()
        t = time.monotonic()
        for r in joiners:
            # a joiner enters the RUNNING launch at its join boundary:
            # queue wait ends and launch residence begins here
            r.t_start = r.t_launch = t
            with obs.attach(r.ctx):
                obs.span_event(
                    "serve.admission", t - r.t_submit, client=r.client,
                    tier=r.tier, joined_at_rung=stage,
                )
            metrics.observe("serve.admission_latency_seconds",
                            t - r.t_submit)
            metrics.observe("serve.class_admission_latency_seconds",
                            t - r.t_submit, tier=r.tier)
        return joiners

    def _probe_placement(self) -> None:
        """Mesh health probe (interval-gated by ``health_probe_every_s``):
        a tiny per-device op through the ``faults.INJECT``-seamed
        ``Placement.probe``.  On a failed device, shrink placement to
        the survivors — the NEXT batch launches on the reduced mesh —
        and re-arm the parity probe so the first reduced launch is
        verified against single-device execution."""
        if (self.health_probe_every_s is None
                or self._placement.mesh is None):
            return
        now = time.monotonic()
        with self._lock:
            # Check-and-set atomically: the scheduler thread and a
            # continuous ladder's rung poll (on the watchdog worker
            # thread) both reach here, and two passing the interval
            # gate together would double-probe the mesh.
            if now - self._t_probe < self.health_probe_every_s:
                return
            self._t_probe = now
        try:
            healthy, failed = self._placement.probe()
        except Exception:  # noqa: BLE001 — a broken probe must not
            # take down the scheduler; it retries next interval
            logger.exception("placement health probe itself failed")
            return
        if not failed:
            return
        if not healthy:
            # Every device failed: nothing to shrink TO.  Leave
            # placement alone — the launches will fail, the breaker
            # will open, and the operator sees both.
            logger.error("ALL %d devices failed the placement health "
                         "probe; placement unchanged", len(failed))
            obs.counter("serve.placement_probe_all_failed",
                        devices=len(failed))
            return
        self._placement.shrink_to(healthy)
        with self._lock:
            self._totals["devices_replaced"] += len(failed)
            self._parity_checked = False
        metrics.inc("serve.devices_lost", len(failed))
        metrics.set_gauge("serve.placement_devices", len(healthy))
        obs.counter("serve.placement_replaced", lost=len(failed),
                    devices=len(healthy))
        logger.warning(
            "device loss: placement shrunk to %d device(s) after %d "
            "failed health probe(s); parity probe re-armed",
            len(healthy), len(failed),
        )

    def _bundle(self, r: CheckRequest, res: dict,
                extra_path: Sequence[Mapping] | None = None) -> None:
        """Build + retain this request's evidence bundle
        (``obs.provenance``) BEFORE its future resolves, so the verdict
        the client reads already carries the ``evidence`` pointer.  The
        bundle id IS the request id — GET /evidence/<id> and GET
        /check/<id> share a key.  Bundles land in the in-memory ring
        (bounded like the request registry) and, when ``evidence_dir``
        is set, as durable envelopes on disk.  Never raises — evidence
        is observability, not the verdict."""
        try:
            path = [{"event": "serve.request", "tier": r.tier,
                     "client": r.client}]
            if r.escalated:
                path.append({"event": "serve.escalated"})
            if extra_path:
                path.extend(dict(e) for e in extra_path)
            bundle = _prov.build_bundle(
                history=list(r.history), result=res, source="serve",
                model=r.model,
                checker=(type(r.checker).__name__
                         if r.checker is not None else None),
                trace_id=r.trace_id, bundle_id=r.id, extra_path=path,
            )
            written = None
            if self.evidence_dir is not None:
                written = _prov.write_bundle(self.evidence_dir, bundle)
            with self._lock:
                self._evidence[r.id] = bundle
                if len(self._evidence) > _KEEP_DONE:
                    drop = list(self._evidence)[
                        : len(self._evidence) - _KEEP_DONE]
                    for k in drop:
                        del self._evidence[k]
            res["evidence"] = {"id": bundle["id"],
                               "digest": bundle["digest"]}
            if written is not None:
                res["evidence"]["path"] = str(written)
            else:
                # write_bundle counts the persisted case; the
                # in-memory-only emission counts here so the
                # provenance.* rollup sees every served bundle.
                obs.counter("provenance.bundle", source="serve",
                            verdict=bundle["verdict"])
        except Exception:  # noqa: BLE001 — see docstring
            logger.exception("evidence bundle emission failed for %s",
                             r.id)
            obs.counter("provenance.emit_error", error="serve")

    def get_evidence(self, request_id: str) -> dict | None:
        """The evidence bundle behind GET /evidence/<id>: the in-memory
        ring first, then the durable ``evidence_dir`` copy (a restart
        empties the ring; the disk envelope survives)."""
        with self._lock:
            b = self._evidence.get(request_id)
        if b is not None:
            return b
        if self.evidence_dir is not None:
            p = self.evidence_dir / f"{request_id}.json"
            if p.is_file():
                try:
                    return _prov.read_bundle(p)
                except _durable.DurableError:
                    return None
        return None

    # ------------------------------------------------------------------
    # Streaming sessions (checker.streaming — POST /stream)
    # ------------------------------------------------------------------

    def _stream_retry_after(self) -> float:  # holds: _lock
        """Stream-lane Retry-After quote: active sessions over lane
        width times the STREAM-session duration EWMA — the same shape
        as ``AdmissionQueues.retry_after`` but fed exclusively from
        stream wall clocks, never the batch ladder's cycle EWMA.
        Caller holds ``_lock``."""
        active = sum(1 for s in self._streams.values() if not s.closed)
        waves = max(1.0, active / max(1, self.max_streams))
        return round(max(0.02, waves * self._stream_ewma_s), 3)

    def _stream_opts(self) -> dict:
        """Scan parameters a stream shares with the service's ladder
        config (dedup backend, spill, closure depth) — a stream compiles
        no kernel geometry the batch path wouldn't."""
        keep = ("dedup_backend", "spill", "fast", "rounds",
                "chunk_barriers", "max_groups", "max_procs")
        return {k: self._check_opts[k] for k in keep
                if k in self._check_opts}

    def stream_open(self, *, model=None, stream_id: str | None = None,
                    resume: bool = False, client: str = "http",
                    trace_id: str | None = None) -> dict:
        """Open (or re-open) an incremental checking stream.

        Admission is bounded by ``max_streams``; beyond it raises
        ``QueueFull(tier="stream")`` quoted from the stream lane's own
        duration EWMA.  ``resume=True`` with a ``stream_dir`` checkpoint
        reconstructs a SIGKILL'd stream mid-history (the feeder then
        continues from the returned ``ops`` count).  Re-opening an id
        that is already active is idempotent and returns its status.

        Streams are replica-sticky (carried frontier state): the fleet
        router does not front this surface.  Shutdown leaves open
        streams un-finalized on purpose — finalizing would classify
        still-pending invokes as crashed and CHANGE the eventual
        verdict; the per-feed checkpoint is the durable state."""
        from jepsen_tpu.checker import streaming as _streaming
        from jepsen_tpu.store import checkpoint as _ckpt

        if model is None or isinstance(model, str):
            model = model_by_name(model or "cas-register")
        with self._lock:
            if self._closed:
                raise ServiceClosed("service is shutting down")
            live = self._streams.get(stream_id) if stream_id else None
            if live is not None and not live.closed:
                return live.describe()
            active = sum(1 for s in self._streams.values() if not s.closed)
            if active >= self.max_streams:
                self._totals["streams_rejected"] += 1
                retry = self._stream_retry_after()
                obs.counter("stream.rejected")
                raise QueueFull(active, self.max_streams, retry,
                                tier="stream")
        sid = stream_id or uuid.uuid4().hex[:16]
        ckdir = (self.stream_dir / sid
                 if self.stream_dir is not None else None)
        sc = None
        if resume and ckdir is not None and _ckpt.stream_exists(ckdir):
            try:
                sc = _streaming.StreamingChecker.resume(ckdir, model)
                with self._lock:
                    self._totals["streams_resumed"] += 1
            except _ckpt.CheckpointError as e:
                logger.warning("unreadable stream checkpoint in %s (%s); "
                               "opening fresh", ckdir, e)
                obs.counter("fault.checkpoint.mismatch",
                            reason="unreadable")
        if sc is None:
            sc = _streaming.StreamingChecker(
                model, capacity=self.capacity, checkpoint_dir=ckdir,
                stream_id=sid, **self._stream_opts(),
            )
        sess = StreamSession(checker=sc, client=client, trace_id=trace_id)
        with self._lock:
            # lost an open race for the same id: first one wins
            live = self._streams.get(sess.id)
            if live is not None and not live.closed:
                return live.describe()
            self._streams[sess.id] = sess
            self._totals["streams_opened"] += 1
            active = sum(1 for s in self._streams.values() if not s.closed)
        obs.counter("stream.opened", resumed=str(sc.ops_consumed > 0))
        metrics.set_gauge("stream.active", active)
        self._stream_gauges(sess)
        return sess.describe()

    _STREAM_GAUGES = ("stream.ops_fed", "stream.epochs",
                      "stream.frontier_rows", "stream.rescans")

    def _stream_gauges(self, sess: StreamSession) -> None:
        """Live per-stream progress gauges, labelled ``stream=<id>``.
        Cardinality is bounded: at most ``max_streams`` concurrent label
        sets, and :meth:`stream_close` removes the series so a finished
        stream's last values don't render forever."""
        sc = sess.checker
        metrics.set_gauge("stream.ops_fed", sc.ops_consumed, stream=sess.id)
        metrics.set_gauge("stream.epochs", sc.epochs, stream=sess.id)
        metrics.set_gauge("stream.frontier_rows", sc.frontier_rows,
                          stream=sess.id)
        metrics.set_gauge("stream.rescans", sc.rescans, stream=sess.id)

    def _stream_get(self, stream_id: str) -> StreamSession:
        with self._lock:
            sess = self._streams.get(stream_id)
        if sess is None:
            raise KeyError(f"unknown stream {stream_id!r}")
        return sess

    def stream_feed(self, stream_id: str, ops, seq: int | None = None) -> dict:
        """Feed one epoch of ops into a stream; returns its status doc
        (a verdict-on-violation shows up here the moment the frontier
        dies).  ``seq`` is the count of ops the CLIENT believes it has
        already delivered before this chunk: overlap with the stream's
        consumed count is dropped (idempotent re-feeds after a
        kill/resume), a gap is refused — silently skipping unseen ops
        would corrupt the verdict."""
        sess = self._stream_get(stream_id)
        ops = [dict(o) for o in ops]
        with sess.lock:
            if sess.closed:
                raise ValueError(f"stream {stream_id!r} is closed")
            if seq is not None:
                have = sess.checker.ops_consumed
                if seq > have:
                    raise ValueError(
                        f"sequence gap: stream has {have} ops, chunk "
                        f"starts at {seq}")
                if seq < have:
                    ops = ops[have - seq:]
            sess.t_last = time.monotonic()
            with obs.attach(obs.capture(trace=sess.trace_id)):
                status = sess.checker.feed(ops)
            self._stream_gauges(sess)
            if sess.checker.terminal:
                self._stream_bundle(sess, status)
        return status

    def stream_close(self, stream_id: str) -> dict:
        """End of stream: finalize (still-pending invokes classify as
        crashed, exactly the post-hoc treatment), emit the evidence
        bundle, fold the session wall into the stream lane's EWMA, and
        return ``{"result": ..., **status}``.  Idempotent."""
        sess = self._stream_get(stream_id)
        with sess.lock:
            if not sess.closed:
                with obs.attach(obs.capture(trace=sess.trace_id)):
                    result = sess.checker.finalize()
                sess.closed = True
                sess.t_close = time.monotonic()
                wall = sess.t_close - sess.t_open
                status = sess.checker.status()
                self._stream_bundle(sess, status)
                with self._lock:
                    self._totals["streams_closed"] += 1
                    self._stream_ewma_s += _sched_adm._EWMA_ALPHA * (
                        wall - self._stream_ewma_s)
                    active = sum(1 for s in self._streams.values()
                                 if not s.closed)
                    self._prune_streams()
                obs.counter("stream.closed",
                            verdict=str(result.get("valid?")).lower())
                obs.span_event("stream.session", wall, stream=sess.id,
                               verdict=str(result.get("valid?")),
                               ops=sess.checker.ops_consumed)
                metrics.set_gauge("stream.active", active)
                for g in self._STREAM_GAUGES:
                    metrics.REGISTRY.remove(g, stream=sess.id)
            else:
                result = sess.checker.result
                status = sess.checker.status()
        out = dict(sess.describe())
        out["result"] = result
        if "evidence" in status:
            out["evidence"] = status["evidence"]
        else:
            # the bundle may have been emitted at the MID-STREAM verdict
            # (feed time) — the pointer then lives in the evidence ring
            with self._lock:
                bundle = self._evidence.get(sess.id)
            if bundle is not None:
                out["evidence"] = {"id": bundle["id"],
                                   "digest": bundle["digest"]}
        return out

    def stream_status(self, stream_id: str) -> dict:
        """The status doc behind GET /stream/<id> (404s via KeyError)."""
        return self._stream_get(stream_id).describe()

    def _stream_bundle(self, sess: StreamSession, status: dict) -> None:
        """Evidence for a terminal stream, emitted ONCE — at the
        mid-stream verdict when one fires, else at close.  Lands in the
        same ring + ``evidence_dir`` as request bundles (GET
        /evidence/<stream-id>).  Never raises; caller holds the session
        lock."""
        if sess.evidence_done or not sess.checker.terminal:
            return
        sess.evidence_done = True
        try:
            bundle = sess.checker.evidence(trace_id=sess.trace_id)
            if bundle is None:
                return
            written = None
            if self.evidence_dir is not None:
                written = _prov.write_bundle(self.evidence_dir, bundle)
            with self._lock:
                self._evidence[sess.id] = bundle
                if len(self._evidence) > _KEEP_DONE:
                    drop = list(self._evidence)[
                        : len(self._evidence) - _KEEP_DONE]
                    for k in drop:
                        del self._evidence[k]
            status["evidence"] = {"id": bundle["id"],
                                  "digest": bundle["digest"]}
            if written is not None:
                status["evidence"]["path"] = str(written)
            else:
                obs.counter("provenance.bundle", source="stream",
                            verdict=bundle["verdict"])
        except Exception:  # noqa: BLE001 — observability, not the verdict
            logger.exception("stream evidence emission failed for %s",
                             sess.id)
            obs.counter("provenance.emit_error", error="stream")

    def _prune_streams(self) -> None:  # holds: _lock
        """Bound the closed-session registry (caller holds ``_lock``);
        active sessions are bounded by admission and never pruned."""
        done = [sid for sid, s in self._streams.items() if s.closed]
        if len(done) > _KEEP_DONE:
            for sid in done[: len(done) - _KEEP_DONE]:
                del self._streams[sid]

    def _settle_member(self, r: CheckRequest, res: dict,
                       status: str = "done",
                       extra_path: Sequence[Mapping] | None = None) -> bool:
        """Resolve one request's future with its verdict (idempotent —
        the ladder's early demux and the final settle loop may both
        reach a member).  Annotates mid-flight deadline overrun and
        emits the per-request telemetry + evidence bundle."""
        if r.deadline is not None and r.deadline.expired():
            # Launched before the budget ran out: the verdict is
            # already paid for, so hand it over — annotated, so an
            # SLA-bound caller can still discount it.
            res = {**res, "deadline-overrun": True}
        if not r.future.done():
            self._bundle(r, res, extra_path)
        if not r.resolve(res, status=status):
            return False
        with obs.attach(r.ctx):
            obs.span_event(
                "serve.request", r.t_done - r.t_submit, client=r.client,
                verdict=str(res.get("valid?")), tier=r.tier,
            )
        metrics.observe("serve.request_latency_seconds",
                        r.t_done - r.t_submit)
        metrics.observe("serve.class_request_latency_seconds",
                        r.t_done - r.t_submit, tier=r.tier)
        metrics.inc("serve.verdicts", verdict=str(res.get("valid?")).lower())
        with self._lock:
            self._totals["completed"] += 1
        obs.counter("serve.completed")
        self._journal_done(r)
        return True

    def _run_batch(self, batch_reqs: list[CheckRequest], feeder) -> None:
        from jepsen_tpu.parallel import batch

        model = batch_reqs[0].model
        n = len(batch_reqs)
        mesh = self._placement.mesh
        n_pad = batch.padded_batch(n, mesh)
        geom = batch_reqs[0].group[1:]
        trace_ids = [r.trace_id for r in batch_reqs]
        metrics.set_gauge("serve.batch_occupancy", round(n / n_pad, 4))
        metrics.set_gauge("serve.batch_padding_waste",
                          round(1.0 - n / n_pad, 4))
        metrics.set_gauge("serve.batch_requests", n)
        hung = False
        with self._placement.span(requests=n, tier="batch"):
            with obs.span(
                "serve.batch", requests=n, padded=n_pad,
                occupancy=round(n / n_pad, 4),
                padding_waste=round(1.0 - n / n_pad, 4),
                model=model.name, geometry=str(geom),
                trace_ids=trace_ids, continuous=feeder is not None,
            ) as sp:
                t0 = time.monotonic()
                for r in batch_reqs:
                    r.t_launch = t0

                def _launch():
                    # The serve-level fault-injection seam: unlike the
                    # per-kernel INJECT calls inside the ladder, this
                    # one names WHICH members share the launch (history
                    # fingerprints), so poison-request chaos scenarios
                    # compose through faults.inject_scope without
                    # monkeypatching the ladder.
                    hook = faults.INJECT
                    if hook is not None:
                        hook({"what": "serve.batch",
                              "members": [r.fp for r in batch_reqs],
                              "lanes": n}, 0)
                    # The shared-batch trace scope: everything the
                    # launch emits (ladder stages, confirmations, fault
                    # retries) carries the member trace ids, so one
                    # request's journey is findable inside the shared
                    # work.  Attached HERE (inside the callable) so it
                    # holds on the watchdog worker thread too.
                    with obs.attach(trace=trace_ids, parent="serve.batch"):
                        return batch.batch_analysis(
                            model, [r.history for r in batch_reqs],
                            capacity=self.capacity, mesh=mesh,
                            admission=feeder,
                            **self._check_opts,
                        )

                try:
                    if self._watchdog is not None:
                        results = self._watchdog.run(_launch)
                    else:
                        results = _launch()
                    err = None
                except _health.HungLaunch as e:
                    # The launch blew its wall-clock cap: the worker
                    # thread may still be running — abandon it (its
                    # late verdicts lose the first-write-wins race) and
                    # close the feeder so it can't pull new joiners
                    # into a zombie ladder.
                    logger.warning(
                        "check-service batch hung (%s); abandoning and "
                        "retrying on reduced placement", e,
                    )
                    results, err, hung = None, e, True
                    if feeder is not None:
                        feeder.close()
                except Exception as e:  # noqa: BLE001 — degrade the batch's
                    # requests, never the service (the scheduler lives on)
                    logger.exception("check-service batch failed")
                    results, err = None, e
                dt = time.monotonic() - t0
                if feeder is not None:
                    sp.set(
                        joined=feeder.joined, members=len(feeder.members),
                        rungs=feeder.rungs,
                        continuous_occupancy=feeder.mean_occupancy,
                    )
        members = list(feeder.members) if feeder is not None else batch_reqs
        t_launch_end = time.monotonic()
        for r in members:
            if r.t_launch_end is None:
                r.t_launch_end = t_launch_end
        # Per-device bubble attribution: lanes shard contiguously over
        # the placement, so device k's live-lane count (and with it the
        # padded-slot bubble) is computable without a device round-trip.
        # On a single device this is exactly 1 − occupancy — the
        # identity the acceptance gate (and loadgen) assert.
        dev_ids = batch.mesh_device_ids(mesh)
        shard = max(1, n_pad // len(dev_ids))
        for k, did in enumerate(dev_ids):
            live = min(max(0, n - k * shard), shard)
            metrics.set_gauge("serve.device_bubble_ratio",
                              round(1.0 - live / shard, 4), device=str(did))
        metrics.observe("serve.batch_seconds", dt)
        with self._lock:
            # The batch-tier retry-after quotes SLOT-RECYCLE cadence: a
            # continuous ladder lives as long as joiners keep coming
            # (minutes, under steady arrival), but lanes free at every
            # rung — feeding the whole-ladder wall into the EWMA would
            # tell a rejected client to come back a ladder-lifetime
            # later for a slot that frees in milliseconds.
            cycles = feeder.rungs if (feeder is not None
                                      and feeder.rungs) else 1
            self._adm.record_wall("batch", dt / cycles)
            self._totals["batches"] += 1
            self._occ_sum += n / n_pad
            if feeder is not None:
                self._rung_lane_sum += feeder.lane_sum
                self._rung_slot_sum += feeder.slot_sum
                self._rungs += feeder.rungs
            if err is not None:
                self._totals["batch_errors"] += 1
        metrics.inc("serve.batches")
        if err is not None:
            metrics.inc("serve.batch_errors")
            obs.counter("serve.batch_error", error=faults.describe(err))
            if hung:
                self._retry_hung(model, members, err)
                return
            unresolved = [r for r in members if not r.future.done()]
            if (self.poison_bisect and len(unresolved) > 0
                    and faults.error_kind(err) is None):
                # A NON-transient shared-launch failure (transients and
                # OOM already retried/halved inside the ladder): bisect
                # the member set so only the poison member(s) degrade —
                # everyone else gets their real verdict from the
                # succeeding halves.
                self._bisect_poison(model, unresolved, err, mesh)
                return
            opened = self.breaker.record_failure()
            if opened:
                obs.counter("serve.breaker_opened",
                            failures=self.breaker.consecutive_failures)
                logger.error(
                    "circuit breaker OPEN after %d consecutive batch "
                    "failures (cooldown %.0fs)",
                    self.breaker.consecutive_failures,
                    self.breaker.cooldown_s,
                )
            metrics.set_gauge("serve.breaker_open",
                              self.breaker.state == "open")
            for r in unresolved:
                metrics.inc("serve.verdicts", verdict="unknown")
                eres = {
                    "valid?": "unknown",
                    "cause": (
                        "service batch failed: "
                        f"{faults.describe(err)}"
                    ),
                }
                self._bundle(r, eres, [{
                    "event": "fault.batch-error",
                    "error": faults.describe(err),
                }])
                r.resolve(eres, status="error")
                self._journal_done(r)
            return
        self.breaker.record_success()
        metrics.set_gauge("serve.breaker_open", False)
        # Settle every member the ladder's early demux didn't (unknowns
        # and confirmation leftovers); _settle_member is idempotent so
        # already-resolved members are skipped.
        for r, res in zip(members, results):
            self._settle_member(r, res)
        if self.verify_placement and mesh is not None:
            with self._lock:
                # claim-under-lock: a device-loss shrink re-arms the
                # probe concurrently, and two batches racing the bare
                # flag could both (or neither) run the parity check
                run_parity = not self._parity_checked
                self._parity_checked = True
            if run_parity:
                self._verify_placement(model, [r.history for r in members],
                                       results)

    def _bisect_poison(self, model, members: list[CheckRequest],
                       err: BaseException, mesh) -> None:
        """Blast-radius isolation for a poisoned shared launch: bisect
        ``members`` with bounded relaunches (serve.health.bisect_poison)
        — innocents settle with the verdicts the succeeding halves
        produce; the isolated poison member(s) resolve unknown and land
        in the TTL'd quarantine registry so repeat offenders skip
        straight to rejection."""
        from jepsen_tpu.parallel import batch

        cause0 = faults.describe(err)

        def launch(reqs: list[CheckRequest]) -> list[dict]:
            def _go():
                hook = faults.INJECT
                if hook is not None:
                    hook({"what": "serve.batch",
                          "members": [r.fp for r in reqs],
                          "lanes": len(reqs)}, 0)
                return batch.batch_analysis(
                    model, [r.history for r in reqs],
                    capacity=self.capacity, mesh=mesh, **self._check_opts,
                )

            if self._watchdog is not None:
                # A poison member may WEDGE a relaunch instead of
                # raising; without the cap one bisection step would
                # hang the scheduler forever.  HungLaunch is an
                # Exception, so bisect_poison treats it as this
                # group's failure signature and keeps isolating.
                return self._watchdog.run(_go)
            return _go()

        with obs.span("serve.poison_bisect", members=len(members),
                      error=cause0) as sp:
            poison, good, launches = _health.bisect_poison(launch, members)
            sp.set(poison=len(poison), launches=launches)
        with self._lock:
            self._totals["bisect_launches"] += launches
            self._totals["poison_isolated"] += len(poison)
            self._totals["quarantined"] += len(poison)
        metrics.inc("serve.poison_bisect_launches", launches)
        metrics.inc("serve.poison_isolated", len(poison))
        for r, res in good.items():
            self._settle_member(r, res)
        for r in poison:
            if r.fp:
                self.quarantine.add(r.fp, cause0)
            with obs.attach(r.ctx):
                obs.counter("serve.quarantined", client=r.client,
                            error=cause0)
            self._settle_member(
                r,
                {
                    "valid?": "unknown",
                    "quarantined": True,
                    "cause": (
                        "poisoned shared launch (isolated by bisection): "
                        f"{cause0}; fingerprint quarantined for "
                        f"{self.quarantine.ttl_s:.0f}s"
                    ),
                },
                status="quarantined",
                extra_path=[{"event": "fault.poison-bisect",
                             "error": cause0}],
            )
        logger.warning(
            "poison bisection: %d member(s) quarantined, %d innocent "
            "verdict(s) recovered in %d relaunch(es) (%s)",
            len(poison), len(good), launches, cause0,
        )
        # The breaker reads the bisection outcome as the device's
        # health: recovered innocent verdicts prove the device serves
        # (the REQUEST was the problem); an all-poison outcome is
        # indistinguishable from a broken device and counts against it.
        if good:
            self.breaker.record_success()
        else:
            self.breaker.record_failure()
        metrics.set_gauge("serve.breaker_open", self.breaker.state == "open")

    def _retry_hung(self, model, members: list[CheckRequest],
                    err: BaseException) -> None:
        """Cancel-and-retry for a hung launch: the abandoned worker
        thread keeps whatever device it wedged; the still-unresolved
        members retry ONCE on reduced placement (single device, no
        continuous admission, a doubled watchdog cap).  A retry that
        also fails degrades only these members."""
        from jepsen_tpu.parallel import batch

        with self._lock:
            self._totals["watchdog_trips"] += 1
        metrics.inc("serve.watchdog_trips")
        obs.counter("serve.watchdog_trip", error=faults.describe(err))
        self.breaker.record_failure()
        retry = [r for r in members if not r.future.done()]
        if not retry:
            return

        def _relaunch():
            return batch.batch_analysis(
                model, [r.history for r in retry],
                capacity=self.capacity, mesh=None, **self._check_opts,
            )

        try:
            cap = (self._watchdog.timeout_s() * 2
                   if self._watchdog is not None else None)
            if self._watchdog is not None:
                results = self._watchdog.run(_relaunch, cap)
            else:  # pragma: no cover — hung implies a watchdog exists
                results = _relaunch()
        except Exception as e2:  # noqa: BLE001 — bounded degradation:
            # these members only, with both failures named
            for r in retry:
                metrics.inc("serve.verdicts", verdict="unknown")
                hres = {
                    "valid?": "unknown",
                    "cause": (
                        f"hung launch ({faults.describe(err)}); "
                        "reduced-placement retry failed: "
                        f"{faults.describe(e2)}"
                    ),
                }
                self._bundle(r, hres, [
                    {"event": "fault.watchdog-trip",
                     "error": faults.describe(err)},
                    {"event": "fault.retry-failed",
                     "error": faults.describe(e2)},
                ])
                r.resolve(hres, status="error")
                self._journal_done(r)
            return
        for r, res in zip(retry, results):
            self._settle_member(r, res)
        self.breaker.record_success()
        metrics.set_gauge("serve.breaker_open", False)
        obs.counter("serve.watchdog_retry_ok", requests=len(retry))

    def _verify_placement(self, model, histories, sharded_results) -> None:
        """The placement parity check (first sharded batch only): the
        SAME histories one-shot on a single device must produce the
        same verdicts.  A mismatch is reported loudly (counter + log)
        but never degrades the already-delivered verdicts — placement
        bugs are for operators, crashes are not a remedy."""
        from jepsen_tpu.parallel import batch

        try:
            single = batch.batch_analysis(
                model, histories, capacity=self.capacity, mesh=None,
                **self._check_opts,
            )
        except Exception as e:  # noqa: BLE001 — the probe is best-effort,
            # but a swallowed probe failure left operators thinking
            # parity was verified: count it and name the error
            logger.exception("placement parity probe failed")
            metrics.inc("serve.placement_probe_errors")
            obs.counter("serve.placement_probe_error",
                        error=faults.describe(e))
            return
        got = [r["valid?"] for r in sharded_results]
        want = [r["valid?"] for r in single]
        if got == want:
            obs.counter("serve.placement_parity_ok",
                        histories=len(histories))
            logger.info("placement parity verified over %d histories "
                        "(%d devices)", len(histories),
                        self._placement.n_devices)
        else:
            obs.counter("serve.placement_parity_mismatch")
            metrics.inc("serve.placement_parity_mismatch")
            logger.error(
                "PLACEMENT PARITY MISMATCH: mesh verdicts %s != "
                "single-device %s", got, want,
            )

    # ------------------------------------------------------------------
    # Introspection (GET /queue, GET /check/<id>)
    # ------------------------------------------------------------------

    def get(self, request_id: str) -> CheckRequest | None:
        with self._lock:
            return self._requests.get(request_id)

    @staticmethod
    def _spill_stats() -> dict:
        from jepsen_tpu.ops import spill as _spill

        return _spill.stats_snapshot()

    def stats(self) -> dict:
        """The queue-status document (GET /queue, web panel)."""
        with self._lock:
            queued = [r for q in self._adm.queues.values() for r in q]
            by_client: dict[str, int] = {}
            for r in queued:
                by_client[r.client] = by_client.get(r.client, 0) + 1
            groups = len({r.group for r in queued})
            t = dict(self._totals)
            return {
                "queue_depth": self._adm.depth(),
                "graph_queue_depth": sum(
                    1 for r in queued if r.kind == "graph"
                ),
                "queue_groups": groups,
                "running": len(self._inflight),
                "max_queue": self.max_queue,
                "max_batch": self.max_batch,
                "closed": self._closed,
                "by_client": by_client,
                "classes": self._adm.describe(self.max_batch),
                "placement": self._placement.describe(),
                "continuous": self.continuous,
                "batch_ewma_s": round(self._adm.ewma_s["batch"], 4),
                "avg_occupancy": round(
                    self._occ_sum / t["batches"], 4) if t["batches"] else None,
                "continuous_occupancy": round(
                    self._rung_lane_sum / self._rung_slot_sum, 4
                ) if self._rung_slot_sum else None,
                # raw device-time accumulators behind continuous_occupancy
                # (live lane-seconds / launched lane-slot-seconds): a
                # load harness snapshots these around a measured window
                # to get steady-state occupancy with warmup (compile
                # rungs) excluded — see tools/loadgen.py.
                "rung_lane_s": round(self._rung_lane_sum, 6),
                "rung_slot_s": round(self._rung_slot_sum, 6),
                "retry_after_hint_s": self._adm.retry_after(
                    "batch", self.max_batch),
                # -- streaming lane (checker.streaming) -----------------
                # its retry-after hint comes from the stream-session
                # duration EWMA, NOT the batch ladder's cycle EWMA (the
                # per-class quoting rule extends to the new lane).
                "streams": {
                    "active": sum(1 for s in self._streams.values()
                                  if not s.closed),
                    "max_streams": self.max_streams,
                    "ewma_s": round(self._stream_ewma_s, 4),
                    "retry_after_hint_s": self._stream_retry_after(),
                },
                "uptime_s": round(time.monotonic() - self._t_start, 3),
                # -- self-healing layer (serve.health) ------------------
                "breaker": self.breaker.describe(),
                "quarantine": self.quarantine.describe(),
                "idempotency": self.idempotency.describe(),
                "journal_depth": (
                    self.journal.depth() if self.journal is not None
                    else None
                ),
                "journal_errors": (
                    self.journal.errors if self.journal is not None
                    else None
                ),
                "watchdog_timeout_s": (
                    round(self._watchdog.timeout_s(), 3)
                    if self._watchdog is not None else None
                ),
                # -- bounded-memory layer (ops.spill) -------------------
                # process-wide spill/factorization totals: how much exact
                # frontier state moved to host RAM and how many crashed
                # groups factored away, plus reduced-size retry launches
                # excluded from the watchdog EWMA baseline.  (spill is
                # imported lazily: it pulls ops.hashing and with it jax,
                # which this module defers to function bodies by design.)
                "memory": {
                    **self._spill_stats(),
                    "retry_launches": faults.retry_launch_count(),
                },
                **t,
            }

    # ------------------------------------------------------------------
    # Shutdown / drain
    # ------------------------------------------------------------------

    def shutdown(self, *, drain: bool = True, wait: bool = False,
                 join_timeout: float = 600.0) -> dict:
        """Stop admitting, stop the scheduler, settle EVERY admitted
        request.

        ``wait=True`` finishes ALL queued work first (every future gets
        its real verdict).  Otherwise the in-flight batch is given
        ``join_timeout`` seconds to complete and the still-queued
        remainder is DRAINED: with a ``drain_dir``, each compatibility
        group's histories + a resumable ``store.checkpoint`` land on
        disk (finish later with ``resume_drained``); the futures
        resolve unknown with the checkpoint path in ``cause``.  A batch
        still on the device after ``join_timeout`` has its requests
        drained too (resolve() is first-write-wins, so the zombie
        batch's late verdicts are discarded harmlessly).  Closing also
        stops rung-boundary admission — a running continuous ladder
        finishes its current members but takes no new joiners.  Returns
        a summary dict."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if wait:
            # Settle the backlog before stopping the scheduler.  If the
            # scheduler thread isn't running, step() here.
            while True:
                with self._lock:
                    empty = self._adm.depth() == 0 and not self._inflight
                if empty:
                    break
                if self._thread is None:
                    self.step()
                else:
                    time.sleep(0.01)
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=join_timeout)
            if self._thread.is_alive():
                logger.warning(
                    "scheduler still mid-batch after %.0fs; draining its "
                    "requests (late verdicts will be discarded)",
                    join_timeout,
                )
            self._thread = None
        if self._fp_thread is not None:
            self._fp_thread.join(timeout=30.0)
            self._fp_thread = None
        with self._lock:
            pool, self._graph_pool = self._graph_pool, None
        if pool is not None:
            # joined outside the lock: queued graph batches take it in
            # _settle_member, and a held lock here would deadlock them
            pool.shutdown(wait=True)
        with self._lock:
            # _inflight is non-empty only when the join timed out: those
            # requests were admitted and must still settle (drain below).
            remaining = list(self._inflight) + self._adm.drain_all()
            self._inflight = []
        remaining = [r for r in remaining if not r.future.done()]
        summary = {"drained": 0, "checkpoints": []}
        if remaining:
            if drain:
                summary = self._drain(remaining)
            else:
                for r in remaining:
                    dres = {"valid?": "unknown",
                            "cause": "service shut down before this "
                                     "request was checked"}
                    self._bundle(r, dres, [{"event": "serve.drained",
                                            "checkpoint": False}])
                    r.resolve(dres, status="drained")
                    # Keep the journal entry under drain=False too?  No:
                    # the caller explicitly declined a resumable drain,
                    # so a restart re-running these would contradict the
                    # resolution the client was handed.
                    self._journal_done(r)
                summary["drained"] = len(remaining)
        with self._lock:
            self._totals["drained"] += summary["drained"]
        return summary

    def _drain(self, remaining: list[CheckRequest]) -> dict:
        """Checkpoint still-queued work, one group per subdir: the
        histories + request ids (DRAIN_META) and a resumable
        ``store.checkpoint`` written by the real ladder machinery (a
        zero-budget ``batch_analysis`` trips its deadline at stage 0 and
        persists config + fingerprint + pending set — exactly the state
        ``resume=True`` re-enters).  Graph requests have no ladder state
        to checkpoint; they resolve unknown without one."""
        from jepsen_tpu.parallel import batch

        groups: dict[tuple | None, list[CheckRequest]] = {}
        for r in remaining:
            groups.setdefault(r.group, []).append(r)
        out = {"drained": len(remaining), "checkpoints": []}
        # Timestamped group dirs: a second drain into the same drain_dir
        # (service restarted with the same --drain-dir, drained again)
        # must never overwrite an earlier drain's checkpoint.
        stamp = store.time_str()
        for gi, (group, rs) in enumerate(sorted(
                groups.items(), key=lambda kv: kv[1][0].seq)):
            sub = None
            checkpointable = not (group and group[0] == "graph")
            if self.drain_dir is not None and checkpointable:
                sub = self.drain_dir / f"{stamp}-g{gi:02d}"
                try:
                    sub.mkdir(parents=True, exist_ok=True)
                    meta = {
                        "model": rs[0].model.name,
                        "ids": [r.id for r in rs],
                        "clients": [r.client for r in rs],
                        "histories": [
                            store._jsonable(list(r.history)) for r in rs
                        ],
                    }
                    _durable.write_record(sub / DRAIN_META, KIND_DRAIN, meta)
                    batch.batch_analysis(
                        rs[0].model, [r.history for r in rs],
                        capacity=self.capacity, mesh=self._placement.mesh,
                        checkpoint_dir=sub, deadline=faults.Deadline(0.0),
                        **self._check_opts,
                    )
                    out["checkpoints"].append(str(sub))
                except Exception as e:  # noqa: BLE001 — drain stays
                    # best-effort (the futures below still resolve),
                    # but the failure is COUNTED and carried on each
                    # affected request instead of vanishing into a log
                    # nobody tails: an operator trusting "drained means
                    # resumable" must see when it wasn't.
                    logger.exception("drain checkpoint failed for %s", sub)
                    drain_err = faults.describe(e)
                    with self._lock:
                        self._totals["drain_errors"] += 1
                    metrics.inc("serve.drain_errors")
                    for r in rs:
                        with obs.attach(r.ctx):
                            obs.counter("serve.drain_error",
                                        client=r.client, error=drain_err)
                    sub = None
            cause = "service shut down before this request was checked"
            if sub is not None:
                cause += f"; resumable drain checkpoint: {sub}"
            elif self.drain_dir is not None and checkpointable:
                cause += (
                    "; drain checkpoint FAILED (not resumable): "
                    f"{drain_err}"
                )
            for r in rs:
                with obs.attach(r.ctx):
                    obs.counter("serve.drained", client=r.client)
                metrics.inc("serve.verdicts", verdict="unknown")
                dres = {"valid?": "unknown", "cause": cause}
                self._bundle(r, dres, [{"event": "serve.drained",
                                        "checkpoint": sub is not None}])
                r.resolve(dres, status="drained")
                if sub is not None:
                    # the drain checkpoint supersedes the journal entry
                    # (resume_drained is the recovery path now); a
                    # FAILED drain keeps the entry — the journal is the
                    # only copy of the request left.
                    self._journal_done(r)
        return out


def resume_drained(drain_dir: str | Path, **kw) -> list[dict]:
    """Finish work a shutdown drained: for each group subdir, reload the
    histories from DRAIN_META (verified + migrated by ``store.durable``;
    pre-envelope drain dirs still resume) and re-enter the saved
    checkpoint (``batch_analysis(resume=True)`` — the saved ladder
    config wins).  Returns [{"dir", "model", "ids", "results"}] per
    group; a group whose meta is CORRUPT is quarantined aside and
    reported as {"dir", "error": <corruption report>} instead of being
    silently skipped — the operator learns which group's work is gone,
    the rest still resume."""
    from jepsen_tpu.parallel import batch

    out = []
    root = Path(drain_dir)
    for sub in sorted(p for p in root.iterdir() if p.is_dir()):
        meta_p = sub / DRAIN_META
        if not meta_p.is_file():
            continue
        try:
            meta = _durable.read_verified(meta_p, KIND_DRAIN).payload
        except _durable.DurableError as e:
            logger.warning("corrupt drain meta %s: %s", meta_p, e)
            out.append({"dir": str(sub), "error": e.report})
            continue
        model = model_by_name(meta["model"])
        results = batch.batch_analysis(
            model, meta["histories"], checkpoint_dir=sub, resume=True, **kw
        )
        out.append({
            "dir": str(sub), "model": meta["model"],
            "ids": meta.get("ids", []), "results": results,
        })
    return out
